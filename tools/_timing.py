"""Shared micro-benchmark timing — implementation lives in
paddle_tpu.utils.timing (single source of truth; the attention
dispatch autotuner uses it in-package). See that module's docstring for
the two axon-tunnel hardware facts that drive the design."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddle_tpu.utils.timing import scalar_of, timeit, vary  # noqa: F401,E402
