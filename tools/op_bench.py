"""Op-level micro-benchmark harness (reference
operators/benchmark/op_tester.cc + operators/jit/benchmark.cc): times the
hot kernels — matmul, attention (XLA and Pallas flash), layernorm,
embedding lookup, conv — on the current backend and appends one JSON
line per op to a per-round history file so a single-kernel regression
between rounds is visible without running a full model.

Usage:
    python tools/op_bench.py                 # bench all ops, print rows
    python tools/op_bench.py --ops matmul,attention
    python tools/op_bench.py --append bench_ops.jsonl  # history file

Each row: {"op", "shape", "ms", "gflops" (if meaningful), "backend",
"device_kind", "round": $BENCH_ROUND}. Smoke shapes via BENCH_SMOKE=1.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _timeit(fn, *args, iters=20):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters * 1e3  # ms


def bench_matmul(smoke):
    import jax.numpy as jnp

    n = 512 if smoke else 4096
    key = jax.random.key(0)
    a = jax.random.normal(key, (n, n), jnp.bfloat16)
    b = jax.random.normal(key, (n, n), jnp.bfloat16)
    f = jax.jit(lambda a, b: a @ b)
    ms = _timeit(f, a, b)
    return {"op": "matmul_bf16", "shape": f"{n}x{n}x{n}", "ms": ms,
            "gflops": 2 * n ** 3 / (ms / 1e3) / 1e9}


def bench_attention(smoke):
    import jax.numpy as jnp

    from paddle_tpu.nn import functional as F

    # (B, L, H, D) paddle layout; sdpa dispatches Pallas flash on TPU
    b, h, s, d = (2, 4, 256, 64) if smoke else (8, 12, 512, 64)
    key = jax.random.key(0)
    q = jax.random.normal(key, (b, s, h, d), jnp.float32)
    f = jax.jit(lambda q: F.scaled_dot_product_attention(
        q, q, q, is_causal=True, training=False).value)
    ms = _timeit(f, q)
    flops = 4 * b * h * s * s * d
    return {"op": "attention_causal", "shape": f"b{b}h{h}s{s}d{d}",
            "ms": ms, "gflops": flops / (ms / 1e3) / 1e9}


def bench_flash_attention(smoke):
    import jax.numpy as jnp

    from paddle_tpu.framework.bringup import TPU_PLATFORMS
    from paddle_tpu.ops.pallas.flash_attention import (
        _local_attention, _xla_attention)

    if jax.default_backend() not in TPU_PLATFORMS:
        return {"op": "flash_vs_xla", "skipped": "tpu-only"}
    b, h, s, d = (2, 4, 256, 64) if smoke else (8, 12, 512, 64)
    key = jax.random.key(0)
    q = jax.random.normal(key, (b, s, h, d), jnp.float32)
    flash = jax.jit(lambda q: _local_attention(q, q, q, True))
    xla = jax.jit(lambda q: _xla_attention(q, q, q, None, 0.0, True, None))
    ms_flash = _timeit(flash, q)
    ms_xla = _timeit(xla, q)
    return {"op": "flash_vs_xla", "shape": f"b{b}h{h}s{s}d{d}",
            "ms": ms_flash, "ms_xla": round(ms_xla, 4),
            "speedup": round(ms_xla / ms_flash, 3)}


def bench_layernorm(smoke):
    import jax.numpy as jnp

    from paddle_tpu.nn import functional as F

    rows, dim = (1 << 12, 256) if smoke else (1 << 16, 1024)
    key = jax.random.key(0)
    x = jax.random.normal(key, (rows, dim), jnp.float32)
    w = jnp.ones((dim,), jnp.float32)
    bvec = jnp.zeros((dim,), jnp.float32)
    f = jax.jit(lambda x: F.layer_norm(x, (dim,), w, bvec).value)
    ms = _timeit(f, x)
    gbps = x.nbytes * 2 / (ms / 1e3) / 1e9
    return {"op": "layernorm", "shape": f"{rows}x{dim}", "ms": ms,
            "gbps": gbps}


def bench_embedding(smoke):
    import jax.numpy as jnp

    vocab, dim = (10000, 128) if smoke else (100000, 768)
    tokens = 1 << 12 if smoke else 1 << 15
    key = jax.random.key(0)
    table = jax.random.normal(key, (vocab, dim), jnp.float32)
    ids = jax.random.randint(key, (tokens,), 0, vocab)
    f = jax.jit(lambda t, i: t[i])
    ms = _timeit(f, table, ids)
    gbps = tokens * dim * 4 / (ms / 1e3) / 1e9
    return {"op": "embedding", "shape": f"{vocab}x{dim}@{tokens}",
            "ms": ms, "gbps": gbps}


def bench_conv(smoke):
    import jax.numpy as jnp

    from paddle_tpu.nn import functional as F

    b, c, hw, k = (4, 32, 32, 64) if smoke else (64, 128, 56, 128)
    key = jax.random.key(0)
    x = jax.random.normal(key, (b, c, hw, hw), jnp.bfloat16)
    w = jax.random.normal(key, (k, c, 3, 3), jnp.bfloat16)
    f = jax.jit(lambda x, w: F.conv2d(x, w, padding=1).value)
    ms = _timeit(f, x, w)
    flops = 2 * b * k * c * 9 * hw * hw
    return {"op": "conv2d_bf16", "shape": f"b{b}c{c}x{hw}->k{k}",
            "ms": ms, "gflops": flops / (ms / 1e3) / 1e9}


BENCHES = {
    "matmul": bench_matmul,
    "attention": bench_attention,
    "flash_attention": bench_flash_attention,
    "layernorm": bench_layernorm,
    "embedding": bench_embedding,
    "conv": bench_conv,
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ops", default=",".join(BENCHES))
    ap.add_argument("--append", default=None,
                    help="JSONL history file to append rows to")
    args = ap.parse_args()
    smoke = os.environ.get("BENCH_SMOKE") == "1"

    from paddle_tpu.framework.bringup import ensure_backend

    backend = ensure_backend()
    global jax
    import jax

    kind = jax.devices()[0].device_kind
    rows = []
    for name in args.ops.split(","):
        name = name.strip()
        if not name:
            continue
        try:
            row = BENCHES[name](smoke)
        except Exception as e:
            row = {"op": name, "error": f"{type(e).__name__}: {e}"}
        row.update({"backend": backend, "device_kind": kind,
                    "round": os.environ.get("BENCH_ROUND", "")})
        if "ms" in row:
            row["ms"] = round(row["ms"], 4)
        for k in ("gflops", "gbps"):
            if k in row:
                row[k] = round(row[k], 2)
        rows.append(row)
        print(json.dumps(row), flush=True)
    if args.append:
        with open(args.append, "a") as f:
            for row in rows:
                f.write(json.dumps(row) + "\n")


jax = None  # set in main() after backend resolution

if __name__ == "__main__":
    main()
