"""Op-level micro-benchmark harness (reference
operators/benchmark/op_tester.cc + operators/jit/benchmark.cc): times the
hot kernels — matmul, attention (XLA and Pallas flash), layernorm,
embedding lookup, conv — on the current backend and appends one JSON
line per op to a per-round history file so a single-kernel regression
between rounds is visible without running a full model.

Usage:
    python tools/op_bench.py                 # bench all ops, print rows
    python tools/op_bench.py --ops matmul,attention
    python tools/op_bench.py --append bench_ops.jsonl  # history file

Each row: {"op", "shape", "ms", "gflops" (if meaningful), "backend",
"device_kind", "round": $BENCH_ROUND}. Smoke shapes via BENCH_SMOKE=1.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _timeit(fn, *args, iters=20, vary=-1):
    from tools._timing import timeit

    return timeit(fn, *args, iters=iters, vary_arg=vary)


def bench_matmul(smoke):
    import jax.numpy as jnp

    n = 512 if smoke else 4096
    key = jax.random.key(0)
    a = jax.random.normal(key, (n, n), jnp.bfloat16)
    b = jax.random.normal(key, (n, n), jnp.bfloat16)
    f = jax.jit(lambda a, b: a @ b)
    ms = _timeit(f, a, b)
    return {"op": "matmul_bf16", "shape": f"{n}x{n}x{n}", "ms": ms,
            "gflops": 2 * n ** 3 / (ms / 1e3) / 1e9}


def bench_attention(smoke):
    import jax.numpy as jnp

    from paddle_tpu.nn import functional as F

    # (B, L, H, D) paddle layout; sdpa dispatches Pallas flash on TPU
    b, h, s, d = (2, 4, 256, 64) if smoke else (8, 12, 512, 64)
    key = jax.random.key(0)
    q = jax.random.normal(key, (b, s, h, d), jnp.float32)
    f = jax.jit(lambda q: F.scaled_dot_product_attention(
        q, q, q, is_causal=True, training=False).value)
    ms = _timeit(f, q)
    flops = 4 * b * h * s * s * d
    return {"op": "attention_causal", "shape": f"b{b}h{h}s{s}d{d}",
            "ms": ms, "gflops": flops / (ms / 1e3) / 1e9}


def bench_flash_attention(smoke):
    import jax.numpy as jnp

    from paddle_tpu.framework.bringup import TPU_PLATFORMS
    from paddle_tpu.ops.pallas.flash_attention import (
        _local_attention, _xla_attention)

    if jax.default_backend() not in TPU_PLATFORMS:
        return {"op": "flash_vs_xla", "skipped": "tpu-only"}
    b, h, s, d = (2, 4, 256, 64) if smoke else (8, 12, 512, 64)
    key = jax.random.key(0)
    q = jax.random.normal(key, (b, s, h, d), jnp.float32)
    flash = jax.jit(lambda q: _local_attention(q, q, q, True))
    xla = jax.jit(lambda q: _xla_attention(q, q, q, None, 0.0, True, None))
    ms_flash = _timeit(flash, q)
    ms_xla = _timeit(xla, q)
    return {"op": "flash_vs_xla", "shape": f"b{b}h{h}s{s}d{d}",
            "ms": ms_flash, "ms_xla": round(ms_xla, 4),
            "speedup": round(ms_xla / ms_flash, 3)}


def bench_flash_short(smoke):
    """Seq-128 dispatch-floor A/B: single-block short kernel vs the
    streaming kernel vs XLA (VERDICT r3 weak #3)."""
    import jax.numpy as jnp

    from paddle_tpu.framework.bringup import TPU_PLATFORMS
    from paddle_tpu.ops.pallas.flash_attention import (
        _flash_attention_pallas, _flash_attention_pallas_short,
        _xla_attention)

    if jax.default_backend() not in TPU_PLATFORMS:
        return {"op": "flash_short_vs_xla", "skipped": "tpu-only"}
    b, h, s, d = (2, 4, 128, 64) if smoke else (128, 12, 128, 64)
    key = jax.random.key(0)
    q = jax.random.normal(key, (b, s, h, d), jnp.float32)
    short = jax.jit(lambda q: _flash_attention_pallas_short(
        q, q, q, causal=False))
    stream = jax.jit(lambda q: _flash_attention_pallas(
        q, q, q, causal=False, block_q=128, block_kv=128))
    xla = jax.jit(lambda q: _xla_attention(q, q, q, None, 0.0, False,
                                           None))
    ms_short = _timeit(short, q)
    ms_stream = _timeit(stream, q)
    ms_xla = _timeit(xla, q)
    return {"op": "flash_short_vs_xla", "shape": f"b{b}h{h}s{s}d{d}",
            "ms": ms_short, "ms_stream": round(ms_stream, 4),
            "ms_xla": round(ms_xla, 4),
            "speedup_vs_xla": round(ms_xla / ms_short, 3)}


def bench_layernorm(smoke):
    import jax.numpy as jnp

    from paddle_tpu.nn import functional as F

    rows, dim = (1 << 12, 256) if smoke else (1 << 16, 1024)
    key = jax.random.key(0)
    x = jax.random.normal(key, (rows, dim), jnp.float32)
    w = jnp.ones((dim,), jnp.float32)
    bvec = jnp.zeros((dim,), jnp.float32)
    f = jax.jit(lambda x: F.layer_norm(x, (dim,), w, bvec).value)
    ms = _timeit(f, x)
    gbps = x.nbytes * 2 / (ms / 1e3) / 1e9
    return {"op": "layernorm", "shape": f"{rows}x{dim}", "ms": ms,
            "gbps": gbps}


def bench_embedding(smoke):
    import jax.numpy as jnp

    vocab, dim = (10000, 128) if smoke else (100000, 768)
    tokens = 1 << 12 if smoke else 1 << 15
    key = jax.random.key(0)
    table = jax.random.normal(key, (vocab, dim), jnp.float32)
    ids = jax.random.randint(key, (tokens,), 0, vocab)
    f = jax.jit(lambda t, i: t[i])
    ms = _timeit(f, table, ids)
    gbps = tokens * dim * 4 / (ms / 1e3) / 1e9
    return {"op": "embedding", "shape": f"{vocab}x{dim}@{tokens}",
            "ms": ms, "gbps": gbps}


def bench_conv(smoke):
    import jax.numpy as jnp

    from paddle_tpu.nn import functional as F

    b, c, hw, k = (4, 32, 32, 64) if smoke else (64, 128, 56, 128)
    key = jax.random.key(0)
    x = jax.random.normal(key, (b, c, hw, hw), jnp.bfloat16)
    w = jax.random.normal(key, (k, c, 3, 3), jnp.bfloat16)
    f = jax.jit(lambda x, w: F.conv2d(x, w, padding=1).value)
    ms = _timeit(f, x, w)
    flops = 2 * b * k * c * 9 * hw * hw
    return {"op": "conv2d_bf16", "shape": f"b{b}c{c}x{hw}->k{k}",
            "ms": ms, "gflops": flops / (ms / 1e3) / 1e9}


def bench_fused_embedding(smoke):
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas.fused_embedding import \
        fused_embedding_seq_pool

    vocab, dim = (5000, 128) if smoke else (100000, 256)
    b, s = (256, 16) if smoke else (4096, 64)
    key = jax.random.key(0)
    table = jax.random.normal(key, (vocab, dim), jnp.float32)
    ids = jax.random.randint(key, (b, s), 0, vocab)
    f = jax.jit(lambda t, i: fused_embedding_seq_pool(
        t, i, combiner="sum"))
    ms = _timeit(f, table, ids)
    gbps = b * s * dim * 4 / (ms / 1e3) / 1e9
    return {"op": "fused_embedding_bag", "shape": f"{vocab}x{dim}@{b}x{s}",
            "ms": ms, "gbps": gbps}


def bench_softmax_xent(smoke):
    import jax.numpy as jnp

    from paddle_tpu.nn import functional as F

    rows, classes = (1 << 10, 1000) if smoke else (1 << 14, 32000)
    key = jax.random.key(0)
    logits = jax.random.normal(key, (rows, classes), jnp.float32)
    labels = jax.random.randint(key, (rows,), 0, classes)

    def step(lg, lb):
        return F.cross_entropy(lg, lb).value

    f = jax.jit(step)
    ms = _timeit(f, logits, labels)
    return {"op": "softmax_xent", "shape": f"{rows}x{classes}", "ms": ms,
            "gbps": logits.nbytes / (ms / 1e3) / 1e9}


def bench_optimizer_update(smoke):
    """AdamW slot update over a flat param bundle (optimizer hot loop)."""
    import jax.numpy as jnp
    import optax

    n = (1 << 20) if smoke else (1 << 24)
    key = jax.random.key(0)
    p = jax.random.normal(key, (n,), jnp.float32)
    g = jax.random.normal(key, (n,), jnp.float32)
    opt = optax.adamw(1e-3)
    state = opt.init(p)

    @jax.jit
    def step(p, g, state):
        up, state = opt.update(g, state, p)
        return optax.apply_updates(p, up), state

    ms = _timeit(step, p, g, state, iters=10, vary=1)  # vary the grads
    return {"op": "adamw_update", "shape": f"{n}", "ms": ms,
            "gbps": p.nbytes * 5 / (ms / 1e3) / 1e9}


def bench_transpose(smoke):
    """HBM bandwidth probe: non-fusible major-axis transpose copy."""
    import jax.numpy as jnp

    n = 1024 if smoke else 8192
    key = jax.random.key(0)
    x = jax.random.normal(key, (n, n), jnp.float32)
    f = jax.jit(lambda x: jnp.swapaxes(x, 0, 1) + 1.0)
    ms = _timeit(f, x)
    return {"op": "transpose_add", "shape": f"{n}x{n}", "ms": ms,
            "gbps": x.nbytes * 2 / (ms / 1e3) / 1e9}


def bench_fused_xent(smoke):
    """MLM-head A/B (VERDICT r4 #2): fused streamed linear+xent kernel
    vs the materialised-logits XLA path, fwd+bwd at BERT shapes."""
    import jax.numpy as jnp

    from paddle_tpu.framework.bringup import TPU_PLATFORMS
    from paddle_tpu.ops.pallas.fused_xent import (
        _fused_xent_core, fused_linear_cross_entropy)

    if jax.default_backend() not in TPU_PLATFORMS:
        return {"op": "fused_xent_vs_xla", "skipped": "tpu-only"}
    n, hd, v = (512, 128, 1024) if smoke else (4096, 768, 30592)
    key = jax.random.key(0)
    h = jax.random.normal(key, (n, hd), jnp.bfloat16) * 0.2
    w = jax.random.normal(jax.random.key(1), (v, hd), jnp.bfloat16) * 0.2
    b = jnp.zeros((v,), jnp.float32)
    lab = jax.random.randint(jax.random.key(2), (n,), 0, v, jnp.int32)

    fused = jax.jit(jax.grad(
        lambda h_, w_: _fused_xent_core(h_, w_, b, lab, -100),
        argnums=(0, 1)))

    def xla_loss(h_, w_):
        logits = (h_ @ w_.T).astype(jnp.float32) + b
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.mean(jnp.take_along_axis(
            logp, lab[:, None], axis=1))

    xla = jax.jit(jax.grad(xla_loss, argnums=(0, 1)))
    ms_fused = _timeit(fused, h, w)
    ms_xla = _timeit(xla, h, w)
    return {"op": "fused_xent_vs_xla", "shape": f"{n}x{hd}x{v}",
            "ms": ms_fused, "ms_xla": round(ms_xla, 4),
            "speedup": round(ms_xla / ms_fused, 3)}


BENCHES = {
    "matmul": bench_matmul,
    "attention": bench_attention,
    "flash_attention": bench_flash_attention,
    "flash_short": bench_flash_short,
    "fused_xent": bench_fused_xent,
    "layernorm": bench_layernorm,
    "embedding": bench_embedding,
    "fused_embedding": bench_fused_embedding,
    "conv": bench_conv,
    "softmax_xent": bench_softmax_xent,
    "optimizer_update": bench_optimizer_update,
    "transpose": bench_transpose,
}


def run_benches(ops=None, smoke=None):
    """Resolve the backend, run the named benches (default: all), return
    the row dicts. Importable so the regression-gate test shares the
    exact measurement path with the CLI."""
    if smoke is None:
        smoke = os.environ.get("BENCH_SMOKE") == "1"

    from paddle_tpu.framework.bringup import ensure_backend

    backend = ensure_backend()
    global jax
    import jax

    kind = jax.devices()[0].device_kind
    rows = []
    for name in (ops or list(BENCHES)):
        name = name.strip()
        if not name:
            continue
        try:
            row = BENCHES[name](smoke)
        except Exception as e:
            row = {"op": name, "error": f"{type(e).__name__}: {e}"}
        row.update({"backend": backend, "device_kind": kind, "smoke": smoke,
                    "round": os.environ.get("BENCH_ROUND", "")})
        if "ms" in row:
            row["ms"] = round(row["ms"], 4)
        for k in ("gflops", "gbps"):
            if k in row:
                row[k] = round(row[k], 2)
        rows.append(row)
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ops", default=",".join(BENCHES))
    ap.add_argument("--append", default=None,
                    help="JSONL history file to append rows to")
    args = ap.parse_args()
    rows = run_benches(args.ops.split(","))
    from tools._captures import persist_row

    for row in rows:
        print(json.dumps(row), flush=True)
        persist_row(row, kind="opbench")
    if args.append:
        with open(args.append, "a") as f:
            for row in rows:
                f.write(json.dumps(row) + "\n")


jax = None  # set in main() after backend resolution

if __name__ == "__main__":
    main()
