"""One-command live-TPU capture session.

The axon tunnel is up for short unpredictable windows (observed ~1-2 h
per day); this script packs everything the perf contract needs into one
invocation so a single window produces committed evidence:

  1. full bench matrix (headline + bert512/resnet/nmt/ctr/mnist) —
     every measured row appends to BENCH_CAPTURES.jsonl via bench.py
  2. op-level micro-bench -> OPBENCH_r05.jsonl (device_kind=TPU rows,
     host-fetch timing methodology) + capture log
  3. flash-attention block/crossover sweep at seq 128/256/512
     (fwd-only and fwd+bwd) for the dispatch-floor decision

Usage (default env — PYTHONPATH must keep /root/.axon_site):
    python tools/live_tpu_session.py [--skip-sweep]
Then commit BENCH_CAPTURES.jsonl + OPBENCH_r04.jsonl.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _run(cmd, timeout, env=None):
    print(f"\n=== {' '.join(cmd)} (timeout {timeout}s)", flush=True)
    t0 = time.time()
    try:
        p = subprocess.run(cmd, cwd=REPO, timeout=timeout, env=env)
        rc = p.returncode
    except subprocess.TimeoutExpired:
        rc = "timeout"
    print(f"=== rc={rc} in {time.time() - t0:.0f}s", flush=True)
    return rc


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--skip-sweep", action="store_true")
    ap.add_argument("--skip-bench", action="store_true")
    args = ap.parse_args()

    from paddle_tpu.framework.bringup import TPU_PLATFORMS, ensure_backend

    backend = ensure_backend()
    if backend not in TPU_PLATFORMS:
        print(f"backend is {backend!r} — tunnel down, nothing to capture")
        return 1
    import jax

    kind = jax.devices()[0].device_kind
    print(f"LIVE TPU: backend={backend} device_kind={kind}")

    env = dict(os.environ)
    env.setdefault("BENCH_ROUND", "r05")

    # hardware-only kernel validation first (interpret mode can't vouch
    # for Mosaic lowering — the r3 fused-embedding lesson)
    _run([sys.executable, "-m", "pytest", "-q",
          "tests/test_flash_short_tpu.py", "tests/test_flash_dropout_tpu.py",
          "tests/test_ring_flash_tpu.py", "tests/test_fused_xent_tpu.py",
          "-p", "no:cacheprovider", "--noconftest"],
         timeout=900, env=dict(os.environ))

    if not args.skip_bench:
        # the default driver invocation: headline + extras, rows persist
        _run([sys.executable, "bench.py"], timeout=3600, env=env)
        # A/B for the seq-128 dispatch floor: short single-block kernel
        # vs the XLA floor (VERDICT r3 weak #3). Rows land in the
        # capture log; pallas_fallback distinguishes the two arms.
        ab = dict(env)
        ab["FLAGS_flash_short_seq"] = "1"
        _run([sys.executable, "bench.py", "--config", "bert"],
             timeout=1200, env=ab)
        # fused-vocab-xent A/B at seq 512 (the MFU push, VERDICT r4 #2):
        # the default run above measures the fused path; this arm
        # re-measures bert512 with logits materialised via XLA
        ab2 = dict(env)
        ab2["FLAGS_fused_vocab_xent"] = "0"
        _run([sys.executable, "bench.py", "--config", "bert512"],
             timeout=1200, env=ab2)

    # op-bench: TPU baseline rows (the gate's committed reference)
    _run([sys.executable, "tools/op_bench.py",
          "--append", "OPBENCH_r05.jsonl"], timeout=1200, env=env)

    if not args.skip_sweep:
        for extra in ([], ["--grad"]):
            _run([sys.executable, "tools/tune_flash.py"] + extra,
                 timeout=1800, env=env)
        # bottleneck diagnosis: device-time-by-op summaries appended to
        # the committed XPLANE_SUMMARY.md (bert512 is the MFU target;
        # resnet sits at ~20% and needs the same answer)
        for cfg in ("bert512", "resnet"):
            _run([sys.executable, "tools/profile_step.py",
                  "--config", cfg, "--out",
                  f"/tmp/paddle_tpu_profile_{cfg}",
                  "--summary", "XPLANE_SUMMARY.md"],
                 timeout=900, env=env)

    # summary of what landed in the capture log this session
    try:
        with open(os.path.join(REPO, "BENCH_CAPTURES.jsonl")) as f:
            rows = [json.loads(ln) for ln in f if ln.strip()]
        tpu_rows = [r for r in rows if r.get("backend") in ("axon", "tpu")
                    or "tpu" in str(r.get("device_kind", "")).lower()
                    or "v5" in str(r.get("device_kind", "")).lower()]
        print(f"\nBENCH_CAPTURES.jsonl: {len(rows)} rows total, "
              f"{len(tpu_rows)} TPU rows")
        for r in tpu_rows[-12:]:
            print(" ", {k: r.get(k) for k in
                        ("ts", "config", "op", "value", "ms", "mfu",
                         "device_kind", "git_sha")})
    except OSError:
        pass
    print("\nNow: git add BENCH_CAPTURES.jsonl OPBENCH_r04.jsonl && commit")
    return 0


if __name__ == "__main__":
    sys.exit(main())
