"""One-shot TPU validation batch (run when the axon tunnel is alive):
1. flash-attention dropout kernel tests (tests/test_flash_dropout_tpu.py)
2. attention micro-bench: XLA+dropout vs Pallas in-kernel dropout
3. bench.py (BERT-base tokens/s; the driver-contract metric)
Usage: PYTHONPATH=/root/repo python tools/tpu_validation.py
"""
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_kernel_tests():
    r = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/test_flash_dropout_tpu.py",
         "-q", "-p", "no:cacheprovider"],
        env={**os.environ, "PYTHONPATH": "/root/repo"},
        capture_output=True, text=True, timeout=2400)
    tail = "\n".join((r.stdout + r.stderr).splitlines()[-6:])
    print("== kernel tests ==\n" + tail)
    return r.returncode == 0


def attention_microbench():
    import numpy as np
    import jax, jax.numpy as jnp
    from paddle_tpu.ops.pallas.flash_attention import (
        _flash_attention_pallas_dropout, _xla_attention)

    rng = np.random.RandomState(0)
    B, L, H, D = 128, 128, 12, 64
    q = jnp.asarray(rng.randn(B, L, H, D), jnp.bfloat16)
    k = jnp.asarray(rng.randn(B, L, H, D), jnp.bfloat16)
    v = jnp.asarray(rng.randn(B, L, H, D), jnp.bfloat16)
    seed = jnp.asarray([[7]], jnp.int32)
    key = jax.random.PRNGKey(0)

    def timeit(fn, n=30):
        fn()  # compile
        t0 = time.perf_counter()
        for _ in range(n):
            out = fn()
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / n * 1e3

    xla = jax.jit(lambda: _xla_attention(q, k, v, None, 0.1, False, key))
    pallas = lambda: _flash_attention_pallas_dropout(q, k, v, seed, 0.1)
    print(f"== attention fwd (B{B} L{L} H{H} D{D} bf16, dropout 0.1) ==")
    print(f"xla+dropout:    {timeit(xla):.3f} ms")
    print(f"pallas dropout: {timeit(pallas):.3f} ms")

    def grad_of(fn):
        g = jax.jit(jax.grad(lambda qq: jnp.sum(fn(qq).astype(jnp.float32))))
        return lambda: g(q)

    print(f"xla+dropout grad:    "
          f"{timeit(grad_of(lambda qq: _xla_attention(qq, k, v, None, 0.1, False, key))):.3f} ms")
    print(f"pallas dropout grad: "
          f"{timeit(grad_of(lambda qq: _flash_attention_pallas_dropout(qq, k, v, seed, 0.1))):.3f} ms")


def run_bench():
    r = subprocess.run([sys.executable, "bench.py"],
                       env={**os.environ, "PYTHONPATH": "/root/repo"},
                       capture_output=True, text=True, timeout=2400)
    metric_lines = [line for line in r.stdout.splitlines()
                    if line.startswith("{")]
    print("== bench ==\n" + "\n".join(metric_lines))
    if r.returncode != 0 or not metric_lines:
        print("bench FAILED (rc=%d):\n%s" % (
            r.returncode, "\n".join(r.stderr.splitlines()[-8:])))
        return False
    return True


if __name__ == "__main__":
    ok = run_kernel_tests()
    attention_microbench()
    ok = run_bench() and ok
    sys.exit(0 if ok else 1)
