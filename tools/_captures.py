"""Append-only durable capture log for benchmark rows.

Every measured row from bench.py and tools/op_bench.py is appended to
``BENCH_CAPTURES.jsonl`` at the repo root — a COMMITTED artifact — so a
live-TPU measurement leaves a durable, attributable record even when
the driver window misses the flaky tunnel (the reference persists its
numbers next to the harness too: operators/benchmark/op_tester.cc).
Each record carries a UTC timestamp and the git sha at measurement
time, so any number can be traced to the exact code that produced it.

Knobs:
  BENCH_CAPTURES_PATH  override the destination file (tests point this
                       at a tmp path)
  BENCH_NO_PERSIST=1   disable persistence entirely
"""
from __future__ import annotations

import json
import os
import subprocess
from datetime import datetime, timezone

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_sha_cache = None


def git_sha() -> str:
    """Short sha of HEAD, cached; 'unknown' outside a git checkout."""
    global _sha_cache
    if _sha_cache is None:
        try:
            out = subprocess.run(
                ["git", "rev-parse", "--short", "HEAD"], cwd=_REPO,
                capture_output=True, text=True, timeout=10)
            _sha_cache = out.stdout.strip() or "unknown"
        except Exception:
            _sha_cache = "unknown"
    return _sha_cache


def captures_path() -> str:
    return os.environ.get(
        "BENCH_CAPTURES_PATH", os.path.join(_REPO, "BENCH_CAPTURES.jsonl"))


def persist_row(row: dict, kind: str = "bench") -> bool:
    """Append one measured row (with ts/git_sha/kind prepended).

    Never raises: a read-only checkout or full disk must not take down
    the bench whose primary contract is the stdout JSON row. Returns
    whether the write happened.
    """
    if os.environ.get("BENCH_NO_PERSIST") == "1":
        return False
    rec = {"ts": datetime.now(timezone.utc).isoformat(timespec="seconds"),
           "git_sha": git_sha(), "kind": kind}
    rec.update(row)
    try:
        with open(captures_path(), "a") as f:
            f.write(json.dumps(rec) + "\n")
        return True
    except Exception:
        # includes json TypeError on a non-serializable field: the
        # stdout row is the primary contract and must still be printed
        return False
