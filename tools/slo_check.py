#!/usr/bin/env python
"""SLO burn-rate check over a ``/metrics`` scrape — CI-able: exits
non-zero when an objective burns.

Usage::

    python tools/slo_check.py --metrics 127.0.0.1:8321
    python tools/slo_check.py --metrics scrape.txt          # saved scrape
    python tools/slo_check.py --metrics new.txt --baseline old.txt \
        --window-s 300
    python tools/slo_check.py --metrics ... --objectives slo.json
    python tools/slo_check.py --metrics 127.0.0.1:8101 \
        --metrics 127.0.0.1:8102 --metrics 127.0.0.1:8103   # a fleet

With one scrape, objectives evaluate over the CUMULATIVE totals (the
window is "since process start"). With ``--baseline`` (an earlier
scrape of the same process), they evaluate over the DELTA — the real
burn-rate window; ``--window-s`` only labels it. Objectives default to
:func:`paddle_tpu.observability.slo.default_objectives`; pass a JSON
list (see ``objectives_from_json``) to declare your own. Works against
a federated scrape too — pass ``--instance host:port`` to narrow to
one member.

``--metrics`` repeats: each endpoint/file is scraped and its samples
are merged under an ``instance`` label (exactly the federation plane's
convention), so objectives evaluate the FLEET aggregate by default and
``--instance`` still narrows to one member. Repeat ``--baseline`` the
same number of times, in the same order, for a fleet-wide delta. One
unreachable endpoint is an input error (exit 2), never a silent gap.

Exit codes: 0 healthy, 1 burning (the CI signal), 2 input/usage error.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from paddle_tpu.observability.metrics import (  # noqa: E402
    parse_prometheus_text,
)
from paddle_tpu.observability.slo import (  # noqa: E402
    SLOEvaluator, default_objectives, objectives_from_json,
)


def _load_samples(target: str):
    if os.path.exists(target):
        with open(target) as fh:
            return parse_prometheus_text(fh.read())
    from tools.metrics_watch import scrape

    return scrape(target)


def _load_fleet(targets):
    """One target -> its samples verbatim (single-scrape back-compat).
    Several -> the union with each sample ``instance``-labeled by the
    target it came from, so per-member objectives keep working and
    unlabeled ones sum fleet-wide."""
    if len(targets) == 1:
        return _load_samples(targets[0])
    from paddle_tpu.observability.federation import _inject_instance

    merged = {}
    for target in targets:
        for key, v in _load_samples(target).items():
            merged[_inject_instance(key, target)] = v
    return merged


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="evaluate SLO burn rates against a /metrics "
                    "scrape; exit 1 on burn")
    ap.add_argument("--metrics", required=True, action="append",
                    help="host:port to scrape, or a saved scrape file; "
                         "repeat for a fleet (samples merge under an "
                         "instance label)")
    ap.add_argument("--baseline", default=None, action="append",
                    help="earlier scrape (host:port or file) — "
                         "evaluate the delta instead of cumulative "
                         "totals; repeat to mirror a multi --metrics "
                         "fleet")
    ap.add_argument("--objectives", default=None,
                    help="JSON file declaring objectives (default: "
                         "the stock fleet objectives)")
    ap.add_argument("--window-s", type=float, default=3600.0,
                    help="window label for the delta/cumulative "
                         "evaluation (seconds)")
    ap.add_argument("--burn-factor", type=float, default=1.0,
                    help="burn-rate factor above which an objective "
                         "burns (1.0 = budget-neutral pace)")
    ap.add_argument("--instance", default=None,
                    help="narrow a federated scrape to one member "
                         "endpoint")
    ap.add_argument("--json", action="store_true",
                    help="print the verdicts as one JSON document")
    args = ap.parse_args(argv)

    try:
        if args.objectives:
            with open(args.objectives) as fh:
                objectives = objectives_from_json(fh.read())
        else:
            objectives = default_objectives()
        if args.instance:
            for o in objectives:
                o.instance = args.instance
        if args.baseline and len(args.baseline) != len(args.metrics):
            raise ValueError(
                f"{len(args.baseline)} --baseline scrape(s) for "
                f"{len(args.metrics)} --metrics endpoint(s); repeat "
                "--baseline once per endpoint, in the same order")
        samples = _load_fleet(args.metrics)
        base = (_load_fleet(args.baseline)
                if args.baseline else None)
    # TypeError: an --objectives row with a wrong/unknown field
    # (Objective(**row)) — a usage error, which must NOT exit 1 and
    # read as a burning SLO to CI
    except (OSError, RuntimeError, TypeError, ValueError) as e:
        print(f"slo_check: {e}", file=sys.stderr)
        return 2
    if not samples:
        print(f"slo_check: no samples in {args.metrics!r}",
              file=sys.stderr)
        return 2

    ev = SLOEvaluator(objectives,
                      windows=((args.window_s, args.burn_factor),),
                      clock=lambda: float(args.window_s) * 2)
    if base is not None:
        ev.add_snapshot(base, t=0.0)
    # the newest snapshot lands just inside the window; with a baseline
    # it predates the window edge, so the delta is baseline->now
    ev.add_snapshot(samples, t=float(args.window_s) * 1.5)
    verdicts = ev.evaluate()

    burning = [v for v in verdicts if v.burning]
    if args.json:
        print(json.dumps({"burning": [v.objective for v in burning],
                          "verdicts": [v.to_dict() for v in verdicts]},
                         indent=2))
    else:
        for v in verdicts:
            rates = ", ".join(
                f"{int(w['window_s'])}s: "
                + (f"{w['burn_rate']:.3f}" if w["burn_rate"] is not None
                   else "no-signal")
                for w in v.windows)
            flag = "BURNING" if v.burning else "ok"
            print(f"{v.objective:<24}{flag:<9}{rates}")
    if burning:
        print(f"slo_check: {len(burning)} objective(s) burning: "
              + ", ".join(v.objective for v in burning),
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
