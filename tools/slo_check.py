#!/usr/bin/env python
"""SLO burn-rate check over a ``/metrics`` scrape — CI-able: exits
non-zero when an objective burns.

Usage::

    python tools/slo_check.py --metrics 127.0.0.1:8321
    python tools/slo_check.py --metrics scrape.txt          # saved scrape
    python tools/slo_check.py --metrics new.txt --baseline old.txt \
        --window-s 300
    python tools/slo_check.py --metrics ... --objectives slo.json

With one scrape, objectives evaluate over the CUMULATIVE totals (the
window is "since process start"). With ``--baseline`` (an earlier
scrape of the same process), they evaluate over the DELTA — the real
burn-rate window; ``--window-s`` only labels it. Objectives default to
:func:`paddle_tpu.observability.slo.default_objectives`; pass a JSON
list (see ``objectives_from_json``) to declare your own. Works against
a federated scrape too — pass ``--instance host:port`` to narrow to
one member.

Exit codes: 0 healthy, 1 burning (the CI signal), 2 input/usage error.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from paddle_tpu.observability.metrics import (  # noqa: E402
    parse_prometheus_text,
)
from paddle_tpu.observability.slo import (  # noqa: E402
    SLOEvaluator, default_objectives, objectives_from_json,
)


def _load_samples(target: str):
    if os.path.exists(target):
        with open(target) as fh:
            return parse_prometheus_text(fh.read())
    from tools.metrics_watch import scrape

    return scrape(target)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="evaluate SLO burn rates against a /metrics "
                    "scrape; exit 1 on burn")
    ap.add_argument("--metrics", required=True,
                    help="host:port to scrape, or a saved scrape file")
    ap.add_argument("--baseline", default=None,
                    help="earlier scrape (host:port or file) — "
                         "evaluate the delta instead of cumulative "
                         "totals")
    ap.add_argument("--objectives", default=None,
                    help="JSON file declaring objectives (default: "
                         "the stock fleet objectives)")
    ap.add_argument("--window-s", type=float, default=3600.0,
                    help="window label for the delta/cumulative "
                         "evaluation (seconds)")
    ap.add_argument("--burn-factor", type=float, default=1.0,
                    help="burn-rate factor above which an objective "
                         "burns (1.0 = budget-neutral pace)")
    ap.add_argument("--instance", default=None,
                    help="narrow a federated scrape to one member "
                         "endpoint")
    ap.add_argument("--json", action="store_true",
                    help="print the verdicts as one JSON document")
    args = ap.parse_args(argv)

    try:
        if args.objectives:
            with open(args.objectives) as fh:
                objectives = objectives_from_json(fh.read())
        else:
            objectives = default_objectives()
        if args.instance:
            for o in objectives:
                o.instance = args.instance
        samples = _load_samples(args.metrics)
        base = (_load_samples(args.baseline)
                if args.baseline else None)
    # TypeError: an --objectives row with a wrong/unknown field
    # (Objective(**row)) — a usage error, which must NOT exit 1 and
    # read as a burning SLO to CI
    except (OSError, RuntimeError, TypeError, ValueError) as e:
        print(f"slo_check: {e}", file=sys.stderr)
        return 2
    if not samples:
        print(f"slo_check: no samples in {args.metrics!r}",
              file=sys.stderr)
        return 2

    ev = SLOEvaluator(objectives,
                      windows=((args.window_s, args.burn_factor),),
                      clock=lambda: float(args.window_s) * 2)
    if base is not None:
        ev.add_snapshot(base, t=0.0)
    # the newest snapshot lands just inside the window; with a baseline
    # it predates the window edge, so the delta is baseline->now
    ev.add_snapshot(samples, t=float(args.window_s) * 1.5)
    verdicts = ev.evaluate()

    burning = [v for v in verdicts if v.burning]
    if args.json:
        print(json.dumps({"burning": [v.objective for v in burning],
                          "verdicts": [v.to_dict() for v in verdicts]},
                         indent=2))
    else:
        for v in verdicts:
            rates = ", ".join(
                f"{int(w['window_s'])}s: "
                + (f"{w['burn_rate']:.3f}" if w["burn_rate"] is not None
                   else "no-signal")
                for w in v.windows)
            flag = "BURNING" if v.burning else "ok"
            print(f"{v.objective:<24}{flag:<9}{rates}")
    if burning:
        print(f"slo_check: {len(burning)} objective(s) burning: "
              + ", ".join(v.objective for v in burning),
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
