#!/bin/bash
# Unattended tunnel watcher: probe every 10 min; when the axon tunnel is
# up, immediately run the full live-TPU capture session (hardware kernel
# tests + bench matrix + A/B + op-bench + sweeps), then back off 2 h so
# repeated windows don't re-burn the same captures. Log: /tmp/tunnel_watch.log
cd "$(dirname "$0")/.." || exit 1
while true; do
  rm -f ~/.cache/paddle_tpu/probe.json
  if timeout 90 python -c "import jax; assert jax.devices()" 2>/dev/null; then
    echo "=== tunnel UP at $(date -u) — running live session" >> /tmp/tunnel_watch.log
    python tools/live_tpu_session.py >> /tmp/tunnel_watch.log 2>&1
    echo "=== session done at $(date -u) rc=$?" >> /tmp/tunnel_watch.log
    sleep 7200
  else
    echo "down $(date -u)" >> /tmp/tunnel_watch.log
    sleep 600
  fi
done
