#!/bin/bash
# Unattended tunnel watcher: probe every 10 min; when the axon tunnel is
# up, immediately run the full live-TPU capture session (hardware kernel
# tests + bench matrix + A/B + op-bench + sweeps), auto-commit whatever
# landed, then back off — but ONLY if captures actually landed; a probe
# that flapped mid-session retries on the short cadence so a second
# window isn't wasted.
#
# Arm it (documented in README):
#   nohup bash tools/tunnel_watch.sh >/dev/null 2>&1 &
# Log: /tmp/tunnel_watch.log (rotated at ~1 MB).
cd "$(dirname "$0")/.." || exit 1
LOG=/tmp/tunnel_watch.log

tpu_rows() {
  # count durable TPU evidence rows in the capture log (grep -c prints 0
  # itself on no-match; only a missing file leaves $n empty)
  local n
  n=$(grep -ciE '"device_kind": "[^"]*(tpu|v5)' BENCH_CAPTURES.jsonl 2>/dev/null)
  echo "${n:-0}"
}

while true; do
  # rotate the log so a multi-day run can't fill /tmp
  if [ -f "$LOG" ] && [ "$(stat -c%s "$LOG" 2>/dev/null || echo 0)" -gt 1000000 ]; then
    tail -c 200000 "$LOG" > "$LOG.1" && mv "$LOG.1" "$LOG"
  fi
  rm -f ~/.cache/paddle_tpu/probe.json
  if timeout 90 python -c "import jax; assert jax.devices()" 2>/dev/null; then
    before=$(tpu_rows)
    echo "=== tunnel UP at $(date -u) — running live session (tpu_rows=$before)" >> "$LOG"
    # 3 h ceiling: the session's per-step timeouts sum past 2 h in the
    # worst case, and its steps are ordered most-important-first, so a
    # kill only ever costs the tail (sweeps/profiles)
    timeout 10800 python tools/live_tpu_session.py >> "$LOG" 2>&1
    rc=$?
    after=$(tpu_rows)
    echo "=== session done at $(date -u) rc=$rc tpu_rows $before -> $after" >> "$LOG"
    # durability: commit whatever the session captured so a container
    # restart can't lose the evidence
    # commit when TPU rows landed, tracked capture files changed, or a
    # fresh (untracked) artifact like XPLANE_SUMMARY.md appeared
    new_untracked=$(git ls-files --others --exclude-standard -- \
      XPLANE_SUMMARY.md OPBENCH_r*.jsonl 2>/dev/null | head -1)
    if [ "$after" -gt "$before" ] \
        || ! git diff --quiet -- BENCH_CAPTURES.jsonl OPBENCH_r*.jsonl 2>/dev/null \
        || [ -n "$new_untracked" ]; then
      # add per file AND commit with an explicit pathspec: the
      # unattended commit must never sweep up unrelated staged work
      capture_files=""
      for f in BENCH_CAPTURES.jsonl OPBENCH_r*.jsonl XPLANE_SUMMARY.md; do
        [ -f "$f" ] && { git add "$f" >> "$LOG" 2>&1; capture_files="$capture_files $f"; }
      done
      if [ -n "$capture_files" ]; then
        # shellcheck disable=SC2086
        git commit -m "Live TPU capture session: bench + op-bench rows" \
          -- $capture_files >> "$LOG" 2>&1 || true
      fi
    fi
    if [ "$after" -gt "$before" ]; then
      sleep 7200   # real captures landed — no need to re-burn the window
    else
      sleep 600    # session ran but nothing landed (flap?) — keep probing
    fi
  else
    echo "down $(date -u)" >> "$LOG"
    sleep 600
  fi
done
