#!/usr/bin/env python
"""Span-tree viewer over step-trace JSONL: render the distributed
traces the observability plane emits as ``kind="span"`` records
(schema v3 — observability/tracing.py).

Usage::

    python tools/trace_view.py trace.jsonl                 # trace index
    python tools/trace_view.py trace.jsonl --slowest 5     # slowest roots
    python tools/trace_view.py trace.jsonl --trace <hexid> # one tree

The tree view shows every span of the trace with parent indentation,
monotonic offsets, durations, typed status, events (e.g. a decode
preemption), and the **critical path** — the chain of child spans that
ends latest at every level, i.e. where the time actually went.
Per-tick decode spans reference their member requests by trace id
(``attrs.requests``); the tree view folds ticks that reference the
requested trace in.

Refuses unknown ``schema`` versions like tools/perf_report.py (history
in MIGRATION.md). Exit codes: 0 ok, 1 empty/unreadable/not-found,
2 unknown schema.
"""
from __future__ import annotations

import argparse
import os
import sys
from typing import Dict, List, Optional, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from paddle_tpu.observability.step_trace import (  # noqa: E402
    UnknownTraceSchema, read_trace_records,
)


class TraceViewError(Exception):
    """Typed failure: unreadable trace or unknown schema version."""


def load_spans(path: str) -> List[dict]:
    """``kind="span"`` records from one step-trace JSONL file, through
    the shared schema-gated loader (``step_trace.read_trace_records``).
    Raises TraceViewError on an unknown schema version — misparsing a
    future format would silently draw wrong trees."""
    try:
        records = read_trace_records(path, reader="tools/trace_view.py")
    except UnknownTraceSchema as e:
        raise TraceViewError(str(e))
    except OSError as e:
        raise TraceViewError(f"cannot read trace {path!r}: {e}")
    return [rec for rec in records if rec.get("kind") == "span"]


def group_traces(spans: List[dict]) -> Dict[str, List[dict]]:
    """{trace_id: spans} — ticks/batch spans that only REFERENCE a
    trace (attrs.requests) are folded into every trace they name."""
    out: Dict[str, List[dict]] = {}
    for s in spans:
        tid = s.get("trace")
        if tid:
            out.setdefault(tid, []).append(s)
        for ref in (s.get("attrs", {}) or {}).get("requests", ()) or ():
            if ref and ref != tid:
                out.setdefault(ref, []).append(s)
    return out


def _roots(spans: List[dict], trace_id: str) -> List[dict]:
    ids = {s["span"] for s in spans if s.get("trace") == trace_id}
    return [s for s in spans
            if s.get("trace") == trace_id
            and (not s.get("parent") or s["parent"] not in ids)]


def _children_index(spans: List[dict]) -> Dict[str, List[dict]]:
    idx: Dict[str, List[dict]] = {}
    for s in spans:
        if s.get("parent"):
            idx.setdefault(s["parent"], []).append(s)
    for kids in idx.values():
        kids.sort(key=lambda s: s.get("t0", 0.0))
    return idx


def critical_path(root: dict,
                  children: Dict[str, List[dict]]) -> List[dict]:
    """Chain from the root through, at each level, the child that ENDS
    latest — the spans that actually bound the root's duration."""
    path = [root]
    node = root
    seen = {root["span"]}
    while True:
        kids = [k for k in children.get(node["span"], ())
                if k["span"] not in seen]
        if not kids:
            return path
        node = max(kids, key=lambda s: s.get("t0", 0.0)
                   + s.get("dur_ms", 0.0) / 1e3)
        seen.add(node["span"])
        path.append(node)


def _fmt_span(s: dict, t_base: float, depth: int,
              referenced: bool = False) -> str:
    off_ms = (s.get("t0", t_base) - t_base) * 1e3
    status = s.get("status", "?")
    mark = "~" if referenced else ("!" if status != "ok" else " ")
    line = (f"{mark} {'  ' * depth}{s.get('name', '?'):<{28 - 2 * min(depth, 8)}}"
            f"+{off_ms:>9.3f}ms  {s.get('dur_ms', 0.0):>9.3f}ms"
            f"  {status}")
    evs = s.get("events") or []
    for ev in evs:
        line += (f"\n  {'  ' * depth}  * {ev.get('name', '?')} "
                 f"@+{ev.get('t_ms', 0.0):.3f}ms "
                 + " ".join(f"{k}={v}" for k, v in sorted(ev.items())
                            if k not in ("name", "t_ms")))
    return line


def render_trace(trace_id: str, spans: List[dict]) -> str:
    own = [s for s in spans if s.get("trace") == trace_id]
    refs = [s for s in spans if s.get("trace") != trace_id]
    if not own and not refs:
        raise TraceViewError(f"trace {trace_id!r} not found")
    lines = [f"== trace {trace_id} =="]
    t_base = min(s.get("t0", 0.0) for s in own + refs)
    children = _children_index(own)
    printed = set()

    def walk(s: dict, depth: int):
        lines.append(_fmt_span(s, t_base, depth))
        printed.add(s["span"])
        for kid in children.get(s["span"], ()):
            walk(kid, depth + 1)

    roots = _roots(own, trace_id)
    for root in sorted(roots, key=lambda s: s.get("t0", 0.0)):
        walk(root, 0)
    # spans of this trace whose parent never landed in the file (e.g.
    # a remote caller's span on the other side of the wire)
    for s in sorted(own, key=lambda x: x.get("t0", 0.0)):
        if s["span"] not in printed:
            lines.append(_fmt_span(s, t_base, 1))
    if refs:
        lines.append("-- referencing spans (batched ticks naming this "
                     "trace) --")
        for s in sorted(refs, key=lambda x: x.get("t0", 0.0)):
            lines.append(_fmt_span(s, t_base, 1, referenced=True))
    if roots:
        main = max(roots, key=lambda s: s.get("dur_ms", 0.0))
        path = critical_path(main, children)
        lines.append("-- critical path --")
        total = main.get("dur_ms", 0.0) or 1.0
        for s in path:
            pct = 100.0 * s.get("dur_ms", 0.0) / total
            lines.append(f"  {s.get('name', '?'):<28}"
                         f"{s.get('dur_ms', 0.0):>9.3f}ms  {pct:>5.1f}%"
                         f"  {s.get('status', '?')}")
    return "\n".join(lines) + "\n"


def _is_batch_span(s: dict) -> bool:
    """Batch-level spans (decode ticks, serve dispatches) carry the
    member request trace ids as ``attrs.requests`` — each one is its
    own fresh trace by construction."""
    return isinstance((s.get("attrs") or {}).get("requests"), list)


def _trace_rows(traces: Dict[str, List[dict]]
                ) -> Tuple[List[Tuple[str, dict, int]], int]:
    """(rows, batch_only_count): one (trace_id, root span, span count)
    row per REQUEST trace. Traces whose every span is a batch-level
    tick/dispatch are counted, not listed — under load there is one
    tick per compiled step and they would drown the request index
    (they still render inside the traces they reference)."""
    rows = []
    batch_only = 0
    for tid, spans in traces.items():
        own = [s for s in spans if s.get("trace") == tid]
        if not own:
            continue
        if all(_is_batch_span(s) for s in own):
            batch_only += 1
            continue
        roots = _roots(own, tid)
        root = max(roots or own, key=lambda s: s.get("dur_ms", 0.0))
        rows.append((tid, root, len(own)))
    return rows, batch_only


def render_index(traces: Dict[str, List[dict]],
                 slowest: Optional[int] = None) -> str:
    rows, batch_only = _trace_rows(traces)
    rows.sort(key=lambda r: r[1].get("dur_ms", 0.0), reverse=True)
    title = (f"== slowest {slowest} traces ==" if slowest
             else f"== {len(rows)} traces ==")
    if slowest:
        rows = rows[:slowest]
    lines = [title,
             f"{'trace':<18}{'root':<22}{'dur_ms':>10}{'spans':>7}"
             f"  status"]
    for tid, root, n in rows:
        lines.append(f"{tid:<18}{root.get('name', '?'):<22}"
                     f"{root.get('dur_ms', 0.0):>10.3f}{n:>7}"
                     f"  {root.get('status', '?')}")
    if batch_only:
        lines.append(f"({batch_only} batch-level tick/dispatch spans "
                     "not listed; they render inside the traces they "
                     "reference)")
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="span-tree viewer over step-trace JSONL "
                    "(kind=span records)")
    ap.add_argument("trace_file", help="step-trace JSONL file")
    ap.add_argument("--trace", default=None,
                    help="render one trace id's span tree")
    ap.add_argument("--slowest", type=int, default=None, metavar="N",
                    help="list the N slowest traces by root duration")
    args = ap.parse_args(argv)
    try:
        spans = load_spans(args.trace_file)
        if not spans:
            print(f"no span records in {args.trace_file} (enable "
                  "PADDLE_STEP_TRACE and run traced work)",
                  file=sys.stderr)
            return 1
        traces = group_traces(spans)
        if args.trace:
            sys.stdout.write(render_trace(args.trace,
                                          traces.get(args.trace, [])))
        else:
            sys.stdout.write(render_index(traces,
                                          slowest=args.slowest))
    except TraceViewError as e:
        print(f"trace_view: {e}", file=sys.stderr)
        return 2 if "unknown step-trace schema" in str(e) else 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
