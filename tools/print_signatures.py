"""Public-API signature dump (reference tools/print_signatures.py, which
feeds paddle/fluid/API.spec — the frozen public API that CI diffs so
interface changes need explicit approval).

Usage:
    python tools/print_signatures.py > API.spec

tests/test_api_spec.py regenerates the dump and compares it against the
committed API.spec; an intentional API change must refresh the file.
"""
from __future__ import annotations

import importlib
import inspect
import sys

MODULES = [
    "paddle_tpu",
    "paddle_tpu.amp",
    "paddle_tpu.autograd",
    "paddle_tpu.distributed",
    "paddle_tpu.distributed.elastic",
    "paddle_tpu.distributed.fleet",
    "paddle_tpu.fault",
    "paddle_tpu.hapi",
    "paddle_tpu.inference",
    "paddle_tpu.inference.decode",
    "paddle_tpu.io",
    "paddle_tpu.jit",
    "paddle_tpu.metric",
    "paddle_tpu.nn",
    "paddle_tpu.nn.functional",
    "paddle_tpu.nn.initializer",
    "paddle_tpu.observability",
    "paddle_tpu.observability.device_peaks",
    "paddle_tpu.observability.federation",
    "paddle_tpu.observability.metrics",
    "paddle_tpu.observability.slo",
    "paddle_tpu.observability.tracing",
    "paddle_tpu.ops",
    "paddle_tpu.optimizer",
    "paddle_tpu.optimizer.lr",
    "paddle_tpu.parallel",
    "paddle_tpu.parallel.collectives",
    "paddle_tpu.profiler",
    "paddle_tpu.ps",
    "paddle_tpu.ps.codec",
    "paddle_tpu.ps.replication",
    "paddle_tpu.quantization",
    "paddle_tpu.regularizer",
    "paddle_tpu.serving",
    "paddle_tpu.static",
    "paddle_tpu.static.cost_model",
    "paddle_tpu.static.stepplan",
    "paddle_tpu.static.substrate",
    "paddle_tpu.text",
    "paddle_tpu.utils",
    "paddle_tpu.vision",
]


def _sig(obj):
    try:
        return str(inspect.signature(obj))
    except (ValueError, TypeError):
        return "(...)"


def collect():
    lines = set()
    for mod_name in MODULES:
        mod = importlib.import_module(mod_name)
        for name in dir(mod):
            if name.startswith("_"):
                continue
            obj = getattr(mod, name)
            qual = f"{mod_name}.{name}"
            if inspect.ismodule(obj):
                continue
            if inspect.isclass(obj):
                if not obj.__module__.startswith("paddle_tpu"):
                    continue
                lines.add(f"{qual}.__init__ {_sig(obj.__init__)}")
                continue
            if callable(obj):
                owner = getattr(obj, "__module__", "") or ""
                if not owner.startswith("paddle_tpu"):
                    continue
                lines.add(f"{qual} {_sig(obj)}")
    return sorted(lines)


if __name__ == "__main__":
    sys.stdout.write("\n".join(collect()) + "\n")
