#!/usr/bin/env python
"""Performance report over step-trace JSONL and/or a ``/metrics``
scrape: MFU trend, phase breakdown, top-K ops by flops/bytes, compute-
vs bandwidth-bound roofline buckets, and a before/after regression
delta — the reading side of the graph-derived cost model
(paddle_tpu/static/cost_model.py + the executor's live gauges).

Usage::

    python tools/perf_report.py trace.jsonl [--top 8]
    python tools/perf_report.py --compare before.jsonl after.jsonl
    python tools/perf_report.py --metrics 127.0.0.1:8321
    python tools/perf_report.py --metrics scrape.txt   # saved scrape

Traces come from ``PADDLE_STEP_TRACE=<file-or-dir>`` (or
``enable_step_trace``): per-step records carry measured phases plus the
cost-model gauges (step_model_flops/step_hbm_bytes/step_comm_bytes/
mfu/arith_intensity), and one ``kind="cost"`` record per compiled
executable carries the per-op breakdown this report's top-K/roofline
sections read. Records are schema-versioned (``"schema"``, see
MIGRATION.md): unknown versions fail loudly here instead of misparsing.

Exit codes: 0 ok, 1 empty/unreadable input, 2 unknown schema.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from paddle_tpu.observability.step_trace import (  # noqa: E402
    UnknownTraceSchema, read_trace_records,
)


class PerfReportError(Exception):
    """Typed failure: unreadable trace or unknown schema version."""


# ---------------------------------------------------------------------------
# loading
# ---------------------------------------------------------------------------
def load_trace(path: str) -> Tuple[List[dict], List[dict]]:
    """Parse one step-trace JSONL file into (step records, cost
    records) through the shared schema-gated loader
    (``step_trace.read_trace_records``). Raises PerfReportError on an
    unknown ``schema`` version — a reader silently misparsing a future
    format is how perf regressions hide."""
    try:
        records = read_trace_records(path, reader="tools/perf_report.py")
    except UnknownTraceSchema as e:
        raise PerfReportError(str(e))
    except OSError as e:
        raise PerfReportError(f"cannot read trace {path!r}: {e}")
    steps: List[dict] = []
    costs: List[dict] = []
    for rec in records:
        if rec.get("kind") == "cost":
            costs.append(rec)
        elif rec.get("phases", {}).get("dispatch") is not None:
            steps.append(rec)
    return steps, costs


# ---------------------------------------------------------------------------
# formatting
# ---------------------------------------------------------------------------
def _fmt_count(v) -> str:
    """Engineering notation with 2 decimals (golden-stable)."""
    v = float(v)
    for div, suf in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "K")):
        if abs(v) >= div:
            return f"{v / div:.2f}{suf}"
    return f"{v:.0f}"


def _mean(vals) -> float:
    vals = list(vals)
    return sum(vals) / len(vals) if vals else 0.0


def _roofline_bound(ai: float, balance: Optional[float]) -> str:
    if balance is None:
        return "?"
    return "compute" if ai >= balance else "bandwidth"


# ---------------------------------------------------------------------------
# report
# ---------------------------------------------------------------------------
def render_report(steps: List[dict], costs: List[dict],
                  top: int = 8) -> str:
    lines: List[str] = []
    n = len(steps)
    lines.append("== step summary ==")
    if not n:
        lines.append("no step records (phases.dispatch missing on "
                     "every row)")
    else:
        durs = [s.get("dur_ms", 0.0) for s in steps]
        lines.append(f"steps {n}   total {sum(durs):.1f} ms   "
                     f"mean {_mean(durs):.2f} ms/step")
        mean_dur = _mean(durs) or 1.0
        for phase in ("feed", "dispatch", "fetch"):
            ms = _mean(s.get("phases", {}).get(phase, 0.0)
                       for s in steps)
            lines.append(f"  phase {phase:<9}{ms:>10.2f} ms  "
                         f"{100.0 * ms / mean_dur:>5.1f}%")
        hits = sum(1 for s in steps if s.get("cache_hit"))
        lines.append(f"  cache hits {hits}/{n}")

    lines.append("")
    lines.append("== mfu trend ==")
    # mfu=0 rows are published when the peak is unknown or a step did
    # no model flops — they carry no utilization signal, so an all-zero
    # trace gets the guidance message, not a flat 0.0000 trend
    mfu_steps = [s for s in steps if s.get("mfu")]
    if not mfu_steps:
        lines.append("no nonzero mfu samples — device peak unknown "
                     "(run on a known TPU or set PADDLE_PEAK_FLOPS), "
                     "or every step was matmul-free")
    else:
        nb = min(8, len(mfu_steps))
        per = -(-len(mfu_steps) // nb)  # ceil
        lines.append(f"{'steps':<14}{'mean_mfu':>10}{'mean_ms':>10}"
                     f"{'model_flops':>13}")
        for b in range(0, len(mfu_steps), per):
            chunk = mfu_steps[b:b + per]
            label = f"{chunk[0]['step']}..{chunk[-1]['step']}"
            lines.append(
                f"{label:<14}"
                f"{_mean(c['mfu'] for c in chunk):>10.4f}"
                f"{_mean(c.get('dur_ms', 0.0) for c in chunk):>10.2f}"
                f"{_fmt_count(_mean(c.get('step_model_flops', 0) for c in chunk)):>13}")

    lines.append("")
    lines.append("== cost model (per compiled step) ==")
    if not costs:
        lines.append("no cost records in trace (pre-cost-model trace, "
                     "or the program could not be costed)")
        return "\n".join(lines) + "\n"
    cost = costs[-1]  # the latest compiled executable's breakdown
    balance = None
    peak_fl = cost.get("peak_flops")
    peak_bw = cost.get("peak_hbm_bytes_per_s")
    if peak_fl and peak_bw:
        balance = peak_fl / peak_bw
    lines.append(
        f"model_flops {_fmt_count(cost.get('model_flops', 0))}   "
        f"hbm_bytes {_fmt_count(cost.get('hbm_bytes', 0))}   "
        f"comm_bytes {_fmt_count(cost.get('comm_bytes', 0))}   "
        f"arith_intensity {cost.get('arith_intensity', 0.0)}")
    lines.append(
        f"batch {cost.get('batch', 1)}   gm_k {cost.get('gm_k', 1)}   "
        f"pp_stages {cost.get('pp_stages', 1)}   "
        f"n_shards {cost.get('n_shards', 1)}   "
        f"device {cost.get('device_kind', 'unknown')}")
    if balance is not None:
        step_bound = _roofline_bound(
            float(cost.get("arith_intensity", 0.0)), balance)
        lines.append(f"machine balance {balance:.1f} flops/byte -> "
                     f"step is {step_bound}-bound")
    # kernel MFU push (ISSUE 19): the two places step time hides from
    # the matmul roofline — optimizer-region HBM traffic (now one fused
    # Pallas pass per ZeRO chunk instead of 5-8 elementwise ops) and
    # the MoE expert exchange (explicit all_to_all, charged into
    # comm_bytes by the cost model)
    moe_b = int(cost.get("moe_a2a_bytes", 0) or 0)
    if moe_b:
        comm_b = int(cost.get("comm_bytes", 0) or 1)
        lines.append("")
        lines.append("-- kernel MFU push --")
        lines.append(
            f"moe_a2a_bytes {_fmt_count(moe_b)} "
            f"({100.0 * moe_b / comm_b:.1f}% of comm_bytes) — the "
            f"explicit expert-parallel dispatch/combine exchange")
        lines.append(
            "fused optimizer: dispatch counters ride /metrics "
            "(fused_opt.pallas / fused_opt.xla) and "
            "`tools/dump_passes.py --fused-opt`")
    for field, title in (("top_flops", "top ops by model flops"),
                         ("top_bytes", "top ops by hbm bytes")):
        rows = cost.get(field) or []
        if not rows:
            continue
        lines.append("")
        lines.append(f"-- {title} --")
        lines.append(f"{'op':<26}{'out':<26}{'flops':>9}{'bytes':>9}"
                     f"{'AI':>8}  bound")
        for o in rows[:top]:
            ai = float(o.get("arith_intensity", 0.0))
            lines.append(
                f"{o.get('type', '?'):<26}"
                f"{str(o.get('out', ''))[:24]:<26}"
                f"{_fmt_count(o.get('flops', 0)):>9}"
                f"{_fmt_count(o.get('hbm_bytes', 0)):>9}"
                f"{ai:>8.2f}  {_roofline_bound(ai, balance)}")
    # roofline buckets over the per-op tables (dedup by op index)
    seen: Dict[int, dict] = {}
    for o in (cost.get("top_flops") or []) + (cost.get("top_bytes")
                                              or []):
        seen[o.get("index", id(o))] = o
    if balance is not None and seen:
        comp = [o for o in seen.values()
                if float(o.get("arith_intensity", 0.0)) >= balance]
        band = [o for o in seen.values()
                if float(o.get("arith_intensity", 0.0)) < balance]
        cf = sum(o.get("flops", 0) for o in comp)
        bf = sum(o.get("flops", 0) for o in band)
        tot = (cf + bf) or 1
        lines.append("")
        lines.append("-- roofline buckets (costed ops) --")
        lines.append(f"compute-bound   {len(comp):>4} ops  "
                     f"{100.0 * cf / tot:>5.1f}% of flops")
        lines.append(f"bandwidth-bound {len(band):>4} ops  "
                     f"{100.0 * bf / tot:>5.1f}% of flops")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# compare
# ---------------------------------------------------------------------------
def _trace_metrics(steps: List[dict], costs: List[dict]
                   ) -> Dict[str, float]:
    out = {
        "mean_step_ms": round(_mean(s.get("dur_ms", 0.0)
                                    for s in steps), 3),
        "mean_dispatch_ms": round(_mean(
            s.get("phases", {}).get("dispatch", 0.0) for s in steps), 3),
        # zeros mean "no utilization signal" (unknown peak /
        # matmul-free), not a measured 0% — exclude them like the trend
        "mean_mfu": round(_mean(s["mfu"] for s in steps
                                if s.get("mfu")), 4),
    }
    src = costs[-1] if costs else {}
    for key in ("model_flops", "hbm_bytes", "comm_bytes"):
        out[key] = src.get(key, 0)
    return out


def render_compare(before: Tuple[List[dict], List[dict]],
                   after: Tuple[List[dict], List[dict]]) -> str:
    b = _trace_metrics(*before)
    a = _trace_metrics(*after)
    lines = ["== regression delta (before -> after) ==",
             f"{'metric':<20}{'before':>14}{'after':>14}{'delta':>10}"]
    for key in ("mean_step_ms", "mean_dispatch_ms", "mean_mfu",
                "model_flops", "hbm_bytes", "comm_bytes"):
        bv, av = b.get(key, 0), a.get(key, 0)
        if key.startswith("mean_"):
            bs, as_ = f"{bv:.4g}", f"{av:.4g}"
        else:
            bs, as_ = _fmt_count(bv), _fmt_count(av)
        delta = (f"{100.0 * (av - bv) / bv:+.1f}%" if bv else "n/a")
        lines.append(f"{key:<20}{bs:>14}{as_:>14}{delta:>10}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# decode overlap view (async tick pipelining + host KV tier)
# ---------------------------------------------------------------------------
def _phase_sum(samples: Dict[str, float], phase: str) -> float:
    """Cumulative ms for one decode tick phase, summed across any
    instance labels a federated scrape injected."""
    total = 0.0
    for key, v in samples.items():
        if key.startswith("decode_tick_phase_ms_sum") \
                and f'phase="{phase}"' in key:
            total += v
    return total


def decode_overlap_metrics(samples: Dict[str, float]
                           ) -> Dict[str, float]:
    """The decode-overlap scorecard from one parsed scrape: the tick
    wall split by phase (dispatch / host / fetch — fetch is the time
    the host sat blocked on device tokens, the thing async pipelining
    exists to hide), the engine's cumulative ``decode_overlap_frac``
    gauge, and the host-tier counters."""
    out: Dict[str, float] = {}
    for ph in ("dispatch", "host", "fetch"):
        out[f"tick_{ph}_ms"] = round(_phase_sum(samples, ph), 3)
    total = sum(out.values())
    out["tick_total_ms"] = round(total, 3)
    if total:
        out["overlap_frac"] = round(
            (total - out["tick_fetch_ms"]) / total, 4)
    for g in ("decode_overlap_frac", "kv_pages_host",
              "kv_offload_bytes", "kv_page_restores",
              "kv_sessions_parked", "kv_sessions_resumed",
              "kv_restore_fallbacks"):
        if g in samples:
            out[g] = samples[g]
    return out


def render_decode_overlap(samples: Dict[str, float]) -> str:
    m = decode_overlap_metrics(samples)
    if not m.get("tick_total_ms") and "decode_overlap_frac" not in m:
        return ""   # scrape has no decode tick phase data
    lines = ["-- decode overlap --"]
    for key in ("tick_dispatch_ms", "tick_host_ms", "tick_fetch_ms",
                "tick_total_ms", "overlap_frac",
                "decode_overlap_frac", "kv_pages_host",
                "kv_offload_bytes", "kv_page_restores",
                "kv_sessions_parked", "kv_sessions_resumed",
                "kv_restore_fallbacks"):
        if key in m:
            lines.append(f"{key:<22}{m[key]:>12g}")
    return "\n".join(lines) + "\n"


def render_metrics_compare(before: Dict[str, float],
                           after: Dict[str, float]) -> str:
    """``--compare`` over two SAVED SCRAPES instead of step traces:
    the decode-overlap deltas (sync baseline vs async run is the
    intended pairing — fetch wall should collapse and overlap_frac
    rise while token counts match)."""
    b, a = decode_overlap_metrics(before), decode_overlap_metrics(after)
    lines = ["== decode overlap delta (before -> after) ==",
             f"{'metric':<22}{'before':>14}{'after':>14}{'delta':>10}"]
    keys = [k for k in (
        "tick_dispatch_ms", "tick_host_ms", "tick_fetch_ms",
        "tick_total_ms", "overlap_frac", "decode_overlap_frac",
        "kv_pages_host", "kv_offload_bytes", "kv_page_restores",
        "kv_sessions_parked", "kv_sessions_resumed",
        "kv_restore_fallbacks") if k in b or k in a]
    for key in keys:
        bv, av = b.get(key, 0.0), a.get(key, 0.0)
        delta = (f"{100.0 * (av - bv) / bv:+.1f}%" if bv else "n/a")
        lines.append(f"{key:<22}{bv:>14g}{av:>14g}{delta:>10}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# /metrics scrape view
# ---------------------------------------------------------------------------
def render_metrics(samples: Dict[str, float]) -> str:
    """Utilization view of one parsed ``/metrics`` scrape: the cost
    gauges plus bucket-derived phase percentiles, and — when the
    scrape carries the decode-serving plane — the token-economics
    section (speculation accept rate, KV page occupancy/sharing,
    prefix-cache hits)."""
    from tools.metrics_watch import (format_percentile_table,
                                     histogram_percentile_deltas)

    lines = ["== /metrics utilization =="]
    for g in ("mfu", "arith_intensity", "step_model_flops",
              "step_hbm_bytes", "step_comm_bytes", "executor_steps"):
        if g in samples:
            v = samples[g]
            fmt = _fmt_count(v) if g.startswith("step_") else f"{v:g}"
            lines.append(f"{g:<20}{fmt:>14}")
    decode = [(g, samples[g]) for g in (
        "decode_requests", "decode_tokens", "decode_prefills",
        "decode_steps", "decode_batch_fill_pct", "spec_proposed",
        "spec_accepted", "spec_accept_rate", "kv_pages_in_use",
        "kv_pages_shared", "kv_pages_cached", "kv_prefix_hits",
        "kv_page_evictions", "kv_cow_copies") if g in samples]
    if decode:
        lines.append("")
        lines.append("-- decode token economics --")
        for g, v in decode:
            lines.append(f"{g:<22}{v:>12g}")
    overlap = render_decode_overlap(samples)
    if overlap:
        lines.append("")
        lines.append(overlap.rstrip("\n"))
    pct = histogram_percentile_deltas(samples, None)
    phase = {k: v for k, v in pct.items()
             if k.startswith("executor_step_phase_ms")}
    if phase:
        lines.append("")
        lines.append(format_percentile_table(
            phase, title="executor phase percentiles (cumulative)"))
    return "\n".join(lines) + "\n"


def _is_metrics_file(path: str) -> bool:
    """True when ``path`` reads as Prometheus text exposition rather
    than step-trace JSONL (whose every line is a JSON object)."""
    if not os.path.exists(path):
        return False
    try:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    json.loads(line)
                    return False
                except ValueError:
                    return True
    except OSError:
        return False
    return False


def _load_metrics(target: str) -> Dict[str, float]:
    from paddle_tpu.observability.metrics import parse_prometheus_text
    from tools.metrics_watch import scrape

    if os.path.exists(target):
        with open(target) as fh:
            return parse_prometheus_text(fh.read())
    return scrape(target)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="MFU / roofline report over step-trace JSONL "
                    "and/or a /metrics scrape")
    ap.add_argument("trace", nargs="?", help="step-trace JSONL file")
    ap.add_argument("--top", type=int, default=8,
                    help="rows per top-ops table")
    ap.add_argument("--compare", nargs=2,
                    metavar=("BEFORE", "AFTER"),
                    help="two traces; print the regression delta")
    ap.add_argument("--metrics", default=None,
                    help="host:port to scrape, or a saved scrape file")
    args = ap.parse_args(argv)
    try:
        wrote = False
        if args.compare:
            if all(_is_metrics_file(p) for p in args.compare):
                # two saved /metrics scrapes: decode-overlap deltas
                # (the async-vs-sync pairing)
                b, a = (_load_metrics(p) for p in args.compare)
                sys.stdout.write(render_metrics_compare(b, a))
            else:
                before, after = (load_trace(p) for p in args.compare)
                sys.stdout.write(render_compare(before, after))
            wrote = True
        elif args.trace:
            steps, costs = load_trace(args.trace)
            if not steps and not costs:
                print(f"no usable records in {args.trace}",
                      file=sys.stderr)
                return 1
            sys.stdout.write(render_report(steps, costs, top=args.top))
            wrote = True
        if args.metrics:
            try:
                samples = _load_metrics(args.metrics)
            except (OSError, RuntimeError, ValueError) as e:
                # ValueError: a typo'd filename with no colon reaches
                # scrape()'s int(port)
                print(f"perf_report: cannot scrape "
                      f"{args.metrics!r}: {e}", file=sys.stderr)
                return 1
            sys.stdout.write(render_metrics(samples))
            wrote = True
        if not wrote:
            ap.print_usage(sys.stderr)
            return 1
    except PerfReportError as e:
        print(f"perf_report: {e}", file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
