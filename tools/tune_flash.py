"""Flash-attention block-size sweep on a live TPU.

Times the Pallas dropout kernel (the BERT training path: mask=None,
dropout>0) across (block_q, block_kv) candidates at the bench shapes,
plus the XLA reference. Prints one JSON line per timing. Use after
kernel changes to re-pick the default blocks — the defaults encode the
winner at the bench configs (see flash_attention.py's dispatch-floor
comment for measured context).

Usage: python tools/tune_flash.py [--seq 512] [--batch 32] [--steps 30]
"""
import argparse
import functools
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddle_tpu.framework.bringup import TPU_PLATFORMS, ensure_backend  # noqa: E402

import jax  # noqa: E402  (importing jax does not init a backend)
import jax.numpy as jnp  # noqa: E402


def _time(fn, args, steps):
    # shared methodology (tools/_timing.py): host-fetch completion
    # forcing + per-iteration value-distinct inputs — the remote plugin
    # neither honors block_until_ready nor reliably re-executes
    # value-identical dispatches. q is the varied argument (the seed, if
    # present, is a constant int and immune to perturbation).
    from tools._timing import timeit

    return timeit(fn, *args, iters=steps, vary_arg=0)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--heads", type=int, default=12)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--dropout", type=float, default=0.1)
    ap.add_argument("--grad", action="store_true",
                    help="time fwd+bwd (the training path) instead of "
                         "forward only — bwd is ~2/3 of attention time "
                         "and prefers LARGER q blocks (measured: "
                         "bq=512,bkv=512 beats 256,512 by 7% combined "
                         "at seq 512 though it loses the fwd-only race)")
    ns = ap.parse_args()

    backend = ensure_backend()
    if backend not in TPU_PLATFORMS:
        print(json.dumps({"error": f"needs a TPU backend, got {backend}"}))
        return
    import numpy as np

    from paddle_tpu.ops.pallas import flash_attention as fa

    rng = np.random.RandomState(0)
    shape = (ns.batch, ns.seq, ns.heads, ns.dim)
    q, k, v = (jnp.asarray(rng.randn(*shape), jnp.bfloat16)
               for _ in range(3))
    seed = jnp.zeros((1, 1), jnp.int32)

    base = {"seq": ns.seq, "batch": ns.batch, "heads": ns.heads,
            "dim": ns.dim, "mode": "fwd+bwd" if ns.grad else "fwd"}

    def wrap(fn):
        if not ns.grad:
            return fn
        return jax.jit(jax.grad(
            lambda *a: fn(*a).astype(jnp.float32).sum(), argnums=(0, 1, 2)))

    ms = _time(wrap(jax.jit(functools.partial(
        fa._xla_attention, mask=None, dropout_p=ns.dropout,
        is_causal=False, key_rng=jax.random.key(0)))), (q, k, v), ns.steps)
    print(json.dumps({**base, "kernel": "xla_dropout",
                      "ms": round(ms, 4)}), flush=True)
    cands = [(bq, bkv) for bq in (128, 256, 512) for bkv in (128, 256, 512)
             if ns.seq % bq == 0 and ns.seq % bkv == 0]
    for bq, bkv in cands:
        try:
            pallas = functools.partial(
                fa._flash_attention_pallas_dropout,
                dropout_p=ns.dropout, block_q=bq, block_kv=bkv)
            if ns.grad:
                ms = _time(wrap(lambda q, k, v: pallas(q, k, v, seed)),
                           (q, k, v), ns.steps)
            else:
                ms = _time(pallas, (q, k, v, seed), ns.steps)
        except Exception as e:
            print(json.dumps({**base, "kernel": "pallas_dropout",
                              "bq": bq, "bkv": bkv,
                              "error": f"{type(e).__name__}"}), flush=True)
            continue
        print(json.dumps({**base, "kernel": "pallas_dropout", "bq": bq,
                          "bkv": bkv, "ms": round(ms, 4)}), flush=True)


if __name__ == "__main__":
    main()
