"""Capture an XPlane/TensorBoard profile of one bench config's train
step on the live chip (jax.profiler), for offline bottleneck analysis —
the resnet config sits at ~20% MFU vs BERT's 41%, and only a hardware
trace can say where the time goes.

Usage: python tools/profile_step.py [--config resnet] [--out DIR]
"""
from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def summarize_trace(out_dir: str, config: str, row: dict,
                    summary_path: str, top_k: int = 25) -> bool:
    """Aggregate the chrome-trace events jax.profiler wrote under
    `out_dir` into a committed markdown table: total device time by op
    name, top offenders first — the offline 'where does the non-MXU
    time go' answer VERDICT r4 #2 asks for, without needing the
    tensorboard profile plugin in the image."""
    import glob
    import gzip
    import json
    from collections import defaultdict

    traces = sorted(glob.glob(
        os.path.join(out_dir, "**", "*.trace.json.gz"), recursive=True))
    if not traces:
        print(f"no .trace.json.gz under {out_dir}; summary skipped")
        return False
    with gzip.open(traces[-1], "rt") as f:
        data = json.load(f)
    events = data.get("traceEvents", [])
    pid_names = {e.get("pid"): e.get("args", {}).get("name", "")
                 for e in events if e.get("name") == "process_name"}
    device_pids = {p for p, n in pid_names.items()
                   if "TPU" in str(n) or "/device" in str(n).lower()}
    # a device pid carries several thread lines ("XLA Modules", "Steps",
    # "XLA Ops"); module/step spans equal the SUM of the op events below
    # them, so summing across tids double-counts — keep op-level only
    tid_names = {(e.get("pid"), e.get("tid")):
                 str(e.get("args", {}).get("name", ""))
                 for e in events if e.get("name") == "thread_name"}
    # explicit op-line match: a substring like "op" also hits
    # "TensorFlow Name Scope" (sc-op-e), whose hierarchical spans
    # already contain every op under them — double counting
    op_tids = {k for k, n in tid_names.items()
               if k[0] in device_pids and "xla ops" in n.lower()}

    per_tid = defaultdict(lambda: defaultdict(float))
    counts = defaultdict(int)
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in device_pids:
            continue
        key = (e.get("pid"), e.get("tid"))
        if op_tids and key not in op_tids:
            continue
        dur = float(e.get("dur", 0.0))   # microseconds
        name = str(e.get("name", "?"))
        # fold fusion instances: fusion.123 -> fusion; keep op kind
        base = name.split(".")[0] if name.split(".")[-1].isdigit() else name
        per_tid[key][base] += dur
        counts[key] += 1
    if not per_tid:
        print("trace had no device events; summary skipped")
        return False
    if op_tids:
        # merge the explicit op-level threads (one per core)
        agg = defaultdict(float)
        for t in per_tid.values():
            for k, v in t.items():
                agg[k] += v
    else:
        # no thread_name metadata: the op line has by far the most
        # events (module/step lines have a handful of giant spans)
        agg = per_tid[max(counts, key=counts.get)]
    total = sum(agg.values())
    rows = sorted(agg.items(), key=lambda kv: -kv[1])[:top_k]
    from tools._captures import git_sha

    with open(summary_path, "a") as f:
        f.write(f"\n## {config} @ {row.get('device_kind', '?')} "
                f"(sha {git_sha()}, {row.get('value')} {row.get('unit')}"
                f", mfu {row.get('mfu')})\n\n")
        f.write("| op | device ms | % of device time |\n|---|---|---|\n")
        for name, us in rows:
            f.write(f"| {name} | {us / 1e3:.2f} | "
                    f"{100.0 * us / total:.1f}% |\n")
        f.write(f"| TOTAL (all ops) | {total / 1e3:.2f} | 100% |\n")
    print(f"summary appended to {summary_path} "
          f"({len(rows)} rows, total {total / 1e3:.1f} ms device time)")
    return True


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="resnet")
    ap.add_argument("--out", default="/tmp/paddle_tpu_profile")
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--summary", default=None,
                    help="markdown file to append a device-time-by-op "
                         "table to (e.g. XPLANE_SUMMARY.md)")
    args = ap.parse_args()

    from paddle_tpu.framework.bringup import TPU_PLATFORMS, ensure_backend

    backend = ensure_backend()
    if backend not in TPU_PLATFORMS:
        print(f"backend {backend!r}: profiling a CPU run is not useful")
        return 1
    import jax

    import bench

    os.environ.setdefault("BENCH_STEPS", str(args.steps))
    os.makedirs(args.out, exist_ok=True)
    with jax.profiler.trace(args.out):
        row = bench.CONFIGS[args.config](False)
    bench.attach_mfu(row)
    print({k: row.get(k) for k in ("value", "unit", "dt", "steps", "mfu")})
    print(f"trace written under {args.out} (tensorboard --logdir {args.out})")
    if args.summary:
        summarize_trace(args.out, args.config, row, args.summary)
    return 0


if __name__ == "__main__":
    sys.exit(main())
