"""Capture an XPlane/TensorBoard profile of one bench config's train
step on the live chip (jax.profiler), for offline bottleneck analysis —
the resnet config sits at ~20% MFU vs BERT's 41%, and only a hardware
trace can say where the time goes.

Usage: python tools/profile_step.py [--config resnet] [--out DIR]
"""
from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="resnet")
    ap.add_argument("--out", default="/tmp/paddle_tpu_profile")
    ap.add_argument("--steps", type=int, default=5)
    args = ap.parse_args()

    from paddle_tpu.framework.bringup import TPU_PLATFORMS, ensure_backend

    backend = ensure_backend()
    if backend not in TPU_PLATFORMS:
        print(f"backend {backend!r}: profiling a CPU run is not useful")
        return 1
    import jax

    import bench

    os.environ.setdefault("BENCH_STEPS", str(args.steps))
    os.makedirs(args.out, exist_ok=True)
    with jax.profiler.trace(args.out):
        row = bench.CONFIGS[args.config](False)
    print({k: row.get(k) for k in ("value", "unit", "dt", "steps")})
    print(f"trace written under {args.out} (tensorboard --logdir {args.out})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
