"""Deterministic closed-loop load generator for the serving engine.

Closed loop: each worker submits a request, BLOCKS for its completion,
then submits the next — so offered load self-regulates to the engine's
service rate and the measurement is a throughput/latency probe, not a
queue-explosion test (open-loop overload is what the admission-control
tests cover). Request CONTENT is deterministic: request ``i`` always
carries the same rows (seeded by ``i``) and the same size from the
``sizes`` cycle, whatever thread runs it — so a bench row or a chaos
drill replays identically.

Library use (bench.py's serving probe)::

    from tools.load_gen import LoadGen
    summary = LoadGen(engine, total_requests=60, workers=4,
                      sizes=(1, 2, 3)).run()

CLI (against a saved inference blob)::

    python tools/load_gen.py --model-dir /path/to/blob \
        --requests 64 --workers 4 --sizes 1,2,3 [--deadline-s 5]

prints one JSON summary: requests/s, p50/p99 latency, and the
shed/deadline/degraded/failed outcome counts.

Decode workload mode (``DecodeLoadGen`` / ``--decode``): drives the
LLM decode engine with a DETERMINISTIC mixed-length workload —
request ``i`` cycles its prompt length and ``max_new_tokens`` through
the configured ``prompt_lens``/``output_lens`` tuples and draws its
token content from ``RandomState(i)`` — and reports the
autoregressive latency decomposition next to the closed-loop fields:
per-token client latency, TTFT (submit → first token) vs inter-token
percentiles, ``decode_tokens_per_sec``, the prefill-vs-decode token
split (``prefill_tokens[_per_sec]``), and the speculative-decoding
economics (``spec_proposed`` / ``spec_accepted`` /
``spec_accept_rate`` — zeros when ``--spec-k`` is 0). ``--kv-codec
int8`` drives the same workload over int8 KV pages.

Fleet mode (``FleetLoadGen`` / ``--fleet N``): N decode engines behind
one in-process ``FleetRouter``, sprayed with a zipf-distributed
session workload (a few hot sessions dominate — the shape that makes
session affinity and prefix caching earn their keep). Reports
``fleet_tokens_per_sec``, ``fleet_p99_ttft_ms``, the PER-ENGINE token
share (each engine's ``decode_tokens`` delta), the session spread, and
the router's dispatch/failover/affinity/shed counters.
"""
from __future__ import annotations

import itertools
import json
import os
import sys
import threading
import time
import zlib
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from paddle_tpu.observability import tracing


def _slowest_traces(rows: List[Tuple[float, Optional[str]]],
                    n: int = 5) -> List[dict]:
    """Top-N slowest requests as ``{"trace_id", "ms"}`` rows — the
    bridge from a bad client p99 to ``trace_view --trace <id>``."""
    ranked = sorted((r for r in rows if r[1]), key=lambda r: -r[0])
    return [{"trace_id": t, "ms": round(ms, 3)}
            for ms, t in ranked[:n]]


def default_feed_maker(predictor) -> Callable[[int, int], Dict[str, np.ndarray]]:
    """Feed factory from the predictor's declared feed specs: request
    ``i`` of ``size`` rows gets RandomState(i)-seeded values — floats
    standard-normal, ints in [0, 8)."""

    specs = predictor._feed_specs

    def make(size: int, i: int) -> Dict[str, np.ndarray]:
        rng = np.random.RandomState(i)
        feed = {}
        for name, (tail, dtype) in specs.items():
            shape = (size,) + tail
            if np.issubdtype(dtype, np.floating):
                feed[name] = rng.randn(*shape).astype(dtype)
            else:
                feed[name] = rng.randint(0, 8, shape).astype(dtype)
        return feed

    return make


class LoadGen:
    """Drive ``engine`` with ``total_requests`` requests from ``workers``
    closed-loop threads; sizes cycle deterministically per request index.
    ``run()`` returns the summary dict (and stores it as ``.summary``)."""

    def __init__(self, engine, total_requests: int = 64, workers: int = 4,
                 sizes: Sequence[int] = (1, 2, 3),
                 deadline_s: Optional[float] = None,
                 make_feed: Optional[Callable] = None,
                 timeout_s: float = 120.0):
        self.engine = engine
        self.total_requests = int(total_requests)
        self.workers = max(1, int(workers))
        self.sizes = tuple(int(s) for s in sizes)
        self.deadline_s = deadline_s
        self.make_feed = make_feed or default_feed_maker(engine.predictor)
        self.timeout_s = float(timeout_s)
        self.summary: Optional[dict] = None

    def run(self) -> dict:
        from paddle_tpu.inference.serving import (DeadlineExceeded,
                                                  EngineStopped,
                                                  Overloaded,
                                                  RequestFailed)

        counter = itertools.count()
        outcomes = {"ok": 0, "shed": 0, "deadline_expired": 0,
                    "failed": 0, "stopped": 0, "other_error": 0}
        lock = threading.Lock()

        def record(kind: str):
            with lock:
                outcomes[kind] += 1

        client_lat_ms = []
        traced: List[Tuple[float, Optional[str]]] = []

        def worker():
            while True:
                i = next(counter)
                if i >= self.total_requests:
                    return
                feed = self.make_feed(self.sizes[i % len(self.sizes)], i)
                t0 = time.perf_counter()
                try:
                    # client-side root span: the engine's serve.request
                    # span parents under it, so the trace id reported
                    # next to a bad client p99 names the WHOLE tree
                    with tracing.span("loadgen.request", parent=False,
                                      request_index=i) as sp:
                        self.engine.infer(feed,
                                          deadline_s=self.deadline_s,
                                          timeout=self.timeout_s)
                    dt_ms = (time.perf_counter() - t0) * 1e3
                    with lock:
                        client_lat_ms.append(dt_ms)
                        traced.append((dt_ms,
                                       format(sp.trace_id, "016x")))
                    record("ok")
                except Overloaded:
                    record("shed")
                except DeadlineExceeded:
                    record("deadline_expired")
                except RequestFailed:
                    record("failed")
                except EngineStopped:
                    record("stopped")
                    return
                except Exception:
                    record("other_error")

        threads = [threading.Thread(target=worker, daemon=True,
                                    name=f"loadgen-{w}")
                   for w in range(self.workers)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=self.timeout_s)
        dt = time.perf_counter() - t0
        completed = sum(outcomes.values())
        lat = self.engine.latency_stats()
        # engine-side truth: percentiles DERIVED FROM THE HISTOGRAM
        # BUCKETS the engine records per request — the latency record a
        # /metrics scraper sees, independent of this client's clocks
        eng = self.engine.engine_latency_stats()
        clat = np.asarray(client_lat_ms, np.float64)
        self.summary = {
            "requests": self.total_requests,
            "completed": completed,
            "wall_s": round(dt, 4),
            # throughput counts SERVED requests only: sheds/expiries are
            # rejected at CPU speed in a closed loop, so counting them
            # would report near the offered rate while the engine
            # actually serves a fraction of it
            "requests_per_sec":
                round(outcomes.get("ok", 0) / dt, 2) if dt else 0.0,
            "completed_per_sec":
                round(completed / dt, 2) if dt else 0.0,
            "workers": self.workers,
            "sizes": list(self.sizes),
            "p50_ms": lat["p50_ms"],
            "p99_ms": lat["p99_ms"],
            "mean_ms": lat["mean_ms"],
            # client-observed: wall time around infer() in THIS process
            # (submit -> result delivery, including handle wakeup)
            "client_p50_ms": (round(float(np.percentile(clat, 50)), 3)
                              if clat.size else 0.0),
            "client_p99_ms": (round(float(np.percentile(clat, 99)), 3)
                              if clat.size else 0.0),
            # engine-reported: bucket-derived, scrape-reproducible
            "engine_p50_ms": eng["e2e_p50_ms"],
            "engine_p99_ms": eng["e2e_p99_ms"],
            "queue_wait_p50_ms": eng["queue_wait_p50_ms"],
            "queue_wait_p99_ms": eng["queue_wait_p99_ms"],
            # the tail, NAMED: a bad client_p99 is one
            # `trace_view --trace <id>` away from its span tree
            "slowest_traces": _slowest_traces(traced),
            **outcomes,
        }
        return self.summary


class DecodeLoadGen:
    """Closed-loop decode workload: ``workers`` threads each submit a
    generation request, block for ALL its tokens, then submit the
    next. Mixed lengths are deterministic per request index: request
    ``i`` draws ``prompt_len`` from ``prompt_lens``, ``max_new_tokens``
    from ``output_lens`` (cycled), and its token ids from
    ``RandomState(i)`` — a bench row or drill replays identically.

    ``run()`` returns (and stores as ``.summary``) the decode metrics:
    ``decode_tokens_per_sec`` (generated tokens / wall), client-side
    TTFT and inter-token-latency percentiles (from the engine's
    per-token clock stamps), engine-side bucket-derived e2e/step
    percentiles, and the typed outcome counts.

    ``arrival_rate`` (requests/second) switches the gen OPEN-LOOP:
    request ``i`` is submitted no earlier than ``i / arrival_rate``
    seconds after the run starts — a deterministic arrival schedule,
    so queueing (and with a host KV tier, session parking) is driven
    by the OFFERED rate instead of adapting to service time the way
    closed-loop workers do. ``workers`` then caps in-flight requests:
    if all workers are blocked the schedule slips, which is exactly
    the saturation evidence an open-loop run exists to surface."""

    def __init__(self, engine, total_requests: int = 16, workers: int = 4,
                 prompt_lens: Sequence[int] = (4, 12, 24, 8),
                 output_lens: Sequence[int] = (4, 8, 16),
                 deadline_s: Optional[float] = None,
                 timeout_s: float = 300.0, keep_outputs: bool = False,
                 arrival_rate: Optional[float] = None):
        self.engine = engine
        self.total_requests = int(total_requests)
        self.workers = max(1, int(workers))
        self.prompt_lens = tuple(int(p) for p in prompt_lens)
        self.output_lens = tuple(int(o) for o in output_lens)
        self.deadline_s = deadline_s
        self.timeout_s = float(timeout_s)
        self.keep_outputs = bool(keep_outputs)
        self.arrival_rate = float(arrival_rate) if arrival_rate else None
        self.outputs: dict = {}   # request index -> generated tokens
        self.summary: Optional[dict] = None

    def _make_prompt(self, i: int) -> list:
        rng = np.random.RandomState(i)
        n = self.prompt_lens[i % len(self.prompt_lens)]
        vocab = self.engine.config.vocab_size
        return [int(t) for t in rng.randint(0, vocab, size=n)]

    def run(self) -> dict:
        from paddle_tpu.inference.serving import (DeadlineExceeded,
                                                  EngineStopped,
                                                  Overloaded,
                                                  RequestFailed)

        counter = itertools.count()
        outcomes = {"ok": 0, "shed": 0, "deadline_expired": 0,
                    "failed": 0, "stopped": 0, "other_error": 0}
        lock = threading.Lock()
        ttft_ms: list = []
        itl_ms: list = []
        tokens_out = [0]
        tokens_in = [0]
        traced: List[Tuple[float, Optional[str]]] = []

        def record(kind: str):
            with lock:
                outcomes[kind] += 1

        t_start = [0.0]

        def worker():
            while True:
                i = next(counter)
                if i >= self.total_requests:
                    return
                if self.arrival_rate:
                    # open loop: hold request i until its scheduled
                    # arrival — the schedule is a pure function of the
                    # index, so two runs offer identical load
                    delay = (t_start[0] + i / self.arrival_rate
                             - time.perf_counter())
                    if delay > 0:
                        time.sleep(delay)
                prompt = self._make_prompt(i)
                out_n = self.output_lens[i % len(self.output_lens)]
                t0 = time.perf_counter()
                try:
                    # client root span: the engine's decode.request
                    # parents under it — the trace id reported in
                    # slowest_traces names the full tree
                    with tracing.span("loadgen.decode", parent=False,
                                      request_index=i) as sp:
                        h = self.engine.submit(
                            prompt, max_new_tokens=out_n,
                            deadline_s=self.deadline_s)
                        toks = h.result(self.timeout_s)
                    st = h.stats()
                    dt_ms = (time.perf_counter() - t0) * 1e3
                    with lock:
                        if self.keep_outputs:
                            self.outputs[i] = list(toks)
                        tokens_out[0] += len(toks)
                        tokens_in[0] += len(prompt)
                        if "ttft_ms" in st:
                            ttft_ms.append(st["ttft_ms"])
                        times = st.get("token_times") or []
                        itl_ms.extend(
                            (b - a) * 1e3
                            for a, b in zip(times, times[1:]))
                        traced.append((dt_ms,
                                       format(sp.trace_id, "016x")))
                    record("ok")
                except Overloaded:
                    record("shed")
                except DeadlineExceeded:
                    record("deadline_expired")
                except RequestFailed:
                    record("failed")
                except EngineStopped:
                    record("stopped")
                    return
                except Exception:
                    record("other_error")

        threads = [threading.Thread(target=worker, daemon=True,
                                    name=f"decode-loadgen-{w}")
                   for w in range(self.workers)]
        t0 = time.perf_counter()
        t_start[0] = t0
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=self.timeout_s)
        dt = time.perf_counter() - t0

        def pct(arr, q):
            a = np.asarray(arr, np.float64)
            return round(float(np.percentile(a, q)), 3) if a.size else 0.0

        eng = self.engine.engine_latency_stats()
        try:
            ectr = self.engine.counters
        except Exception:
            ectr = {}
        self.summary = {
            "requests": self.total_requests,
            "completed": sum(outcomes.values()),
            "wall_s": round(dt, 4),
            "decode_tokens": tokens_out[0],
            # generated tokens per wall second across the whole
            # closed-loop run — the headline the padded-bucket
            # baseline is compared against
            "decode_tokens_per_sec":
                round(tokens_out[0] / dt, 2) if dt else 0.0,
            # prefill vs decode split: prompt tokens ingested (batched
            # prefill) vs tokens generated (one ragged step each) —
            # the two phases have opposite economics, so a workload
            # row that only reports decode throughput hides half the
            # token bill
            "prefill_tokens": tokens_in[0],
            "prefill_tokens_per_sec":
                round(tokens_in[0] / dt, 2) if dt else 0.0,
            # speculative-decoding economics (0s when spec is off):
            # drafted vs accepted counts and the engine's accept-rate
            # gauge — accepted/proposed, the fraction of draft work
            # that became real tokens
            "spec_proposed": int(ectr.get("spec_proposed", 0)),
            "spec_accepted": int(ectr.get("spec_accepted", 0)),
            "spec_accept_rate": float(ectr.get("spec_accept_rate", 0.0)),
            "workers": self.workers,
            # open- vs closed-loop provenance: at a fixed offered rate
            # the latency percentiles mean something different than
            # under back-pressure-adapted submission
            "mode": "open" if self.arrival_rate else "closed",
            "arrival_rate": self.arrival_rate or 0.0,
            "prompt_lens": list(self.prompt_lens),
            "output_lens": list(self.output_lens),
            # TTFT vs inter-token: the autoregressive latency split
            # (client view, from the engine's per-token clock stamps)
            "ttft_p50_ms": pct(ttft_ms, 50),
            "ttft_p99_ms": pct(ttft_ms, 99),
            "itl_p50_ms": pct(itl_ms, 50),
            "itl_p99_ms": pct(itl_ms, 99),
            # engine-reported: bucket-derived, scrape-reproducible
            "engine_p50_ms": eng["e2e_p50_ms"],
            "engine_p99_ms": eng["e2e_p99_ms"],
            "step_p50_ms": eng["step_p50_ms"],
            "step_p99_ms": eng["step_p99_ms"],
            # the tail, NAMED: the worst requests' trace ids next to
            # the client p99 (`trace_view --trace <id>`)
            "slowest_traces": _slowest_traces(traced),
            **outcomes,
        }
        return self.summary


class FleetLoadGen:
    """Closed-loop fleet workload: spray a :class:`FleetRouter` from
    ``workers`` threads with requests whose SESSION ids follow a zipf
    distribution — a few hot sessions dominate, the realistic shape for
    session-affine routing (uniform sessions would make affinity free
    and prefix caching useless). Deterministic like the other gens:
    request ``i`` draws its session from ``RandomState(77000 + i)``,
    its prompt is the session's shared prefix (so affinity converts to
    prefix-cache hits) plus an ``i``-seeded tail, and lengths cycle
    through ``prompt_lens``/``output_lens``.

    ``run()`` reports the fleet view next to the closed-loop fields:
    ``fleet_tokens_per_sec``, ``fleet_p99_ttft_ms``, PER-ENGINE token
    share (from each engine's ``decode_tokens`` delta — the balance
    evidence), the session spread, and the router's own counters
    (dispatches/failovers/affinity hits/sheds)."""

    def __init__(self, router, total_requests: int = 24, workers: int = 4,
                 prompt_lens: Sequence[int] = (4, 12, 24, 8),
                 output_lens: Sequence[int] = (4, 8, 16),
                 n_sessions: Optional[int] = None, zipf_a: float = 1.5,
                 deadline_s: Optional[float] = None,
                 timeout_s: float = 300.0, keep_outputs: bool = False):
        self.router = router
        self.total_requests = int(total_requests)
        self.workers = max(1, int(workers))
        self.prompt_lens = tuple(int(p) for p in prompt_lens)
        self.output_lens = tuple(int(o) for o in output_lens)
        self.n_sessions = int(n_sessions or max(4, total_requests // 3))
        self.zipf_a = float(zipf_a)
        self.deadline_s = deadline_s
        self.timeout_s = float(timeout_s)
        self.keep_outputs = bool(keep_outputs)
        self.outputs: dict = {}   # request index -> generated tokens
        self.summary: Optional[dict] = None

    def _session_for(self, i: int) -> str:
        rng = np.random.RandomState(77_000 + i)
        rank = int(rng.zipf(self.zipf_a))
        return f"sess-{(rank - 1) % self.n_sessions:03d}"

    def _make_prompt(self, i: int, session: str) -> list:
        cfg = getattr(self.router, "config", None)
        vocab = cfg.vocab_size if cfg is not None else 128
        n = self.prompt_lens[i % len(self.prompt_lens)]
        # shared per-session prefix: affinity keeps the session on one
        # replica, whose prefix cache then serves these tokens for free
        # (crc32, NOT hash(): str hash is salted per process and this
        # workload must replay identically)
        srng = np.random.RandomState(
            zlib.crc32(session.encode()) & 0x7FFFFFFF)
        prefix = [int(t) for t in srng.randint(0, vocab, size=4)]
        rng = np.random.RandomState(i)
        tail = [int(t) for t in rng.randint(0, vocab, size=max(1, n - 4))]
        return prefix + tail

    def run(self) -> dict:
        from paddle_tpu.inference.serving import (DeadlineExceeded,
                                                  EngineStopped,
                                                  Overloaded,
                                                  RequestFailed)

        counter = itertools.count()
        outcomes = {"ok": 0, "shed": 0, "deadline_expired": 0,
                    "failed": 0, "stopped": 0, "other_error": 0}
        lock = threading.Lock()
        ttft_ms: list = []
        tokens_out = [0]
        session_hits: Dict[str, int] = {}

        def engine_tokens() -> Dict[str, int]:
            out = {}
            for r in getattr(self.router, "replicas", []):
                eng = getattr(r, "engine", None)
                if eng is None:
                    continue
                try:
                    out[r.name] = int(eng.counters.get("decode_tokens", 0))
                except Exception:
                    out[r.name] = 0
            return out

        base_tokens = engine_tokens()

        def record(kind: str):
            with lock:
                outcomes[kind] += 1

        def worker():
            while True:
                i = next(counter)
                if i >= self.total_requests:
                    return
                session = self._session_for(i)
                prompt = self._make_prompt(i, session)
                out_n = self.output_lens[i % len(self.output_lens)]
                try:
                    h = self.router.submit(
                        prompt, max_new_tokens=out_n,
                        deadline_s=self.deadline_s, session=session)
                    toks = h.result(self.timeout_s)
                    st = h.stats()
                    with lock:
                        if self.keep_outputs:
                            self.outputs[i] = list(toks)
                        tokens_out[0] += len(toks)
                        session_hits[session] = \
                            session_hits.get(session, 0) + 1
                        if "ttft_ms" in st:
                            ttft_ms.append(st["ttft_ms"])
                    record("ok")
                except Overloaded:
                    record("shed")
                except DeadlineExceeded:
                    record("deadline_expired")
                except RequestFailed:
                    record("failed")
                except EngineStopped:
                    record("stopped")
                    return
                except Exception:
                    record("other_error")

        threads = [threading.Thread(target=worker, daemon=True,
                                    name=f"fleet-loadgen-{w}")
                   for w in range(self.workers)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=self.timeout_s)
        dt = time.perf_counter() - t0

        def pct(arr, q):
            a = np.asarray(arr, np.float64)
            return round(float(np.percentile(a, q)), 3) if a.size else 0.0

        per_engine = {
            name: tok - base_tokens.get(name, 0)
            for name, tok in engine_tokens().items()}
        total_eng = sum(per_engine.values())
        rctr = self.router.counters
        self.summary = {
            "requests": self.total_requests,
            "completed": sum(outcomes.values()),
            "wall_s": round(dt, 4),
            "fleet_tokens": tokens_out[0],
            "fleet_tokens_per_sec":
                round(tokens_out[0] / dt, 2) if dt else 0.0,
            "fleet_ttft_p50_ms": pct(ttft_ms, 50),
            "fleet_p99_ttft_ms": pct(ttft_ms, 99),
            # balance evidence: each engine's decode_tokens delta over
            # the run, and its share of the fleet total
            "per_engine_tokens": per_engine,
            "per_engine_token_share": {
                name: (round(tok / total_eng, 4) if total_eng else 0.0)
                for name, tok in per_engine.items()},
            "sessions": self.n_sessions,
            "session_spread": dict(sorted(
                session_hits.items(), key=lambda kv: -kv[1])[:8]),
            "zipf_a": self.zipf_a,
            "workers": self.workers,
            "prompt_lens": list(self.prompt_lens),
            "output_lens": list(self.output_lens),
            "router_requests": int(rctr.get("router_requests", 0)),
            "router_dispatches": int(rctr.get("router_dispatches", 0)),
            "router_failovers": int(rctr.get("router_failovers", 0)),
            "router_affinity_hits":
                int(rctr.get("router_affinity_hits", 0)),
            "router_sheds": int(rctr.get("router_sheds", 0)),
            **outcomes,
        }
        return self.summary


def _fleet_main(args):
    """--fleet N CLI leg: N self-contained decode engines behind one
    in-process ``FleetRouter``, sprayed with the zipf-session
    workload."""
    from paddle_tpu.inference.decode import DecodeEngine, DecodeModelConfig
    from paddle_tpu.serving import FleetRouter

    cfg = DecodeModelConfig(vocab_size=args.vocab, n_layers=args.layers,
                            n_heads=args.heads, head_dim=args.head_dim,
                            ffn_dim=args.ffn,
                            max_context=args.pages_per_seq
                            * args.page_size)
    engines = []
    for _ in range(max(1, args.fleet)):
        e = DecodeEngine(
            cfg, seed=0, max_batch=args.max_batch, n_pages=args.pages,
            page_size=args.page_size,
            max_pages_per_seq=args.pages_per_seq,
            kv_codec=args.kv_codec)
        e.warm()
        e.start()
        engines.append(e)
    router = FleetRouter(engines, config=cfg,
                         chunk_tokens=args.chunk_tokens)
    try:
        gen = FleetLoadGen(
            router, total_requests=args.requests, workers=args.workers,
            prompt_lens=[int(p) for p in args.prompt_lens.split(",")],
            output_lens=[int(o) for o in args.output_lens.split(",")],
            n_sessions=args.sessions or None, zipf_a=args.zipf_a,
            deadline_s=args.deadline_s)
        summary = gen.run()
        print(json.dumps(summary))
    finally:
        router.drain(timeout=30)


def _decode_main(args):
    """--decode CLI leg: a self-contained tiny decode engine (no blob
    needed — the mode demos/benches the decode data path itself)."""
    from paddle_tpu.inference.decode import DecodeEngine, DecodeModelConfig

    cfg = DecodeModelConfig(vocab_size=args.vocab, n_layers=args.layers,
                            n_heads=args.heads, head_dim=args.head_dim,
                            ffn_dim=args.ffn,
                            max_context=args.pages_per_seq
                            * args.page_size)
    proposer = None
    if args.spec_k:
        from paddle_tpu.inference.decode import NgramProposer
        proposer = NgramProposer()
    engine = DecodeEngine(
        cfg, seed=0, max_batch=args.max_batch, n_pages=args.pages,
        page_size=args.page_size, max_pages_per_seq=args.pages_per_seq,
        kv_codec=args.kv_codec, spec_k=args.spec_k, proposer=proposer,
        host_kv_bytes=args.host_kv_bytes)
    engine.warm()
    engine.start()
    try:
        gen = DecodeLoadGen(
            engine, total_requests=args.requests, workers=args.workers,
            prompt_lens=[int(p) for p in args.prompt_lens.split(",")],
            output_lens=[int(o) for o in args.output_lens.split(",")],
            deadline_s=args.deadline_s, arrival_rate=args.arrival_rate)
        summary = gen.run()
        summary["engine_counters"] = {
            k: v for k, v in sorted(engine.counters.items())
            if k.startswith(("decode_", "kv_", "spec_"))}
        print(json.dumps(summary))
    finally:
        engine.drain(timeout=30)


def main():
    import argparse

    ap = argparse.ArgumentParser("tools/load_gen.py")
    ap.add_argument("--model-dir",
                    help="static.save_inference_model directory "
                         "(serving mode)")
    ap.add_argument("--decode", action="store_true",
                    help="decode workload mode: drive a self-contained "
                         "LLM decode engine with deterministic mixed "
                         "prompt/output lengths")
    ap.add_argument("--fleet", type=int, default=0, metavar="N",
                    help="fleet mode: N decode engines behind one "
                         "FleetRouter, sprayed with a zipf-session "
                         "workload; reports per-engine token share and "
                         "fleet p99 TTFT")
    ap.add_argument("--sessions", type=int, default=0,
                    help="fleet mode: session pool size (0 = derive "
                         "from --requests)")
    ap.add_argument("--zipf-a", type=float, default=1.5,
                    help="fleet mode: zipf exponent for the session "
                         "distribution (higher = hotter head)")
    ap.add_argument("--chunk-tokens", type=int, default=8,
                    help="fleet mode: router dispatch chunk size "
                         "(failover granularity)")
    ap.add_argument("--prompt-lens", default="4,12,24,8",
                    help="decode mode: comma-separated prompt lengths "
                         "(cycled per request)")
    ap.add_argument("--output-lens", default="4,8,16",
                    help="decode mode: comma-separated max_new_tokens "
                         "(cycled per request)")
    ap.add_argument("--spec-k", type=int, default=0,
                    help="decode mode: speculative draft length per "
                         "slot (0 = off; uses the n-gram prompt-lookup "
                         "proposer)")
    ap.add_argument("--kv-codec", default="off", choices=("off", "int8"),
                    help="decode mode: KV page codec (int8 halves pool "
                         "bytes; per-token-row scales)")
    ap.add_argument("--arrival-rate", type=float, default=None,
                    help="decode mode: OPEN-LOOP arrivals at this "
                         "requests/second (request i submits at "
                         "i/rate — deterministic schedule; default is "
                         "closed-loop workers)")
    ap.add_argument("--host-kv-bytes", type=int, default=0,
                    help="decode mode: host-RAM KV offload tier budget "
                         "in bytes (0 = off; under pool pressure the "
                         "engine parks the coldest session to host RAM "
                         "instead of preempt-requeuing)")
    ap.add_argument("--max-batch", type=int, default=4)
    ap.add_argument("--pages", type=int, default=64)
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--pages-per-seq", type=int, default=8)
    ap.add_argument("--vocab", type=int, default=128)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--head-dim", type=int, default=16)
    ap.add_argument("--ffn", type=int, default=128)
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--sizes", default="1,2,3",
                    help="comma-separated request row counts (cycled)")
    ap.add_argument("--buckets", default="1,2,4,8",
                    help="comma-separated padded batch buckets")
    ap.add_argument("--deadline-s", type=float, default=None)
    args = ap.parse_args()

    if args.fleet:
        _fleet_main(args)
        return
    if args.decode:
        _decode_main(args)
        return
    if not args.model_dir:
        ap.error("--model-dir is required (or pass --decode)")

    from paddle_tpu.inference.serving import (AnalysisPredictor,
                                              ServingEngine)

    predictor = AnalysisPredictor(
        args.model_dir,
        batch_buckets=[int(b) for b in args.buckets.split(",")])
    predictor.warm()
    engine = ServingEngine(predictor).start()
    try:
        gen = LoadGen(engine, total_requests=args.requests,
                      workers=args.workers,
                      sizes=[int(s) for s in args.sizes.split(",")],
                      deadline_s=args.deadline_s)
        summary = gen.run()
        summary["engine_counters"] = {
            k: v for k, v in sorted(engine.counters.items())
            if k.startswith("serve_")}
        print(json.dumps(summary))
    finally:
        engine.drain(timeout=10)


if __name__ == "__main__":
    main()
