#!/usr/bin/env python
"""Poll a paddle_tpu ``/metrics`` endpoint and print counter deltas as
a table — the live-fleet companion to the chaos drills' post-run
counter tables (same formatter).

Any http_kv listener is a valid target: the elastic/PS coordination
KVServer, a ServingHealthServer, or the standalone sidecar a trainer or
pserver starts when ``PADDLE_METRICS_PORT`` is set.

Usage::

    python tools/metrics_watch.py --endpoint 127.0.0.1:8321 \
        [--interval 2] [--count 0] [--filter serve_] [--all]

Each poll prints the samples that MOVED since the previous poll (the
first poll prints non-zero values); ``--all`` prints every sample every
poll; ``--count N`` stops after N polls (0 = forever). Exit code 1 when
the endpoint never answered.
"""
from __future__ import annotations

import argparse
import http.client
import os
import sys
import time
from typing import Dict, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from paddle_tpu.observability.metrics import (  # noqa: E402
    parse_prometheus_text,
)


def format_counter_table(counters: Dict[str, float],
                         title: Optional[str] = None,
                         name_width: int = 44) -> str:
    """The chaos-drill counter-table format: one ``name  value`` row per
    sorted counter (shared by tools/chaos_drill.py's PS report)."""
    lines = []
    if title:
        lines.append(title)
    lines.append(f"{'counter':<{name_width}}{'value':>12}")
    for name, value in sorted(counters.items()):
        v = int(value) if float(value) == int(value) else round(value, 3)
        lines.append(f"{name:<{name_width}}{v:>12}")
    return "\n".join(lines)


def scrape(endpoint: str, timeout: float = 5.0) -> Dict[str, float]:
    """One GET /metrics -> {sample_key: value} (histogram buckets keep
    their ``name_bucket{le="..."}`` keys)."""
    host, _, port = endpoint.replace("http://", "").rpartition(":")
    conn = http.client.HTTPConnection(host or "127.0.0.1", int(port),
                                      timeout=timeout)
    try:
        conn.request("GET", "/metrics")
        resp = conn.getresponse()
        body = resp.read().decode("utf-8", "replace")
        if resp.status != 200:
            raise RuntimeError(f"GET /metrics -> HTTP {resp.status}")
        return parse_prometheus_text(body)
    finally:
        conn.close()


def watch(endpoint: str, interval: float = 2.0, count: int = 0,
          name_filter: str = "", show_all: bool = False,
          out=sys.stdout) -> int:
    """Poll loop; returns the number of successful scrapes."""
    prev: Optional[Dict[str, float]] = None
    polls = ok = 0
    while count <= 0 or polls < count:
        polls += 1
        try:
            cur = scrape(endpoint)
        except (OSError, RuntimeError) as e:
            print(f"[{time.strftime('%H:%M:%S')}] scrape failed: {e}",
                  file=out)
            if count <= 0 or polls < count:
                time.sleep(interval)
            continue
        ok += 1
        cur = {k: v for k, v in cur.items()
               if not name_filter or name_filter in k}
        if show_all:
            shown = cur
        elif prev is None:
            shown = {k: v for k, v in cur.items() if v}
        else:
            shown = {k: v - prev.get(k, 0.0) for k, v in cur.items()
                     if v != prev.get(k, 0.0)}
        stamp = time.strftime("%H:%M:%S")
        if shown:
            title = (f"[{stamp}] {endpoint} "
                     f"({'values' if prev is None or show_all else 'deltas'})")
            print(format_counter_table(shown, title=title) + "\n",
                  file=out)
        else:
            print(f"[{stamp}] {endpoint}: no movement", file=out)
        prev = cur
        if count <= 0 or polls < count:
            time.sleep(interval)
    return ok


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="poll a /metrics endpoint, print counter deltas")
    ap.add_argument("--endpoint", required=True, help="host:port")
    ap.add_argument("--interval", type=float, default=2.0)
    ap.add_argument("--count", type=int, default=0,
                    help="polls before exiting (0 = forever)")
    ap.add_argument("--filter", default="", dest="name_filter",
                    help="substring filter on sample names")
    ap.add_argument("--all", action="store_true", dest="show_all",
                    help="print every sample each poll, not deltas")
    args = ap.parse_args(argv)
    ok = watch(args.endpoint, interval=args.interval, count=args.count,
               name_filter=args.name_filter, show_all=args.show_all)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
