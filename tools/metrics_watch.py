#!/usr/bin/env python
"""Poll a paddle_tpu ``/metrics`` endpoint and print counter deltas as
a table — the live-fleet companion to the chaos drills' post-run
counter tables (same formatter).

Any http_kv listener is a valid target: the elastic/PS coordination
KVServer, a ServingHealthServer, or the standalone sidecar a trainer or
pserver starts when ``PADDLE_METRICS_PORT`` is set.

Usage::

    python tools/metrics_watch.py --endpoint 127.0.0.1:8321 \
        [--interval 2] [--count 0] [--filter serve_] [--all]

Each poll prints the samples that MOVED since the previous poll (the
first poll prints non-zero values); ``--all`` prints every sample every
poll; ``--count N`` stops after N polls (0 = forever). Exit code 1 when
the endpoint never answered.

Histogram samples additionally render as a derived p50/p99 table per
poll — the percentiles of the INTERVAL distribution (cumulative-bucket
deltas between polls, interpolated exactly like
``metrics.Histogram.percentile``), not raw bucket counters.
"""
from __future__ import annotations

import argparse
import os
import re
import sys
import time
from typing import Dict, List, Optional, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from paddle_tpu.observability.metrics import (  # noqa: E402
    parse_prometheus_text, percentile_from_buckets,
)


def format_counter_table(counters: Dict[str, float],
                         title: Optional[str] = None,
                         name_width: int = 44) -> str:
    """The chaos-drill counter-table format: one ``name  value`` row per
    sorted counter (shared by tools/chaos_drill.py's PS report)."""
    lines = []
    if title:
        lines.append(title)
    lines.append(f"{'counter':<{name_width}}{'value':>12}")
    for name, value in sorted(counters.items()):
        v = int(value) if float(value) == int(value) else round(value, 3)
        lines.append(f"{name:<{name_width}}{v:>12}")
    return "\n".join(lines)


_BUCKET_RE = re.compile(r"^(?P<name>[a-zA-Z_:][\w:]*)_bucket"
                        r"\{(?P<labels>.*)\}$")
_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def histogram_series(samples: Dict[str, float]
                     ) -> Dict[Tuple[str, tuple],
                               List[Tuple[float, float]]]:
    """Group parsed scrape samples into cumulative histogram bucket
    series: ``{(metric, non-le labels): [(le, cumulative), ...]}`` with
    the +Inf bucket last — the ``Histogram.snapshot`` layout, rebuilt
    from exposition text."""
    out: Dict[Tuple[str, tuple], List[Tuple[float, float]]] = {}
    for key, value in samples.items():
        m = _BUCKET_RE.match(key)
        if not m:
            continue
        le, rest = None, []
        for k, v in _LABEL_RE.findall(m.group("labels")):
            if k == "le":
                le = v
            else:
                rest.append((k, v))
        if le is None:
            continue
        bound = float("inf") if le == "+Inf" else float(le)
        out.setdefault((m.group("name"), tuple(rest)), []).append(
            (bound, value))
    for buckets in out.values():
        buckets.sort(key=lambda bv: bv[0])
    return out


def histogram_percentile_deltas(cur: Dict[str, float],
                                prev: Optional[Dict[str, float]] = None,
                                qs=(50, 99)) -> Dict[str, dict]:
    """Between-poll histogram movement: for every histogram series whose
    cumulative buckets advanced since ``prev``, the new-sample count and
    the interpolated percentiles of the INTERVAL distribution (bucket
    deltas) — the same cumulative-bucket interpolation
    ``metrics.Histogram.percentile`` uses, so a poll loop shows live
    p50/p99 instead of raw bucket samples. ``prev=None`` reports the
    cumulative distribution."""
    cur_h = histogram_series(cur)
    prev_h = histogram_series(prev) if prev else {}
    out: Dict[str, dict] = {}
    for (name, labels), buckets in sorted(cur_h.items()):
        pb = dict(prev_h.get((name, labels), ()))
        delta = [(b, c - pb.get(b, 0.0)) for b, c in buckets]
        if any(c < 0 for _b, c in delta):
            # counter reset (scraped server restarted between polls):
            # the cumulative counts went backwards, so the delta is
            # garbage — fall back to the fresh process's cumulative
            # distribution instead of interpolating a non-monotone
            # series or silently dropping the row
            delta = buckets
        total = delta[-1][1] if delta else 0.0
        if total <= 0:
            continue
        disp = name + ("{" + ",".join(f'{k}="{v}"' for k, v in labels)
                       + "}" if labels else "")
        row = {"count": int(total)}
        for q in qs:
            row[f"p{q}"] = round(percentile_from_buckets(delta, q), 3)
        out[disp] = row
    return out


def format_percentile_table(rows: Dict[str, dict],
                            title: Optional[str] = None,
                            name_width: int = 52) -> str:
    """``histogram  count  p50  p99`` table for the poll loop."""
    qs = sorted({k for r in rows.values() for k in r if k != "count"},
                key=lambda s: float(s[1:]))
    lines = []
    if title:
        lines.append(title)
    header = f"{'histogram':<{name_width}}{'count':>8}"
    header += "".join(f"{q + '_ms':>10}" for q in qs)
    lines.append(header)
    for name, row in rows.items():
        line = f"{name:<{name_width}}{row['count']:>8}"
        line += "".join(f"{row.get(q, 0.0):>10}" for q in qs)
        lines.append(line)
    return "\n".join(lines)


def scrape(endpoint: str, timeout: float = 5.0) -> Dict[str, float]:
    """One GET /metrics -> {sample_key: value} (histogram buckets keep
    their ``name_bucket{le="..."}`` keys). Delegates the HTTP leg to
    the ONE scraper the federation layer owns — endpoint parsing and
    status handling must not fork between the tools and the library.
    A non-200/dead endpoint raises ConnectionError (an OSError, which
    every existing caller already catches)."""
    from paddle_tpu.observability.federation import scrape_text

    return parse_prometheus_text(scrape_text(endpoint, timeout=timeout))


def watch(endpoint: str, interval: float = 2.0, count: int = 0,
          name_filter: str = "", show_all: bool = False,
          out=sys.stdout) -> int:
    """Poll loop; returns the number of successful scrapes."""
    prev: Optional[Dict[str, float]] = None
    polls = ok = 0
    while count <= 0 or polls < count:
        polls += 1
        try:
            cur = scrape(endpoint)
        except (OSError, RuntimeError) as e:
            print(f"[{time.strftime('%H:%M:%S')}] scrape failed: {e}",
                  file=out)
            if count <= 0 or polls < count:
                time.sleep(interval)
            continue
        ok += 1
        cur = {k: v for k, v in cur.items()
               if not name_filter or name_filter in k}
        if show_all:
            shown = cur
        elif prev is None:
            shown = {k: v for k, v in cur.items() if v}
        else:
            shown = {k: v - prev.get(k, 0.0) for k, v in cur.items()
                     if v != prev.get(k, 0.0)}
        stamp = time.strftime("%H:%M:%S")
        if shown:
            title = (f"[{stamp}] {endpoint} "
                     f"({'values' if prev is None or show_all else 'deltas'})")
            print(format_counter_table(shown, title=title) + "\n",
                  file=out)
        else:
            print(f"[{stamp}] {endpoint}: no movement", file=out)
        # derived histogram view: p50/p99 of the samples that landed
        # since the previous poll (cumulative on the first poll)
        pct = histogram_percentile_deltas(cur, prev)
        if pct:
            span = "cumulative" if prev is None else "interval"
            print(format_percentile_table(
                pct, title=f"[{stamp}] histogram p50/p99 ({span})")
                + "\n", file=out)
        prev = cur
        if count <= 0 or polls < count:
            time.sleep(interval)
    return ok


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="poll a /metrics endpoint, print counter deltas")
    ap.add_argument("--endpoint", required=True, help="host:port")
    ap.add_argument("--interval", type=float, default=2.0)
    ap.add_argument("--count", type=int, default=0,
                    help="polls before exiting (0 = forever)")
    ap.add_argument("--filter", default="", dest="name_filter",
                    help="substring filter on sample names")
    ap.add_argument("--all", action="store_true", dest="show_all",
                    help="print every sample each poll, not deltas")
    args = ap.parse_args(argv)
    ok = watch(args.endpoint, interval=args.interval, count=args.count,
               name_filter=args.name_filter, show_all=args.show_all)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
