#!/usr/bin/env python
"""Chaos helper: damage a committed snapshot to exercise load-time
verification (io.snapshot.SnapshotStore) in CI and by hand.

Usage:
    python tools/corrupt_ckpt.py PATH [--mode flip|truncate|unmanifest]
                                 [--file NAME] [--offset N]

PATH is either one snapshot dir (.../epoch_<k>, .../step_<k>,
.../seq_<k>), a store root, an auto-checkpoint job dir, or a pserver
snapshot root (shard_<k>/seq_<n>/ layout — search descends one level),
in which case the NEWEST committed snapshot (highest tag) is picked.
Modes:

    flip        XOR one payload byte (default: middle of the file) —
                the sha256 manifest check must reject the snapshot
    truncate    cut the payload in half (or at --offset) — torn write
    unmanifest  delete MANIFEST.json — uncommitted/torn snapshot

Prints a JSON summary of what was damaged so CI logs show the exact
chaos applied. After corruption, loading must fall back to the newest
still-valid snapshot (see tests/test_fault_layer.py).
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddle_tpu.io.snapshot import MANIFEST_NAME  # noqa: E402

# any SnapshotStore naming scheme: epoch_<k>, step_<k>, seq_<k>, ...
_SNAP_DIR = re.compile(r"^[A-Za-z_]*?(-?\d+)$")


def _committed_under(root: str):
    """(tag, path) for every committed snapshot dir directly under
    ``root``, prefix-agnostic."""
    out = []
    try:
        names = sorted(os.listdir(root))
    except (FileNotFoundError, NotADirectoryError, OSError):
        return out
    for name in names:
        m = _SNAP_DIR.match(name)
        path = os.path.join(root, name)
        if (m and os.path.isdir(path)
                and os.path.exists(os.path.join(path, MANIFEST_NAME))):
            out.append((int(m.group(1)), path))
    return out


def pick_snapshot(path: str) -> str:
    """Resolve PATH to one committed snapshot dir (newest tag wins).
    Handles a snapshot dir itself, a store root, and a root of stores
    (pserver shard_<k>/ dirs, auto-checkpoint job dirs) one level down."""
    if os.path.exists(os.path.join(path, MANIFEST_NAME)):
        return path
    committed = _committed_under(path)
    if not committed:
        try:
            names = sorted(os.listdir(path))
        except OSError as e:
            raise SystemExit(f"cannot read {path!r}: {e}")
        for name in names:
            committed += _committed_under(os.path.join(path, name))
    if not committed:
        raise SystemExit(f"no committed snapshot under {path!r}")
    return max(committed)[1]


def pick_payload(snap_dir: str, name=None) -> str:
    with open(os.path.join(snap_dir, MANIFEST_NAME), encoding="utf-8") as f:
        files = json.load(f)["files"]
    if name is None:
        name = sorted(files)[-1]  # deterministic default
    if name not in files:
        raise SystemExit(f"{name!r} not in manifest ({sorted(files)})")
    return os.path.join(snap_dir, name)


def corrupt(path: str, mode: str = "flip", file: str = None,
            offset: int = None) -> dict:
    """Damage one snapshot; returns a summary dict (importable for
    tests)."""
    snap = pick_snapshot(path)
    if mode == "unmanifest":
        target = os.path.join(snap, MANIFEST_NAME)
        os.remove(target)
        return {"snapshot": snap, "mode": mode, "target": target}
    target = pick_payload(snap, file)
    size = os.path.getsize(target)
    if size == 0:
        raise SystemExit(f"{target!r} is empty; nothing to corrupt")
    at = offset if offset is not None else size // 2
    at = max(0, min(size - 1, at))
    if mode == "flip":
        with open(target, "r+b") as f:
            f.seek(at)
            byte = f.read(1)
            f.seek(at)
            f.write(bytes([byte[0] ^ 0xFF]))
        detail = {"offset": at}
    elif mode == "truncate":
        with open(target, "r+b") as f:
            f.truncate(at)
        detail = {"truncated_to": at, "was": size}
    else:
        raise SystemExit(f"unknown mode {mode!r}")
    return {"snapshot": snap, "mode": mode, "target": target, **detail}


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        "corrupt_ckpt", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("path", help="snapshot dir or store root")
    parser.add_argument("--mode", default="flip",
                        choices=("flip", "truncate", "unmanifest"))
    parser.add_argument("--file", default=None,
                        help="payload file name inside the snapshot")
    parser.add_argument("--offset", type=int, default=None)
    args = parser.parse_args(argv)
    print(json.dumps(corrupt(args.path, mode=args.mode, file=args.file,
                             offset=args.offset)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
