#!/usr/bin/env python
"""Deterministic chaos drills: elastic kill/resume (ISSUE 7),
parameter-server kill-a-primary (ISSUE 8, ``--ps``), and fleet decode
serving kill-an-engine (ISSUE 17, ``--fleet``).

Fleet drill (``--fleet``): N decode engines come up as subprocesses,
each behind its ``DecodeEngineServer`` HTTP surface; a ``FleetRouter``
sprays deterministic traffic over them, then SIGKILLs the engine a
probe session is pinned to — mid-generation, under live load. The
router's health gate flips the victim out, its chunked
retry-with-failover replays every stranded session on a survivor
(emitted tokens folded into the prompt), and the drill asserts: zero
lost, zero doubled, every output BITWISE equal to the never-killed
dense oracle; ``/readyz`` flipped; the parent's flight-recorder dump
names the killed endpoint. The KV-migration legs then run against a
survivor: a ``PrefillWorker`` ships int8 page frames (adopt +
prefix-hit + dedupe on re-ship + typed malformed reject), the
dead-endpoint ship exercises the ``kv_migration_fallbacks`` degrade
leg with the request still serving, ship-vs-recompute is gated at a
serving-scale config, and a multi-endpoint ``slo_check`` over every
surviving ``/metrics`` must come back healthy.

PS drill (``--ps``): a KVServer comes up in-process; one 2-replica
group serves shard 0 — primary A as a SUPERVISED SUBPROCESS
(``launch.Supervisor``, the real relaunch path), backup B in-process.
The parent is the trainer: it pushes a deterministic gradient stream
through a replicated ``PSClient``. ``PADDLE_FAULT_SPEC=
ps.apply:1@K:SystemExit`` (armed only in A's env) kills A at its
(K+1)-th applied write — mid-stream, with snapshots already committed.
The ReplicaCoordinator observes A's lease expiry, promotes B (shard-map
epoch bump); the client fails over with typed errors only and REPLAYS
the in-flight push (write dedup makes the replay exactly-once); the
supervisor relaunches A, which restores its newest valid SnapshotStore
snapshot and catches up from B's delta log, rejoining as a backup. The
drill asserts: the final pull is BITWISE identical to the never-killed
reference (a local same-backend oracle table fed the same stream — in
sync replication mode zero updates may be lost or doubled), a promotion
and a failover really happened, the relaunched replica reconverged
(digest parity across the group), and the ``ps_*`` counter table.
"""
from __future__ import annotations

_ELASTIC_DOC = """Deterministic elastic-training chaos drill (ISSUE 7 crown test).

Promotes the PR 2 chaos recipe (arm a ``PADDLE_FAULT_SPEC``, supervise,
resume) to a tool that drives the WHOLE elastic story end to end with
real processes and real kills:

1. a KVServer comes up in-process; ``nranks`` trainer workers launch
   under ``launch.Supervisor`` relaunch supervision;
2. every worker rendezvous through ``distributed.elastic.ElasticAgent``
   into generation 0, holds a heartbeat lease, trains the same
   deterministic toy job with ``TrainEpochRange`` mid-epoch
   checkpointing, and barriers each epoch end;
3. ``PADDLE_FAULT_SPEC=drill.step:1@K:SystemExit`` kills ``kill_rank``
   mid-epoch at its (K+1)-th batch (the env spec re-arms per process;
   ``@after`` is what lets the relaunched incarnation run past it);
4. survivors observe the lease expiry as a typed ``WorkerLost``, bump
   the generation, and reform; the supervisor relaunches the dead rank,
   which resumes AT THE EXACT NEXT BATCH from its mid-epoch snapshot
   and rejoins the bumped generation;
5. the drill asserts the killed rank's final loss is **bitwise
   identical** to the never-killed rank 0's (both run the same
   deterministic schedule, so rank 0 *is* the uninterrupted run), that
   a generation bump really happened, and that exactly the expected
   relaunches were spent — then prints the counter table.

Usage::

    JAX_PLATFORMS=cpu python tools/chaos_drill.py [--workdir DIR]
        [--epochs 3] [--batches 4] [--kill-after 6] [--lease-ttl 3]

Exit code 0 = drill passed (bitwise parity + generation bump); the
counter table goes to stdout either way. ``--no-kill`` runs the same
job without the fault spec (a clean baseline of the harness itself).
"""

import argparse
import json
import os
import socket
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


# ---------------------------------------------------------------------------
# worker (runs in the supervised subprocesses)
# ---------------------------------------------------------------------------

def worker_main() -> int:
    import numpy as np

    import paddle_tpu.static as static
    from paddle_tpu import fault, profiler
    from paddle_tpu.distributed.elastic import ElasticAgent
    from paddle_tpu.incubate.checkpoint.auto_checkpoint import (
        TrainEpochRange,
    )

    rank = int(os.environ["PADDLE_TRAINER_ID"])
    world = int(os.environ["PADDLE_TRAINERS_NUM"])
    endpoint = os.environ["PADDLE_ELASTIC_ENDPOINT"]
    epochs = int(os.environ["DRILL_EPOCHS"])
    batches = int(os.environ["DRILL_BATCHES"])
    save_every = int(os.environ["DRILL_SAVE_EVERY"])
    kill_rank = int(os.environ.get("DRILL_KILL_RANK", "-1"))
    lease_ttl = float(os.environ.get("DRILL_LEASE_TTL", "3.0"))
    log_path = os.environ["DRILL_LOG"]
    h, b = 8, 8

    def log(kind, **fields):
        with open(log_path, "a") as f:
            f.write(json.dumps({"kind": kind, "rank": rank, **fields})
                    + "\n")

    main, startup = static.Program(), static.Program()
    main.random_seed = startup.random_seed = 1234
    with static.program_guard(main, startup):
        x = static.data("x", [-1, h])
        label = static.data("label", [-1, 1], dtype="int64")
        hid = static.nn.fc(x, 16, act="relu")
        hid = static.dropout(hid, dropout_prob=0.2)
        logits = static.nn.fc(hid, 4)
        loss = static.mean(static.softmax_with_cross_entropy(logits, label))
        static.SGD(0.05).minimize(loss)

    exe = static.Executor()
    exe.run(startup)
    cp = static.CompiledProgram(main)
    tr = TrainEpochRange(epochs, name=f"drill_r{rank}",
                         save_every_steps=save_every)
    tr.register(executor=exe, program=main)
    log("start", restored_epoch=tr.restored_epoch,
        restored_batch=tr.restored_batch, exe_step=exe._step)

    agent = ElasticAgent(endpoint, rank, world, job="drill",
                         lease_ttl=lease_ttl)
    agent.join(timeout=240.0)
    agent.start_heartbeat()

    def reader(epoch):
        def gen():
            for i in range(batches):
                rng = np.random.RandomState(epoch * 100 + i)
                yield {"x": rng.randn(b, h).astype(np.float32),
                       "label": rng.randint(0, 4, (b, 1)).astype(np.int64)}
        return gen

    last = None
    for epoch in tr.get():
        for i, batch in tr.steps(epoch, reader(epoch)):
            if rank == kill_rank:
                # the armed PADDLE_FAULT_SPEC decides which visit dies
                fault.point("drill.step")
            out = exe.run(cp, feed=batch, fetch_list=[loss])
            last = np.ravel(out[0]).astype(np.float32)
            log("batch", epoch=epoch, batch=i, step=exe._step - 1,
                loss=float(last[0]))
        agent.synchronize(f"epoch{epoch}", timeout=240.0, max_reforms=3)
    agent.stop_heartbeat()

    counters = {k: v for k, v in profiler.counters_snapshot().items()
                if k in profiler.ELASTIC_COUNTER_NAMES
                or k in profiler.FAULT_COUNTER_NAMES}
    log("final", loss=float(last[0]), loss_hex=last.tobytes().hex(),
        generation=agent.generation, counters=counters)
    return 0


# ---------------------------------------------------------------------------
# the drill (parent process)
# ---------------------------------------------------------------------------

def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _read_log(path):
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(ln) for ln in f if ln.strip()]


def _flightrec_dir(workdir: str) -> str:
    return os.path.join(workdir, "flightrec")


def _clean_flightrec(workdir: str) -> None:
    d = _flightrec_dir(workdir)
    if os.path.isdir(d):
        for fn in os.listdir(d):
            if fn.startswith("flightrec_"):
                os.remove(os.path.join(d, fn))


def _flightrec_report(workdir: str, error_name: str = "SystemExit") -> dict:
    """Scan the drill's flight-recorder dumps: the postmortem contract
    is that a killed process left a dump whose LAST recorded events
    name the typed error that killed it."""
    d = _flightrec_dir(workdir)
    dumps = []
    if os.path.isdir(d):
        for fn in sorted(os.listdir(d)):
            if fn.startswith("flightrec_") and fn.endswith(".json"):
                try:
                    with open(os.path.join(d, fn)) as f:
                        dumps.append(json.load(f))
                except (OSError, ValueError):
                    pass
    names_killer = any(
        ev.get("error") == error_name
        for dump in dumps for ev in dump.get("events", [])[-3:])
    return {"dumps": len(dumps),
            "reasons": [dump.get("reason") for dump in dumps],
            "names_killer": names_killer}


def run_drill(workdir: str, nranks: int = 2, epochs: int = 3,
              batches: int = 4, save_every: int = 2, kill_rank: int = 1,
              kill_after: int = 6, max_restarts: int = 2,
              lease_ttl: float = 3.0, kill: bool = True) -> dict:
    """Run the drill; returns a report dict (see keys in `main`).

    ``kill_after=K`` kills ``kill_rank`` at its (K+1)-th training batch
    — pick K so the death lands mid-epoch and the relaunched
    incarnation has fewer than K batches left (the re-armed env spec
    then never re-fires, per the ``@after`` skip count).

    ``PADDLE_CHAOS_LEASE_TTL`` overrides ``lease_ttl``: a 3s lease is
    proven-stable on an idle box, but under full-suite load the first
    ``exe.run`` trace holds the GIL long enough to starve the heartbeat
    thread past the TTL — a spurious expiry on a HEALTHY rank double
    -bumps the generation and flakes the drill. Tests that share the
    box with cold compiles pin the knob instead of editing call sites.
    """
    from paddle_tpu.distributed.http_kv import KVServer
    from paddle_tpu.distributed.launch import Supervisor
    from paddle_tpu.fault.retry import Backoff

    lease_ttl = float(os.environ.get("PADDLE_CHAOS_LEASE_TTL",
                                     lease_ttl))
    os.makedirs(workdir, exist_ok=True)
    port = _free_port()
    srv = KVServer(port)
    srv.start()

    logs = {r: os.path.join(workdir, f"rank{r}.jsonl")
            for r in range(nranks)}
    for p in logs.values():
        if os.path.exists(p):
            os.remove(p)
    _clean_flightrec(workdir)

    def env_for(rank):
        env = dict(os.environ)
        env.update({
            "PYTHONPATH": _REPO,
            "JAX_PLATFORMS": env.get("JAX_PLATFORMS", "cpu"),
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(nranks),
            "PADDLE_ELASTIC_ENDPOINT": f"127.0.0.1:{port}",
            "PADDLE_AUTO_CHECKPOINT_PATH": os.path.join(workdir, "ckpt"),
            "DRILL_EPOCHS": str(epochs),
            "DRILL_BATCHES": str(batches),
            "DRILL_SAVE_EVERY": str(save_every),
            "DRILL_KILL_RANK": str(kill_rank if kill else -1),
            "DRILL_LEASE_TTL": repr(lease_ttl),
            "DRILL_LOG": logs[rank],
            # every worker dumps a crash postmortem here; the report
            # asserts the killed rank's dump names the SystemExit
            "PADDLE_FLIGHTREC_DIR": _flightrec_dir(workdir),
        })
        if kill:
            env["PADDLE_FAULT_SPEC"] = (
                f"drill.step:1@{kill_after}:SystemExit")
        else:
            env.pop("PADDLE_FAULT_SPEC", None)
        return env

    def start_fn(rank):
        return subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker"],
            env=env_for(rank))

    # relaunch backoff WIDER than the lease TTL: the drill exercises the
    # lease-expiry -> WorkerLost -> generation-bump path, and a relaunch
    # that re-leases the same (generation, rank) key before the TTL
    # sweep observes the gap reads as continuity — the survivors never
    # reform and the bump assertion goes flaky (the same
    # relaunch-beats-the-sweep race the PS coordinator closes with lease
    # incarnation tokens; here the relaunch hook IS a kill switch, so
    # the deterministic fix is the drill's own backoff policy)
    sup = Supervisor(nranks, start_fn=start_fn,
                     max_restarts=max_restarts,
                     backoff=Backoff(base=float(lease_ttl) + 1.0,
                                     factor=2.0, jitter=0),
                     poll_interval=0.2)
    from paddle_tpu.distributed.launch import RestartBudgetExceeded

    t0 = time.monotonic()
    try:
        rc = sup.run()
    except RestartBudgetExceeded as e:
        # deaths outran the budget: still report (the counter table is
        # the point of a failed drill), just never as "ok"
        print(f"chaos drill: {e}", file=sys.stderr)
        rc = -1
    finally:
        srv.stop()
    wall = time.monotonic() - t0

    rows = {r: _read_log(p) for r, p in logs.items()}
    finals = {r: [row for row in rs if row["kind"] == "final"]
              for r, rs in rows.items()}
    starts = {r: [row for row in rs if row["kind"] == "start"]
              for r, rs in rows.items()}
    report = {
        "rc": rc,
        "wall_s": round(wall, 1),
        "supervisor": sup.stats(),
        "loss_hex": {r: (f[-1]["loss_hex"] if f else None)
                     for r, f in finals.items()},
        "loss": {r: (f[-1]["loss"] if f else None)
                 for r, f in finals.items()},
        "generation": {r: (f[-1]["generation"] if f else None)
                       for r, f in finals.items()},
        "counters": {r: (f[-1]["counters"] if f else {})
                     for r, f in finals.items()},
        "resume": {r: [{k: s[k] for k in
                        ("restored_epoch", "restored_batch", "exe_step")}
                       for s in starts[r]] for r in rows},
        "batches_trained": {r: sum(1 for row in rs
                                   if row["kind"] == "batch")
                            for r, rs in rows.items()},
    }
    hexes = [h for h in report["loss_hex"].values() if h]
    report["parity_bitwise"] = (len(hexes) == nranks
                                and len(set(hexes)) == 1)
    report["generation_bumped"] = any(
        (g or 0) > 0 for g in report["generation"].values())
    report["flightrec"] = _flightrec_report(workdir)
    survivor = next((r for r in range(nranks) if r != kill_rank), 0)
    report["ok"] = bool(
        rc == 0 and report["parity_bitwise"]
        and (not kill or (report["generation_bumped"]
                          and sup.stats()["restarts_by_rank"]
                          .get(kill_rank, 0) >= 1
                          and report["counters"][survivor]
                          .get("worker_lost", 0) >= 1
                          # postmortem contract: the killed rank left a
                          # flight-recorder dump naming its killer
                          and report["flightrec"]["dumps"] >= 1
                          and report["flightrec"]["names_killer"])))
    return report


def _print_table(report: dict) -> None:
    print(f"\nchaos drill: rc={report['rc']} wall={report['wall_s']}s "
          f"supervisor={report['supervisor']}")
    print(f"{'rank':>4} {'final loss':>12} {'loss hex':>10} "
          f"{'gen':>4} {'batches':>8}  resume")
    for r in sorted(report["loss"]):
        print(f"{r:>4} {report['loss'][r]!r:>12} "
              f"{report['loss_hex'][r] or '-':>10} "
              f"{report['generation'][r] if report['generation'][r] is not None else '-':>4} "
              f"{report['batches_trained'][r]:>8}  {report['resume'][r]}")
    names = sorted({k for c in report["counters"].values() for k in c})
    if names:
        print(f"\n{'counter':<24}" + "".join(
            f"rank{r:>2} " for r in sorted(report["counters"])))
        for n in names:
            print(f"{n:<24}" + "".join(
                f"{report['counters'][r].get(n, 0):>6} "
                for r in sorted(report["counters"])))
    print(f"flightrec={report.get('flightrec')}")
    print(f"\nparity_bitwise={report['parity_bitwise']} "
          f"generation_bumped={report['generation_bumped']} "
          f"ok={report['ok']}")


# ---------------------------------------------------------------------------
# the PS drill (ISSUE 8): kill-a-primary, promote, fail over, rejoin
# ---------------------------------------------------------------------------

def ps_server_main() -> int:
    """Supervised pserver subprocess: env-driven replicated bootstrap
    (restore + rejoin happen inside run_server)."""
    from paddle_tpu.ps.server import run_server

    run_server(block=True)
    return 0


def _push_stream(dim: int, pushes: int, rows: int):
    """The deterministic gradient stream both the drill and its oracle
    consume: (ids, grads, lr) per push."""
    import numpy as np

    for i in range(pushes):
        rng = np.random.RandomState(1000 + i)
        ids = rng.randint(0, 200, (rows,)).astype(np.int64)
        grads = rng.randn(rows, dim).astype(np.float32)
        yield ids, grads, 0.05


def run_ps_drill(workdir: str, dim: int = 8, pushes: int = 12,
                 rows: int = 16, kill_after: int = 5,
                 snapshot_every: int = 3, lease_ttl: float = 3.0,
                 max_restarts: int = 1, sync: bool = True,
                 kill: bool = True, rejoin_wait: float = 60.0) -> dict:
    """Run the kill-a-primary drill; returns a report dict.

    ``kill_after=K`` kills the primary at its (K+1)-th applied write.
    Pick K inside [snapshot_every, pushes) so the death lands mid-stream
    with at least one snapshot committed. The re-armed env spec in the
    relaunched process never re-fires: the relaunch rejoins as a BACKUP,
    and backups apply forwards through the replication channel, which
    bypasses the ``ps.apply`` client-write fault point.
    """
    import threading

    import numpy as np

    from paddle_tpu import profiler
    from paddle_tpu.distributed.http_kv import KVClient, KVServer
    from paddle_tpu.distributed.launch import Supervisor
    from paddle_tpu.fault.retry import Backoff
    from paddle_tpu.ps.replication import (
        ReplicaCoordinator, ReplicatedPSServer, _RawPeer, fetch_shard_map,
        local_digest, verify_replicas,
    )
    from paddle_tpu.ps.service import PSClient, table_digest
    from paddle_tpu.ps.table import SparseTable

    os.makedirs(workdir, exist_ok=True)
    _clean_flightrec(workdir)
    job = "psdrill"
    counters0 = profiler.counters_snapshot()
    kv_port = _free_port()
    kvs = KVServer(kv_port)
    kvs.start()
    kv_ep = f"127.0.0.1:{kv_port}"
    kv = KVClient(kv_ep)

    port_a, port_b = _free_port(), _free_port()
    ep_a, ep_b = f"127.0.0.1:{port_a}", f"127.0.0.1:{port_b}"

    coord = ReplicaCoordinator(kv, job=job, lease_ttl=lease_ttl,
                               interval=0.2, boot_grace=60.0)
    coord.publish([[ep_a, ep_b]], sync=sync)

    mk_table = lambda: {0: SparseTable(dim, optimizer="sgd")}  # noqa: E731
    srv_b = ReplicatedPSServer(
        mk_table(), kv, job=job, port=port_b, lease_ttl=lease_ttl,
        snapshot_dir=os.path.join(workdir, "B"),
        snapshot_every=snapshot_every).start()

    def env_for(rank):
        env = dict(os.environ)
        env.update({
            "PYTHONPATH": _REPO,
            "JAX_PLATFORMS": env.get("JAX_PLATFORMS", "cpu"),
            "PADDLE_PORT": str(port_a),
            "PADDLE_PS_KV_ENDPOINT": kv_ep,
            "PADDLE_PS_JOB": job,
            "PADDLE_PS_TABLES": f"0:{dim}:sgd",
            "PADDLE_PS_SNAPSHOT_DIR": os.path.join(workdir, "A"),
            "PADDLE_PS_SNAPSHOT_EVERY": str(snapshot_every),
            "PADDLE_PS_LEASE_TTL": repr(lease_ttl),
            "PADDLE_PS_SYNC": "1" if sync else "0",
            "PADDLE_PS_EXIT_ON_CRASH": "1",
            "PADDLE_FLIGHTREC_DIR": _flightrec_dir(workdir),
        })
        if kill:
            env["PADDLE_FAULT_SPEC"] = (
                f"ps.apply:1@{kill_after}:SystemExit")
        else:
            env.pop("PADDLE_FAULT_SPEC", None)
        return env

    def start_fn(rank):
        return subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--ps-server"],
            env=env_for(rank))

    sup = Supervisor(1, start_fn=start_fn, max_restarts=max_restarts,
                     backoff=Backoff(base=0.5, factor=2.0, jitter=0),
                     poll_interval=0.2)
    sup_rc = {}

    def sup_run():
        try:
            sup_rc["rc"] = sup.run()
        except BaseException as e:  # noqa: B036 (reported, not masked)
            sup_rc["error"] = repr(e)

    sup_thread = threading.Thread(target=sup_run, daemon=True)
    sup_thread.start()
    coord.start()

    t0 = time.monotonic()
    report = {"ok": False, "kill": kill}
    try:
        # wait for A's first lease (its heavy jax import dominates)
        kv.wait(f"ps/{job}/lease/{ep_a}", timeout=120.0)

        client = PSClient(kv=kv, job=job, failover_timeout=60.0)
        oracle = SparseTable(dim, optimizer="sgd")   # never-killed ref
        touched = set()
        for ids, grads, lr in _push_stream(dim, pushes, rows):
            client.push(0, ids, grads, dim, lr)
            oracle.push(ids, grads, lr)
            touched.update(int(i) for i in ids)

        all_ids = np.array(sorted(touched), np.int64)
        final = client.pull(0, all_ids, dim)
        report["final_digest"] = final.tobytes().hex()[:32]
        report["expected_digest"] = (
            oracle.pull(all_ids).tobytes().hex()[:32])
        report["parity_bitwise"] = (
            report["final_digest"] == report["expected_digest"])
        m = fetch_shard_map(kv, job)
        report["epoch"] = m.epoch
        report["groups"] = m.groups
        report["client_epoch"] = client.epoch

        # the relaunched replica must reconverge: same seq, same digest
        deadline = time.monotonic() + (rejoin_wait if kill else 1.0)
        converged = False
        while time.monotonic() < deadline:
            probe = _RawPeer(ep_a)
            try:
                seq_a, _ = probe.seq_epoch()
            except (ConnectionError, OSError):
                time.sleep(0.3)
                continue
            finally:
                probe.close()
            if seq_a == srv_b.seq:
                converged = True
                break
            time.sleep(0.3)
        report["replicas_converged"] = converged
        report["seq"] = {"A": (seq_a if converged else None),
                         "B": srv_b.seq}
        if converged:
            verify_replicas(m)   # raises ReplicaDiverged on mismatch
            try:
                dig_a = _RawPeer(ep_a).digest(0).hex()
            except (ConnectionError, OSError):
                dig_a = None
            report["digest_parity"] = (
                dig_a == table_digest(srv_b.tables[0]).hex())
        client.stop_heartbeat()
        client.close()
    except BaseException as e:  # noqa: B036 (the report IS the output)
        report["error"] = repr(e)
    finally:
        coord.stop()
        sup.request_stop()
        sup_thread.join(timeout=45)
        srv_b.stop()
        kvs.stop()
    report["wall_s"] = round(time.monotonic() - t0, 1)
    report["supervisor"] = sup.stats()
    report["supervisor_rc"] = sup_rc
    delta = {k: v - counters0.get(k, 0)
             for k, v in profiler.counters_snapshot().items()}
    from paddle_tpu.profiler import PS_COUNTER_NAMES

    report["counters"] = {n: delta.get(n, 0) for n in PS_COUNTER_NAMES}
    report["promotions"] = coord.promotions
    report["flightrec"] = _flightrec_report(workdir)
    report["ok"] = bool(
        "error" not in report
        and report.get("parity_bitwise")
        and report.get("replicas_converged")
        and (not kill or (
            report["counters"]["ps_failovers"] >= 1
            and report["counters"]["ps_promotions"] >= 1
            and report.get("epoch", 1) >= 2
            and report.get("digest_parity")
            and sup.stats()["restarts_by_rank"].get(0, 0) >= 1
            # postmortem contract: the killed primary left a dump
            # whose last events name the injected SystemExit
            and report["flightrec"]["dumps"] >= 1
            and report["flightrec"]["names_killer"])))
    return report


def _print_ps_table(report: dict) -> None:
    print(f"\nps chaos drill: kill={report['kill']} "
          f"wall={report['wall_s']}s supervisor={report['supervisor']}")
    if "error" in report:
        print(f"ERROR: {report['error']}")
    print(f"epoch={report.get('epoch')} groups={report.get('groups')}")
    print(f"final    {report.get('final_digest')}")
    print(f"expected {report.get('expected_digest')}  "
          f"parity_bitwise={report.get('parity_bitwise')}")
    print(f"seq={report.get('seq')} "
          f"replicas_converged={report.get('replicas_converged')} "
          f"digest_parity={report.get('digest_parity')}")
    from tools.metrics_watch import format_counter_table

    print("\n" + format_counter_table(report.get("counters", {}),
                                      name_width=24))
    print(f"flightrec={report.get('flightrec')}")
    print(f"\nok={report['ok']}")


def ps_main(argv) -> int:
    ap = argparse.ArgumentParser(
        description="deterministic PS kill-a-primary chaos drill")
    ap.add_argument("--workdir", default="/tmp/paddle_tpu_ps_drill")
    ap.add_argument("--dim", type=int, default=8)
    ap.add_argument("--pushes", type=int, default=12)
    ap.add_argument("--rows", type=int, default=16)
    ap.add_argument("--kill-after", type=int, default=5)
    ap.add_argument("--snapshot-every", type=int, default=3)
    # 3.0s matches the elastic drill's proven-stable TTL on the noisy
    # 2-core CI box: a shorter lease can expire SPURIOUSLY when the
    # GIL-starved parent delays serving a renewal, promoting the backup
    # before the kill even lands (the drill then exercises the fence
    # path instead of the crash-failover path it asserts)
    ap.add_argument("--lease-ttl", type=float, default=3.0)
    ap.add_argument("--max-restarts", type=int, default=1)
    ap.add_argument("--async-repl", action="store_true",
                    help="async replication (bounded lag) instead of sync")
    ap.add_argument("--no-kill", action="store_true",
                    help="clean baseline: same harness, no fault spec")
    args = ap.parse_args(argv)
    report = run_ps_drill(
        args.workdir, dim=args.dim, pushes=args.pushes, rows=args.rows,
        kill_after=args.kill_after, snapshot_every=args.snapshot_every,
        lease_ttl=args.lease_ttl, max_restarts=args.max_restarts,
        sync=not args.async_repl, kill=not args.no_kill)
    _print_ps_table(report)
    return 0 if report["ok"] else 1


# ---------------------------------------------------------------------------
# the fleet drill (ISSUE 17): SIGKILL a decode engine under live traffic
# ---------------------------------------------------------------------------

def fleet_engine_main() -> int:
    """One fleet member: a decode engine + its HTTP surface, env-driven.
    Lives until SIGTERM (drained by ``install_sigterm_drain`` — the
    zero-lost shutdown) or SIGKILL (the chaos)."""
    from paddle_tpu.inference.decode import DecodeEngine, DecodeModelConfig
    from paddle_tpu.inference.serving import install_sigterm_drain
    from paddle_tpu.serving import DecodeEngineServer

    env = os.environ
    cfg = DecodeModelConfig(
        vocab_size=int(env["FLEET_VOCAB"]),
        n_layers=int(env["FLEET_LAYERS"]),
        n_heads=int(env["FLEET_HEADS"]),
        head_dim=int(env["FLEET_HEAD_DIM"]),
        ffn_dim=int(env["FLEET_FFN"]),
        max_context=int(env["FLEET_PAGES_PER_SEQ"])
        * int(env["FLEET_PAGE_SIZE"]))
    engine = DecodeEngine(
        cfg, seed=int(env["FLEET_SEED"]),
        n_pages=int(env["FLEET_PAGES"]),
        page_size=int(env["FLEET_PAGE_SIZE"]),
        max_pages_per_seq=int(env["FLEET_PAGES_PER_SEQ"]),
        kv_codec=env.get("FLEET_KV_CODEC", "int8"))
    engine.warm()
    engine.start()
    srv = DecodeEngineServer(engine, port=int(env["FLEET_PORT"]))
    srv.start()
    install_sigterm_drain(engine, exit_code=0)
    with open(env["FLEET_LOG"], "a") as f:
        f.write(json.dumps({"kind": "ready", "pid": os.getpid(),
                            "port": srv.port}) + "\n")
    while True:   # the parent owns this process's death
        time.sleep(3600)


def _http_get(endpoint: str, path: str, timeout: float = 2.0):
    """(status, body) — raises OSError family when the port is dead."""
    import http.client

    host, _, port = endpoint.rpartition(":")
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def _wait_ready(endpoint: str, timeout: float = 180.0) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            status, _ = _http_get(endpoint, "/readyz")
            if status == 200:
                return True
        except OSError:
            pass
        time.sleep(0.2)
    return False


def _port_dead(endpoint: str, timeout: float = 10.0) -> bool:
    """True once /readyz stops answering 200 — refused OR non-ready."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            status, _ = _http_get(endpoint, "/readyz")
            if status != 200:
                return True
        except OSError:
            return True
        time.sleep(0.1)
    return False


def run_fleet_drill(workdir: str, n_engines: int = 3, requests: int = 9,
                    chunk_tokens: int = 4, kill: bool = True,
                    kv_codec: str = "int8", seed: int = 11) -> dict:
    """SIGKILL one of ``n_engines`` decode engines mid-generation under
    live router traffic; assert the fleet absorbed it with zero lost,
    zero doubled, and every output bitwise equal to the never-killed
    dense oracle. Then run the KV-migration legs against a survivor
    (ship + dedupe + malformed reject + dead-endpoint fallback) and the
    fleet-wide SLO burn gate."""
    import threading

    import numpy as np

    from paddle_tpu import profiler
    from paddle_tpu.inference.decode import (DecodeModelConfig,
                                             init_decode_params,
                                             reference_generate)
    from paddle_tpu.observability.flight_recorder import flight_recorder
    from paddle_tpu.serving import (FleetRouter, HTTPReplica,
                                    MalformedPageFrame, MigrationClient,
                                    PrefillWorker, migration_cost)

    geom = {"FLEET_VOCAB": "64", "FLEET_LAYERS": "2",
            "FLEET_HEADS": "4", "FLEET_HEAD_DIM": "16",
            "FLEET_FFN": "128", "FLEET_PAGES": "64",
            "FLEET_PAGE_SIZE": "8", "FLEET_PAGES_PER_SEQ": "8"}
    cfg = DecodeModelConfig(
        vocab_size=64, n_layers=2, n_heads=4, head_dim=16, ffn_dim=128,
        max_context=64)
    params = init_decode_params(cfg, seed)   # the oracle's weights

    os.makedirs(workdir, exist_ok=True)
    _clean_flightrec(workdir)
    counters0 = profiler.counters_snapshot()
    log_path = os.path.join(workdir, "fleet.jsonl")
    if os.path.exists(log_path):
        os.remove(log_path)

    ports = [_free_port() for _ in range(n_engines)]
    endpoints = [f"127.0.0.1:{p}" for p in ports]

    def env_for(port):
        env = dict(os.environ)
        env.update(geom)
        env.update({
            "PYTHONPATH": _REPO,
            "JAX_PLATFORMS": env.get("JAX_PLATFORMS", "cpu"),
            "FLEET_PORT": str(port),
            "FLEET_SEED": str(seed),
            "FLEET_KV_CODEC": kv_codec,
            "FLEET_LOG": log_path,
            "PADDLE_FLIGHTREC_DIR": _flightrec_dir(workdir),
        })
        env.pop("PADDLE_FAULT_SPEC", None)
        return env

    procs = [subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--fleet-engine"],
        env=env_for(p)) for p in ports]

    t0 = time.monotonic()
    report: dict = {"ok": False, "kill": kill, "engines": n_engines,
                    "endpoints": endpoints}
    router = None
    try:
        for ep in endpoints:
            if not _wait_ready(ep):
                raise RuntimeError(f"engine {ep} never became ready")
        report["readyz_before"] = True

        router = FleetRouter([HTTPReplica(ep) for ep in endpoints],
                             chunk_tokens=chunk_tokens, config=cfg)

        # --- live traffic: deterministic prompts, zipf-free spread ---
        out_lens = (8, 12, 16)
        prompts = {}
        for i in range(requests):
            rng = np.random.RandomState(i)
            n = (6, 14, 10)[i % 3]
            prompts[i] = [int(t) for t in
                          rng.randint(0, cfg.vocab_size, size=n)]
        results: dict = {}
        errors: dict = {}

        def traffic(i):
            try:
                h = router.submit(prompts[i],
                                  max_new_tokens=out_lens[i % 3],
                                  session=f"s{i:02d}")
                results[i] = h.result(120.0)
            except BaseException as e:  # noqa: B036 (reported below)
                errors[i] = repr(e)

        threads = [threading.Thread(target=traffic, args=(i,),
                                    daemon=True)
                   for i in range(requests)]
        for t in threads:
            t.start()

        # --- the kill: SIGKILL the probe session's pinned engine the
        # moment its first chunk lands (mid-generation by construction)
        probe_rng = np.random.RandomState(999)
        probe_prompt = [int(t) for t in
                        probe_rng.randint(0, cfg.vocab_size, size=12)]
        victim_box: dict = {}
        killed = threading.Event()

        def killer(emitted):
            if kill and not killed.is_set():
                name = router.session_replica("probe")
                victim_box["endpoint"] = name
                procs[endpoints.index(name)].kill()   # SIGKILL, no grace
                killed.set()

        h_probe = router.submit(probe_prompt, max_new_tokens=24,
                                session="probe", on_chunk=killer)
        probe_tokens = h_probe.result(120.0)
        for t in threads:
            t.join(timeout=120.0)

        victim = victim_box.get("endpoint")
        report["victim"] = victim
        if kill:
            report["readyz_flipped"] = _port_dead(victim)

        # --- zero lost, zero doubled, bitwise oracle parity ---
        report["traffic_errors"] = errors
        report["lost"] = sorted(set(range(requests)) - set(results))
        report["probe_len"] = len(probe_tokens)
        probe_oracle = reference_generate(cfg, params, probe_prompt, 24)
        traffic_parity = all(
            results.get(i) == reference_generate(
                cfg, params, prompts[i], out_lens[i % 3])
            for i in range(requests))
        report["parity_bitwise"] = (probe_tokens == probe_oracle
                                    and traffic_parity)

        # --- KV migration legs against a survivor ---
        survivor = next(ep for ep in endpoints if ep != victim)
        report["survivor"] = survivor
        worker = PrefillWorker(cfg, params=params, page_size=8,
                               codec=kv_codec)
        mig_rng = np.random.RandomState(555)
        mig_prompt = [int(t) for t in
                      mig_rng.randint(0, cfg.vocab_size, size=24)]
        shipment = worker.prefill(mig_prompt)
        s_replica = HTTPReplica(survivor)

        def hits(ep):
            _, body = _http_get(ep, "/metrics", timeout=5.0)
            from paddle_tpu.observability.metrics import (
                parse_prometheus_text,
            )
            samples = parse_prometheus_text(body.decode())
            return sum(v for k, v in samples.items()
                       if k.split("{")[0] == "kv_prefix_hits")

        hits0 = hits(survivor)
        mig1 = MigrationClient(s_replica.adopt).migrate(shipment)
        report["migrate"] = {k: mig1.get(k) for k in
                            ("ok", "adopted", "shared", "pages",
                             "frame_bytes", "encoded_bytes",
                             "f32_bytes")}
        mig_tokens = s_replica.generate_chunk(mig_prompt, 8, None)
        report["migrate_parity"] = (
            mig_tokens == reference_generate(cfg, params,
                                             mig_prompt, 8))
        report["migrate_prefix_hits"] = hits(survivor) - hits0
        # shipping the same prefix again must DEDUPE, not duplicate
        mig2 = MigrationClient(s_replica.adopt).migrate(shipment)
        report["migrate_dedupe"] = {
            "adopted": mig2.get("adopted"), "shared": mig2.get("shared")}

        # malformed frame: typed reject at the wire, not a 500
        try:
            s_replica.adopt(shipment.frame[:-3])
            report["malformed_reject"] = False
        except MalformedPageFrame:
            report["malformed_reject"] = True

        # degrade leg: ship at the DEAD endpoint — retries burn, the
        # fallback counter ticks, and the request itself still serves
        # (local recompute; the user never sees the failed migration)
        fb_target = victim if kill else "127.0.0.1:1"
        fb = MigrationClient(HTTPReplica(fb_target).adopt,
                             max_attempts=2).migrate(shipment)
        report["fallback"] = {"ok": fb.get("ok"),
                              "reason": fb.get("reason")}
        fb_rng = np.random.RandomState(556)
        fb_prompt = [int(t) for t in
                     fb_rng.randint(0, cfg.vocab_size, size=16)]
        report["fallback_parity"] = (
            router.generate(fb_prompt, max_new_tokens=8)
            == reference_generate(cfg, params, fb_prompt, 8))

        # --- ship-vs-recompute: the toy model is honest (too small to
        # be worth shipping); the gate runs at a serving-scale shape
        report["cost_toy"] = migration_cost(cfg, len(mig_prompt),
                                            codec=kv_codec)
        serving_cfg = DecodeModelConfig(
            vocab_size=256_000, n_layers=48, n_heads=32, head_dim=128,
            ffn_dim=32_768, max_context=8192)
        report["cost_serving"] = migration_cost(serving_cfg, 2048,
                                                codec=kv_codec)

        # --- fleet-wide SLO burn gate over every surviving /metrics ---
        from tools import slo_check

        scrapes = []
        for ep in endpoints:
            if ep == victim:
                continue
            _, body = _http_get(ep, "/metrics", timeout=5.0)
            path = os.path.join(
                workdir, f"scrape_{ep.replace(':', '_')}.txt")
            with open(path, "w") as f:
                f.write(body.decode())
            scrapes.append(path)
        slo_argv = []
        for p in scrapes:
            slo_argv += ["--metrics", p]
        report["slo_rc"] = slo_check.main(slo_argv)

        # --- postmortem: the router named the kill; dump the ring ---
        os.makedirs(_flightrec_dir(workdir), exist_ok=True)
        flight_recorder().dump(
            reason="fleet_failover",
            path=os.path.join(_flightrec_dir(workdir),
                              f"flightrec_{os.getpid()}.json"))
    except BaseException as e:  # noqa: B036 (the report IS the output)
        report["error"] = repr(e)
    finally:
        if router is not None:
            try:
                router.drain(timeout=10.0)
            except Exception:
                pass
        for proc in procs:
            proc.terminate()
        for proc in procs:
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()
    report["wall_s"] = round(time.monotonic() - t0, 1)

    delta = {k: v - counters0.get(k, 0)
             for k, v in profiler.counters_snapshot().items()}
    report["counters"] = {
        n: delta.get(n, 0)
        for n in (*profiler.ROUTER_COUNTER_NAMES, "retry_attempts",
                  "retry_giveups", "kv_migration_fallbacks")}
    if router is not None:
        report["counters"].update(
            {k: v for k, v in router.counters.items()
             if k.startswith("router_")})

    dumps = _flightrec_report(workdir)
    victim = report.get("victim")
    names_kill = False
    d = _flightrec_dir(workdir)
    if os.path.isdir(d) and victim:
        for fn in os.listdir(d):
            if not fn.startswith("flightrec_"):
                continue
            try:
                with open(os.path.join(d, fn)) as f:
                    dump = json.load(f)
            except (OSError, ValueError):
                continue
            if dump.get("reason") == "fleet_failover" and any(
                    ev.get("kind") == "replica_dead"
                    and ev.get("replica") == victim
                    for ev in dump.get("events", [])):
                names_kill = True
    report["flightrec"] = {"dumps": dumps["dumps"],
                           "reasons": dumps["reasons"],
                           "names_kill": names_kill}

    ctr = report["counters"]
    report["ok"] = bool(
        "error" not in report
        and not report.get("lost")
        and not report.get("traffic_errors")
        and report.get("parity_bitwise")
        and report.get("migrate", {}).get("ok")
        and report.get("migrate_parity")
        and report.get("migrate_prefix_hits", 0) >= 1
        and report.get("migrate_dedupe", {}).get("adopted") == 0
        and report.get("migrate_dedupe", {}).get("shared", 0) >= 1
        and report.get("malformed_reject")
        and report.get("fallback", {}).get("ok") is False
        and report.get("fallback_parity")
        and ctr.get("kv_migration_fallbacks", 0) >= 1
        and report.get("cost_serving", {}).get("cheaper_to_ship")
        and report.get("slo_rc") == 0
        and (not kill or (report.get("readyz_flipped")
                          and ctr.get("router_failovers", 0) >= 1
                          and ctr.get("router_replays", 0) >= 1
                          and report["flightrec"]["names_kill"])))
    return report


def _print_fleet_table(report: dict) -> None:
    print(f"\nfleet chaos drill: kill={report['kill']} "
          f"engines={report.get('engines')} wall={report['wall_s']}s")
    if "error" in report:
        print(f"ERROR: {report['error']}")
    print(f"victim={report.get('victim')} "
          f"readyz_flipped={report.get('readyz_flipped')} "
          f"survivor={report.get('survivor')}")
    print(f"lost={report.get('lost')} "
          f"traffic_errors={report.get('traffic_errors')} "
          f"parity_bitwise={report.get('parity_bitwise')}")
    print(f"migrate={report.get('migrate')} "
          f"parity={report.get('migrate_parity')} "
          f"prefix_hits={report.get('migrate_prefix_hits')} "
          f"dedupe={report.get('migrate_dedupe')}")
    print(f"malformed_reject={report.get('malformed_reject')} "
          f"fallback={report.get('fallback')} "
          f"fallback_parity={report.get('fallback_parity')}")
    cost_t, cost_s = report.get("cost_toy", {}), \
        report.get("cost_serving", {})
    print(f"cost: toy cheaper_to_ship={cost_t.get('cheaper_to_ship')} "
          f"({cost_t.get('encoded_bytes')}B vs "
          f"{cost_t.get('flops_equiv_bytes')}B-equiv) | serving-scale "
          f"cheaper_to_ship={cost_s.get('cheaper_to_ship')} "
          f"({cost_s.get('encoded_bytes')}B vs "
          f"{cost_s.get('flops_equiv_bytes')}B-equiv, "
          f"saved {cost_s.get('bytes_saved_pct')}%)")
    print(f"slo_rc={report.get('slo_rc')} "
          f"flightrec={report.get('flightrec')}")
    from tools.metrics_watch import format_counter_table

    print("\n" + format_counter_table(report.get("counters", {}),
                                      name_width=28))
    print(f"\nok={report['ok']}")


def fleet_main(argv) -> int:
    ap = argparse.ArgumentParser(
        description="fleet decode drill: SIGKILL an engine under live "
                    "router traffic; assert failover, bitwise replay "
                    "parity, and the KV-migration legs")
    ap.add_argument("--workdir", default="/tmp/paddle_tpu_fleet_drill")
    ap.add_argument("--engines", type=int, default=3)
    ap.add_argument("--requests", type=int, default=9)
    ap.add_argument("--chunk-tokens", type=int, default=4)
    ap.add_argument("--kv-codec", default="int8",
                    choices=("off", "int8"))
    ap.add_argument("--no-kill", action="store_true",
                    help="clean baseline: same traffic, no SIGKILL")
    args = ap.parse_args(argv)
    report = run_fleet_drill(
        args.workdir, n_engines=args.engines, requests=args.requests,
        chunk_tokens=args.chunk_tokens, kv_codec=args.kv_codec,
        kill=not args.no_kill)
    _print_fleet_table(report)
    return 0 if report["ok"] else 1


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "--worker":
        return worker_main()
    if argv and argv[0] == "--ps-server":
        return ps_server_main()
    if argv and argv[0] == "--fleet-engine":
        return fleet_engine_main()
    if argv and argv[0] == "--ps":
        return ps_main(argv[1:])
    if argv and argv[0] == "--fleet":
        return fleet_main(argv[1:])
    ap = argparse.ArgumentParser(
        description="deterministic elastic kill/resume chaos drill")
    ap.add_argument("--workdir", default="/tmp/paddle_tpu_chaos_drill")
    ap.add_argument("--nranks", type=int, default=2)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batches", type=int, default=4)
    ap.add_argument("--save-every", type=int, default=2)
    ap.add_argument("--kill-rank", type=int, default=1)
    ap.add_argument("--kill-after", type=int, default=6)
    ap.add_argument("--max-restarts", type=int, default=2)
    ap.add_argument("--lease-ttl", type=float, default=3.0)
    ap.add_argument("--no-kill", action="store_true",
                    help="clean baseline: same job, no fault spec")
    args = ap.parse_args(argv)
    report = run_drill(args.workdir, nranks=args.nranks,
                       epochs=args.epochs, batches=args.batches,
                       save_every=args.save_every,
                       kill_rank=args.kill_rank,
                       kill_after=args.kill_after,
                       max_restarts=args.max_restarts,
                       lease_ttl=args.lease_ttl, kill=not args.no_kill)
    _print_table(report)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
