#!/usr/bin/env python
"""Deterministic chaos drills: elastic kill/resume (ISSUE 7) and
parameter-server kill-a-primary (ISSUE 8, ``--ps``).

PS drill (``--ps``): a KVServer comes up in-process; one 2-replica
group serves shard 0 — primary A as a SUPERVISED SUBPROCESS
(``launch.Supervisor``, the real relaunch path), backup B in-process.
The parent is the trainer: it pushes a deterministic gradient stream
through a replicated ``PSClient``. ``PADDLE_FAULT_SPEC=
ps.apply:1@K:SystemExit`` (armed only in A's env) kills A at its
(K+1)-th applied write — mid-stream, with snapshots already committed.
The ReplicaCoordinator observes A's lease expiry, promotes B (shard-map
epoch bump); the client fails over with typed errors only and REPLAYS
the in-flight push (write dedup makes the replay exactly-once); the
supervisor relaunches A, which restores its newest valid SnapshotStore
snapshot and catches up from B's delta log, rejoining as a backup. The
drill asserts: the final pull is BITWISE identical to the never-killed
reference (a local same-backend oracle table fed the same stream — in
sync replication mode zero updates may be lost or doubled), a promotion
and a failover really happened, the relaunched replica reconverged
(digest parity across the group), and the ``ps_*`` counter table.
"""
from __future__ import annotations

_ELASTIC_DOC = """Deterministic elastic-training chaos drill (ISSUE 7 crown test).

Promotes the PR 2 chaos recipe (arm a ``PADDLE_FAULT_SPEC``, supervise,
resume) to a tool that drives the WHOLE elastic story end to end with
real processes and real kills:

1. a KVServer comes up in-process; ``nranks`` trainer workers launch
   under ``launch.Supervisor`` relaunch supervision;
2. every worker rendezvous through ``distributed.elastic.ElasticAgent``
   into generation 0, holds a heartbeat lease, trains the same
   deterministic toy job with ``TrainEpochRange`` mid-epoch
   checkpointing, and barriers each epoch end;
3. ``PADDLE_FAULT_SPEC=drill.step:1@K:SystemExit`` kills ``kill_rank``
   mid-epoch at its (K+1)-th batch (the env spec re-arms per process;
   ``@after`` is what lets the relaunched incarnation run past it);
4. survivors observe the lease expiry as a typed ``WorkerLost``, bump
   the generation, and reform; the supervisor relaunches the dead rank,
   which resumes AT THE EXACT NEXT BATCH from its mid-epoch snapshot
   and rejoins the bumped generation;
5. the drill asserts the killed rank's final loss is **bitwise
   identical** to the never-killed rank 0's (both run the same
   deterministic schedule, so rank 0 *is* the uninterrupted run), that
   a generation bump really happened, and that exactly the expected
   relaunches were spent — then prints the counter table.

Usage::

    JAX_PLATFORMS=cpu python tools/chaos_drill.py [--workdir DIR]
        [--epochs 3] [--batches 4] [--kill-after 6] [--lease-ttl 3]

Exit code 0 = drill passed (bitwise parity + generation bump); the
counter table goes to stdout either way. ``--no-kill`` runs the same
job without the fault spec (a clean baseline of the harness itself).
"""

import argparse
import json
import os
import socket
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


# ---------------------------------------------------------------------------
# worker (runs in the supervised subprocesses)
# ---------------------------------------------------------------------------

def worker_main() -> int:
    import numpy as np

    import paddle_tpu.static as static
    from paddle_tpu import fault, profiler
    from paddle_tpu.distributed.elastic import ElasticAgent
    from paddle_tpu.incubate.checkpoint.auto_checkpoint import (
        TrainEpochRange,
    )

    rank = int(os.environ["PADDLE_TRAINER_ID"])
    world = int(os.environ["PADDLE_TRAINERS_NUM"])
    endpoint = os.environ["PADDLE_ELASTIC_ENDPOINT"]
    epochs = int(os.environ["DRILL_EPOCHS"])
    batches = int(os.environ["DRILL_BATCHES"])
    save_every = int(os.environ["DRILL_SAVE_EVERY"])
    kill_rank = int(os.environ.get("DRILL_KILL_RANK", "-1"))
    lease_ttl = float(os.environ.get("DRILL_LEASE_TTL", "3.0"))
    log_path = os.environ["DRILL_LOG"]
    h, b = 8, 8

    def log(kind, **fields):
        with open(log_path, "a") as f:
            f.write(json.dumps({"kind": kind, "rank": rank, **fields})
                    + "\n")

    main, startup = static.Program(), static.Program()
    main.random_seed = startup.random_seed = 1234
    with static.program_guard(main, startup):
        x = static.data("x", [-1, h])
        label = static.data("label", [-1, 1], dtype="int64")
        hid = static.nn.fc(x, 16, act="relu")
        hid = static.dropout(hid, dropout_prob=0.2)
        logits = static.nn.fc(hid, 4)
        loss = static.mean(static.softmax_with_cross_entropy(logits, label))
        static.SGD(0.05).minimize(loss)

    exe = static.Executor()
    exe.run(startup)
    cp = static.CompiledProgram(main)
    tr = TrainEpochRange(epochs, name=f"drill_r{rank}",
                         save_every_steps=save_every)
    tr.register(executor=exe, program=main)
    log("start", restored_epoch=tr.restored_epoch,
        restored_batch=tr.restored_batch, exe_step=exe._step)

    agent = ElasticAgent(endpoint, rank, world, job="drill",
                         lease_ttl=lease_ttl)
    agent.join(timeout=240.0)
    agent.start_heartbeat()

    def reader(epoch):
        def gen():
            for i in range(batches):
                rng = np.random.RandomState(epoch * 100 + i)
                yield {"x": rng.randn(b, h).astype(np.float32),
                       "label": rng.randint(0, 4, (b, 1)).astype(np.int64)}
        return gen

    last = None
    for epoch in tr.get():
        for i, batch in tr.steps(epoch, reader(epoch)):
            if rank == kill_rank:
                # the armed PADDLE_FAULT_SPEC decides which visit dies
                fault.point("drill.step")
            out = exe.run(cp, feed=batch, fetch_list=[loss])
            last = np.ravel(out[0]).astype(np.float32)
            log("batch", epoch=epoch, batch=i, step=exe._step - 1,
                loss=float(last[0]))
        agent.synchronize(f"epoch{epoch}", timeout=240.0, max_reforms=3)
    agent.stop_heartbeat()

    counters = {k: v for k, v in profiler.counters_snapshot().items()
                if k in profiler.ELASTIC_COUNTER_NAMES
                or k in profiler.FAULT_COUNTER_NAMES}
    log("final", loss=float(last[0]), loss_hex=last.tobytes().hex(),
        generation=agent.generation, counters=counters)
    return 0


# ---------------------------------------------------------------------------
# the drill (parent process)
# ---------------------------------------------------------------------------

def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _read_log(path):
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(ln) for ln in f if ln.strip()]


def _flightrec_dir(workdir: str) -> str:
    return os.path.join(workdir, "flightrec")


def _clean_flightrec(workdir: str) -> None:
    d = _flightrec_dir(workdir)
    if os.path.isdir(d):
        for fn in os.listdir(d):
            if fn.startswith("flightrec_"):
                os.remove(os.path.join(d, fn))


def _flightrec_report(workdir: str, error_name: str = "SystemExit") -> dict:
    """Scan the drill's flight-recorder dumps: the postmortem contract
    is that a killed process left a dump whose LAST recorded events
    name the typed error that killed it."""
    d = _flightrec_dir(workdir)
    dumps = []
    if os.path.isdir(d):
        for fn in sorted(os.listdir(d)):
            if fn.startswith("flightrec_") and fn.endswith(".json"):
                try:
                    with open(os.path.join(d, fn)) as f:
                        dumps.append(json.load(f))
                except (OSError, ValueError):
                    pass
    names_killer = any(
        ev.get("error") == error_name
        for dump in dumps for ev in dump.get("events", [])[-3:])
    return {"dumps": len(dumps),
            "reasons": [dump.get("reason") for dump in dumps],
            "names_killer": names_killer}


def run_drill(workdir: str, nranks: int = 2, epochs: int = 3,
              batches: int = 4, save_every: int = 2, kill_rank: int = 1,
              kill_after: int = 6, max_restarts: int = 2,
              lease_ttl: float = 3.0, kill: bool = True) -> dict:
    """Run the drill; returns a report dict (see keys in `main`).

    ``kill_after=K`` kills ``kill_rank`` at its (K+1)-th training batch
    — pick K so the death lands mid-epoch and the relaunched
    incarnation has fewer than K batches left (the re-armed env spec
    then never re-fires, per the ``@after`` skip count).
    """
    from paddle_tpu.distributed.http_kv import KVServer
    from paddle_tpu.distributed.launch import Supervisor
    from paddle_tpu.fault.retry import Backoff

    os.makedirs(workdir, exist_ok=True)
    port = _free_port()
    srv = KVServer(port)
    srv.start()

    logs = {r: os.path.join(workdir, f"rank{r}.jsonl")
            for r in range(nranks)}
    for p in logs.values():
        if os.path.exists(p):
            os.remove(p)
    _clean_flightrec(workdir)

    def env_for(rank):
        env = dict(os.environ)
        env.update({
            "PYTHONPATH": _REPO,
            "JAX_PLATFORMS": env.get("JAX_PLATFORMS", "cpu"),
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(nranks),
            "PADDLE_ELASTIC_ENDPOINT": f"127.0.0.1:{port}",
            "PADDLE_AUTO_CHECKPOINT_PATH": os.path.join(workdir, "ckpt"),
            "DRILL_EPOCHS": str(epochs),
            "DRILL_BATCHES": str(batches),
            "DRILL_SAVE_EVERY": str(save_every),
            "DRILL_KILL_RANK": str(kill_rank if kill else -1),
            "DRILL_LEASE_TTL": repr(lease_ttl),
            "DRILL_LOG": logs[rank],
            # every worker dumps a crash postmortem here; the report
            # asserts the killed rank's dump names the SystemExit
            "PADDLE_FLIGHTREC_DIR": _flightrec_dir(workdir),
        })
        if kill:
            env["PADDLE_FAULT_SPEC"] = (
                f"drill.step:1@{kill_after}:SystemExit")
        else:
            env.pop("PADDLE_FAULT_SPEC", None)
        return env

    def start_fn(rank):
        return subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker"],
            env=env_for(rank))

    # relaunch backoff WIDER than the lease TTL: the drill exercises the
    # lease-expiry -> WorkerLost -> generation-bump path, and a relaunch
    # that re-leases the same (generation, rank) key before the TTL
    # sweep observes the gap reads as continuity — the survivors never
    # reform and the bump assertion goes flaky (the same
    # relaunch-beats-the-sweep race the PS coordinator closes with lease
    # incarnation tokens; here the relaunch hook IS a kill switch, so
    # the deterministic fix is the drill's own backoff policy)
    sup = Supervisor(nranks, start_fn=start_fn,
                     max_restarts=max_restarts,
                     backoff=Backoff(base=float(lease_ttl) + 1.0,
                                     factor=2.0, jitter=0),
                     poll_interval=0.2)
    from paddle_tpu.distributed.launch import RestartBudgetExceeded

    t0 = time.monotonic()
    try:
        rc = sup.run()
    except RestartBudgetExceeded as e:
        # deaths outran the budget: still report (the counter table is
        # the point of a failed drill), just never as "ok"
        print(f"chaos drill: {e}", file=sys.stderr)
        rc = -1
    finally:
        srv.stop()
    wall = time.monotonic() - t0

    rows = {r: _read_log(p) for r, p in logs.items()}
    finals = {r: [row for row in rs if row["kind"] == "final"]
              for r, rs in rows.items()}
    starts = {r: [row for row in rs if row["kind"] == "start"]
              for r, rs in rows.items()}
    report = {
        "rc": rc,
        "wall_s": round(wall, 1),
        "supervisor": sup.stats(),
        "loss_hex": {r: (f[-1]["loss_hex"] if f else None)
                     for r, f in finals.items()},
        "loss": {r: (f[-1]["loss"] if f else None)
                 for r, f in finals.items()},
        "generation": {r: (f[-1]["generation"] if f else None)
                       for r, f in finals.items()},
        "counters": {r: (f[-1]["counters"] if f else {})
                     for r, f in finals.items()},
        "resume": {r: [{k: s[k] for k in
                        ("restored_epoch", "restored_batch", "exe_step")}
                       for s in starts[r]] for r in rows},
        "batches_trained": {r: sum(1 for row in rs
                                   if row["kind"] == "batch")
                            for r, rs in rows.items()},
    }
    hexes = [h for h in report["loss_hex"].values() if h]
    report["parity_bitwise"] = (len(hexes) == nranks
                                and len(set(hexes)) == 1)
    report["generation_bumped"] = any(
        (g or 0) > 0 for g in report["generation"].values())
    report["flightrec"] = _flightrec_report(workdir)
    survivor = next((r for r in range(nranks) if r != kill_rank), 0)
    report["ok"] = bool(
        rc == 0 and report["parity_bitwise"]
        and (not kill or (report["generation_bumped"]
                          and sup.stats()["restarts_by_rank"]
                          .get(kill_rank, 0) >= 1
                          and report["counters"][survivor]
                          .get("worker_lost", 0) >= 1
                          # postmortem contract: the killed rank left a
                          # flight-recorder dump naming its killer
                          and report["flightrec"]["dumps"] >= 1
                          and report["flightrec"]["names_killer"])))
    return report


def _print_table(report: dict) -> None:
    print(f"\nchaos drill: rc={report['rc']} wall={report['wall_s']}s "
          f"supervisor={report['supervisor']}")
    print(f"{'rank':>4} {'final loss':>12} {'loss hex':>10} "
          f"{'gen':>4} {'batches':>8}  resume")
    for r in sorted(report["loss"]):
        print(f"{r:>4} {report['loss'][r]!r:>12} "
              f"{report['loss_hex'][r] or '-':>10} "
              f"{report['generation'][r] if report['generation'][r] is not None else '-':>4} "
              f"{report['batches_trained'][r]:>8}  {report['resume'][r]}")
    names = sorted({k for c in report["counters"].values() for k in c})
    if names:
        print(f"\n{'counter':<24}" + "".join(
            f"rank{r:>2} " for r in sorted(report["counters"])))
        for n in names:
            print(f"{n:<24}" + "".join(
                f"{report['counters'][r].get(n, 0):>6} "
                for r in sorted(report["counters"])))
    print(f"flightrec={report.get('flightrec')}")
    print(f"\nparity_bitwise={report['parity_bitwise']} "
          f"generation_bumped={report['generation_bumped']} "
          f"ok={report['ok']}")


# ---------------------------------------------------------------------------
# the PS drill (ISSUE 8): kill-a-primary, promote, fail over, rejoin
# ---------------------------------------------------------------------------

def ps_server_main() -> int:
    """Supervised pserver subprocess: env-driven replicated bootstrap
    (restore + rejoin happen inside run_server)."""
    from paddle_tpu.ps.server import run_server

    run_server(block=True)
    return 0


def _push_stream(dim: int, pushes: int, rows: int):
    """The deterministic gradient stream both the drill and its oracle
    consume: (ids, grads, lr) per push."""
    import numpy as np

    for i in range(pushes):
        rng = np.random.RandomState(1000 + i)
        ids = rng.randint(0, 200, (rows,)).astype(np.int64)
        grads = rng.randn(rows, dim).astype(np.float32)
        yield ids, grads, 0.05


def run_ps_drill(workdir: str, dim: int = 8, pushes: int = 12,
                 rows: int = 16, kill_after: int = 5,
                 snapshot_every: int = 3, lease_ttl: float = 3.0,
                 max_restarts: int = 1, sync: bool = True,
                 kill: bool = True, rejoin_wait: float = 60.0) -> dict:
    """Run the kill-a-primary drill; returns a report dict.

    ``kill_after=K`` kills the primary at its (K+1)-th applied write.
    Pick K inside [snapshot_every, pushes) so the death lands mid-stream
    with at least one snapshot committed. The re-armed env spec in the
    relaunched process never re-fires: the relaunch rejoins as a BACKUP,
    and backups apply forwards through the replication channel, which
    bypasses the ``ps.apply`` client-write fault point.
    """
    import threading

    import numpy as np

    from paddle_tpu import profiler
    from paddle_tpu.distributed.http_kv import KVClient, KVServer
    from paddle_tpu.distributed.launch import Supervisor
    from paddle_tpu.fault.retry import Backoff
    from paddle_tpu.ps.replication import (
        ReplicaCoordinator, ReplicatedPSServer, _RawPeer, fetch_shard_map,
        local_digest, verify_replicas,
    )
    from paddle_tpu.ps.service import PSClient, table_digest
    from paddle_tpu.ps.table import SparseTable

    os.makedirs(workdir, exist_ok=True)
    _clean_flightrec(workdir)
    job = "psdrill"
    counters0 = profiler.counters_snapshot()
    kv_port = _free_port()
    kvs = KVServer(kv_port)
    kvs.start()
    kv_ep = f"127.0.0.1:{kv_port}"
    kv = KVClient(kv_ep)

    port_a, port_b = _free_port(), _free_port()
    ep_a, ep_b = f"127.0.0.1:{port_a}", f"127.0.0.1:{port_b}"

    coord = ReplicaCoordinator(kv, job=job, lease_ttl=lease_ttl,
                               interval=0.2, boot_grace=60.0)
    coord.publish([[ep_a, ep_b]], sync=sync)

    mk_table = lambda: {0: SparseTable(dim, optimizer="sgd")}  # noqa: E731
    srv_b = ReplicatedPSServer(
        mk_table(), kv, job=job, port=port_b, lease_ttl=lease_ttl,
        snapshot_dir=os.path.join(workdir, "B"),
        snapshot_every=snapshot_every).start()

    def env_for(rank):
        env = dict(os.environ)
        env.update({
            "PYTHONPATH": _REPO,
            "JAX_PLATFORMS": env.get("JAX_PLATFORMS", "cpu"),
            "PADDLE_PORT": str(port_a),
            "PADDLE_PS_KV_ENDPOINT": kv_ep,
            "PADDLE_PS_JOB": job,
            "PADDLE_PS_TABLES": f"0:{dim}:sgd",
            "PADDLE_PS_SNAPSHOT_DIR": os.path.join(workdir, "A"),
            "PADDLE_PS_SNAPSHOT_EVERY": str(snapshot_every),
            "PADDLE_PS_LEASE_TTL": repr(lease_ttl),
            "PADDLE_PS_SYNC": "1" if sync else "0",
            "PADDLE_PS_EXIT_ON_CRASH": "1",
            "PADDLE_FLIGHTREC_DIR": _flightrec_dir(workdir),
        })
        if kill:
            env["PADDLE_FAULT_SPEC"] = (
                f"ps.apply:1@{kill_after}:SystemExit")
        else:
            env.pop("PADDLE_FAULT_SPEC", None)
        return env

    def start_fn(rank):
        return subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--ps-server"],
            env=env_for(rank))

    sup = Supervisor(1, start_fn=start_fn, max_restarts=max_restarts,
                     backoff=Backoff(base=0.5, factor=2.0, jitter=0),
                     poll_interval=0.2)
    sup_rc = {}

    def sup_run():
        try:
            sup_rc["rc"] = sup.run()
        except BaseException as e:  # noqa: B036 (reported, not masked)
            sup_rc["error"] = repr(e)

    sup_thread = threading.Thread(target=sup_run, daemon=True)
    sup_thread.start()
    coord.start()

    t0 = time.monotonic()
    report = {"ok": False, "kill": kill}
    try:
        # wait for A's first lease (its heavy jax import dominates)
        kv.wait(f"ps/{job}/lease/{ep_a}", timeout=120.0)

        client = PSClient(kv=kv, job=job, failover_timeout=60.0)
        oracle = SparseTable(dim, optimizer="sgd")   # never-killed ref
        touched = set()
        for ids, grads, lr in _push_stream(dim, pushes, rows):
            client.push(0, ids, grads, dim, lr)
            oracle.push(ids, grads, lr)
            touched.update(int(i) for i in ids)

        all_ids = np.array(sorted(touched), np.int64)
        final = client.pull(0, all_ids, dim)
        report["final_digest"] = final.tobytes().hex()[:32]
        report["expected_digest"] = (
            oracle.pull(all_ids).tobytes().hex()[:32])
        report["parity_bitwise"] = (
            report["final_digest"] == report["expected_digest"])
        m = fetch_shard_map(kv, job)
        report["epoch"] = m.epoch
        report["groups"] = m.groups
        report["client_epoch"] = client.epoch

        # the relaunched replica must reconverge: same seq, same digest
        deadline = time.monotonic() + (rejoin_wait if kill else 1.0)
        converged = False
        while time.monotonic() < deadline:
            probe = _RawPeer(ep_a)
            try:
                seq_a, _ = probe.seq_epoch()
            except (ConnectionError, OSError):
                time.sleep(0.3)
                continue
            finally:
                probe.close()
            if seq_a == srv_b.seq:
                converged = True
                break
            time.sleep(0.3)
        report["replicas_converged"] = converged
        report["seq"] = {"A": (seq_a if converged else None),
                         "B": srv_b.seq}
        if converged:
            verify_replicas(m)   # raises ReplicaDiverged on mismatch
            try:
                dig_a = _RawPeer(ep_a).digest(0).hex()
            except (ConnectionError, OSError):
                dig_a = None
            report["digest_parity"] = (
                dig_a == table_digest(srv_b.tables[0]).hex())
        client.stop_heartbeat()
        client.close()
    except BaseException as e:  # noqa: B036 (the report IS the output)
        report["error"] = repr(e)
    finally:
        coord.stop()
        sup.request_stop()
        sup_thread.join(timeout=45)
        srv_b.stop()
        kvs.stop()
    report["wall_s"] = round(time.monotonic() - t0, 1)
    report["supervisor"] = sup.stats()
    report["supervisor_rc"] = sup_rc
    delta = {k: v - counters0.get(k, 0)
             for k, v in profiler.counters_snapshot().items()}
    from paddle_tpu.profiler import PS_COUNTER_NAMES

    report["counters"] = {n: delta.get(n, 0) for n in PS_COUNTER_NAMES}
    report["promotions"] = coord.promotions
    report["flightrec"] = _flightrec_report(workdir)
    report["ok"] = bool(
        "error" not in report
        and report.get("parity_bitwise")
        and report.get("replicas_converged")
        and (not kill or (
            report["counters"]["ps_failovers"] >= 1
            and report["counters"]["ps_promotions"] >= 1
            and report.get("epoch", 1) >= 2
            and report.get("digest_parity")
            and sup.stats()["restarts_by_rank"].get(0, 0) >= 1
            # postmortem contract: the killed primary left a dump
            # whose last events name the injected SystemExit
            and report["flightrec"]["dumps"] >= 1
            and report["flightrec"]["names_killer"])))
    return report


def _print_ps_table(report: dict) -> None:
    print(f"\nps chaos drill: kill={report['kill']} "
          f"wall={report['wall_s']}s supervisor={report['supervisor']}")
    if "error" in report:
        print(f"ERROR: {report['error']}")
    print(f"epoch={report.get('epoch')} groups={report.get('groups')}")
    print(f"final    {report.get('final_digest')}")
    print(f"expected {report.get('expected_digest')}  "
          f"parity_bitwise={report.get('parity_bitwise')}")
    print(f"seq={report.get('seq')} "
          f"replicas_converged={report.get('replicas_converged')} "
          f"digest_parity={report.get('digest_parity')}")
    from tools.metrics_watch import format_counter_table

    print("\n" + format_counter_table(report.get("counters", {}),
                                      name_width=24))
    print(f"flightrec={report.get('flightrec')}")
    print(f"\nok={report['ok']}")


def ps_main(argv) -> int:
    ap = argparse.ArgumentParser(
        description="deterministic PS kill-a-primary chaos drill")
    ap.add_argument("--workdir", default="/tmp/paddle_tpu_ps_drill")
    ap.add_argument("--dim", type=int, default=8)
    ap.add_argument("--pushes", type=int, default=12)
    ap.add_argument("--rows", type=int, default=16)
    ap.add_argument("--kill-after", type=int, default=5)
    ap.add_argument("--snapshot-every", type=int, default=3)
    # 3.0s matches the elastic drill's proven-stable TTL on the noisy
    # 2-core CI box: a shorter lease can expire SPURIOUSLY when the
    # GIL-starved parent delays serving a renewal, promoting the backup
    # before the kill even lands (the drill then exercises the fence
    # path instead of the crash-failover path it asserts)
    ap.add_argument("--lease-ttl", type=float, default=3.0)
    ap.add_argument("--max-restarts", type=int, default=1)
    ap.add_argument("--async-repl", action="store_true",
                    help="async replication (bounded lag) instead of sync")
    ap.add_argument("--no-kill", action="store_true",
                    help="clean baseline: same harness, no fault spec")
    args = ap.parse_args(argv)
    report = run_ps_drill(
        args.workdir, dim=args.dim, pushes=args.pushes, rows=args.rows,
        kill_after=args.kill_after, snapshot_every=args.snapshot_every,
        lease_ttl=args.lease_ttl, max_restarts=args.max_restarts,
        sync=not args.async_repl, kill=not args.no_kill)
    _print_ps_table(report)
    return 0 if report["ok"] else 1


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "--worker":
        return worker_main()
    if argv and argv[0] == "--ps-server":
        return ps_server_main()
    if argv and argv[0] == "--ps":
        return ps_main(argv[1:])
    ap = argparse.ArgumentParser(
        description="deterministic elastic kill/resume chaos drill")
    ap.add_argument("--workdir", default="/tmp/paddle_tpu_chaos_drill")
    ap.add_argument("--nranks", type=int, default=2)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batches", type=int, default=4)
    ap.add_argument("--save-every", type=int, default=2)
    ap.add_argument("--kill-rank", type=int, default=1)
    ap.add_argument("--kill-after", type=int, default=6)
    ap.add_argument("--max-restarts", type=int, default=2)
    ap.add_argument("--lease-ttl", type=float, default=3.0)
    ap.add_argument("--no-kill", action="store_true",
                    help="clean baseline: same job, no fault spec")
    args = ap.parse_args(argv)
    report = run_drill(args.workdir, nranks=args.nranks,
                       epochs=args.epochs, batches=args.batches,
                       save_every=args.save_every,
                       kill_rank=args.kill_rank,
                       kill_after=args.kill_after,
                       max_restarts=args.max_restarts,
                       lease_ttl=args.lease_ttl, kill=not args.no_kill)
    _print_table(report)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
