"""Vendor the reference public-API name lists into a committed data file.

Statically (ast, no imports) resolves each reference namespace's
``__all__`` — including the aggregation idiom ``__all__ += sub.__all__``
and literal helper lists like ``__activations_noattr__`` — and writes
``tests/data/reference_api_freeze.json``. The committed JSON is what
tests/test_namespace_freeze.py audits against, making the parity claims
executable instead of prose (reference posture:
tools/check_api_approvals.sh + paddle/fluid/API.spec freeze).

Run only when regenerating the freeze:
    python tools/freeze_namespaces.py
"""
from __future__ import annotations

import ast
import json
import os

REF = "/root/reference/python/paddle"
OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "tests", "data", "reference_api_freeze.json")

# namespace -> reference module path (relative to python/paddle)
NAMESPACES = {
    "fluid.layers": "fluid/layers/__init__.py",
    "nn": "nn/__init__.py",
    "nn.functional": "nn/functional/__init__.py",
    "optimizer": "optimizer/__init__.py",
    "metric": "metric/__init__.py",
    "distribution": "distribution.py",
    "distributed.fleet": "distributed/fleet/__init__.py",
    "distributed.fleet.meta_optimizers":
        "distributed/fleet/meta_optimizers/__init__.py",
    "incubate": "incubate/__init__.py",
    "incubate.hapi": "incubate/hapi/__init__.py",
    "io": "io/__init__.py",
    "static": "static/__init__.py",
    "utils": "utils/__init__.py",
    "fluid.contrib": "fluid/contrib/__init__.py",
    "fluid.contrib.layers": "fluid/contrib/layers/__init__.py",
    "jit": "jit/__init__.py",
    "framework": "framework/__init__.py",
    "nn.initializer": "nn/initializer/__init__.py",
    "dataset": "dataset/__init__.py",
    "distributed.fleet.utils": "distributed/fleet/utils/__init__.py",
    "fluid.dataloader": "fluid/dataloader/__init__.py",
    "fluid.dygraph.amp": "fluid/dygraph/amp/__init__.py",
    "fluid.transpiler": "fluid/transpiler/__init__.py",
    "fluid.incubate.data_generator":
        "fluid/incubate/data_generator/__init__.py",
    "incubate.hapi.datasets": "incubate/hapi/datasets/__init__.py",
    "incubate.hapi.text": "incubate/hapi/text/__init__.py",
    "incubate.hapi.vision": "incubate/hapi/vision/__init__.py",
    "fluid.metrics": "fluid/metrics.py",
    "fluid.initializer": "fluid/initializer.py",
    "fluid.regularizer": "fluid/regularizer.py",
    "fluid.clip": "fluid/clip.py",
    "fluid.optimizer": "fluid/optimizer.py",
}

_memo: dict = {}


def _module_file(base_dir: str, dotted: str):
    """Resolve a (possibly dotted) module name relative to base_dir."""
    parts = dotted.split(".")
    cand = os.path.join(base_dir, *parts)
    for p in (cand + ".py", os.path.join(cand, "__init__.py")):
        if os.path.exists(p):
            return p
    return None


def extract_all(path: str):
    """Names in this module's __all__, following literal lists, helper
    list variables, and sub-module `x.__all__` aggregation."""
    path = os.path.abspath(path)
    if path in _memo:
        return list(_memo[path])
    _memo[path] = []  # cycle guard
    with open(path, "r", encoding="utf-8") as f:
        tree = ast.parse(f.read())
    base_dir = os.path.dirname(path)

    # import map: local name -> module file (from-import of submodules)
    imports: dict = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            # from .layer import norm / from . import nn / from ..x import y
            prefix_dir = base_dir
            for _ in range(max(node.level - 1, 0)):
                prefix_dir = os.path.dirname(prefix_dir)
            mod = node.module or ""
            for alias in node.names:
                dotted = f"{mod}.{alias.name}" if mod else alias.name
                f_ = _module_file(prefix_dir, dotted)
                if f_ is None and mod:
                    # "from .common import *"-style: the module itself
                    f_ = _module_file(prefix_dir, mod)
                if f_:
                    imports[alias.asname or alias.name] = f_
        elif isinstance(node, ast.Import):
            for alias in node.names:
                f_ = _module_file(base_dir, alias.name)
                if f_:
                    imports[alias.asname or alias.name] = f_

    env: dict = {}  # helper literal list variables
    names: list = []

    def resolve(value) -> list:
        if isinstance(value, (ast.List, ast.Tuple)):
            out = []
            for elt in value.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                    out.append(elt.value)
            return out
        if isinstance(value, ast.Name):
            return list(env.get(value.id, []))
        if isinstance(value, ast.Attribute) and value.attr == "__all__":
            if isinstance(value.value, ast.Name):
                f_ = imports.get(value.value.id)
                if f_:
                    return extract_all(f_)
            if isinstance(value.value, ast.Attribute):
                # e.g. fluid.layers.__all__ — resolve the dotted chain
                chain = []
                cur = value.value
                while isinstance(cur, ast.Attribute):
                    chain.append(cur.attr)
                    cur = cur.value
                if isinstance(cur, ast.Name):
                    chain.append(cur.id)
                    chain.reverse()
                    f_ = imports.get(chain[0])
                    if f_ is None:
                        f_ = _module_file(os.path.dirname(REF),
                                          ".".join(chain))
                    else:
                        sub = _module_file(os.path.dirname(f_), ".".join(
                            [os.path.splitext(os.path.basename(f_))[0]]
                            + chain[1:])) if len(chain) > 1 else f_
                        f_ = sub or f_
                    if f_:
                        return extract_all(f_)
            return []
        if isinstance(value, ast.BinOp) and isinstance(value.op, ast.Add):
            return resolve(value.left) + resolve(value.right)
        return []

    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            tgt = node.targets[0].id
            if tgt == "__all__":
                names = resolve(node.value)
            else:
                vals = resolve(node.value)
                if vals or isinstance(node.value, (ast.List, ast.Tuple)):
                    env[tgt] = vals
        elif isinstance(node, ast.AugAssign) and \
                isinstance(node.target, ast.Name) and isinstance(
                    node.op, ast.Add):
            if node.target.id == "__all__":
                names += resolve(node.value)
            elif node.target.id in env:
                env[node.target.id] = env[node.target.id] + resolve(
                    node.value)

    # de-dup, preserve order
    seen, out = set(), []
    for n in names:
        if n not in seen:
            seen.add(n)
            out.append(n)
    _memo[path] = out
    return list(out)


# namespaces whose surface is the union of per-submodule __all__s (the
# package __init__ has no __all__ of its own in the reference)
AGGREGATE_DIRS = {
    "tensor": "tensor",
}

# namespaces with aggregated __all__ handled by extract_all directly
EXTRA = {
    "fluid": "fluid/__init__.py",
    "fluid.dygraph": "fluid/dygraph/__init__.py",
}


def extract_toplevel_imports(path: str):
    """The top-level `paddle` surface: python/paddle/__init__.py has no
    __all__ — its public names are the from-import aliases (198
    #DEFINE_ALIAS rows plus framework/device/hapi imports)."""
    with open(path, "r", encoding="utf-8") as f:
        tree = ast.parse(f.read())
    seen, names = set(), []
    for node in tree.body:
        if isinstance(node, ast.ImportFrom):
            for alias in node.names:
                n = alias.asname or alias.name
                if n.startswith("_") or n == "*":
                    continue
                if n not in seen:
                    seen.add(n)
                    names.append(n)
    return names


def main():
    freeze = {}
    for ns, rel in NAMESPACES.items():
        path = os.path.join(REF, rel)
        names = extract_all(path)
        freeze[ns] = names
        print(f"{ns}: {len(names)} names")
    for ns, rel in AGGREGATE_DIRS.items():
        agg, seen = [], set()
        d = os.path.join(REF, rel)
        for fname in sorted(os.listdir(d)):
            if not fname.endswith(".py") or fname == "__init__.py":
                continue
            for n in extract_all(os.path.join(d, fname)):
                if n not in seen:
                    seen.add(n)
                    agg.append(n)
        freeze[ns] = agg
        print(f"{ns}: {len(agg)} names (dir aggregate)")
    for ns, rel in EXTRA.items():
        freeze[ns] = extract_all(os.path.join(REF, rel))
        print(f"{ns}: {len(freeze[ns])} names")
    freeze["paddle"] = extract_toplevel_imports(
        os.path.join(REF, "__init__.py"))
    print(f"paddle (top-level): {len(freeze['paddle'])} names")
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    with open(OUT, "w") as f:
        json.dump(freeze, f, indent=1, sort_keys=True)
        f.write("\n")
    print("wrote", OUT)


if __name__ == "__main__":
    main()
