"""Page-pool inspector: render a decode engine's KV page-table state.

Reads a JSON snapshot produced by ``DecodeEngine.kv_debug_snapshot()``
(or a bare ``PageTableManager.snapshot()``) and prints the human view:
pool geometry and codec, occupancy (in use / free / cached / shared),
the per-sequence page tables with refcounts inlined, the shared-page
list, and the decode/spec counters when the snapshot carries them.

    python tools/dump_kv.py snapshot.json
    python tools/dump_kv.py --demo            # no file needed
    python tools/dump_kv.py --demo --json     # raw snapshot JSON

``--demo`` exercises a small in-process ``PageTableManager`` (pure
Python — no jax, no model): one sequence registers its prefix, a
second allocates against it via ``match_prefix``, so the rendered view
shows live prefix sharing and refcounts > 1. The snapshot format is
the stable contract; this tool only formats it.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def render_snapshot(snap: dict) -> str:
    """Format one snapshot dict as the human page-pool view."""
    lines: List[str] = ["== kv page pool =="]
    lines.append(f"{'pages':<22}{snap.get('n_pages', 0)} x "
                 f"{snap.get('page_size', 0)} tokens "
                 f"(max {snap.get('max_pages_per_seq', 0)}/seq)")
    if "kv_codec" in snap:
        lines.append(f"{'kv_codec':<22}{snap['kv_codec']}")
    if "spec_k" in snap:
        lines.append(f"{'spec_k':<22}{snap['spec_k']}")
    if "max_batch" in snap:
        lines.append(f"{'max_batch':<22}{snap['max_batch']}")
    lines.append(f"{'in use / free':<22}{snap.get('pages_in_use', 0)}"
                 f" / {snap.get('pages_free', 0)}")
    lines.append(f"{'cached (reclaimable)':<22}"
                 f"{snap.get('pages_cached', 0)}")
    lines.append(f"{'shared (ref > 1)':<22}{snap.get('pages_shared', 0)}")
    lines.append(f"{'utilization':<22}{snap.get('utilization_pct', 0.0)}%"
                 f"  (peak {snap.get('peak_pages_in_use', 0)}, "
                 f"peak shared {snap.get('peak_pages_shared', 0)})")
    lines.append(f"{'prefix hits':<22}{snap.get('prefix_hits', 0)}"
                 f"   evictions {snap.get('evicted_pages', 0)}"
                 f"   cache reclaims {snap.get('cached_reclaimed', 0)}")
    refs = {int(p): int(r) for p, r in (snap.get("refs") or {}).items()}
    seqs = snap.get("seqs") or {}
    if seqs:
        lines.append("")
        lines.append("-- sequences --")
        for sid in sorted(seqs, key=int):
            pages = [int(p) for p in seqs[sid]]
            rr = [refs.get(p, 0) for p in pages]
            lines.append(f"seq {sid:<6}{len(pages)} pages  "
                         f"{pages}  refs {rr}")
    shared = sorted(p for p, r in refs.items() if r > 1)
    if shared:
        lines.append("")
        lines.append("-- shared pages (ref > 1) --")
        for p in shared:
            lines.append(f"page {p:<6}refs {refs[p]}")
    cached = snap.get("cached") or []
    if cached:
        lines.append("")
        lines.append(f"-- cached (LRU, reclaimable) --  {list(cached)}")
    host = snap.get("host_tier")
    if host:
        lines.append("")
        lines.append("-- host offload tier --")
        cap = int(host.get("capacity_bytes", 0))
        used = int(host.get("bytes_in_use", 0))
        pct = round(100.0 * used / cap, 1) if cap else 0.0
        lines.append(f"{'pages host':<22}{host.get('pages_host', 0)}"
                     f"  ({used} / {cap} bytes, {pct}%, "
                     f"{host.get('page_nbytes', 0)} B/page encoded)")
        lines.append(f"{'spilled / restored':<22}"
                     f"{host.get('spilled_pages', 0)} / "
                     f"{host.get('restored_pages', 0)}"
                     f"   lru drops {host.get('dropped_pages', 0)}")
        if "parked_sessions" in host:
            lines.append(f"{'parked sessions':<22}"
                         f"{host['parked_sessions']}")
        sessions = host.get("sessions") or {}
        if sessions:
            lines.append("")
            lines.append("-- parked sessions (host-resident KV) --")
            for sid in sorted(sessions, key=int):
                lines.append(f"seq {sid:<6}{sessions[sid]} pages on host")
        lru = host.get("prefix_lru") or []
        if lru:
            # oldest first == next to be aged out: the temperature order
            lines.append("")
            lines.append(f"-- host prefix LRU (coldest first) --  "
                         f"{list(lru)}")
    if "async_decode" in snap:
        lines.append("")
        lines.append(f"{'async_decode':<22}{snap['async_decode']}")
    counters = snap.get("counters") or {}
    if counters:
        lines.append("")
        lines.append("-- counters --")
        for name in sorted(counters):
            lines.append(f"{name:<28}{counters[name]}")
    return "\n".join(lines) + "\n"


def _demo_snapshot() -> dict:
    """A live prefix-sharing scene from a bare PageTableManager: seq 1
    owns a registered 12-token prefix; seq 2 allocates against it so
    its first pages are shared (ref 2). A small HostKVPool rides along
    with one parked session and one spilled prefix page, so the host
    offload tier renders too."""
    import numpy as np

    from paddle_tpu.inference.decode.kv_cache import (HostKVPool,
                                                      PageTableManager)

    pool = PageTableManager(n_pages=16, page_size=4, max_pages_per_seq=4)
    toks = list(range(1, 13))
    pool.alloc_seq(1, len(toks))
    pool.register_prefix(1, toks)
    shared = pool.match_prefix(toks + [99], limit=2)
    pool.alloc_seq_shared(2, shared, len(toks) + 1)

    host = HostKVPool(n_layers=2, page_size=4, heads=2, head_dim=8,
                      capacity_bytes=1 << 16)

    def rec(seed):
        rng = np.random.RandomState(seed)
        kq = rng.randint(-128, 127, (2, 4, 2, 8)).astype(np.int8)
        ks = rng.rand(2, 4).astype(np.float32)
        return kq, ks, kq.copy(), ks.copy()

    host.put_seq(7, [rec(0), rec(1)])          # a parked session
    host.put_prefix(b"demo-prefix-key", rec(2))  # a spilled prefix page
    snap = pool.snapshot()
    snap["host_tier"] = host.snapshot()
    snap["host_tier"]["parked_sessions"] = 1
    return snap


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        "tools/dump_kv.py",
        description="render a DecodeEngine.kv_debug_snapshot() / "
                    "PageTableManager.snapshot() JSON file")
    ap.add_argument("snapshot", nargs="?",
                    help="snapshot JSON file (omit with --demo)")
    ap.add_argument("--demo", action="store_true",
                    help="render a small in-process demo pool with "
                         "live prefix sharing (no file, no jax)")
    ap.add_argument("--json", action="store_true",
                    help="print the raw snapshot JSON instead of the "
                         "rendered view")
    args = ap.parse_args(argv)
    if args.demo:
        snap = _demo_snapshot()
    elif args.snapshot:
        try:
            with open(args.snapshot) as fh:
                snap = json.load(fh)
        except (OSError, ValueError) as e:
            print(f"dump_kv: cannot read {args.snapshot!r}: {e}",
                  file=sys.stderr)
            return 1
    else:
        ap.print_usage(sys.stderr)
        return 1
    if args.json:
        json.dump(snap, sys.stdout, indent=1, sort_keys=True)
        sys.stdout.write("\n")
    else:
        sys.stdout.write(render_snapshot(snap))
    return 0


if __name__ == "__main__":
    sys.exit(main())
