"""Per-pass op-count / timing table for a static Program.

The CLI face of static/passes.py (reference: the --print_ir flavor of
build_strategy + graph_viz_pass): run the IR pass pipeline over a
program and print what each pass removed and how long it took, without
executing anything.

Usage:
    # serialized program (static.save_program output, e.g. the
    # main_program file save_train_program writes)
    python tools/dump_passes.py path/to/main_program --fetch loss_name

    # save_inference_model directory (feed/fetch read from the blob)
    python tools/dump_passes.py path/to/inference_dir

    # built-in demo program (no artifact needed)
    python tools/dump_passes.py --demo

    # graphviz dump of the optimized block, viz.py style
    python tools/dump_passes.py --demo --dot /tmp/optimized.dot

Knobs off by name: --disable fuse_elewise_add_act_ops,cse

Mixed precision: --amp [bf16|fp16] enables the auto_mixed_precision
pass and prints a per-op dtype table (inserted/elided casts, f32-pinned
ops, low-precision ops) after the usual per-pass report.

Rematerialization: --remat [N] enables the recompute_segmentation pass
(N segments; omit N for the automatic sqrt split, or pass checkpoint
var names via --checkpoints a,b) and prints the per-segment table: ops
per segment, stashed (boundary) vs recomputed (interior) var counts and
estimated bytes.

Sharding: --sharding [dp=2,tp=2] enables the shard_propagation pass
over that mesh shape and prints the per-var PartitionSpec table (hint
vs propagated vs conflict-replicated). Seed specs ride --shard-hints
"w0=-,tp;w1=tp,-" (dims comma-separated, '-' = replicated,
'dp+sp' = multi-axis dim); without hints the demo auto-hints the first
divisible 2-D parameters column-/row-parallel so the psum accounting
shows up. No devices are touched — the pass is pure annotation.

Quantized collectives: --comm [int8|bf16] enables the comm_bucketing
pass over a pure-dp mesh (--sharding dp=N, default dp=8) and prints
the per-bucket size/order/codec table: the gradient buckets in
backward-completion order with their f32 vs encoded ring bytes.
Bucket size rides --comm-bucket-bytes (default 1 MiB).

Pipelining: --pipeline [S] stamps pipeline_stages=S (with
--microbatches M as gradient_merge_k) and prints the tick-by-tick
schedule timeline grid for --schedule [gpipe|1f1b|interleaved] plus
the modeled bubble fractions of all three schedules at (S, M) — the
same parallel.pipeline generators the compiled step replays.

ZeRO: --zero [2|3] plans the sharded-optimizer decomposition over the
comm buckets (implies --comm int8 over dp=8) and prints the per-bucket
state-bytes table: replicated vs per-device (g, chunk) row bytes and
the saved fraction — or the counted refusal reason when the build
falls back to the replicated step.
"""
from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _demo_program():
    """A small training program with food for every pass (the same
    shape bench.py's _static_pass_probe measures)."""
    import paddle_tpu.static as static

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [-1, 16])
        label = static.data("label", [-1, 1], dtype="int64")
        h = static.nn.fc(x, 32, act="relu")
        h = static.scale(h, scale=1.0)
        a = static.reduce_mean(h, dim=[1], keep_dim=True)
        b = static.reduce_mean(h, dim=[1], keep_dim=True)
        h = static.elementwise_add(static.elementwise_sub(h, a),
                                   static.elementwise_sub(h, b))
        c = static.elementwise_mul(
            static.fill_constant([1], "float32", 0.5),
            static.fill_constant([1], "float32", 4.0))
        h = static.elementwise_mul(h, c)
        static.nn.fc(h, 8)  # dead branch
        logits = static.nn.fc(h, 4)
        loss = static.mean(
            static.softmax_with_cross_entropy(logits, label))
        static.SGD(0.01).minimize(loss)
    return main, ["x", "label"], [loss.name]


def _load_target(path):
    """Resolve (program, feeds, fetches) from a serialized program file
    or a save_inference_model directory."""
    import paddle_tpu.static as static

    if os.path.isdir(path):
        from paddle_tpu.io.serialization import _load_pickle

        blob = _load_pickle(os.path.join(path, "__model__"))
        program = static.Program.from_dict(blob["program"])
        meta = blob["meta"]
        return program, meta["feed_names"], meta["fetch_names"]
    program = static.load_program(path)
    return program, [], []


def _amp_table(program, report):
    """Per-op dtype table of the optimized block: which ops run low
    precision, which are f32-pinned, where casts were inserted."""
    from paddle_tpu.static.passes import _LOW_PRECISION, _amp_lists

    _, black = _amp_lists()
    blk = program.global_block
    lines = [f"{'#':>3} {'op':<26}{'out dtype':<12}{'amp':<12}outputs"]
    for i, op in enumerate(blk.ops):
        outs = op.output_names()
        dts = {str(getattr(blk.vars.get(n), "dtype", "?")) for n in outs}
        if op.type == "cast":
            note = ("cast" if not any(
                "@amp." in n for n in outs + op.input_names())
                else "cast(amp)")
        elif op.type in black:
            note = "f32-pinned"
        elif dts & _LOW_PRECISION:
            note = "lowprec"
        else:
            note = "-"
        lines.append(f"{i:>3} {op.type:<26}"
                     f"{','.join(sorted(dts)) or '-':<12}{note:<12}"
                     f"{','.join(outs)[:44]}")
    if report.amp:
        lines.append("amp counters: " + "  ".join(
            f"{k}={v}" for k, v in sorted(report.amp.items())))
    return "\n".join(lines)


def _parse_shard_hints(spec, program, mesh_shape):
    """'w0=-,tp;w1=tp,-' -> {name: spec tuple}. With no spec given,
    auto-hint: the first 2-D trainable params whose dims divide the
    'tp' axis get column-/row-parallel seeds, so the demo's propagation
    (and the psum on the row-parallel contraction) is visible without
    memorizing parameter names."""
    if spec:
        hints = {}
        for entry in spec.split(";"):
            entry = entry.strip()
            if not entry:
                continue
            name, _, dims = entry.partition("=")
            parsed = []
            for d in dims.split(","):
                d = d.strip()
                if d in ("", "-", "None"):
                    parsed.append(None)
                elif "+" in d:
                    parsed.append(tuple(a for a in d.split("+") if a))
                else:
                    parsed.append(d)
            hints[name.strip()] = tuple(parsed)
        return hints
    tp = mesh_shape.get("tp", 0)
    if tp <= 1:
        return {}
    hints, want = {}, [(1, (None, "tp")), (0, ("tp", None))]
    for p in program.all_parameters():
        if not want:
            break
        shape = p.shape or ()
        if len(shape) != 2:
            continue
        dim, spec_t = want[0]
        if shape[dim] and shape[dim] % tp == 0:
            hints[p.name] = spec_t
            want.pop(0)
    return hints


def _timeline_table(schedule, s_count, m_count, interleave):
    """Tick-by-tick grid of the compiled schedule (rows = stages,
    columns = ticks, F<m>/B<m> slots) + the modeled bubble comparison
    across all three schedules at the same (S, M)."""
    from paddle_tpu.parallel.pipeline import (pipeline_timeline,
                                              schedule_bubble_fraction)

    grid, ticks = {}, 0
    for t, slots in pipeline_timeline(schedule, s_count, m_count,
                                      interleave):
        ticks = max(ticks, t + 1)
        for kind, s, m in slots:
            grid[(s, t)] = f"{kind}{m}"
    w = max(2, len(str(m_count - 1)) + 1)
    head = f"{schedule} timeline: S={s_count} M={m_count}"
    if schedule == "interleaved":
        head += f" v={interleave}"
    lines = [head,
             "stage " + " ".join(f"{t:>{w}}" for t in range(ticks))]
    for s in range(s_count):
        lines.append(f"{s:>5} " + " ".join(
            f"{grid.get((s, t), '.'):>{w}}" for t in range(ticks)))
    lines.append("modeled bubble fraction: " + "  ".join(
        f"{name}={schedule_bubble_fraction(name, s_count, m_count, interleave):.4f}"
        for name in ("gpipe", "1f1b", "interleaved")))
    return "\n".join(lines)


def _zero_state_table(program, strategy, stage):
    """Per-bucket replicated vs per-device optimizer-state bytes under
    the ZeRO plan — or the counted refusal reason on fallback."""
    from paddle_tpu.static import passes as passes_mod
    from paddle_tpu.static.stepplan import (zero_eligibility,
                                            zero_state_layout)

    comm = passes_mod.resolve_comm(strategy)
    shard_cfg = passes_mod.resolve_sharding(strategy)
    axis = passes_mod.comm_data_axis(shard_cfg)
    block = program.global_block
    comm_plan = None
    if comm is not None and axis is not None:
        cplan = passes_mod.comm_bucket_plan(block, comm, axis[1])
        if cplan:
            comm_plan = (axis[0], axis[1], cplan)
    reasons = []

    def bump(cat, kind, reason=None):
        if reason:
            reasons.append(reason)

    _, plan = zero_eligibility(
        program, block, stage, comm, comm_plan, shard_cfg,
        passes_mod.resolve_gradient_merge(strategy),
        passes_mod.resolve_pipeline(strategy), (), bump=bump)
    if plan is None:
        return ("zero refused (replicated fallback): "
                + (reasons[0] if reasons else "(no reason recorded)"))
    g = plan["group"]
    lines = [f"zero stage {plan['stage']} over axis {plan['axis']!r} "
             f"(g={g}): one (g, chunk) f32 row per (bucket, role)",
             f"{'bucket':>6}  {'opt':<10}{'params':>7}{'elems':>10}"
             f"{'chunk':>9}{'rows':>5}{'repl B':>12}{'/dev B':>12}"
             f"{'saved':>8}"]
    for i, b in enumerate(plan["buckets"]):
        nrows = len(b["roles"]) + (1 if plan["stage"] >= 3 else 0)
        rep = b["elems"] * 4 * nrows
        sh = b["chunk"] * 4 * nrows
        saved = 1 - sh / rep if rep else 0.0
        lines.append(f"{i:>6}  {b['op_type']:<10}{len(b['params']):>7}"
                     f"{b['elems']:>10}{b['chunk']:>9}{nrows:>5}"
                     f"{rep:>12}{sh:>12}{saved:>7.1%}")
    rows = zero_state_layout(plan)
    if rows:
        lines.append("state rows: " + ", ".join(
            f"{n}{list(shape)}" for n, _role, _bi, shape in rows))
    tot_r, tot_s = plan["bytes_replicated"], plan["bytes_sharded"]
    pct = 100.0 * (1 - tot_s / tot_r) if tot_r else 0.0
    lines.append(f"total optimizer-state bytes: replicated {tot_r} -> "
                 f"per-device {tot_s} ({pct:.1f}% saved)")
    return "\n".join(lines)


def _moe_demo_program(ep):
    """Demo program with an expert-parallel MoE block (2*ep experts so
    the ep axis divides them, capacity_factor 1.25 so overflow drops
    show up in the route table)."""
    import paddle_tpu.static as static

    e = 2 * max(2, ep)
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [64, 16])
        label = static.data("label", [64, 1], dtype="int64")
        h = static.nn.fc(x, 16, act="relu")
        m, aux = static.nn.moe(h, num_experts=e, d_hidden=32,
                               capacity_factor=1.25)
        logits = static.nn.fc(m, 4)
        loss = static.mean(
            static.softmax_with_cross_entropy(logits, label)) \
            + static.mean(aux) * 0.01
        static.SGD(0.01).minimize(loss)
    return main, ["x", "label"], [loss.name]


def _moe_table(optimized, ep):
    """Per-moe-op routing/exchange table: the __moe_ep stamp (or why it
    is absent), the per-expert capacity-kept/dropped counts from one
    synthetic untrained-gate evaluation, and the explicit all_to_all
    wire bytes the cost model charges."""
    import numpy as np

    from paddle_tpu.nn.moe import moe_a2a_nbytes, moe_route_stats

    blk = optimized.global_block
    moes = [(i, op) for i, op in enumerate(blk.ops) if op.type == "moe"]
    if not moes:
        return "(no moe ops in the optimized block)"
    lines = []
    for i, op in moes:
        w1 = blk.vars[op.inputs["W1"][0]]
        x = blk.vars[op.inputs["X"][0]]
        e = int(w1.shape[0])
        t = abs(int(x.shape[0] or 1))
        d = int(x.shape[-1])
        cf = float(op.attrs.get("capacity_factor", 2.0))
        cap = max(1, int(cf * t / e))
        stamp = op.attrs.get("__moe_ep")
        head = (f"moe op #{i}: tokens={t} d={d} experts={e} "
                f"capacity={cap} (factor {cf})")
        if stamp:
            axis, n = str(stamp[0]), int(stamp[1])
            head += (f"  [stamped __moe_ep: {axis}={n}, explicit "
                     f"all_to_all x2, "
                     f"{moe_a2a_nbytes(e, cap, d, n)} B/device f32 / "
                     f"{moe_a2a_nbytes(e, cap, d, n, 'int8')} B int8]")
        else:
            head += (f"  [not stamped: needs an 'ep' mesh axis >1 "
                     f"dividing experts={e} (asked ep={ep}) -> dense]")
        lines.append(head)
        rng = np.random.RandomState(0)
        stats = moe_route_stats(
            rng.randn(t, e).astype("float32"), cap)
        lines.append(f"{'expert':>6}{'kept':>7}{'dropped':>9}  "
                     "(one synthetic untrained-gate eval)")
        for j, (k, dr) in enumerate(zip(stats["kept_per_expert"],
                                        stats["dropped_per_expert"])):
            lines.append(f"{j:>6}{k:>7}{dr:>9}")
        lines.append(f"capacity drop: {stats['drop_pct']}% of 2t "
                     f"token-choices, aux_loss="
                     f"{stats['aux_loss']:.4f}")
    return "\n".join(lines)


def _fused_opt_table(optimized, strategy, zero_stage):
    """Per-update-op (and per-ZeRO-bucket) kernel-vs-xla dispatch table
    — the same ``_dispatch`` gate the compiled step funnels through, so
    the table shows exactly which params ride the fused Pallas kernel
    on this backend/env and the refusal reason for the rest."""
    from paddle_tpu.ops.pallas.fused_optimizer import _dispatch

    blk = optimized.global_block
    update_ops = ("sgd", "momentum", "adam", "adamw", "lamb",
                  "rmsprop", "adagrad")
    rows = [(i, op) for i, op in enumerate(blk.ops)
            if op.type in update_ops]
    if not rows:
        return "(no optimizer update ops in the optimized block)"
    lines = [f"{'#':>3} {'op':<10}{'param':<22}{'elems':>9} "
             f"{'dtype':<9}{'path':<8}reason"]
    import numpy as np

    for i, op in rows:
        pname = (op.inputs.get("Param") or ["?"])[0]
        v = blk.vars.get(pname)
        shape = tuple(getattr(v, "shape", ()) or ())
        elems = int(np.prod([abs(s or 1) for s in shape])) if shape else 0
        dtype = str(getattr(v, "dtype", "float32"))
        path, reason, interp = _dispatch(op.type, elems, dtype)
        if path == "pallas" and interp:
            path = "pallas*"
        lines.append(f"{i:>3} {op.type:<10}{pname[:21]:<22}{elems:>9} "
                     f"{dtype:<9}{path:<8}{reason}")
    lines.append("(pallas* = interpret-forced via "
                 "PADDLE_FUSED_OPT_INTERPRET)")
    if zero_stage:
        from paddle_tpu.static import passes as passes_mod
        from paddle_tpu.static.stepplan import zero_eligibility

        comm = passes_mod.resolve_comm(strategy)
        shard_cfg = passes_mod.resolve_sharding(strategy)
        axis = passes_mod.comm_data_axis(shard_cfg)
        comm_plan = None
        if comm is not None and axis is not None:
            cplan = passes_mod.comm_bucket_plan(blk, comm, axis[1])
            if cplan:
                comm_plan = (axis[0], axis[1], cplan)
        _, plan = zero_eligibility(
            optimized, blk, zero_stage, comm, comm_plan, shard_cfg,
            passes_mod.resolve_gradient_merge(strategy),
            passes_mod.resolve_pipeline(strategy), (),
            bump=lambda *a, **k: None)
        if plan is None:
            lines.append("zero refused: per-bucket table unavailable "
                         "(see --zero output)")
        else:
            lines.append(f"zero buckets (g={plan['group']}): the fused "
                         "kernel runs on the PER-DEVICE chunk")
            lines.append(f"{'bucket':>6}  {'opt':<10}{'chunk':>9} "
                         f"{'path':<8}reason")
            for j, b in enumerate(plan["buckets"]):
                path, reason, interp = _dispatch(
                    b["op_type"], int(b["chunk"]), "float32")
                if path == "pallas" and interp:
                    path = "pallas*"
                lines.append(f"{j:>6}  {b['op_type']:<10}"
                             f"{int(b['chunk']):>9} {path:<8}{reason}")
    return "\n".join(lines)


def main():
    ap = argparse.ArgumentParser(
        description="print per-pass op-count/timing table for a program")
    ap.add_argument("target", nargs="?",
                    help="serialized program file or inference-model dir")
    ap.add_argument("--demo", action="store_true",
                    help="use a built-in demo program")
    ap.add_argument("--feed", default=None,
                    help="comma-separated feed names (override)")
    ap.add_argument("--fetch", default=None,
                    help="comma-separated fetch names (override)")
    ap.add_argument("--disable", default=None,
                    help="comma-separated BuildStrategy knobs to turn off")
    ap.add_argument("--amp", nargs="?", const="bf16", default=None,
                    choices=("bf16", "bfloat16", "fp16", "float16"),
                    help="run the auto_mixed_precision pass (default "
                         "bf16) and print the per-op dtype table")
    ap.add_argument("--remat", nargs="?", const=0, default=None, type=int,
                    metavar="N",
                    help="run the recompute_segmentation pass (N "
                         "segments, 0/omitted = sqrt heuristic) and "
                         "print the per-segment stash/recompute table")
    ap.add_argument("--checkpoints", default=None,
                    help="comma-separated checkpoint var names marking "
                         "remat segment boundaries (implies --remat)")
    ap.add_argument("--sharding", nargs="?", const="dp=2,tp=2",
                    default=None, metavar="MESH",
                    help="run the shard_propagation pass over this mesh "
                         "shape (axis=size pairs, default dp=2,tp=2) and "
                         "print the per-var PartitionSpec table")
    ap.add_argument("--shard-hints", default=None, metavar="HINTS",
                    help="seed PartitionSpecs: 'w0=-,tp;w1=tp,-' "
                         "(';'-separated vars, ','-separated dims, '-' = "
                         "replicated, '+' joins multi-axis dims); "
                         "implies --sharding")
    ap.add_argument("--comm", nargs="?", const="int8", default=None,
                    choices=("int8", "bf16"),
                    help="run the comm_bucketing pass (quantized DP "
                         "all-reduce planning, default int8) and print "
                         "the per-bucket size/order/codec table; uses "
                         "--sharding's mesh (default dp=8)")
    ap.add_argument("--comm-bucket-bytes", type=int, default=1 << 20,
                    help="target f32 payload bytes per gradient bucket")
    ap.add_argument("--pipeline", nargs="?", const=4, default=None,
                    type=int, metavar="S",
                    help="stamp pipeline_stages=S (default 4) and print "
                         "the schedule timeline grid + modeled bubble "
                         "fractions")
    ap.add_argument("--schedule", default="gpipe",
                    choices=("gpipe", "1f1b", "interleaved"),
                    help="which schedule the --pipeline grid prints "
                         "(bubbles always compare all three)")
    ap.add_argument("--microbatches", type=int, default=8, metavar="M",
                    help="gradient_merge_k microbatch count for "
                         "--pipeline (default 8)")
    ap.add_argument("--interleave", type=int, default=2,
                    help="virtual chunks per worker for "
                         "--schedule interleaved (default 2)")
    ap.add_argument("--zero", nargs="?", const=2, default=None,
                    type=int, choices=(2, 3), metavar="STAGE",
                    help="plan ZeRO sharded optimizer states (implies "
                         "--comm int8 over dp=8) and print the "
                         "per-bucket state-bytes table or the counted "
                         "refusal reason")
    ap.add_argument("--moe", nargs="?", const=4, default=None,
                    type=int, metavar="EP",
                    help="run over an expert-parallel mesh (ep=EP, "
                         "default 4; demo swaps in an MoE program) and "
                         "print the per-expert capacity/route table + "
                         "the explicit all_to_all wire bytes")
    ap.add_argument("--fused-opt", action="store_true",
                    help="print the per-update-op (and, with --zero, "
                         "per-bucket) fused-kernel-vs-xla dispatch "
                         "table with refusal reasons")
    ap.add_argument("--dot", default=None,
                    help="write the optimized block as graphviz dot")
    args = ap.parse_args()

    import paddle_tpu.static as static

    if args.demo or not args.target:
        program, feeds, fetches = (_moe_demo_program(args.moe)
                                   if args.moe else _demo_program())
    else:
        program, feeds, fetches = _load_target(args.target)
    if args.feed:
        feeds = [s for s in args.feed.split(",") if s]
    if args.fetch:
        fetches = [s for s in args.fetch.split(",") if s]
    if not fetches:
        # default: every leaf output (no consumer) of the global block
        blk = program.global_block
        consumed = {n for op in blk.ops for n in op.input_names()}
        fetches = sorted({n for op in blk.ops for n in op.output_names()}
                         - consumed)
        print(f"(no --fetch given; using leaf outputs: {fetches})",
              file=sys.stderr)

    strategy = static.BuildStrategy()
    for knob in (args.disable or "").split(","):
        knob = knob.strip()
        if knob:
            if not hasattr(strategy, knob):
                ap.error(f"unknown BuildStrategy knob {knob!r}")
            setattr(strategy, knob, False)
    if args.amp:
        strategy.amp = True
        strategy.amp_dtype = args.amp
    if args.remat is not None or args.checkpoints:
        strategy.recompute = True
        strategy.recompute_segments = args.remat or 0
        if args.checkpoints:
            strategy.recompute_checkpoints = tuple(
                s for s in args.checkpoints.split(",") if s)
    if args.sharding or args.shard_hints:
        mesh_shape = {}
        for part in (args.sharding or "dp=2,tp=2").split(","):
            part = part.strip()
            if not part:
                continue
            axis, _, size = part.partition("=")
            mesh_shape[axis.strip()] = int(size or 2)
        strategy.mesh_shape = mesh_shape
        strategy.sharding_hints = _parse_shard_hints(
            args.shard_hints, program, mesh_shape)
    if args.zero and not args.comm:
        args.comm = "int8"   # ZeRO rides the engaged comm plan
    if args.comm:
        if not strategy.mesh_shape:
            strategy.mesh_shape = {"dp": 8}   # pure-dp planning mesh
        strategy.comm_quant = args.comm
        strategy.comm_bucket_bytes = args.comm_bucket_bytes
    if args.pipeline:
        strategy.pipeline_stages = args.pipeline
        strategy.gradient_merge_k = max(int(args.microbatches), 2)
        strategy.pipeline_schedule = args.schedule
        strategy.pipeline_interleave = args.interleave
    if args.zero:
        strategy.zero_stage = args.zero
    if args.moe:
        mesh = dict(strategy.mesh_shape or {})
        mesh.setdefault("ep", args.moe)
        strategy.mesh_shape = mesh

    optimized, report = static.apply_passes(program, feeds, fetches,
                                            strategy)
    print(report.table())
    if args.amp:
        print()
        print(_amp_table(optimized, report))
    if args.remat is not None or args.checkpoints:
        print()
        print(report.remat_segment_table())
    if args.sharding or args.shard_hints:
        print()
        print(report.shard_spec_table())
    if args.comm:
        print()
        print(report.comm_bucket_table())
    if args.pipeline:
        print()
        print(_timeline_table(args.schedule, args.pipeline,
                              strategy.gradient_merge_k,
                              args.interleave))
    if args.zero:
        print()
        print(_zero_state_table(optimized, strategy, args.zero))
    if args.moe:
        print()
        print(_moe_table(optimized, args.moe))
    if args.fused_opt:
        print()
        print(_fused_opt_table(optimized, strategy, args.zero))
    if args.dot:
        static.save_dot(optimized, args.dot)
        print(f"optimized block dot -> {args.dot}")


if __name__ == "__main__":
    main()
