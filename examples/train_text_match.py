"""Text matching with the contrib LoD-op family (reference
fluid.contrib.layers usage: the MatchPyramid/match-matrix text-match
recipe built on match_matrix_tensor + var_conv_2d +
sequence_topk_avg_pooling, cf. contrib/layers/nn.py:245 docstrings).

Synthetic task: query/title pairs of variable lengths; positive pairs
get >= 2 query tokens copied into the title (random negatives can
collide by chance, so the labels carry a little noise — the 0.95+
accuracy below is the clean-signal ceiling, not a bug). The model embeds both,
forms the (channel, n, m) semantic match matrix, runs a variable-size
conv over it, pools with top-k averages per row, and classifies the
pooled features. Everything trains end-to-end through the
dense+lengths contrib ops (gradients flow into the match weight, the
conv filter and the embedding)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import contrib, nn, optimizer

paddle.seed(0)
rng = np.random.RandomState(0)

VOCAB, HID, CH = 50, 16, 3
NMAX, MMAX = 8, 6
BATCH, STEPS = 32, 200
TOPKS = [1, 3]


def make_batch():
    q = rng.randint(1, VOCAB, (BATCH, NMAX)).astype(np.int64)
    t = rng.randint(1, VOCAB, (BATCH, MMAX)).astype(np.int64)
    ql = rng.randint(3, NMAX + 1, BATCH).astype(np.int64)
    tl = rng.randint(2, MMAX + 1, BATCH).astype(np.int64)
    y = np.zeros((BATCH,), np.int64)
    for i in range(BATCH):
        # positive pairs: copy >= 2 query tokens into the title
        if rng.rand() < 0.5:
            k = min(2 + rng.randint(0, 2), int(tl[i]))
            t[i, :k] = q[i, :k]
            y[i] = 1
    return (paddle.to_tensor(q), paddle.to_tensor(t),
            paddle.to_tensor(ql), paddle.to_tensor(tl),
            paddle.to_tensor(y))


emb = nn.Embedding(VOCAB, HID)
head = nn.Linear(NMAX * CH * len(TOPKS), 2)
# contrib functions create their weights on first call; reuse after
match_w = None
conv_w = None


def forward(q, t, ql, tl):
    global match_w, conv_w
    qe, te = emb(q), emb(t)
    if match_w is None:
        mm, _tmp, match_w = contrib.match_matrix_tensor(
            qe, te, CH, x_lengths=ql, y_lengths=tl)
    else:
        mm, _tmp = contrib.match_matrix_tensor(
            qe, te, CH, x_lengths=ql, y_lengths=tl, weight=match_w)
    if conv_w is None:
        cv, oh, ow, conv_w = contrib.var_conv_2d(
            mm, ql, tl, CH, CH, [3, 3], stride=1, act="relu")
    else:
        cv, oh, ow = contrib.var_conv_2d(
            mm, ql, tl, CH, CH, [3, 3], stride=1, act="relu",
            weight=conv_w)
    pooled = contrib.sequence_topk_avg_pooling(cv, oh, ow, TOPKS, CH)
    feat = pooled.reshape([BATCH, -1])
    return head(feat)


params = list(emb.parameters()) + list(head.parameters())
opt = None
ce = nn.CrossEntropyLoss()
first = last = None
for step in range(STEPS):
    q, t, ql, tl, y = make_batch()
    logits = forward(q, t, ql, tl)
    loss = ce(logits, y)
    if opt is None:
        # contrib weights exist after the first forward: optimize them too
        params += [match_w, conv_w]
        opt = optimizer.Adam(learning_rate=1e-2, parameters=params)
    loss.backward()
    opt.step()
    opt.clear_grad()
    v = float(loss.numpy())
    first = v if first is None else first
    last = v
    if step % 50 == 0:
        print(f"step {step}: loss {v:.4f}")

q, t, ql, tl, y = make_batch()
pred = np.asarray(forward(q, t, ql, tl).numpy()).argmax(1)
acc = float((pred == np.asarray(y.numpy())).mean())
print(f"loss {first:.4f} -> {last:.4f}; accuracy {acc:.3f}")
assert last < first * 0.8, "loss must drop through the contrib ops"
assert acc > 0.7, f"match accuracy too low: {acc}"
print("OK")
