"""Static-graph image classification (reference book/
test_image_classification.py shape): small ResNet on CIFAR-sized data via
Program/Executor, with save_inference_model at the end.

Run: PYTHONPATH=. python examples/train_resnet_static.py  (add
JAX_PLATFORMS=cpu off-TPU)
"""
import numpy as np

import paddle_tpu.static as static
from paddle_tpu.vision.datasets import Cifar10


def conv_bn(x, ch, stride=1, act="relu"):
    h = static.nn.conv2d(x, ch, 3, stride=stride, padding=1,
                         bias_attr=False)
    return static.nn.batch_norm(h, act=act)


def basic_block(x, ch, stride=1):
    h = conv_bn(x, ch, stride)
    h = conv_bn(h, ch, act=None)
    short = x if stride == 1 and x.shape[1] == ch else \
        static.nn.conv2d(x, ch, 1, stride=stride, bias_attr=False)
    return static.relu(static.elementwise_add(h, short))


def main():
    ds = Cifar10(mode="train", synthetic_size=1024)
    imgs = np.stack([ds[i][0] for i in range(512)]).astype(np.float32)
    labels = np.stack([ds[i][1] for i in range(512)]).reshape(-1, 1)

    main_prog, startup = static.Program(), static.Program()
    with static.program_guard(main_prog, startup):
        img = static.data("img", [-1, 3, 32, 32])
        label = static.data("label", [-1, 1], dtype="int64")
        h = conv_bn(img, 16)
        h = basic_block(h, 16)
        h = basic_block(h, 32, stride=2)
        h = basic_block(h, 64, stride=2)
        h = static.nn.pool2d(h, 8, pool_type="avg")
        logits = static.nn.fc(h, 10)
        loss = static.mean(
            static.softmax_with_cross_entropy(logits, label))
        acc = static.accuracy(static.softmax(logits), label)
        static.Momentum(learning_rate=0.05, momentum=0.9).minimize(loss)

    exe = static.Executor()
    exe.run(startup)
    for epoch in range(3):
        perm = np.random.RandomState(epoch).permutation(len(imgs))
        for i in range(0, len(imgs) - 63, 64):
            sl = perm[i:i + 64]
            lo, ac = exe.run(main_prog,
                             feed={"img": imgs[sl], "label": labels[sl]},
                             fetch_list=[loss, acc])
        print(f"epoch {epoch}: loss={float(np.asarray(lo)):.4f} "
              f"acc={float(np.asarray(ac)):.3f}")

    static.save_inference_model("/tmp/resnet_static", ["img"], [logits],
                                exe, main_prog)
    print("saved inference model to /tmp/resnet_static")


if __name__ == "__main__":
    main()
