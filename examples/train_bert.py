"""BERT-base pretraining via the public API (bench.py's config as a
user-style script; set BERT_SMOKE=1 for a tiny CPU run)."""
import os
import time

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import amp, optimizer
from paddle_tpu.jit import TrainStep
from paddle_tpu.models.bert import BertConfig, BertForPretraining

smoke = os.environ.get("BERT_SMOKE") == "1"
paddle.seed(0)
print("device:", paddle.get_device())

cfg = BertConfig.tiny() if smoke else BertConfig.base()
batch, seq, steps = (4, 32, 5) if smoke else (128, 128, 50)
model = BertForPretraining(cfg)
opt = optimizer.AdamW(learning_rate=1e-4, parameters=model.parameters())


def loss_fn(m, ids, tt, mlm, nsp):
    with amp.auto_cast(level="O1", dtype="bfloat16"):
        return m.loss(ids, tt, mlm, nsp)


step = TrainStep(model, loss_fn, opt)

rng = np.random.RandomState(0)
ids = paddle.to_tensor(
    rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32))
tt = paddle.to_tensor(np.zeros((batch, seq), np.int32))
mlm = paddle.to_tensor(
    rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32))
nsp = paddle.to_tensor(rng.randint(0, 2, (batch,)).astype(np.int32))

t0 = time.time()
loss0 = float(step(ids, tt, mlm, nsp))
print(f"compile+first step: {time.time() - t0:.1f}s, loss {loss0:.4f}")
t0 = time.time()
for i in range(steps):
    loss = step(ids, tt, mlm, nsp)
loss = float(loss)
dt = time.time() - t0
print(f"{steps} steps, loss {loss0:.4f} -> {loss:.4f}, "
      f"{batch * seq * steps / dt:,.0f} tokens/s")
if smoke:
    assert np.isfinite(loss), loss   # 5 tiny steps: finite is the gate
else:
    assert loss < loss0, "loss must decrease"
print("OK")
