"""WGAN-GP on synthetic 2-D data: the gradient-penalty term exercises
eager double-grad — paddle.grad(..., create_graph=True) — end to end
(reference pattern: test_imperative_double_grad.py / the dygraph
gradient-penalty GAN recipe over partial_grad_engine.cc)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import autograd, nn, optimizer

paddle.seed(0)
rng = np.random.RandomState(0)

LATENT, DATA = 4, 2
BATCH, STEPS, GP_W = 64, 30, 10.0


def real_batch():
    # two-moon-ish gaussian mixture
    c = rng.randint(0, 2, (BATCH, 1)).astype(np.float32)
    x = rng.randn(BATCH, DATA).astype(np.float32) * 0.2 + \
        np.concatenate([c * 2 - 1, 1 - c * 2], 1)
    return paddle.to_tensor(x)


G = nn.Sequential(nn.Linear(LATENT, 32), nn.ReLU(), nn.Linear(32, DATA))
D = nn.Sequential(nn.Linear(DATA, 32), nn.ReLU(), nn.Linear(32, 1))
g_opt = optimizer.Adam(learning_rate=1e-3, parameters=G.parameters())
d_opt = optimizer.Adam(learning_rate=1e-3, parameters=D.parameters())

first_gp = last_gp = None
for step in range(STEPS):
    # -- critic with gradient penalty
    real = real_batch()
    z = paddle.to_tensor(rng.randn(BATCH, LATENT).astype(np.float32))
    fake = G(z).detach()
    eps = paddle.to_tensor(rng.rand(BATCH, 1).astype(np.float32))
    inter = paddle.to_tensor(
        (eps.numpy() * real.numpy() + (1 - eps.numpy()) * fake.numpy()),
        stop_gradient=False)
    d_inter = D(inter).sum()
    (grad_x,) = autograd.grad(d_inter, [inter], create_graph=True)
    gp = (((grad_x * grad_x).sum(axis=1) + 1e-12).sqrt() - 1.0)
    gp = (gp * gp).mean() * GP_W
    d_loss = D(fake).mean() - D(real).mean() + gp
    d_loss.backward()
    d_opt.step()
    d_opt.clear_grad()

    # -- generator
    z = paddle.to_tensor(rng.randn(BATCH, LATENT).astype(np.float32))
    g_loss = -D(G(z)).mean()
    g_loss.backward()
    g_opt.step()
    g_opt.clear_grad()

    if step == 0:
        first_gp = float(gp.numpy())
    last_gp = float(gp.numpy())
    if step % 10 == 0:
        print(f"step {step}: d_loss={float(d_loss.numpy()):.4f} "
              f"gp={float(gp.numpy()):.4f} "
              f"g_loss={float(g_loss.numpy()):.4f}")

print(f"gp first={first_gp:.4f} last={last_gp:.4f}")
assert np.isfinite(last_gp)
print("OK")
