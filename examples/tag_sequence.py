"""Sequence tagging with the hapi BiGRU-CRF model
(paddle_tpu.incubate.SequenceTagging — reference
incubate/hapi/text lexical-analysis example).

Synthetic task: tag each token with its bucket (token id // bucket
size), so the mapping is learnable from the embedding alone and the
CRF transition matrix learns to trust the emissions. Trains eagerly,
then viterbi-decodes and reports exact-match tag accuracy.

Run (CPU): PYTHONPATH=. JAX_PLATFORMS=cpu python examples/tag_sequence.py
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import incubate

VOCAB, LABELS, BUCKET = 40, 4, 10
BATCH, SEQ, STEPS = 16, 12, 60


def batch(rng):
    words = rng.randint(0, VOCAB, (BATCH, SEQ))
    tags = words // BUCKET
    lengths = rng.randint(SEQ // 2, SEQ + 1, BATCH)
    return words, tags, lengths


def main():
    rng = np.random.RandomState(0)
    model = incubate.SequenceTagging(vocab_size=VOCAB, num_labels=LABELS,
                                     word_emb_dim=32, grnn_hidden_dim=32,
                                     bigru_num=1)
    opt = paddle.optimizer.Adam(learning_rate=0.02,
                                parameters=list(model.parameters()))
    for step in range(STEPS):
        words, tags, lengths = batch(rng)
        # the CRF forward already returns the scalar batch-mean loss
        loss = model(paddle.to_tensor(words), paddle.to_tensor(tags),
                     paddle.to_tensor(lengths))
        loss.backward()
        opt.step()
        opt.clear_grad()
        if step % 10 == 0:
            print(f"step {step:3d}  crf loss {float(loss.value):.4f}")

    words, tags, lengths = batch(rng)
    path = np.asarray(model(paddle.to_tensor(words),
                            lengths=paddle.to_tensor(lengths)).value)
    mask = np.arange(SEQ)[None, :] < lengths[:, None]
    acc = (path == tags)[mask].mean()
    print(f"viterbi tag accuracy on valid positions: {acc:.3f}")
    assert acc > 0.9, "tagging did not converge"
    print("OK")


if __name__ == "__main__":
    main()
