"""Wide&Deep-style CTR with the sparse side on a local parameter server
(the reference's dist_fleet_ctr flow: pserver + trainer pull/push over
the TCP KV service; BASELINE config 5 shape)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.ps import SparseEmbedding
from paddle_tpu.ps.service import PSClient, PSServer
from paddle_tpu.ps.table import SparseTable

paddle.seed(0)
FIELDS, VOCAB, DIM, DENSE = 8, 10000, 16, 4

# -- "cluster": one in-process pserver (the reference spawns subprocesses;
# the wire protocol is identical either way)
server = PSServer({0: SparseTable(dim=DIM)}, num_trainers=1).start()
client = PSClient([server.endpoint])
client.start_heartbeat(trainer_id=0, interval_s=5.0)


class WideDeepPS(nn.Layer):
    def __init__(self):
        super().__init__()
        self.emb = SparseEmbedding(DIM, client=client, table_id=0)
        self.deep = nn.Sequential(
            nn.Linear(FIELDS * DIM + DENSE, 64), nn.ReLU(),
            nn.Linear(64, 1))

    def forward(self, ids, dense):
        vecs = self.emb(ids)                       # (B, FIELDS, DIM)
        flat = paddle.reshape(vecs, [ids.shape[0], FIELDS * DIM])
        return self.deep(paddle.concat([flat, dense], axis=1))


model = WideDeepPS()
dense_params = [p for p in model.parameters()]
opt = optimizer.Adam(learning_rate=1e-3, parameters=dense_params)
bce = nn.BCEWithLogitsLoss()
rng = np.random.RandomState(0)

first = last = None
for step_i in range(60):
    ids = paddle.to_tensor(
        rng.randint(0, VOCAB, (64, FIELDS)).astype("int64"))
    dense_np = rng.randn(64, DENSE).astype("float32")
    label = (dense_np.sum(1, keepdims=True) > 0).astype("float32")
    logits = model(ids, paddle.to_tensor(dense_np))
    loss = bce(logits, paddle.to_tensor(label))
    loss.backward()
    model.emb.push_gradients(lr=0.05)   # sparse grads -> pserver
    opt.step()                          # dense params update locally
    opt.clear_grad()
    if first is None:
        first = float(loss)
    last = float(loss)
    if step_i % 20 == 0:
        print(f"step {step_i}: loss {last:.4f}")

print(f"loss {first:.4f} -> {last:.4f}; server rows: {client.rows(0)}")
assert last < first
client.stop_heartbeat(trainer_id=0)
client.stop_servers()
client.close()
server.stop()
print("OK")
