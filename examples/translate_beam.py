"""Transformer NMT: train a copy task, then decode with beam search
(the reference's book/test_machine_translation.py flow on the TPU build)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import optimizer
from paddle_tpu.jit import TrainStep
from paddle_tpu.models.transformer import TransformerNMT

paddle.seed(0)
VOCAB, L, BOS, EOS, PAD = 20, 6, 1, 2, 0

model = TransformerNMT(src_vocab_size=VOCAB, tgt_vocab_size=VOCAB,
                       d_model=64, nhead=4, num_encoder_layers=2,
                       num_decoder_layers=2, dim_feedforward=128,
                       dropout=0.0, max_len=64)
opt = optimizer.Adam(learning_rate=1e-3, parameters=model.parameters())
step = TrainStep(model, lambda m, s, ti, to: m.loss(s, ti, to, pad_id=PAD),
                 opt)

rng = np.random.RandomState(0)


def make_batch(n=64):
    src = rng.randint(3, VOCAB, (n, L)).astype("int64")
    tgt = np.concatenate([np.full((n, 1), BOS), src,
                          np.full((n, 1), EOS)], axis=1).astype("int64")
    return (paddle.to_tensor(src), paddle.to_tensor(tgt[:, :-1]),
            paddle.to_tensor(tgt[:, 1:]))


for i in range(300):
    loss = step(*make_batch())
    if i % 50 == 0:
        print(f"step {i}: loss {float(loss):.4f}")

model.eval()
src, _, _ = make_batch(4)
ids, scores = model.beam_search_decode(src, beam_size=4, bos_id=BOS,
                                       eos_id=EOS, max_len=L + 2)
best = ids.numpy()[:, 0, 1:L + 1]
acc = (best == src.numpy()).mean()
print(f"beam-search copy accuracy: {acc:.2%}")
assert acc > 0.8, acc
print("OK")
