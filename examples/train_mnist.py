"""User-style training script: LeNet on MNIST via the public API."""
import time
import numpy as np
import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.io import DataLoader
from paddle_tpu.vision.datasets import MNIST
from paddle_tpu.vision.models import LeNet
from paddle_tpu.jit import TrainStep

paddle.seed(0)
print("device:", paddle.get_device())

train_ds = MNIST(mode="train")
loader = DataLoader(train_ds, batch_size=128, shuffle=True, drop_last=True)

model = LeNet(num_classes=10)
opt = optimizer.Adam(learning_rate=1e-3, parameters=model.parameters())
ce = nn.CrossEntropyLoss()
step = TrainStep(model, lambda m, x, y: ce(m(x), y), opt)

t0 = time.time()
first = last = None
n = 0
for epoch in range(3):
    for x, y in loader:
        loss = step(x, y)
        n += 1
        if first is None:
            first = float(loss)
            print(f"compile+first step: {time.time()-t0:.1f}s")
        last = float(loss)
print(f"steps={n} first_loss={first:.4f} last_loss={last:.4f}")
assert last < first * 0.5, "loss did not decrease"

# eval through eager path
model.eval()
xb, yb = next(iter(DataLoader(MNIST(mode="test"), batch_size=256)))
with paddle.no_grad():
    logits = model(xb)
acc = float((logits.argmax(-1) == yb).astype("float32").mean())
print(f"test acc: {acc:.3f}")
assert acc > 0.9, "synthetic MNIST should be nearly separable"

# checkpoint round-trip
paddle.save(model.state_dict(), "/tmp/vdemo/lenet.pdparams")
m2 = LeNet()
m2.set_state_dict(paddle.load("/tmp/vdemo/lenet.pdparams"))
d = float(abs(m2.fc[0].weight.numpy() - model.fc[0].weight.numpy()).max())
print("save/load max param delta:", d)
assert d == 0.0
print("OK")
