"""paddle_tpu.nn — layers and functional ops (reference python/paddle/nn)."""
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .layer import Layer, Parameter, ParamAttr  # noqa: F401
from .container import Sequential, LayerList, LayerDict, ParameterList  # noqa: F401
from .common import (  # noqa: F401
    Identity, Linear, Embedding, Dropout, Dropout2D, Dropout3D, AlphaDropout,
    Flatten, Upsample, UpsamplingBilinear2D, UpsamplingNearest2D,
    PixelShuffle, Pad1D, Pad2D, Pad3D, CosineSimilarity, Bilinear,
    ReLU, ReLU6, LeakyReLU, ELU, CELU, SELU, GELU, Silu, Swish, Mish,
    Hardswish, Hardsigmoid, Hardtanh, Hardshrink, Softshrink, Tanhshrink,
    Softplus, Softsign, Sigmoid, LogSigmoid, Tanh, Softmax, LogSoftmax,
    ThresholdedReLU, Maxout, PReLU,
)
from .conv import (  # noqa: F401
    Conv1D, Conv2D, Conv3D, Conv1DTranspose, Conv2DTranspose,
    Conv3DTranspose,
)
from .norm import (  # noqa: F401
    BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D, SyncBatchNorm,
    LayerNorm, GroupNorm, InstanceNorm1D, InstanceNorm2D, InstanceNorm3D,
    LocalResponseNorm, SpectralNorm,
)
from .pooling import (  # noqa: F401
    MaxPool1D, MaxPool2D, MaxPool3D, AvgPool1D, AvgPool2D, AvgPool3D,
    AdaptiveAvgPool1D, AdaptiveAvgPool2D, AdaptiveAvgPool3D,
    AdaptiveMaxPool1D, AdaptiveMaxPool2D, AdaptiveMaxPool3D,
)
from .loss import (  # noqa: F401
    CrossEntropyLoss, NLLLoss, BCELoss, BCEWithLogitsLoss, MSELoss, L1Loss,
    SmoothL1Loss, KLDivLoss, MarginRankingLoss, HingeEmbeddingLoss,
    CosineEmbeddingLoss, TripletMarginLoss, CTCLoss,
)
from .transformer import (  # noqa: F401
    MultiHeadAttention, TransformerEncoderLayer, TransformerEncoder,
    TransformerDecoderLayer, TransformerDecoder, Transformer,
)
from .rnn import (  # noqa: F401
    RNNCellBase, SimpleRNNCell, LSTMCell, GRUCell, RNN, BiRNN, SimpleRNN,
    LSTM, GRU,
)
from .decode import (  # noqa: F401
    Decoder, BeamSearchDecoder, dynamic_decode, DecodeHelper,
    TrainingHelper, GreedyEmbeddingHelper, SampleEmbeddingHelper,
    BasicDecoder,
)
from .clip import ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm  # noqa: F401
from .moe import MoELayer, moe_apply_ep, MOE_EP_RULES  # noqa: F401
from .crf import LinearChainCRF, crf_decoding, linear_chain_crf  # noqa: F401,E402

# 2.0-alpha surface parity: pre-rename spellings + functional re-exports
# + the layers that only lived there (must import LAST — it fills gaps
# without overriding anything above)
from . import compat20  # noqa: F401,E402
