"""Linear-chain CRF.

Parity with /root/reference/paddle/fluid/operators/linear_chain_crf_op.cc
and crf_decoding_op.cc (fluid.layers.linear_chain_crf / crf_decoding),
used for sequence labeling (the label_semantic_roles book test).

Transition layout matches the reference: (num_tags + 2, num_tags) —
row 0 start weights, row 1 stop weights, rows 2: pairwise[from, to].
TPU-native shape: dense (B, L, T) emissions + lengths, recursions as
lax.scan in log space (one compiled kernel; the reference loops per
sequence on CPU).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.op import primitive
from ..framework.tensor import Tensor, unwrap
from .layer import Layer


@primitive("linear_chain_crf", nondiff=("label", "lengths"))
def linear_chain_crf(emission, transition, label, lengths, name=None):
    """Per-sequence log-likelihood log p(label | emission).

    emission: (B, L, T) unary scores; transition: (T+2, T);
    label: (B, L) int; lengths: (B,). Returns (B, 1) log-likelihoods
    (negative numbers; the training loss is their negated sum).
    """
    start, stop, pair = transition[0], transition[1], transition[2:]
    B, L, T = emission.shape
    lens = jnp.asarray(lengths)
    label = jnp.asarray(label)

    # -- partition function: forward algorithm over time ------------------
    alpha0 = start[None, :] + emission[:, 0, :]            # (B, T)

    def fwd(alpha, t):
        e_t = emission[:, t, :]
        nxt = jax.scipy.special.logsumexp(
            alpha[:, :, None] + pair[None, :, :], axis=1) + e_t
        keep = (t < lens)[:, None]
        return jnp.where(keep, nxt, alpha), None

    alpha, _ = jax.lax.scan(fwd, alpha0, jnp.arange(1, L)) \
        if L > 1 else (alpha0, None)
    log_z = jax.scipy.special.logsumexp(alpha + stop[None, :], axis=1)

    # -- gold path score ----------------------------------------------------
    pos = jnp.arange(L)
    unary = jnp.take_along_axis(emission, label[:, :, None],
                                axis=2)[..., 0]            # (B, L)
    unary = jnp.where(pos[None, :] < lens[:, None], unary, 0.0)
    trans_score = pair[label[:, :-1], label[:, 1:]] if L > 1 else \
        jnp.zeros((B, 0))
    trans_score = jnp.where(pos[None, 1:] < lens[:, None],
                            trans_score, 0.0)
    last = jnp.clip(lens - 1, 0, L - 1)
    last_tag = jnp.take_along_axis(label, last[:, None], axis=1)[:, 0]
    score = (unary.sum(1) + trans_score.sum(1)
             + start[label[:, 0]] + stop[last_tag])
    return (score - log_z)[:, None]


def crf_decoding(emission, transition, lengths, label=None, name=None):
    """Viterbi decode (crf_decoding_op.cc). Returns the best tag path
    (B, L) int64 — or, when `label` is given, a (B, L) 0/1 mask marking
    positions where the argmax path agrees with the label (the
    reference's evaluation mode)."""
    em = jnp.asarray(unwrap(emission), jnp.float32)
    tr = jnp.asarray(unwrap(transition), jnp.float32)
    lens = jnp.asarray(unwrap(lengths))
    start, stop, pair = tr[0], tr[1], tr[2:]
    B, L, T = em.shape

    delta0 = start[None, :] + em[:, 0, :]

    def step(delta, t):
        cand = delta[:, :, None] + pair[None, :, :]        # (B, from, to)
        best = jnp.max(cand, axis=1) + em[:, t, :]
        arg = jnp.argmax(cand, axis=1)                     # (B, T)
        keep = (t < lens)[:, None]
        return jnp.where(keep, best, delta), arg

    if L > 1:
        delta, args = jax.lax.scan(step, delta0, jnp.arange(1, L))
    else:
        delta, args = delta0, jnp.zeros((0, B, T), jnp.int32)

    final = delta + stop[None, :]
    last_tag = jnp.argmax(final, axis=1)                   # (B,)

    path = [last_tag]
    tag = last_tag
    for t in range(L - 1, 0, -1):
        prev = jnp.take_along_axis(args[t - 1], tag[:, None], axis=1)[:, 0]
        tag = jnp.where(t < lens, prev, tag)
        path.append(tag)
    path = jnp.stack(path[::-1], axis=1)                   # (B, L)
    # positions past length: pad with 0
    pos = jnp.arange(L)[None, :]
    path = jnp.where(pos < lens[:, None], path, 0)
    if label is not None:
        gold = jnp.asarray(unwrap(label))
        return Tensor((path == gold).astype(jnp.int64)
                      * (pos < lens[:, None]))
    return Tensor(path.astype(jnp.int64))


class LinearChainCRF(Layer):
    """CRF layer owning the transition parameters (fluid exposes this via
    param_attr on the linear_chain_crf layer)."""

    def __init__(self, num_tags: int, param_attr=None, name=None):
        super().__init__()
        self.num_tags = num_tags
        self.transition = self.create_parameter(
            [num_tags + 2, num_tags], attr=param_attr)

    def forward(self, emission, label, lengths):
        ll = linear_chain_crf(emission, self.transition, label, lengths)
        return -ll.mean()

    def decode(self, emission, lengths):
        return crf_decoding(emission, self.transition, lengths)
