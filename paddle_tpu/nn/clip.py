"""Gradient clipping (reference python/paddle/fluid/clip.py:
GradientClipByValue :117, GradientClipByNorm :186, GradientClipByGlobalNorm
:254). Clips operate on (param, grad) lists — used by optimizers before the
update rule, both eagerly and inside jitted train steps.
"""
from __future__ import annotations

import jax.numpy as jnp


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError

    def apply_pytree(self, grads):
        """Functional form over a pytree of raw arrays (for jitted steps)."""
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def apply_pytree(self, grads):
        import jax

        return jax.tree_util.tree_map(
            lambda g: jnp.clip(g, self.min, self.max), grads)


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def apply_pytree(self, grads):
        import jax

        def clip_one(g):
            norm = jnp.sqrt(jnp.sum(jnp.square(g)))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            return g * scale

        return jax.tree_util.tree_map(clip_one, grads)


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)

    def apply_pytree(self, grads):
        import jax

        leaves = jax.tree_util.tree_leaves(grads)
        gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(g)) for g in leaves))
        scale = self.clip_norm / jnp.maximum(gnorm, self.clip_norm)
        return jax.tree_util.tree_map(lambda g: g * scale, grads)


# reference-name aliases
GradientClipByValue = ClipGradByValue
GradientClipByNorm = ClipGradByNorm
GradientClipByGlobalNorm = ClipGradByGlobalNorm


def clip_grad_norm_(parameters, max_norm):
    """Eager utility over Tensors (mutates .grad)."""
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return 0.0
    gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(g.value)) for g in grads))
    scale = float(max_norm) / jnp.maximum(gnorm, float(max_norm))
    for g in grads:
        g._value = g._value * scale
    return float(gnorm)


class ErrorClipByValue:
    """Per-variable backward error clipping (reference fluid/clip.py:46
    ErrorClipByValue, attached to a var's error_clip and applied to its
    gradient ops)."""

    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def append_clip_op(self, block, grad_name):
        block.append_op(type="clip", inputs={"X": [grad_name]},
                        outputs={"Out": [grad_name]},
                        attrs={"min": self.min, "max": self.max})


def _is_static_pairs(params_grads):
    from ..static.ir import Variable
    return bool(params_grads) and isinstance(params_grads[0][1], Variable)


def _eager_pairs(self, params_grads):
    from ..framework.tensor import Tensor
    arrs = self.apply_pytree([g._value for _, g in params_grads])
    return [(p, Tensor(a)) for (p, _), a in zip(params_grads, arrs)]


def _by_value_call(self, params_grads):
    """(param, grad) pair form used by static Optimizer.minimize
    (reference GradientClipBase: _static_clip vs _dygraph_clip)."""
    if _is_static_pairs(params_grads):
        from ..static import layers as L
        return [(p, L.clip(g, self.min, self.max)) for p, g in params_grads]
    return _eager_pairs(self, params_grads)


def _by_norm_call(self, params_grads):
    if _is_static_pairs(params_grads):
        from ..static import layers as L
        out = []
        for p, g in params_grads:
            norm = L.sqrt(L.reduce_sum(L.square(g)))
            limit = L.fill_constant([1], g.dtype, self.clip_norm)
            scale = L.elementwise_div(limit, L.elementwise_max(norm, limit))
            out.append((p, L.elementwise_mul(g, scale)))
        return out
    return _eager_pairs(self, params_grads)


def _by_global_norm_call(self, params_grads):
    if _is_static_pairs(params_grads):
        from ..static import layers as L
        total = None
        for _, g in params_grads:
            s = L.reduce_sum(L.square(g))
            total = s if total is None else L.elementwise_add(total, s)
        limit = L.fill_constant([1], params_grads[0][1].dtype,
                                self.clip_norm)
        scale = L.elementwise_div(
            limit, L.elementwise_max(L.sqrt(total), limit))
        return [(p, L.elementwise_mul(g, scale)) for p, g in params_grads]
    return _eager_pairs(self, params_grads)


ClipGradByValue.__call__ = _by_value_call
ClipGradByNorm.__call__ = _by_norm_call
ClipGradByGlobalNorm.__call__ = _by_global_norm_call


def set_gradient_clip(clip, param_list=None, program=None):
    """fluid.clip.set_gradient_clip parity — delegates to the static
    optimizer-side registration (static/optimizer.py applies it at
    minimize time). Lazy import: static imports this module at load."""
    from ..static.optimizer import set_gradient_clip as _impl

    return _impl(clip, param_list=param_list, program=program)
