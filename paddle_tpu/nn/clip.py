"""Gradient clipping (reference python/paddle/fluid/clip.py:
GradientClipByValue :117, GradientClipByNorm :186, GradientClipByGlobalNorm
:254). Clips operate on (param, grad) lists — used by optimizers before the
update rule, both eagerly and inside jitted train steps.
"""
from __future__ import annotations

import jax.numpy as jnp


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError

    def apply_pytree(self, grads):
        """Functional form over a pytree of raw arrays (for jitted steps)."""
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def apply_pytree(self, grads):
        import jax

        return jax.tree_util.tree_map(
            lambda g: jnp.clip(g, self.min, self.max), grads)


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def apply_pytree(self, grads):
        import jax

        def clip_one(g):
            norm = jnp.sqrt(jnp.sum(jnp.square(g)))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            return g * scale

        return jax.tree_util.tree_map(clip_one, grads)


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)

    def apply_pytree(self, grads):
        import jax

        leaves = jax.tree_util.tree_leaves(grads)
        gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(g)) for g in leaves))
        scale = self.clip_norm / jnp.maximum(gnorm, self.clip_norm)
        return jax.tree_util.tree_map(lambda g: g * scale, grads)


# reference-name aliases
GradientClipByValue = ClipGradByValue
GradientClipByNorm = ClipGradByNorm
GradientClipByGlobalNorm = ClipGradByGlobalNorm


def clip_grad_norm_(parameters, max_norm):
    """Eager utility over Tensors (mutates .grad)."""
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return 0.0
    gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(g.value)) for g in grads))
    scale = float(max_norm) / jnp.maximum(gnorm, float(max_norm))
    for g in grads:
        g._value = g._value * scale
    return float(gnorm)
