"""Parameter initializers.

Parity with /root/reference/python/paddle/fluid/initializer.py
(Constant :120, Uniform :214, Normal :315, Xavier :484, MSRA :613,
Bilinear :744, Assign :857): each initializer is a callable producing a
jax array for a given shape/dtype from the framework PRNG.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtype_mod
from ..framework.random import next_rng_key


def _fans(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    # conv kernels stored OIHW-style (cout, cin, kh, kw)
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(shape, self.value, dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        return jax.random.uniform(next_rng_key(), shape, dtype, self.low, self.high)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0, seed=0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        return self.mean + self.std * jax.random.normal(next_rng_key(), shape, dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, seed=0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        n = jax.random.truncated_normal(next_rng_key(), -2.0, 2.0, shape, dtype)
        return self.mean + self.std * n


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, seed=0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(next_rng_key(), shape, dtype, -limit, limit)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, seed=0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return std * jax.random.normal(next_rng_key(), shape, dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(next_rng_key(), shape, dtype, -limit, limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        std = gain / math.sqrt(fi)
        return std * jax.random.normal(next_rng_key(), shape, dtype)


# reference-name aliases (fluid.initializer). MSRAInitializer defaults
# to uniform=True in the reference (initializer.py:573), i.e. the
# Kaiming-UNIFORM draw.
MSRAInitializer = KaimingUniform
XavierInitializer = XavierUniform
NormalInitializer = Normal
UniformInitializer = Uniform
ConstantInitializer = Constant
TruncatedNormalInitializer = TruncatedNormal


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        arr = jnp.asarray(np.asarray(self.value), dtype)
        if tuple(arr.shape) != tuple(shape):
            raise ValueError(f"Assign shape {arr.shape} != {shape}")
        return arr


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        return self.gain * jax.nn.initializers.orthogonal()(
            next_rng_key(), shape, dtype)


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype):
        out = np.zeros(shape, dtype=np.float32)
        cout, cin = shape[0], shape[1]
        centers = [s // 2 for s in shape[2:]]
        for i in range(min(cout, cin * self.groups)):
            out[(i, i % cin) + tuple(centers)] = 1.0
        return jnp.asarray(out, dtype)


class Bilinear(Initializer):
    def __call__(self, shape, dtype):
        # upsampling deconv kernel (reference initializer.py:744)
        f = math.ceil(shape[-1] / 2)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        out = np.zeros(shape, dtype=np.float32)
        size = int(np.prod(shape))
        weight = np.zeros(size, dtype=np.float32)
        for i in range(size):
            x = i % shape[-1]
            y = (i // shape[-1]) % shape[-2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        return jnp.asarray(weight.reshape(shape), dtype)


NumpyArrayInitializer = Assign
BilinearInitializer = Bilinear

# set_global_initializer (reference fluid/initializer.py:974): process-wide
# default weight/bias initializers consulted when a parameter has neither
# an explicit initializer nor a caller-supplied default override.
_global_weight_init = None
_global_bias_init = None


def set_global_initializer(weight_init, bias_init=None):
    global _global_weight_init, _global_bias_init
    if weight_init is not None and not isinstance(weight_init, Initializer):
        raise TypeError("weight_init must be an Initializer or None")
    if bias_init is not None and not isinstance(bias_init, Initializer):
        raise TypeError("bias_init must be an Initializer or None")
    _global_weight_init = weight_init
    _global_bias_init = bias_init


def global_initializer(is_bias):
    return _global_bias_init if is_bias else _global_weight_init


def _resolve(init, default):
    """ParamAttr/initializer plumbing: accept None, Initializer, number."""
    if init is None:
        return default
    if isinstance(init, Initializer):
        return init
    if isinstance(init, (int, float)):
        return Constant(float(init))
    if callable(init):
        return init
    raise TypeError(f"Cannot use {init!r} as an initializer")


def calculate_gain(nonlinearity, param=None):
    gains = {
        "sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
        "conv3d": 1.0, "tanh": 5.0 / 3.0, "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param if param is not None else 0.01) ** 2)),
        "selu": 3.0 / 4.0,
    }
    return gains[nonlinearity]

def __getattr__(name):
    # fluid.initializer short names Xavier/MSRA resolve to the faithful
    # fluid classes (uniform=True default — static/initializer.py), not
    # the 2.0 XavierUniform/KaimingUniform spellings above. Lazy: the
    # static package imports this module at load.
    if name in ("Xavier", "MSRA"):
        from ..static import initializer as _SI

        return getattr(_SI, name)
    raise AttributeError(name)
