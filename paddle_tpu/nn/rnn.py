"""Recurrent layers: SimpleRNN / LSTM / GRU.

Parity with /root/reference/python/paddle/nn/layer/rnn.py (RNNCellBase :88,
LSTMCell :258, GRUCell :399, RNN :522, SimpleRNN/LSTM/GRU :770+) and the
fluid dynamic_rnn ops. The time loop is jax.lax.scan — a single compiled
XLA while-loop (no per-step kernel launches like the reference CUDA path).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.op import primitive
from ..framework.tensor import Tensor, unwrap
from . import initializer as I
from .layer import Layer


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        from ..ops.creation import full

        b = unwrap(batch_ref).shape[batch_dim_idx]
        shape = shape or (self.hidden_size,)
        return full((b,) + tuple(shape), init_value,
                    dtype=dtype or "float32")


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [hidden_size, input_size], attr=weight_ih_attr, default_initializer=u)
        self.weight_hh = self.create_parameter(
            [hidden_size, hidden_size], attr=weight_hh_attr, default_initializer=u)
        self.bias_ih = self.create_parameter(
            [hidden_size], attr=bias_ih_attr, is_bias=True, default_initializer=u)
        self.bias_hh = self.create_parameter(
            [hidden_size], attr=bias_hh_attr, is_bias=True, default_initializer=u)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h = _simple_rnn_cell(inputs, states, self.weight_ih, self.weight_hh,
                             self.bias_ih, self.bias_hh,
                             act=self.activation)
        return h, h

    @property
    def state_shape(self):
        return (self.hidden_size,)


@primitive("simple_rnn_cell")
def _simple_rnn_cell(x, h, w_ih, w_hh, b_ih, b_hh, act):
    z = x @ w_ih.T + h @ w_hh.T
    if b_ih is not None:
        z = z + b_ih
    if b_hh is not None:
        z = z + b_hh
    return jnp.tanh(z) if act == "tanh" else jax.nn.relu(z)


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [4 * hidden_size, input_size], attr=weight_ih_attr,
            default_initializer=u)
        self.weight_hh = self.create_parameter(
            [4 * hidden_size, hidden_size], attr=weight_hh_attr,
            default_initializer=u)
        self.bias_ih = self.create_parameter(
            [4 * hidden_size], attr=bias_ih_attr, is_bias=True,
            default_initializer=u)
        self.bias_hh = self.create_parameter(
            [4 * hidden_size], attr=bias_hh_attr, is_bias=True,
            default_initializer=u)

    def forward(self, inputs, states=None):
        if states is None:
            h = self.get_initial_states(inputs)
            c = self.get_initial_states(inputs)
        else:
            h, c = states
        h2, c2 = _lstm_cell(inputs, h, c, self.weight_ih, self.weight_hh,
                            self.bias_ih, self.bias_hh)
        return h2, (h2, c2)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))


@primitive("lstm_cell")
def _lstm_cell(x, h, c, w_ih, w_hh, b_ih, b_hh):
    z = x @ w_ih.T + h @ w_hh.T
    if b_ih is not None:
        z = z + b_ih
    if b_hh is not None:
        z = z + b_hh
    i, f, g, o = jnp.split(z, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    c2 = f * c + i * g
    h2 = o * jnp.tanh(c2)
    return h2, c2


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [3 * hidden_size, input_size], attr=weight_ih_attr,
            default_initializer=u)
        self.weight_hh = self.create_parameter(
            [3 * hidden_size, hidden_size], attr=weight_hh_attr,
            default_initializer=u)
        self.bias_ih = self.create_parameter(
            [3 * hidden_size], attr=bias_ih_attr, is_bias=True,
            default_initializer=u)
        self.bias_hh = self.create_parameter(
            [3 * hidden_size], attr=bias_hh_attr, is_bias=True,
            default_initializer=u)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h = _gru_cell(inputs, states, self.weight_ih, self.weight_hh,
                      self.bias_ih, self.bias_hh)
        return h, h

    @property
    def state_shape(self):
        return (self.hidden_size,)


@primitive("gru_cell")
def _gru_cell(x, h, w_ih, w_hh, b_ih, b_hh):
    gi = x @ w_ih.T
    gh = h @ w_hh.T
    if b_ih is not None:
        gi = gi + b_ih
    if b_hh is not None:
        gh = gh + b_hh
    ir, iz, ic = jnp.split(gi, 3, axis=-1)
    hr, hz, hc = jnp.split(gh, 3, axis=-1)
    r = jax.nn.sigmoid(ir + hr)
    z = jax.nn.sigmoid(iz + hz)
    c = jnp.tanh(ic + r * hc)
    return (1 - z) * c + z * h


class RNN(Layer):
    """Wraps a cell into a scan over time (reference rnn.py:522)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        outs, states = _rnn_scan_layer(self.cell, inputs, initial_states,
                                       sequence_length, self.is_reverse,
                                       self.time_major)
        return outs, states


def _valid_mask(seq_len, T, reverse):
    """(T, b) bool mask of valid steps in scan order, or None.

    Forward: step t valid while t < len. Reverse (inputs pre-flipped):
    the valid region sits at the tail of the flipped sequence, so the
    carry stays frozen at the initial state until t >= T - len — the
    backward pass then starts exactly at original position len-1 instead
    of consuming pad embeddings (reference rnn.py mask_fn semantics)."""
    if seq_len is None:
        return None
    lens = jnp.asarray(seq_len)
    t = jnp.arange(T)[:, None]
    if reverse:
        return t >= (T - lens)[None, :]
    return t < lens[None, :]


def _rnn_scan_layer(cell, inputs, initial_states, sequence_length, is_reverse,
                    time_major):
    """Run the cell over time with one traced scan (weights read from cell)."""
    from ..framework import tape as tape_mod
    from ..framework.op import primitive as _prim

    is_lstm = isinstance(cell, LSTMCell)
    x = inputs
    if initial_states is None:
        b = unwrap(x).shape[1 if time_major else 0]
        hs = cell.hidden_size
        from ..ops.creation import zeros

        if is_lstm:
            initial_states = (zeros([b, hs]), zeros([b, hs]))
        else:
            initial_states = zeros([b, hs])

    w = [cell.weight_ih, cell.weight_hh, cell.bias_ih, cell.bias_hh]

    if is_lstm:
        h0, c0 = initial_states

        @_prim("lstm_scan", nondiff=("seq_len",))
        def run(x, h0, c0, w_ih, w_hh, b_ih, b_hh, time_major, reverse, seq_len):
            xs = x if time_major else jnp.swapaxes(x, 0, 1)
            if reverse:
                xs = jnp.flip(xs, 0)
            T = xs.shape[0]
            valid = _valid_mask(seq_len, T, reverse)  # (T, b) or None

            def step(carry, inp):
                h, c = carry
                xt, m = inp
                h2, c2 = _lstm_cell.raw_fn(xt, h, c, w_ih, w_hh, b_ih, b_hh)
                if m is not None:
                    mk = m[:, None]
                    h2 = jnp.where(mk, h2, h)
                    c2 = jnp.where(mk, c2, c)
                    y = jnp.where(mk, h2, 0)
                else:
                    y = h2
                return (h2, c2), y

            (hT, cT), ys = jax.lax.scan(step, (h0, c0), (xs, valid))
            if reverse:
                ys = jnp.flip(ys, 0)
            if not time_major:
                ys = jnp.swapaxes(ys, 0, 1)
            return ys, hT, cT

        ys, hT, cT = run(x, h0, c0, *w, time_major=time_major,
                         reverse=is_reverse, seq_len=sequence_length)
        return ys, (hT, cT)

    h0 = initial_states
    cell_fn = _gru_cell.raw_fn if isinstance(cell, GRUCell) else None
    act = getattr(cell, "activation", "tanh")

    @_prim("rnn_scan", nondiff=("seq_len",))
    def run(x, h0, w_ih, w_hh, b_ih, b_hh, time_major, reverse, is_gru, act,
            seq_len):
        xs = x if time_major else jnp.swapaxes(x, 0, 1)
        if reverse:
            xs = jnp.flip(xs, 0)
        T = xs.shape[0]
        valid = _valid_mask(seq_len, T, reverse)

        def step(h, inp):
            xt, m = inp
            if is_gru:
                h2 = _gru_cell.raw_fn(xt, h, w_ih, w_hh, b_ih, b_hh)
            else:
                h2 = _simple_rnn_cell.raw_fn(xt, h, w_ih, w_hh, b_ih, b_hh, act)
            if m is not None:
                mk = m[:, None]
                h2 = jnp.where(mk, h2, h)
                y = jnp.where(mk, h2, 0)
            else:
                y = h2
            return h2, y

        hT, ys = jax.lax.scan(step, h0, (xs, valid))
        if reverse:
            ys = jnp.flip(ys, 0)
        if not time_major:
            ys = jnp.swapaxes(ys, 0, 1)
        return ys, hT

    ys, hT = run(x, h0, *w, time_major=time_major, reverse=is_reverse,
                 is_gru=isinstance(cell, GRUCell), act=act,
                 seq_len=sequence_length)
    return ys, hT


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from .. import ops

        states_fw, states_bw = (initial_states if initial_states is not None
                                else (None, None))
        out_fw, st_fw = self.fw(inputs, states_fw, sequence_length)
        out_bw, st_bw = self.bw(inputs, states_bw, sequence_length)
        return ops.concat([out_fw, out_bw], axis=-1), (st_fw, st_bw)


class _RNNBase(Layer):
    CELL = None

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation=None, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirectional = direction in ("bidirect", "bidirectional")
        num_dir = 2 if self.bidirectional else 1
        from .container import LayerList

        kw = dict(weight_ih_attr=weight_ih_attr, weight_hh_attr=weight_hh_attr,
                  bias_ih_attr=bias_ih_attr, bias_hh_attr=bias_hh_attr)
        if activation is not None:
            kw["activation"] = activation
        self.rnns = LayerList()
        for layer in range(num_layers):
            isz = input_size if layer == 0 else hidden_size * num_dir
            if self.bidirectional:
                self.rnns.append(BiRNN(self.CELL(isz, hidden_size, **kw),
                                       self.CELL(isz, hidden_size, **kw),
                                       time_major=time_major))
            else:
                self.rnns.append(RNN(self.CELL(isz, hidden_size, **kw),
                                     time_major=time_major))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from . import functional as Fn

        out = inputs
        finals = []
        for i, rnn in enumerate(self.rnns):
            out, st = rnn(out, None, sequence_length)
            finals.append(st)
            if self.dropout > 0 and i < self.num_layers - 1:
                out = Fn.dropout(out, p=self.dropout, training=self.training)
        return out, _stack_states(finals, isinstance(self, LSTM),
                                  self.bidirectional)


def _stack_states(finals, is_lstm, bidirectional):
    from .. import ops

    if bidirectional:
        flat = []
        for st in finals:
            flat.extend(st)
        finals = flat
    if is_lstm:
        h = ops.stack([f[0] for f in finals], axis=0)
        c = ops.stack([f[1] for f in finals], axis=0)
        return (h, c)
    return ops.stack(list(finals), axis=0)


class SimpleRNN(_RNNBase):
    CELL = SimpleRNNCell

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kw):
        super().__init__(input_size, hidden_size, num_layers, direction,
                         time_major, dropout, activation=activation, **kw)


class LSTM(_RNNBase):
    CELL = LSTMCell


class GRU(_RNNBase):
    CELL = GRUCell
