"""paddle.nn 2.0-alpha surface parity (reference python/paddle/nn at
v1.8 — the pre-rename API: Conv2d/AvgPool2d spellings, functional
re-exports at the nn top level, fluid-named initializers/clips, plus a
handful of layers that only ever lived there).

Three kinds of content:
1. Real layers the repo lacked: BilinearTensorProduct (+ functional
   bilinear), PairwiseDistance, RowConv (+ lookahead row_conv if
   absent), HSigmoid (+ functional hsigmoid — complete-binary-tree
   hierarchical softmax, hsigmoid_op.cc), Pool2D (fluid dygraph
   pooling facade), InstanceNorm (rank-dispatching), logsigmoid,
   weight_norm / remove_weight_norm (g * v/||v|| reparametrization via
   forward-pre-hook).
2. Spelling aliases: the since-renamed lowercase-d classes
   (Conv2d -> Conv2D...), pad-mode classes (ReflectionPad2d -> Pad2D
   mode='reflect'), GradientClipBy* -> ClipGradBy*, UpSample,
   initializer short names (Xavier/MSRA/...).
3. Re-exports: every reference paddle.nn __all__ name whose
   implementation lives in nn.functional / static.layers / vision —
   registered on the nn module without overriding existing names.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.op import primitive
from .layer import Layer

__all__ = ["BilinearTensorProduct", "PairwiseDistance", "RowConv",
           "HSigmoid", "Pool2D", "InstanceNorm", "bilinear", "hsigmoid",
           "logsigmoid", "weight_norm", "remove_weight_norm"]

# the reference paddle.nn __all__ at v1.8 (generated once; baked in so
# the re-export sweep has no runtime dependency on the reference tree)
_REFERENCE_NN_ALL = (
    'AdaptiveAvgPool1d', 'AdaptiveAvgPool2d', 'AdaptiveAvgPool3d',
    'AdaptiveMaxPool1d', 'AlphaDropout', 'AvgPool1d', 'AvgPool2d',
    'AvgPool3d', 'BCELoss', 'BCEWithLogitsLoss', 'BatchNorm',
    'Bilinear', 'BilinearTensorProduct', 'CTCLoss', 'Constant',
    'ConstantPad1d', 'ConstantPad2d', 'ConstantPad3d', 'Conv1d',
    'Conv2d', 'Conv3d', 'ConvTranspose1d', 'ConvTranspose2d',
    'ConvTranspose3d', 'CosineSimilarity', 'CrossEntropyLoss',
    'Dropout', 'Dropout2D', 'Dropout3D', 'ELU', 'Embedding', 'GELU',
    'GradientClipByGlobalNorm', 'GradientClipByNorm',
    'GradientClipByValue', 'GroupNorm', 'HSigmoid', 'Hardshrink',
    'Hardtanh', 'InstanceNorm', 'KLDivLoss', 'L1Loss', 'LayerNorm',
    'LeakyReLU', 'Linear', 'LogSigmoid', 'LogSoftmax', 'MSELoss',
    'MSRA', 'MarginRankingLoss', 'MaxPool2d', 'MaxPool3d',
    'MultiHeadAttention', 'NLLLoss', 'Normal', 'PReLU', 'Pad2D',
    'PairwiseDistance', 'PixelShuffle', 'Pool2D', 'ReLU', 'ReLU6',
    'ReflectionPad1d', 'ReflectionPad2d', 'ReplicationPad1d',
    'ReplicationPad2d', 'ReplicationPad3d', 'RowConv', 'SELU',
    'Sigmoid', 'SmoothL1Loss', 'Softmax', 'Softplus', 'Softshrink',
    'Softsign', 'SpectralNorm', 'SyncBatchNorm', 'Tanh', 'Tanhshrink',
    'Transformer', 'TransformerDecoder', 'TransformerDecoderLayer',
    'TransformerEncoder', 'TransformerEncoderLayer', 'TruncatedNormal',
    'Uniform', 'UpSample', 'Xavier', 'ZeroPad2d', 'adaptive_avg_pool1d',
    'adaptive_avg_pool2d', 'adaptive_avg_pool3d', 'adaptive_max_pool1d',
    'adaptive_pool2d', 'adaptive_pool3d', 'add_position_encoding',
    'affine_channel', 'affine_grid', 'alpha_dropout',
    'anchor_generator', 'assign', 'avg_pool1d', 'avg_pool2d',
    'avg_pool3d', 'beam_search', 'beam_search_decode', 'bilinear',
    'binary_cross_entropy', 'binary_cross_entropy_with_logits',
    'bipartite_match', 'box_clip', 'box_coder',
    'box_decoder_and_assign', 'bpr_loss', 'brelu', 'case',
    'center_loss', 'clip', 'clip_by_norm', 'collect_fpn_proposals',
    'cond', 'continuous_value_model', 'conv1d', 'conv2d', 'conv3d',
    'conv_transpose1d', 'conv_transpose2d', 'conv_transpose3d',
    'cosine_decay', 'cosine_similarity', 'cross_entropy', 'ctc_loss',
    'deformable_roi_pooling', 'density_prior_box', 'detection_output',
    'diag_embed', 'dice_loss', 'distribute_fpn_proposals', 'dropout',
    'dropout2d', 'dropout3d', 'edit_distance', 'elu', 'erf',
    'exponential_decay', 'filter_by_instag', 'fsp_matrix',
    'gather_tree', 'gelu', 'generate_mask_labels',
    'generate_proposal_labels', 'generate_proposals', 'grid_sampler',
    'hard_sigmoid', 'hard_swish', 'hardshrink', 'hardtanh', 'hash',
    'hsigmoid', 'huber_loss', 'image_resize', 'image_resize_short',
    'interpolate', 'inverse_time_decay', 'iou_similarity', 'kl_div',
    'l1_loss', 'l2_normalize', 'label_smooth', 'leaky_relu',
    'linear_lr_warmup', 'log_loss', 'log_softmax', 'logsigmoid', 'lrn',
    'margin_ranking_loss', 'maxPool1d', 'max_pool1d', 'max_pool2d',
    'max_pool3d', 'maxout', 'mse_loss', 'multiclass_nms',
    'natural_exp_decay', 'nll_loss', 'noam_decay', 'normalize',
    'npair_loss', 'one_hot', 'pad', 'pad2d', 'pad_constant_like',
    'piecewise_decay', 'pixel_shuffle', 'polygon_box_transform',
    'polynomial_decay', 'pool2d', 'pool3d', 'prelu', 'prior_box',
    'prroi_pool', 'psroi_pool', 'random_crop', 'rank_loss', 'relu',
    'relu6', 'remove_weight_norm', 'resize_bilinear', 'resize_nearest',
    'resize_trilinear', 'retinanet_detection_output',
    'retinanet_target_assign', 'roi_align', 'roi_perspective_transform',
    'roi_pool', 'row_conv', 'rpn_target_assign',
    'sampled_softmax_with_cross_entropy', 'selu', 'shuffle_channel',
    'sigmoid', 'sigmoid_cross_entropy_with_logits',
    'sigmoid_focal_loss', 'similarity_focus', 'smooth_l1',
    'smooth_l1_loss', 'soft_relu', 'softmax',
    'softmax_with_cross_entropy', 'softplus', 'softshrink', 'softsign',
    'space_to_depth', 'square_error_cost', 'ssd_loss', 'swish',
    'switch_case', 'tanh', 'tanhshrink', 'target_assign',
    'teacher_student_sigmoid_loss', 'temporal_shift',
    'thresholded_relu', 'unfold', 'warpctc', 'weight_norm',
    'while_loop', 'yolo_box', 'yolov3_loss')


# ---------------------------------------------------------------------------
# real layers
# ---------------------------------------------------------------------------


@primitive("bilinear_tensor_product")
def bilinear(x1, x2, weight, bias=None):
    """y[b, k] = x1[b, :] @ W[k] @ x2[b, :] (+ bias)
    (bilinear_tensor_product_op.h)."""
    out = jnp.einsum("bi,kij,bj->bk", x1, weight, x2)
    if bias is not None:
        out = out + bias
    return out


class BilinearTensorProduct(Layer):
    """Bilinear map of two inputs (reference nn/layer/common.py
    BilinearTensorProduct over bilinear_tensor_product_op)."""

    def __init__(self, input1_dim, input2_dim, output_dim, name=None,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        self.weight = self.create_parameter(
            [output_dim, input1_dim, input2_dim], attr=weight_attr)
        self.bias = self.create_parameter([output_dim], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x1, x2):
        return bilinear(x1, x2, self.weight, self.bias)


class PairwiseDistance(Layer):
    """p-norm distance between row pairs (reference
    nn/layer/distance.py)."""

    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p, self.epsilon, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        from ..framework.tensor import Tensor, unwrap

        d = jnp.asarray(unwrap(x)) - jnp.asarray(unwrap(y)) + self.epsilon
        out = jnp.linalg.norm(d, ord=self.p, axis=-1,
                              keepdims=self.keepdim)
        return Tensor(out)


@primitive("row_conv_compat")
def _row_conv_fn(x, weight):
    """Lookahead row convolution (row_conv_op.cc, DeepSpeech2):
    y[b, t] = sum_{i=0..k-1} x[b, t+i] * w[i]  (zero past the end)."""
    k = weight.shape[0]
    b, t, d = x.shape
    pad = jnp.concatenate(
        [x, jnp.zeros((b, k - 1, d), x.dtype)], axis=1)
    idx = jnp.arange(t)[:, None] + jnp.arange(k)[None, :]   # (T, k)
    windows = pad[:, idx]                                   # (B, T, k, D)
    return jnp.einsum("btkd,kd->btd", windows, weight)


class RowConv(Layer):
    """Lookahead convolution over the time axis (reference
    fluid/dygraph RowConv / row_conv_op.cc)."""

    def __init__(self, num_channels, future_context_size, param_attr=None,
                 act=None):
        super().__init__()
        self.weight = self.create_parameter(
            [future_context_size + 1, num_channels], attr=param_attr)
        self.act = act

    def forward(self, x):
        out = _row_conv_fn(x, self.weight)
        if self.act == "relu":
            from . import functional as F

            out = F.relu(out)
        return out


def _hsigmoid_paths(label, num_classes):
    """Complete-binary-tree ancestors + branch bits for each label
    (hsigmoid_op.h SimpleCode): node ids follow the heap layout the
    reference uses — code(label) = label + num_classes, ancestors by
    successive halving, bit = parity at each split; internal node
    PARAMETER index is (code >> (d+1)) - 1."""
    depth = max(int(math.ceil(math.log2(max(num_classes, 2)))), 1)
    code = label + num_classes
    ds = np.arange(depth)
    node = (code[:, None] >> (ds[None, :] + 1)) - 1       # (B, depth)
    bit = (code[:, None] >> ds[None, :]) & 1
    valid = node >= 0
    return node, bit, valid


@primitive("hsigmoid", nondiff=("label", "num_classes"))
def hsigmoid(x, weight, bias, label, num_classes):
    """Hierarchical sigmoid loss (hsigmoid_op.cc): binary log-loss
    along the label's root-to-leaf path in a complete binary tree over
    ``num_classes`` leaves. x (B, D); weight (num_classes - 1, D);
    bias (num_classes - 1,); label (B,). Returns (B, 1) losses."""
    label = jnp.asarray(label, jnp.int32)
    node, bit, valid = _hsigmoid_paths(np.asarray(label), int(num_classes))
    node_j = jnp.asarray(np.maximum(node, 0))
    bit_j = jnp.asarray(bit, jnp.float32)
    valid_j = jnp.asarray(valid)
    w = weight[node_j]                                    # (B, depth, D)
    logits = jnp.einsum("bd,bkd->bk", x, w)
    if bias is not None:
        logits = logits + bias[node_j]
    # bce with logits against the branch bit, masked to real path nodes
    losses = (jnp.maximum(logits, 0.0) - logits * bit_j +
              jnp.log1p(jnp.exp(-jnp.abs(logits))))
    return jnp.sum(jnp.where(valid_j, losses, 0.0), axis=1,
                   keepdims=True)


class HSigmoid(Layer):
    """Hierarchical sigmoid classification head (reference
    nn/layer/common.py HSigmoid)."""

    def __init__(self, feature_size, num_classes, param_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        if is_custom:
            raise NotImplementedError(
                "custom-tree hsigmoid: pass path_table/path_code to "
                "functional hsigmoid instead")
        self.num_classes = num_classes
        self.weight = self.create_parameter(
            [num_classes - 1, feature_size], attr=param_attr)
        self.bias = self.create_parameter([num_classes - 1],
                                          attr=bias_attr, is_bias=True)

    def forward(self, x, label):
        return hsigmoid(x, self.weight, self.bias, label,
                        self.num_classes)


class Pool2D(Layer):
    """fluid dygraph Pool2D facade (reference fluid/dygraph/nn.py
    Pool2D) over the functional pool ops."""

    def __init__(self, pool_size=-1, pool_type="max", pool_stride=1,
                 pool_padding=0, global_pooling=False, use_cudnn=True,
                 ceil_mode=False, exclusive=True, data_format="NCHW"):
        super().__init__()
        if pool_type not in ("max", "avg"):
            raise ValueError("pool_type must be 'max' or 'avg'")
        self.cfg = dict(pool_size=pool_size, pool_type=pool_type,
                        pool_stride=pool_stride, pool_padding=pool_padding,
                        global_pooling=global_pooling, ceil_mode=ceil_mode,
                        exclusive=exclusive)

    def forward(self, x):
        from . import functional as F

        c = self.cfg
        if c["global_pooling"]:
            ksize = list(x.shape[2:])
            pad = 0
        else:
            ksize, pad = c["pool_size"], c["pool_padding"]
        fn = F.max_pool2d if c["pool_type"] == "max" else F.avg_pool2d
        kwargs = {}
        if c["pool_type"] == "avg":
            kwargs["exclusive"] = c["exclusive"]
        return fn(x, kernel_size=ksize, stride=c["pool_stride"],
                  padding=pad, ceil_mode=c["ceil_mode"], **kwargs)


class InstanceNorm(Layer):
    """Rank-dispatching InstanceNorm (reference fluid InstanceNorm
    covered 3-5D inputs with one class)."""

    def __init__(self, num_channels, epsilon=1e-5, param_attr=None,
                 bias_attr=None, dtype="float32"):
        super().__init__()
        from .norm import InstanceNorm1D, InstanceNorm2D, InstanceNorm3D

        # attribute assignment registers them as sublayers, so their
        # scale/bias reach parameters()/state_dict()
        self._in3 = InstanceNorm1D(num_channels, epsilon=epsilon)
        self._in4 = InstanceNorm2D(num_channels, epsilon=epsilon)
        self._in5 = InstanceNorm3D(num_channels, epsilon=epsilon)

    def forward(self, x):
        impl = {3: self._in3, 4: self._in4, 5: self._in5}.get(
            len(x.shape))
        if impl is None:
            raise ValueError("InstanceNorm expects a 3-5D input")
        return impl(x)


def logsigmoid(x, name=None):
    """log(sigmoid(x)), numerically via -softplus(-x)."""
    from ..framework.tensor import Tensor, unwrap

    v = jnp.asarray(unwrap(x))
    return Tensor(-jax.nn.softplus(-v))


# ---------------------------------------------------------------------------
# weight norm reparametrization
# ---------------------------------------------------------------------------


def _wn_norm(v, dim):
    if dim is None:
        return jnp.sqrt(jnp.sum(v * v))
    axes = tuple(i for i in range(v.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(v * v, axis=axes, keepdims=True))


def weight_norm(layer, name="weight", dim=0):
    """Reparametrize ``layer.<name>`` as g * v / ||v|| (reference
    nn/utils/weight_norm_hook.py). g and v become the trainable
    parameters; the effective weight is recomputed in a
    forward-pre-hook."""
    from ..framework.tensor import Tensor

    w = getattr(layer, name)
    wv = w.value if hasattr(w, "value") else jnp.asarray(w)
    g0 = _wn_norm(wv, dim)
    v_param = layer.create_parameter(list(wv.shape))
    v_param.set_value(np.asarray(wv))
    g_param = layer.create_parameter(list(np.asarray(g0).shape))
    g_param.set_value(np.asarray(g0))
    setattr(layer, name + "_v", v_param)
    setattr(layer, name + "_g", g_param)
    # the original weight stops being a trainable parameter
    if name in getattr(layer, "_parameters", {}):
        del layer._parameters[name]

    def _recompute(lyr, inputs):
        v = getattr(lyr, name + "_v")
        g = getattr(lyr, name + "_g")
        vv = v.value if hasattr(v, "value") else jnp.asarray(v)
        gv = g.value if hasattr(g, "value") else jnp.asarray(g)
        eff = gv * vv / jnp.maximum(_wn_norm(vv, dim), 1e-12)
        object.__setattr__(lyr, name, Tensor(eff))
        return None

    hook = layer.register_forward_pre_hook(_recompute)
    layer._weight_norm_hook = (hook, name, dim)
    _recompute(layer, None)
    return layer


def remove_weight_norm(layer, name="weight"):
    """Fold g * v/||v|| back into a plain parameter and drop the hook."""
    hook, nm, dim = layer._weight_norm_hook
    if nm != name:
        raise ValueError(f"weight_norm was applied to {nm!r}, not "
                         f"{name!r}")
    v = getattr(layer, name + "_v")
    g = getattr(layer, name + "_g")
    vv = v.value if hasattr(v, "value") else jnp.asarray(v)
    gv = g.value if hasattr(g, "value") else jnp.asarray(g)
    eff = gv * vv / jnp.maximum(_wn_norm(vv, dim), 1e-12)
    try:
        hook.remove()
    except AttributeError:
        pass
    # drop the hook's instance-dict Tensor — it would shadow the fresh
    # Parameter (instance attributes win over Layer.__getattr__)
    try:
        object.__delattr__(layer, name)
    except AttributeError:
        pass
    w = layer.create_parameter(list(eff.shape))
    w.set_value(np.asarray(eff))
    setattr(layer, name, w)
    for suffix in ("_v", "_g"):
        if name + suffix in getattr(layer, "_parameters", {}):
            del layer._parameters[name + suffix]
    del layer._weight_norm_hook
    return layer


# ---------------------------------------------------------------------------
# alias + re-export sweep
# ---------------------------------------------------------------------------


def _pad_class(mode, nd, value=0.0):
    from .common import Pad1D, Pad2D, Pad3D

    base = {1: Pad1D, 2: Pad2D, 3: Pad3D}[nd]

    class _PadAlias(base):
        def __init__(self, padding, data_format=None, name=None):
            kwargs = {"mode": mode}
            if mode == "constant":
                kwargs["value"] = value
            if data_format:
                kwargs["data_format"] = data_format
            super().__init__(padding, **kwargs)

    _PadAlias.__name__ = f"{mode.title()}Pad{nd}d"
    return _PadAlias


def _register():
    import sys

    from . import clip as _clip
    from . import functional as F
    from . import initializer as NI
    from ..static import initializer as SI
    from ..static import layers as SL
    from ..vision import ops as V  # noqa: F401  (via SL facades)

    nn_mod = sys.modules["paddle_tpu.nn"]

    def put(name, value):
        if not hasattr(nn_mod, name):
            setattr(nn_mod, name, value)

    # this module's layers
    for n in __all__:
        put(n, globals()[n])
    # pre-rename class spellings
    renames = {
        "Conv1d": "Conv1D", "Conv2d": "Conv2D", "Conv3d": "Conv3D",
        "ConvTranspose1d": "Conv1DTranspose",
        "ConvTranspose2d": "Conv2DTranspose",
        "ConvTranspose3d": "Conv3DTranspose",
        "AvgPool1d": "AvgPool1D", "AvgPool2d": "AvgPool2D",
        "AvgPool3d": "AvgPool3D", "MaxPool1d": "MaxPool1D",
        "maxPool1d": "MaxPool1D",   # sic — the reference __all__ typo
        "MaxPool2d": "MaxPool2D", "MaxPool3d": "MaxPool3D",
        "AdaptiveAvgPool1d": "AdaptiveAvgPool1D",
        "AdaptiveAvgPool2d": "AdaptiveAvgPool2D",
        "AdaptiveAvgPool3d": "AdaptiveAvgPool3D",
        "AdaptiveMaxPool1d": "AdaptiveMaxPool1D",
        "UpSample": "Upsample",
        "GradientClipByValue": "ClipGradByValue",
        "GradientClipByNorm": "ClipGradByNorm",
        "GradientClipByGlobalNorm": "ClipGradByGlobalNorm",
    }
    for old, new in renames.items():
        tgt = (getattr(nn_mod, new, None) or getattr(_clip, new, None))
        if tgt is not None:
            put(old, tgt)
    # pad-mode classes
    put("ZeroPad2d", _pad_class("constant", 2, 0.0))
    for nd in (1, 2, 3):
        put(f"ConstantPad{nd}d", _pad_class("constant", nd))
    for nd in (1, 2):
        put(f"ReflectionPad{nd}d", _pad_class("reflect", nd))
    for nd in (1, 2, 3):
        put(f"ReplicationPad{nd}d", _pad_class("replicate", nd))
    # fluid initializer short names
    for n in ("Constant", "Normal", "Uniform", "TruncatedNormal",
              "Xavier", "MSRA", "Bilinear"):
        tgt = getattr(NI, n, None) or getattr(SI, n, None)
        if tgt is not None:
            put(n, tgt)
    # functional conv transposes under the pre-rename names
    for old, new in (("conv_transpose1d", "conv1d_transpose"),
                     ("conv_transpose2d", "conv2d_transpose"),
                     ("conv_transpose3d", "conv3d_transpose")):
        if hasattr(F, new):
            put(old, getattr(F, new))
    put("bilinear", bilinear)
    put("logsigmoid", logsigmoid)
    # the reference re-exports its functional surface at nn top level:
    # resolve every remaining name from functional / fluid layers / ops
    from .. import ops as O

    for n in _REFERENCE_NN_ALL:
        if hasattr(nn_mod, n):
            continue
        for src in (F, SL, O):
            if hasattr(src, n):
                put(n, getattr(src, n))
                break


_register()
