"""Normalization layers (reference batch_norm_op.cc, layer_norm_op.cc,
sync_batch_norm_op.cu, python/paddle/nn/layer/norm.py).

SyncBatchNorm computes cross-replica statistics with lax.pmean inside
shard_map/pjit (the reference used a dedicated NCCL kernel).
"""
from __future__ import annotations

import numpy as np

from . import functional as F
from . import initializer as I
from .layer import Layer


class _NormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                [num_features], attr=bias_attr, is_bias=True)
        self.register_buffer("_mean", np.zeros(num_features, np.float32))
        self.register_buffer("_variance", np.ones(num_features, np.float32))


class BatchNorm(_NormBase):
    """fluid.dygraph.BatchNorm parity (acts on axis 1)."""

    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight,
                            self.bias, training=self.training,
                            momentum=self._momentum, epsilon=self._epsilon,
                            data_format=self._data_format,
                            use_global_stats=self._use_global_stats)


class BatchNorm1D(BatchNorm):
    pass


class BatchNorm2D(BatchNorm):
    pass


class BatchNorm3D(BatchNorm):
    pass


class SyncBatchNorm(BatchNorm):
    """Cross-replica BN (reference operators/sync_batch_norm_op.cu): when run
    inside shard_map over a data-parallel mesh axis, moments are averaged
    with lax.pmean over that axis."""

    axis_name = "data"

    def forward(self, x):
        import jax

        try:
            jax.core.get_axis_size(self.axis_name)  # inside pmap/shard_map?
            in_spmd = True
        except Exception:
            in_spmd = False
        if not in_spmd or not self.training:
            return super().forward(x)
        return self._sync_forward(x)

    def _sync_forward(self, x):
        import jax
        import jax.numpy as jnp

        from ..framework.op import primitive

        @primitive("sync_batch_norm")
        def _sync_bn(x, weight, bias, eps, axis_name):
            axes = tuple(i for i in range(x.ndim) if i != 1)
            mean = jax.lax.pmean(jnp.mean(x, axis=axes), axis_name)
            mean2 = jax.lax.pmean(jnp.mean(jnp.square(x), axis=axes), axis_name)
            var = mean2 - jnp.square(mean)
            shape = [1, x.shape[1]] + [1] * (x.ndim - 2)
            out = (x - mean.reshape(shape)) * jax.lax.rsqrt(var.reshape(shape) + eps)
            if weight is not None:
                out = out * weight.reshape(shape)
            if bias is not None:
                out = out + bias.reshape(shape)
            return out

        return _sync_bn(x, self.weight, self.bias, self._epsilon, self.axis_name)

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        """Recursively convert BatchNorm sublayers to SyncBatchNorm."""
        if isinstance(layer, BatchNorm) and not isinstance(layer, SyncBatchNorm):
            new = cls(layer._num_features, layer._momentum, layer._epsilon,
                      data_format=layer._data_format)
            new.weight, new.bias = layer.weight, layer.bias
            new._buffers["_mean"] = layer._mean
            new._buffers["_variance"] = layer._variance
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, (int, np.integer)):
            normalized_shape = (int(normalized_shape),)
        self._normalized_shape = tuple(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                self._normalized_shape, attr=weight_attr,
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                self._normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}"


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = None if weight_attr is False else self.create_parameter(
            [num_channels], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            [num_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self.weight, self.bias,
                            self._epsilon, self._data_format)


class InstanceNorm1D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = None if weight_attr is False else self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            [num_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm2D(InstanceNorm1D):
    pass


class InstanceNorm3D(InstanceNorm1D):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k
        self.data_format = data_format

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta,
                                     self.k, self.data_format)


class SpectralNorm(Layer):
    """Reference spectral_norm_op.cc: power iteration on a weight."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 name=None):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._eps = eps
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        self.weight_u = self.create_parameter(
            [h], default_initializer=I.Normal(0, 1))
        self.weight_u.stop_gradient = True
        self.weight_v = self.create_parameter(
            [w], default_initializer=I.Normal(0, 1))
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        import jax.numpy as jnp

        from ..framework.op import primitive
        from ..framework.tensor import Tensor

        w = weight
        mat = jnp.moveaxis(w.value if isinstance(w, Tensor) else w, self._dim, 0)
        h = mat.shape[0]
        mat = mat.reshape(h, -1)
        u, v = self.weight_u.value, self.weight_v.value
        for _ in range(self._power_iters):
            v = mat.T @ u
            v = v / (jnp.linalg.norm(v) + self._eps)
            u = mat @ v
            u = u / (jnp.linalg.norm(u) + self._eps)
        self.weight_u._value = u
        self.weight_v._value = v

        @primitive("spectral_norm")
        def _apply(weight, u, v, dim):
            mat = jnp.moveaxis(weight, dim, 0).reshape(weight.shape[dim], -1)
            sigma = u @ (mat @ v)
            return weight / sigma

        return _apply(weight, u, v, self._dim)
