"""Layer: the module base class.

Parity with the reference dygraph Layer
(/root/reference/python/paddle/fluid/dygraph/layers.py:675 Layer.__call__,
create_parameter, sublayers, state_dict) re-designed for JAX: parameters
are Tensors (mutable buffer holders), and the whole layer tree can be
snapshotted to / restored from a pytree so one model definition serves
eager mode and jit-compiled functional training steps (see paddle_tpu.jit).
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Iterator, Optional, Tuple

import jax
import numpy as np

from ..framework import dtype as dtype_mod
from ..framework.tensor import Tensor
from . import initializer as I


class ParamAttr:
    """Parity with fluid.ParamAttr (name/initializer/lr/regularizer/trainable)."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=False,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if attr is False:
            return None
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if isinstance(attr, I.Initializer):
            return ParamAttr(initializer=attr)
        raise TypeError(f"Cannot interpret {attr!r} as ParamAttr")


class Parameter(Tensor):
    __slots__ = ("optimize_attr", "regularizer", "need_clip", "is_distributed")

    def __init__(self, value, trainable=True, name=None, learning_rate=1.0,
                 regularizer=None, need_clip=True):
        super().__init__(value, stop_gradient=not trainable, name=name,
                         persistable=True)
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": learning_rate}
        self.regularizer = regularizer
        self.need_clip = need_clip
        self.is_distributed = False


_name_counters = {}


def _unique_name(prefix):
    n = _name_counters.get(prefix, 0)
    _name_counters[prefix] = n + 1
    return f"{prefix}_{n}"


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        object.__setattr__(self, "_parameters", OrderedDict())
        object.__setattr__(self, "_sub_layers", OrderedDict())
        object.__setattr__(self, "_buffers", OrderedDict())
        self._dtype = dtype_mod.convert_dtype(dtype)
        self._full_name = _unique_name(name_scope or type(self).__name__.lower())
        self.training = True
        self._forward_pre_hooks = OrderedDict()
        self._forward_post_hooks = OrderedDict()

    # -- construction -------------------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        attr = ParamAttr._to_attr(attr)
        if attr is None:
            return None
        dtype = dtype_mod.convert_dtype(dtype) if dtype else self._dtype
        if default_initializer is None:
            default_initializer = I.global_initializer(is_bias) or (
                I.Constant(0.0) if is_bias else I.XavierUniform())
        init = I._resolve(attr.initializer, default_initializer)
        value = init(tuple(int(s) for s in shape), dtype)
        return Parameter(value, trainable=attr.trainable,
                         name=attr.name or _unique_name(self._full_name + ".w"),
                         learning_rate=attr.learning_rate,
                         regularizer=attr.regularizer, need_clip=attr.need_clip)

    def add_parameter(self, name, parameter):
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        if tensor is not None and not isinstance(tensor, Tensor):
            tensor = Tensor(tensor)
        if tensor is not None:
            tensor.persistable = persistable
        self._buffers[name] = tensor
        return tensor

    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter) and params is not None:
            params[name] = value
            layers.pop(name, None)
            buffers.pop(name, None) if buffers else None
        elif isinstance(value, Layer) and layers is not None:
            layers[name] = value
            params.pop(name, None)
        else:
            if params is not None and name in params and value is None:
                params[name] = None
                return
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"{type(self).__name__!r} has no attribute {name!r}")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    # -- forward ------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        out = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, inputs, out)
            if result is not None:
                out = result
        return out

    def register_forward_pre_hook(self, hook):
        handle = HookRemoveHelper(self._forward_pre_hooks)
        self._forward_pre_hooks[handle.hook_id] = hook
        return handle

    def register_forward_post_hook(self, hook):
        handle = HookRemoveHelper(self._forward_post_hooks)
        self._forward_post_hooks[handle.hook_id] = hook
        return handle

    # -- traversal ----------------------------------------------------------
    def named_sublayers(self, prefix="", include_self=False) -> Iterator[Tuple[str, "Layer"]]:
        if include_self:
            yield prefix, self
        for name, layer in self._sub_layers.items():
            if layer is None:
                continue
            p = f"{prefix}.{name}" if prefix else name
            yield p, layer
            yield from layer.named_sublayers(prefix=p)

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self):
        return iter(l for l in self._sub_layers.values() if l is not None)

    def named_children(self):
        return iter((n, l) for n, l in self._sub_layers.items() if l is not None)

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        for name, p in self._parameters.items():
            if p is not None and id(p) not in seen:
                seen.add(id(p))
                yield (f"{prefix}.{name}" if prefix else name), p
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                lp = f"{prefix}.{lname}" if prefix else lname
                for n, p in layer.named_parameters(prefix=lp):
                    if id(p) not in seen:
                        seen.add(id(p))
                        yield n, p

    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        for name, b in self._buffers.items():
            if b is not None:
                yield (f"{prefix}.{name}" if prefix else name), b
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                lp = f"{prefix}.{lname}" if prefix else lname
                yield from layer.named_buffers(prefix=lp)

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def apply(self, fn):
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    # -- modes --------------------------------------------------------------
    def train(self):
        for l in self.sublayers(include_self=True):
            l.training = True
        return self

    def eval(self):
        for l in self.sublayers(include_self=True):
            l.training = False
        return self

    # -- state dict ---------------------------------------------------------
    def _state_targets(self, structured_name_prefix=""):
        """The LIVE persistable tensors, un-cast: set_state_dict must
        mutate these, never the save-dtype copies state_dict hands out."""
        out = OrderedDict()
        for n, p in self.named_parameters(prefix=structured_name_prefix.rstrip(".")):
            out[n] = p
        for n, b in self.named_buffers(prefix=structured_name_prefix.rstrip(".")):
            if b.persistable:
                out[n] = b
        return out

    def state_dict(self, include_sublayers=True, structured_name_prefix=""):
        out = self._state_targets(structured_name_prefix)
        # amp.decorate(save_dtype=...): checkpoints keep the requested
        # dtype even when the live params run low precision under O2
        # (fresh Tensors — the live params are not touched)
        save_dtype = getattr(self, "_amp_save_dtype", None)
        if save_dtype is not None:
            target = dtype_mod.convert_dtype(save_dtype)
            for n, t in out.items():
                if dtype_mod.is_inexact(t.dtype) and \
                        dtype_mod.convert_dtype(t.dtype) != target:
                    out[n] = Tensor(t.value.astype(target))
        return out

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self._state_targets()
        missing = []
        for name, tensor in own.items():
            if name in state_dict:
                v = state_dict[name]
                arr = v.numpy() if isinstance(v, Tensor) else np.asarray(v)
                tensor.set_value(arr.astype(np.dtype(tensor.dtype)))
            else:
                missing.append(name)
        return missing

    load_dict = set_state_dict
    set_dict = set_state_dict

    # -- pytree snapshot (bridge to functional/jit execution) ----------------
    def param_pytree(self, trainable_only=False):
        return {
            n: p.value for n, p in self.named_parameters()
            if (p.trainable or not trainable_only)
        }

    def buffer_pytree(self):
        return {n: b.value for n, b in self.named_buffers()}

    def load_param_pytree(self, tree):
        for n, p in self.named_parameters():
            if n in tree:
                p._value = tree[n]

    def load_buffer_pytree(self, tree):
        for n, b in self.named_buffers():
            if n in tree:
                b._value = tree[n]

    # -- dtype / device moves ------------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            dtype = dtype_mod.convert_dtype(dtype)
            for p in self.parameters():
                p._value = p._value.astype(dtype)
            for _, b in self.named_buffers():
                if dtype_mod.is_inexact(b.dtype):
                    b._value = b._value.astype(dtype)
            for l in self.sublayers(include_self=True):
                l._dtype = dtype
        if device is not None:
            from ..framework.place import Place

            if isinstance(device, str):
                from ..framework.place import set_device

                place = set_device(device)
            else:
                place = device
            dev = place.jax_device()
            for p in self.parameters():
                p._value = jax.device_put(p._value, dev)
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def full_name(self):
        return self._full_name

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, layer in self._sub_layers.items():
            mod_str = repr(layer)
            mod_str = "\n".join("  " + l for l in mod_str.split("\n"))
            lines.append(f"  ({name}): {mod_str.strip()}")
        main = f"{type(self).__name__}({extra}"
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"


class HookRemoveHelper:
    _next_id = [0]

    def __init__(self, hooks):
        self._hooks = hooks
        self.hook_id = HookRemoveHelper._next_id[0]
        HookRemoveHelper._next_id[0] += 1

    def remove(self):
        self._hooks.pop(self.hook_id, None)
