"""Common layers: Linear, Embedding, Dropout, activations, padding, upsample.

Parity with the reference 2.0 layer set (/root/reference/python/paddle/nn/
layer/common.py) and the dygraph layers (fluid/dygraph/nn.py).
"""
from __future__ import annotations

from . import functional as F
from .layer import Layer


class Identity(Layer):
    def forward(self, x):
        return x


class Linear(Layer):
    """y = x @ W + b, W: (in_features, out_features) (reference fc/mul op)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr)
        self.bias = self.create_parameter(
            [out_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self._in_features}, out_features={self._out_features}"


class Embedding(Layer):
    """Reference lookup_table_v2_op.cc; rows gathered via jnp.take."""

    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = None if padding_idx is None else (
            padding_idx if padding_idx >= 0 else num_embeddings + padding_idx)
        from . import initializer as I

        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.XavierUniform())
        if self._padding_idx is not None:
            import jax.numpy as jnp

            self.weight._value = self.weight._value.at[self._padding_idx].set(0.0)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self._padding_idx)


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, p=self.p, axis=self.axis, training=self.training,
                         mode=self.mode)


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, p=self.p, training=self.training)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        from .. import ops

        return ops.flatten(x, self.start_axis, self.stop_axis)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.align_mode = align_mode
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, size=self.size, scale_factor=self.scale_factor,
                             mode=self.mode, align_corners=self.align_corners,
                             align_mode=self.align_mode,
                             data_format=self.data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, mode="bilinear",
                         align_corners=True, data_format=data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, mode="nearest",
                         data_format=data_format)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor = upscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)


class Pad1D(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL",
                 name=None):
        super().__init__()
        self.padding, self.mode, self.value = padding, mode, value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, mode=self.mode, value=self.value,
                     data_format=self.data_format)


class Pad2D(Pad1D):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW",
                 name=None):
        super().__init__(padding, mode, value, data_format, name)


class Pad3D(Pad1D):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCDHW",
                 name=None):
        super().__init__(padding, mode, value, data_format, name)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis, self.eps = axis, eps

    def forward(self, x1, x2):
        from ..ops.linalg import cosine_similarity

        return cosine_similarity(x1, x2, axis=self.axis, eps=self.eps)


class Bilinear(Layer):
    """Reference bilinear_tensor_product_op.cc."""

    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [out_features, in1_features, in2_features], attr=weight_attr)
        self.bias = self.create_parameter([1, out_features], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x1, x2):
        from .. import ops

        out = ops.einsum("bi,oij,bj->bo", x1, self.weight, x2)
        if self.bias is not None:
            out = out + self.bias
        return out


# activation layers
def _act_layer(name, fn, params=()):
    def __init__(self, *args, **kwargs):
        Layer.__init__(self)
        for p, default in params:
            setattr(self, p, kwargs.pop(p, args[params.index((p, default))]
                                        if params.index((p, default)) < len(args)
                                        else default))

    def forward(self, x):
        kw = {p: getattr(self, p) for p, _ in params}
        return fn(x, **kw)

    return type(name, (Layer,), {"__init__": __init__, "forward": forward})


ReLU = _act_layer("ReLU", F.relu)
ReLU6 = _act_layer("ReLU6", F.relu6)
LeakyReLU = _act_layer("LeakyReLU", F.leaky_relu, (("negative_slope", 0.01),))
ELU = _act_layer("ELU", F.elu, (("alpha", 1.0),))
CELU = _act_layer("CELU", F.celu, (("alpha", 1.0),))
SELU = _act_layer("SELU", F.selu)
GELU = _act_layer("GELU", F.gelu, (("approximate", False),))
Silu = _act_layer("Silu", F.silu)
Swish = _act_layer("Swish", F.silu)
Mish = _act_layer("Mish", F.mish)
Hardswish = _act_layer("Hardswish", F.hardswish)
Hardsigmoid = _act_layer("Hardsigmoid", F.hardsigmoid)
Hardtanh = _act_layer("Hardtanh", F.hardtanh, (("min", -1.0), ("max", 1.0)))
Hardshrink = _act_layer("Hardshrink", F.hardshrink, (("threshold", 0.5),))
Softshrink = _act_layer("Softshrink", F.softshrink, (("threshold", 0.5),))
Tanhshrink = _act_layer("Tanhshrink", F.tanhshrink)
Softplus = _act_layer("Softplus", F.softplus, (("beta", 1.0), ("threshold", 20.0)))
Softsign = _act_layer("Softsign", F.softsign)
Sigmoid = _act_layer("Sigmoid", F.sigmoid)
LogSigmoid = _act_layer("LogSigmoid", None)
Tanh = _act_layer("Tanh", None)
Softmax = _act_layer("Softmax", F.softmax, (("axis", -1),))
LogSoftmax = _act_layer("LogSoftmax", F.log_softmax, (("axis", -1),))
ThresholdedReLU = _act_layer("ThresholdedReLU", F.thresholded_relu,
                             (("threshold", 1.0),))
Maxout = _act_layer("Maxout", F.maxout, (("groups", 2), ("axis", 1)))


def _tanh_forward(self, x):
    from .. import ops

    return ops.tanh(x)


def _logsigmoid_forward(self, x):
    from .. import ops

    return ops.log_sigmoid(x)


Tanh.forward = _tanh_forward
LogSigmoid.forward = _logsigmoid_forward


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        from . import initializer as I

        self.data_format = data_format
        self.weight = self.create_parameter(
            [num_parameters], attr=weight_attr,
            default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, data_format=self.data_format)
