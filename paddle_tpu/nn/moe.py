"""Mixture-of-Experts with expert parallelism (the "ep" mesh axis).

The reference framework predates MoE entirely (SURVEY §2.6: EP absent) —
this is a TPU-first design, not a port. Tokens are routed top-2 by a
learned gate with a GShard/Switch-style static capacity (overflow tokens
drop to the residual path, keeping every shape static for XLA).

How the expert parallelism actually works: gating and the dispatch/
combine einsums are written on global arrays; the expert FFN runs inside
`shard_map` with the expert-stacked weights and the (e, c, d) expert
blocks sharded over "ep". The token exchange is therefore the resharding
XLA inserts at the shard_map boundary (token-sharded -> expert-sharded
and back) — collectives over ICI equivalent to the classic explicit
all_to_all dispatch. A hand-written all_to_all dispatch that also
parallelizes the dispatch/combine einsums is the known next optimization
if the gate math ever dominates.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

from ..framework.op import primitive
from .layer import Layer

__all__ = ["MoELayer", "moe_apply_ep", "MOE_EP_RULES", "top2_gating"]

# parameter sharding rules: expert-stacked weights shard over "ep"
MOE_EP_RULES = [
    (r".*experts_w1$", PartitionSpec("ep", None, None)),
    (r".*experts_b1$", PartitionSpec("ep", None)),
    (r".*experts_w2$", PartitionSpec("ep", None, None)),
    (r".*experts_b2$", PartitionSpec("ep", None)),
]


def top2_gating(logits, capacity: int):
    """GShard top-2 gating with static capacity.

    logits: (tokens, experts). Returns (dispatch (t, e, c) bool,
    combine (t, e, c) float) — dispatch scatters tokens into expert
    capacity slots, combine holds the normalized gate weights.
    """
    t, e = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    g1_idx = jnp.argmax(probs, axis=-1)                     # (t,)
    g1 = jnp.take_along_axis(probs, g1_idx[:, None], 1)[:, 0]
    probs2 = probs.at[jnp.arange(t), g1_idx].set(0.0)
    g2_idx = jnp.argmax(probs2, axis=-1)
    g2 = jnp.take_along_axis(probs2, g2_idx[:, None], 1)[:, 0]
    denom = jnp.maximum(g1 + g2, 1e-9)
    g1, g2 = g1 / denom, g2 / denom

    def slots_for(idx):
        # position of each token within its expert's queue (running count)
        onehot = jax.nn.one_hot(idx, e, dtype=jnp.int32)    # (t, e)
        pos = jnp.cumsum(onehot, axis=0) - onehot           # tokens before
        return jnp.sum(pos * onehot, axis=-1)               # (t,)

    pos1 = slots_for(g1_idx)
    # second choice queues behind all first choices of that expert
    count1 = jnp.sum(jax.nn.one_hot(g1_idx, e, dtype=jnp.int32), axis=0)
    pos2 = slots_for(g2_idx) + count1[g2_idx]

    def scatter(idx, pos):
        keep = pos < capacity
        d = (jax.nn.one_hot(idx, e, dtype=jnp.float32)[:, :, None] *
             jax.nn.one_hot(jnp.where(keep, pos, 0), capacity,
                            dtype=jnp.float32)[:, None, :])
        d = d * keep[:, None, None]
        return d

    d1 = scatter(g1_idx, pos1)
    d2 = scatter(g2_idx, pos2)
    combine = d1 * g1[:, None, None] + d2 * g2[:, None, None]
    dispatch = (d1 + d2) > 0
    # load-balancing auxiliary loss (GShard eq.4): mean prob * mean assignment
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(jax.nn.one_hot(g1_idx, e, dtype=jnp.float32), axis=0)
    aux = jnp.sum(me * ce) * e
    return dispatch, combine, aux


def _expert_ffn(w1, b1, w2, b2, x):
    """One expert's FFN on its capacity block: x (c, d)."""
    return jax.nn.gelu(x @ w1 + b1) @ w2 + b2


def moe_apply_ep(params, x, *, mesh: Optional[Mesh] = None, axis: str = "ep",
                 capacity_factor: float = 2.0):
    """Expert-parallel MoE apply inside shard_map.

    params: dict with gate_w (d, E), experts_w1 (E, d, h), experts_b1
    (E, h), experts_w2 (E, h, d), experts_b2 (E, d). x: (tokens, d)
    global. Experts shard over `axis`; tokens all_to_all to their
    experts and back. Falls back to the dense einsum path when the mesh
    axis is unusable.
    """
    e = params["experts_w1"].shape[0]
    t, d = x.shape
    capacity = max(1, int(capacity_factor * t / e))

    logits = x @ params["gate_w"]
    dispatch, combine, aux = top2_gating(logits, capacity)
    # gather expert inputs: (e, c, d)
    expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), x)

    if mesh is None or axis not in mesh.axis_names or \
            mesh.shape[axis] <= 1 or e % mesh.shape[axis] != 0:
        out_e = jax.vmap(_expert_ffn)(
            params["experts_w1"], params["experts_b1"],
            params["experts_w2"], params["experts_b2"], expert_in)
    else:
        n = mesh.shape[axis]

        def local(w1, b1, w2, b2, ein):
            # ein arrives (e/n, c, d) after the spec split: this rank's
            # experts' tokens. (XLA inserts the all_to_all when the
            # upstream einsum output resharded from token- to expert-
            # sharded layout.)
            return jax.vmap(_expert_ffn)(w1, b1, w2, b2, ein)

        from ..parallel.collectives import shard_map_fn

        spec_e = PartitionSpec(axis)
        out_e = shard_map_fn()(
            local, mesh=mesh,
            in_specs=(spec_e, spec_e, spec_e, spec_e, spec_e),
            out_specs=spec_e,
        )(params["experts_w1"], params["experts_b1"],
          params["experts_w2"], params["experts_b2"], expert_in)
    # combine back to tokens
    out = jnp.einsum("tec,ecd->td", combine.astype(x.dtype), out_e)
    return out, aux


@primitive("moe")
def _moe_prim(xf, gate_w, w1, b1, w2, b2, mesh=None, capacity_factor=2.0):
    params = {"gate_w": gate_w, "experts_w1": w1, "experts_b1": b1,
              "experts_w2": w2, "experts_b2": b2}
    return moe_apply_ep(params, xf, mesh=mesh,
                        capacity_factor=capacity_factor)


class MoELayer(Layer):
    """Transformer FFN replaced by num_experts expert FFNs + top-2 gate."""

    def __init__(self, d_model: int, d_hidden: int, num_experts: int,
                 capacity_factor: float = 2.0, name=None):
        super().__init__()
        from .initializer import XavierUniform

        self.d_model = d_model
        self.num_experts = num_experts
        self.capacity_factor = capacity_factor
        init = XavierUniform()
        self.gate_w = self.create_parameter(
            [d_model, num_experts], default_initializer=init)
        self.experts_w1 = self.create_parameter(
            [num_experts, d_model, d_hidden], default_initializer=init)
        self.experts_b1 = self.create_parameter(
            [num_experts, d_hidden], is_bias=True)
        self.experts_w2 = self.create_parameter(
            [num_experts, d_hidden, d_model], default_initializer=init)
        self.experts_b2 = self.create_parameter(
            [num_experts, d_model], is_bias=True)
        self._last_aux_loss = None

    def forward(self, x):
        from .. import ops
        from ..parallel.mesh import get_mesh

        shape = x.shape
        xf = ops.reshape(x, [-1, shape[-1]])
        out, aux = _moe_prim(xf, self.gate_w, self.experts_w1,
                             self.experts_b1, self.experts_w2,
                             self.experts_b2, mesh=get_mesh(),
                             capacity_factor=self.capacity_factor)
        self._last_aux_loss = aux
        return ops.reshape(out, list(shape))

    @property
    def aux_loss(self):
        return self._last_aux_loss
