"""Mixture-of-Experts with REAL expert parallelism (the "ep" mesh axis).

The reference framework predates MoE entirely (SURVEY §2.6: EP absent) —
this is a TPU-first design, not a port. Tokens are routed top-2 by a
learned gate with a GShard/Switch-style static capacity (overflow tokens
drop to the residual path, keeping every shape static for XLA).

ISSUE 19 makes the token exchange EXPLICIT. The previous design ran
only the expert FFN inside ``shard_map`` and let GSPMD insert whatever
resharding collectives it liked at the boundary; now the whole
dispatch/combine runs inside ``shard_map`` (tokens sharded over "ep",
expert-stacked weights sharded over "ep") with two hand-placed
``lax.all_to_all`` exchanges:

- dispatch: each device scatters its LOCAL tokens into the full
  (e, c, d) capacity grid (zeros elsewhere — capacity slots are
  globally unique, so contributions are disjoint), splits it by
  destination device and all-to-alls; summing the received per-source
  blocks yields this device's experts' complete inputs. Disjoint + 0/1
  dispatch weights means the sum adds exact zeros: the explicit path
  is numerically the dense path.
- combine: the FFN outputs tile n ways and all-to-all back, giving
  every device the full (e, c, d) expert outputs for its local
  combine einsum.

Gating stays GLOBAL (logits all-gather over "ep" — (t, e), tiny):
capacity positions come from a global running count, so routing — and
therefore the math — is IDENTICAL to the single-device gate, which is
the parity oracle the tests pin. Dispatch payloads can optionally ride
int8 (``dispatch_codec="int8"``, the PR 15 wire codec) with a
straight-through estimator so gradients flow unquantized; that leg is
accuracy-gated by the caller exactly like the int8 ring.

The ``moe_a2a.*`` dispatch counters record which path served each
apply (explicit / the legacy GSPMD-resharding shard_map / dense) with
the refusal reason; ``PADDLE_MOE_A2A=0`` pins the legacy path.
"""
from __future__ import annotations

import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

from ..framework.op import primitive
from .layer import Layer

__all__ = ["MoELayer", "moe_apply_ep", "MOE_EP_RULES", "top2_gating",
           "moe_route_stats", "moe_a2a_nbytes"]

# parameter sharding rules: expert-stacked weights shard over "ep"
MOE_EP_RULES = [
    (r".*experts_w1$", PartitionSpec("ep", None, None)),
    (r".*experts_b1$", PartitionSpec("ep", None)),
    (r".*experts_w2$", PartitionSpec("ep", None, None)),
    (r".*experts_b2$", PartitionSpec("ep", None)),
]


def moe_a2a_escaped() -> bool:
    """True when ``PADDLE_MOE_A2A=0`` pins the legacy GSPMD-resharding
    path (the bitwise escape for the explicit exchange)."""
    return os.environ.get("PADDLE_MOE_A2A", "").strip() in (
        "0", "off", "false")


def top2_gating(logits, capacity: int):
    """GShard top-2 gating with static capacity.

    logits: (tokens, experts). Returns (dispatch (t, e, c) bool,
    combine (t, e, c) float) — dispatch scatters tokens into expert
    capacity slots, combine holds the normalized gate weights.
    """
    t, e = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    g1_idx = jnp.argmax(probs, axis=-1)                     # (t,)
    g1 = jnp.take_along_axis(probs, g1_idx[:, None], 1)[:, 0]
    probs2 = probs.at[jnp.arange(t), g1_idx].set(0.0)
    g2_idx = jnp.argmax(probs2, axis=-1)
    g2 = jnp.take_along_axis(probs2, g2_idx[:, None], 1)[:, 0]
    denom = jnp.maximum(g1 + g2, 1e-9)
    g1, g2 = g1 / denom, g2 / denom

    def slots_for(idx):
        # position of each token within its expert's queue (running count)
        onehot = jax.nn.one_hot(idx, e, dtype=jnp.int32)    # (t, e)
        pos = jnp.cumsum(onehot, axis=0) - onehot           # tokens before
        return jnp.sum(pos * onehot, axis=-1)               # (t,)

    pos1 = slots_for(g1_idx)
    # second choice queues behind all first choices of that expert
    count1 = jnp.sum(jax.nn.one_hot(g1_idx, e, dtype=jnp.int32), axis=0)
    pos2 = slots_for(g2_idx) + count1[g2_idx]

    def scatter(idx, pos):
        keep = pos < capacity
        d = (jax.nn.one_hot(idx, e, dtype=jnp.float32)[:, :, None] *
             jax.nn.one_hot(jnp.where(keep, pos, 0), capacity,
                            dtype=jnp.float32)[:, None, :])
        d = d * keep[:, None, None]
        return d

    d1 = scatter(g1_idx, pos1)
    d2 = scatter(g2_idx, pos2)
    combine = d1 * g1[:, None, None] + d2 * g2[:, None, None]
    dispatch = (d1 + d2) > 0
    # load-balancing auxiliary loss (GShard eq.4): mean prob * mean assignment
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(jax.nn.one_hot(g1_idx, e, dtype=jnp.float32), axis=0)
    aux = jnp.sum(me * ce) * e
    return dispatch, combine, aux


def moe_route_stats(logits, capacity: int):
    """Routing diagnostics for one gate evaluation (dump_passes --moe
    and the bench probe): per-expert assigned token-choice counts
    (capacity-kept), per-expert overflow drops, and the overall
    capacity drop percentage of the 2t token-choices."""
    dispatch, _combine, aux = top2_gating(logits, capacity)
    t, e = logits.shape
    kept = jnp.sum(dispatch, axis=(0, 2))                   # (e,)
    probs = jax.nn.softmax(logits, axis=-1)
    g1 = jnp.argmax(probs, axis=-1)
    p2 = probs.at[jnp.arange(t), g1].set(0.0)
    g2 = jnp.argmax(p2, axis=-1)
    wanted = (jnp.sum(jax.nn.one_hot(g1, e), axis=0)
              + jnp.sum(jax.nn.one_hot(g2, e), axis=0))     # (e,)
    dropped = wanted - kept
    total = 2.0 * t
    return {
        "experts": int(e), "capacity": int(capacity),
        "tokens": int(t),
        "kept_per_expert": [int(v) for v in kept],
        "dropped_per_expert": [int(v) for v in dropped],
        "drop_pct": round(100.0 * float(jnp.sum(dropped)) / total, 2),
        "aux_loss": float(aux),
    }


def moe_a2a_nbytes(e: int, capacity: int, d: int, group: int,
                   codec: Optional[str] = None) -> int:
    """Per-device wire bytes of the two explicit all-to-alls (dispatch
    + combine): each moves ``(g-1)/g`` of the (e, c, d) capacity grid
    off-device. int8 dispatch payloads shrink that leg to 1 byte/elem
    + one f32 scale per d-row; the combine leg always rides f32
    (update results come back exact, like the ZeRO gather)."""
    g = max(1, int(group))
    if g <= 1:
        return 0
    elems = int(e) * int(capacity) * int(d)
    off = (g - 1)
    per_dev = elems // g
    if codec == "int8":
        dispatch = per_dev * (1 + 4 / int(d))
    else:
        dispatch = per_dev * 4
    combine = per_dev * 4
    return int(off * (dispatch + combine))


def _expert_ffn(w1, b1, w2, b2, x):
    """One expert's FFN on its capacity block: x (c, d)."""
    return jax.nn.gelu(x @ w1 + b1) @ w2 + b2


def _moe_dense(params, x, capacity):
    """The single-device oracle: global gate, dense vmap over ALL
    experts. The explicit EP path must match this (tolerance-gated
    when dispatch payloads quantize)."""
    logits = x @ params["gate_w"]
    dispatch, combine, aux = top2_gating(logits, capacity)
    expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), x)
    out_e = jax.vmap(_expert_ffn)(
        params["experts_w1"], params["experts_b1"],
        params["experts_w2"], params["experts_b2"], expert_in)
    out = jnp.einsum("tec,ecd->td", combine.astype(x.dtype), out_e)
    return out, aux


def _st_quant(flat, block):
    """int8 round-trip with a straight-through estimator: forward is
    the decoded payload (what the wire delivers), gradient is identity
    (the router/gate must keep learning through the exchange)."""
    from ..parallel.collectives import quant_decode, quant_encode

    q, sc = quant_encode(flat, "int8", block=block)
    dec = quant_decode(q, sc, "int8", block=block)
    return flat + jax.lax.stop_gradient(dec - flat)


def _moe_explicit_a2a(params, x, mesh, axis, n, capacity, codec):
    """The explicit expert-parallel exchange (module docstring): global
    gate on all-gathered logits, local scatter, all_to_all dispatch,
    local-expert FFN, all_to_all combine."""
    from ..parallel.collectives import shard_map_nocheck

    e = params["experts_w1"].shape[0]
    t, d = x.shape
    t_l, e_l = t // n, e // n

    def local(x_loc, gate_w, w1, b1, w2, b2):
        # global gating: every device computes the SAME dispatch plan
        # from the full token set (the (t, e) logits gather is the
        # cheap exchange; capacity positions need the global running
        # count to match the single-device oracle)
        x_full = jax.lax.all_gather(x_loc, axis, axis=0, tiled=True)
        dispatch, combine, aux = top2_gating(x_full @ gate_w, capacity)
        r = jax.lax.axis_index(axis)
        disp_loc = jax.lax.dynamic_slice_in_dim(
            dispatch.astype(x_loc.dtype), r * t_l, t_l, 0)
        comb_loc = jax.lax.dynamic_slice_in_dim(
            combine.astype(x_loc.dtype), r * t_l, t_l, 0)
        # local scatter into the FULL capacity grid: zeros except this
        # device's tokens' slots (globally unique -> disjoint)
        ein = jnp.einsum("tec,td->ecd", disp_loc, x_loc)
        payload = ein.reshape(n * e_l, capacity, d)
        if codec == "int8":
            payload = _st_quant(payload.reshape(-1), d).reshape(
                payload.shape)
        # dispatch a2a: block j of the result is device j's partial
        # contribution for THIS device's experts; the sum completes
        # the disjoint scatter
        recv = jax.lax.all_to_all(payload, axis, split_axis=0,
                                  concat_axis=0, tiled=True)
        ein_loc = jnp.sum(recv.reshape(n, e_l, capacity, d), axis=0)
        out_loc = jax.vmap(_expert_ffn)(w1, b1, w2, b2, ein_loc)
        # combine a2a: tile n ways so every device assembles the full
        # (e, c, d) expert outputs for its local combine
        full = jax.lax.all_to_all(
            jnp.tile(out_loc, (n, 1, 1)), axis, split_axis=0,
            concat_axis=0, tiled=True)
        out = jnp.einsum("tec,ecd->td", comb_loc,
                         full.reshape(e, capacity, d))
        return out, aux

    spec_t = PartitionSpec(axis, None)
    spec_e1 = PartitionSpec(axis, None)
    spec_e2 = PartitionSpec(axis, None, None)
    return shard_map_nocheck(
        local, mesh,
        (spec_t, PartitionSpec(), spec_e2, spec_e1, spec_e2, spec_e1),
        (spec_t, PartitionSpec()),
    )(x, params["gate_w"], params["experts_w1"], params["experts_b1"],
      params["experts_w2"], params["experts_b2"])


def moe_apply_ep(params, x, *, mesh: Optional[Mesh] = None, axis: str = "ep",
                 capacity_factor: float = 2.0,
                 dispatch_codec: Optional[str] = None):
    """Expert-parallel MoE apply.

    params: dict with gate_w (d, E), experts_w1 (E, d, h), experts_b1
    (E, h), experts_w2 (E, h, d), experts_b2 (E, d). x: (tokens, d)
    global. Experts shard over `axis`; tokens all_to_all to their
    experts and back (explicit exchange — see the module docstring).
    ``dispatch_codec="int8"`` quantizes the dispatch payload on the
    wire (straight-through gradients). Falls back to the legacy
    GSPMD-resharding shard_map when the explicit path is ineligible,
    and to the dense einsum path when the mesh axis is unusable; every
    path lands a ``moe_a2a.*`` counter.
    """
    from ..ops.pallas.counters import bump

    e = params["experts_w1"].shape[0]
    t, d = x.shape
    capacity = max(1, int(capacity_factor * t / e))

    if mesh is None or axis not in mesh.axis_names or \
            mesh.shape[axis] <= 1 or e % mesh.shape[axis] != 0:
        bump("moe_a2a", "xla",
             "dense path: no usable mesh axis "
             f"(mesh={None if mesh is None else dict(mesh.shape)}, "
             f"axis={axis!r}, experts={e})")
        return _moe_dense(params, x, capacity)

    n = mesh.shape[axis]
    if not moe_a2a_escaped() and t % n == 0:
        out, aux = _moe_explicit_a2a(params, x, mesh, axis, n, capacity,
                                     dispatch_codec)
        bump("moe_a2a", "a2a")
        return out, aux
    bump("moe_a2a", "xla",
         "legacy GSPMD resharding: "
         + ("escaped (PADDLE_MOE_A2A=0)" if moe_a2a_escaped()
            else f"tokens={t} not divisible by {axis}={n}"))

    logits = x @ params["gate_w"]
    dispatch, combine, aux = top2_gating(logits, capacity)
    # gather expert inputs: (e, c, d)
    expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), x)

    def local(w1, b1, w2, b2, ein):
        # ein arrives (e/n, c, d) after the spec split: this rank's
        # experts' tokens. (XLA inserts the all_to_all when the
        # upstream einsum output resharded from token- to expert-
        # sharded layout.)
        return jax.vmap(_expert_ffn)(w1, b1, w2, b2, ein)

    from ..parallel.collectives import shard_map_fn

    spec_e = PartitionSpec(axis)
    out_e = shard_map_fn()(
        local, mesh=mesh,
        in_specs=(spec_e, spec_e, spec_e, spec_e, spec_e),
        out_specs=spec_e,
    )(params["experts_w1"], params["experts_b1"],
      params["experts_w2"], params["experts_b2"], expert_in)
    # combine back to tokens
    out = jnp.einsum("tec,ecd->td", combine.astype(x.dtype), out_e)
    return out, aux


@primitive("moe")
def _moe_prim(xf, gate_w, w1, b1, w2, b2, mesh=None, capacity_factor=2.0,
              dispatch_codec=None):
    params = {"gate_w": gate_w, "experts_w1": w1, "experts_b1": b1,
              "experts_w2": w2, "experts_b2": b2}
    return moe_apply_ep(params, xf, mesh=mesh,
                        capacity_factor=capacity_factor,
                        dispatch_codec=dispatch_codec)


class MoELayer(Layer):
    """Transformer FFN replaced by num_experts expert FFNs + top-2 gate."""

    def __init__(self, d_model: int, d_hidden: int, num_experts: int,
                 capacity_factor: float = 2.0, dispatch_codec=None,
                 name=None):
        super().__init__()
        from .initializer import XavierUniform

        self.d_model = d_model
        self.num_experts = num_experts
        self.capacity_factor = capacity_factor
        self.dispatch_codec = dispatch_codec
        init = XavierUniform()
        self.gate_w = self.create_parameter(
            [d_model, num_experts], default_initializer=init)
        self.experts_w1 = self.create_parameter(
            [num_experts, d_model, d_hidden], default_initializer=init)
        self.experts_b1 = self.create_parameter(
            [num_experts, d_hidden], is_bias=True)
        self.experts_w2 = self.create_parameter(
            [num_experts, d_hidden, d_model], default_initializer=init)
        self.experts_b2 = self.create_parameter(
            [num_experts, d_model], is_bias=True)
        self._last_aux_loss = None

    def forward(self, x):
        from .. import ops
        from ..parallel.mesh import get_mesh

        shape = x.shape
        xf = ops.reshape(x, [-1, shape[-1]])
        out, aux = _moe_prim(xf, self.gate_w, self.experts_w1,
                             self.experts_b1, self.experts_w2,
                             self.experts_b2, mesh=get_mesh(),
                             capacity_factor=self.capacity_factor,
                             dispatch_codec=self.dispatch_codec)
        self._last_aux_loss = aux
        return ops.reshape(out, list(shape))

    @property
    def aux_loss(self):
        return self._last_aux_loss
