"""Transformer layers.

Parity with /root/reference/python/paddle/nn/layer/transformer.py
(MultiHeadAttention :91, TransformerEncoderLayer :315, TransformerEncoder
:454, TransformerDecoderLayer :521, TransformerDecoder :695, Transformer
:793). Attention runs through the fused flash-attention path
(ops/pallas/flash_attention.py) when shapes allow.
"""
from __future__ import annotations

from . import functional as F
from .common import Dropout, Linear
from .container import LayerList
from .layer import Layer
from .norm import LayerNorm


def _convert_attention_mask(attn_mask, dtype):
    return attn_mask


class MultiHeadAttention(Layer):
    """q/k/v projections + scaled dot-product attention (B, L, H, D)."""

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None, vdim=None,
                 need_weights=False, weight_attr=None, bias_attr=None,
                 is_causal=False):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.is_causal = is_causal
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim
        self.dropout = dropout
        self.need_weights = need_weights
        kdim = kdim or embed_dim
        vdim = vdim or embed_dim
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        from .. import ops

        key = query if key is None else key
        value = query if value is None else value
        q = self.q_proj(query)
        k = self.k_proj(key)
        v = self.v_proj(value)
        b, lq = q.shape[0], q.shape[1]
        lk = k.shape[1]
        q = ops.reshape(q, [b, lq, self.num_heads, self.head_dim])
        k = ops.reshape(k, [b, lk, self.num_heads, self.head_dim])
        v = ops.reshape(v, [b, lk, self.num_heads, self.head_dim])
        if cache is not None:
            k = ops.concat([cache.k, k], axis=1)
            v = ops.concat([cache.v, v], axis=1)
            cache = type(cache)(k, v)
        if self.is_causal and attn_mask is not None:
            # fold the causal constraint into the user mask (bottom-right
            # aligned, matching the mask-free is_causal path)
            lqk, lkk = q.shape[1], k.shape[1]
            causal = ops.tril(
                ops.ones([lqk, lkk], "bool"), diagonal=lkk - lqk)
            if "bool" in str(attn_mask.dtype):
                attn_mask = ops.logical_and(attn_mask, causal)
            else:
                attn_mask = attn_mask + ops.where(
                    causal, ops.zeros([lqk, lkk], attn_mask.dtype),
                    ops.full([lqk, lkk], -1e30, attn_mask.dtype))
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=self.dropout,
            is_causal=self.is_causal and attn_mask is None,
            training=self.training)
        out = ops.reshape(out, [b, lq, self.embed_dim])
        out = self.out_proj(out)
        if cache is not None:
            return out, cache
        return out

    class Cache:
        def __init__(self, k, v):
            self.k, self.v = k, v

    def gen_cache(self, key, value=None, type=None):
        from .. import ops

        b = key.shape[0]
        from ..ops.creation import zeros

        k = zeros([b, 0, self.num_heads, self.head_dim])
        v = zeros([b, 0, self.num_heads, self.head_dim])
        return MultiHeadAttention.Cache(k, v)


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.activation = activation

    def _act(self, x):
        return F.relu(x) if self.activation == "relu" else F.gelu(x)

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, src, src, src_mask)
        else:
            src, cache = self.self_attn(src, src, src, src_mask, cache)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.dropout(self._act(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src if cache is None else (src, cache)


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        import copy

        self.layers = LayerList(
            [encoder_layer] +
            [copy.deepcopy(encoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None):
        output = src
        new_caches = []
        for i, layer in enumerate(self.layers):
            if cache is None:
                output = layer(output, src_mask)
            else:
                output, c = layer(output, src_mask, cache[i])
                new_caches.append(c)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.cross_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                             weight_attr=weight_attr,
                                             bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.activation = activation

    def _act(self, x):
        return F.relu(x) if self.activation == "relu" else F.gelu(x)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        tgt = self.self_attn(tgt, tgt, tgt, tgt_mask)
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        tgt = self.cross_attn(tgt, memory, memory, memory_mask)
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.dropout(self._act(self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        import copy

        self.layers = LayerList(
            [decoder_layer] +
            [copy.deepcopy(decoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        output = tgt
        for layer in self.layers:
            output = layer(output, memory, tgt_mask, memory_mask)
        if self.norm is not None:
            output = self.norm(output)
        return output


class Transformer(Layer):
    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            enc_norm = LayerNorm(d_model) if normalize_before else None
            self.encoder = TransformerEncoder(enc_layer, num_encoder_layers,
                                              enc_norm)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            dec_norm = LayerNorm(d_model) if normalize_before else None
            self.decoder = TransformerDecoder(dec_layer, num_decoder_layers,
                                              dec_norm)
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None,
                memory_mask=None):
        memory = self.encoder(src, src_mask)
        return self.decoder(tgt, memory, tgt_mask, memory_mask)

    @staticmethod
    def generate_square_subsequent_mask(length):
        import jax.numpy as jnp

        from ..framework.tensor import Tensor

        mask = jnp.where(
            jnp.tril(jnp.ones((length, length), bool)), 0.0, -1e9)
        return Tensor(mask)
