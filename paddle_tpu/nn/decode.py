"""Seq2seq decoding API (reference fluid/layers/rnn.py:585-1900 —
Decoder, BeamSearchDecoder, dynamic_decode, DecodeHelper family,
BasicDecoder).

TPU-native shape: decoding state is a pytree of (batch, beam, ...)
arrays; every step is dense jnp (top-k over the flattened beam*vocab
axis, take_along_axis beam gathers) so a single step jit-compiles
cleanly. The outer time loop is an eager Python loop with early exit
when every beam finishes — decoding is inference-time and
data-dependent-length; the per-step compute is where the FLOPs are.
Outputs are stacked to the reference's [time, batch, beam] layout and
backtraced with ops.gather_tree.
"""
from __future__ import annotations

import collections

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["Decoder", "BeamSearchDecoder", "dynamic_decode",
           "DecodeHelper", "TrainingHelper", "GreedyEmbeddingHelper",
           "SampleEmbeddingHelper", "BasicDecoder"]

_KINF = 1e9


def _unwrap(x):
    from ..framework.tensor import Tensor

    return x.value if isinstance(x, Tensor) else jnp.asarray(x)


def _wrap(x):
    from ..framework.tensor import Tensor

    return Tensor(x)


def _map(fn, tree):
    return jax.tree_util.tree_map(fn, tree)


class Decoder:
    """Abstract decoder protocol (reference rnn.py:585): initialize /
    step / finalize over a (possibly nested) state structure."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        raise NotImplementedError

    @property
    def tracks_own_finished(self):
        return False


class BeamSearchDecoder(Decoder):
    """Beam search over an RNN (or any) cell (reference rnn.py:698).

    cell: callable (inputs, states) -> (outputs, next_states) over
    MERGED (batch*beam, ...) tensors; start_token/end_token: int ids;
    beam_size: int; embedding_fn: optional id -> embedding callable
    applied to sampled ids; output_fn: optional projection from cell
    output to vocab logits.
    """

    OutputWrapper = collections.namedtuple(
        "OutputWrapper", ("scores", "predicted_ids", "parent_ids"))
    StateWrapper = collections.namedtuple(
        "StateWrapper", ("cell_states", "log_probs", "finished", "lengths"))

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # -- beam/batch reshape helpers (reference rnn.py:776-945) --------

    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """(batch, ...) -> (batch*beam, ...) by tiling each row beam
        times (for encoder outputs consumed inside the cell)."""
        v = _unwrap(x)
        tiled = jnp.repeat(v[:, None], beam_size, axis=1)
        return _wrap(tiled.reshape((-1,) + v.shape[1:]))

    def _expand_to_beam_size(self, x):
        v = _unwrap(x)
        return jnp.repeat(v[:, None], self.beam_size, axis=1)

    def _merge_batch_beams(self, x):
        v = jnp.asarray(x)
        # explicit sizes: -1 cannot be inferred when a later axis is 0
        # (e.g. a transformer decoder's empty initial prefix)
        return v.reshape((v.shape[0] * v.shape[1],) + v.shape[2:])

    def _split_batch_beams(self, x):
        v = jnp.asarray(x)
        return v.reshape((v.shape[0] // self.beam_size, self.beam_size)
                         + v.shape[1:])

    def _mask_probs(self, probs, finished):
        """Finished beams may only grow through end_token with score 0
        (so their total log prob freezes)."""
        vocab = probs.shape[-1]
        noend = jnp.full((vocab,), -_KINF, probs.dtype)
        noend = noend.at[self.end_token].set(0.0)
        return jnp.where(finished[..., None], noend, probs)

    @staticmethod
    def _gather(x, indices, *_):
        """Reorder the beam axis: x (batch, beam, ...), indices
        (batch, beam) int."""
        idx = indices
        while idx.ndim < x.ndim:
            idx = idx[..., None]
        return jnp.take_along_axis(x, idx.astype(jnp.int32), axis=1)

    # -- decoder protocol ---------------------------------------------

    def initialize(self, initial_cell_states):
        cell_states = _map(lambda s: self._expand_to_beam_size(s),
                           initial_cell_states)
        batch = jax.tree_util.tree_leaves(cell_states)[0].shape[0]
        init_inputs = jnp.full((batch, self.beam_size), self.start_token,
                               jnp.int64)
        log_probs = jnp.tile(
            jnp.asarray([[0.0] + [-_KINF] * (self.beam_size - 1)],
                        jnp.float32), (batch, 1))
        finished = jnp.zeros((batch, self.beam_size), bool)
        lengths = jnp.zeros((batch, self.beam_size), jnp.int64)
        inputs = (self.embedding_fn(_wrap(init_inputs))
                  if self.embedding_fn else _wrap(init_inputs))
        return inputs, self.StateWrapper(cell_states, log_probs, finished,
                                         lengths), _wrap(finished)

    def _beam_search_step(self, time, logits, next_cell_states, beam_state):
        vocab = logits.shape[-1]
        step_log_probs = jax.nn.log_softmax(logits)
        step_log_probs = self._mask_probs(step_log_probs,
                                          beam_state.finished)
        log_probs = step_log_probs + beam_state.log_probs[..., None]
        scores = log_probs.reshape(-1, self.beam_size * vocab)
        topk_scores, topk_indices = jax.lax.top_k(scores, self.beam_size)
        beam_indices = topk_indices // vocab
        token_indices = (topk_indices % vocab).astype(jnp.int64)
        next_log_probs = jnp.take_along_axis(scores, topk_indices, axis=1)
        next_cell_states = _map(
            lambda x: self._gather(x, beam_indices), next_cell_states)
        next_finished = self._gather(beam_state.finished, beam_indices)
        next_lengths = self._gather(beam_state.lengths, beam_indices)
        next_lengths = next_lengths + (~next_finished).astype(jnp.int64)
        next_finished = next_finished | (token_indices == self.end_token)
        out = self.OutputWrapper(topk_scores, token_indices,
                                 beam_indices.astype(jnp.int64))
        state = self.StateWrapper(next_cell_states, next_log_probs,
                                  next_finished, next_lengths)
        return out, state

    def step(self, time, inputs, states, **kwargs):
        merged_in = _map(lambda x: self._merge_batch_beams(_unwrap(x)),
                         inputs)
        merged_states = _map(self._merge_batch_beams, states.cell_states)
        cell_out, next_cell_states = self.cell(
            _map(_wrap, merged_in), _map(_wrap, merged_states), **kwargs)
        cell_out = _map(lambda x: self._split_batch_beams(_unwrap(x)),
                        cell_out)
        next_cell_states = _map(lambda x: self._split_batch_beams(_unwrap(x)),
                                next_cell_states)
        if self.output_fn is not None:
            cell_out = _unwrap(self.output_fn(_wrap(cell_out)))
        out, state = self._beam_search_step(time, jnp.asarray(cell_out),
                                            next_cell_states, states)
        next_inputs = (self.embedding_fn(_wrap(out.predicted_ids))
                       if self.embedding_fn else _wrap(out.predicted_ids))
        return out, state, next_inputs, _wrap(state.finished)

    def finalize(self, outputs, final_states, sequence_lengths):
        from ..ops.search import gather_tree

        predicted_ids = gather_tree(_wrap(outputs.predicted_ids),
                                    _wrap(outputs.parent_ids))
        return predicted_ids, final_states

    @property
    def tracks_own_finished(self):
        return True


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """Run a decoder until every sequence finishes or ``max_step_num``
    steps (reference rnn.py:1169).

    The step compute is dense jnp (jit-friendly); the loop is an eager
    Python loop with early exit — the TPU translation of the
    reference's while_op + TensorArray machinery. When
    ``max_step_num`` is None a 256-step safety cap applies (outputs
    must have a bounded time axis). Returns
    ``(outputs, final_states[, sequence_lengths])`` with the time axis
    first iff ``output_time_major``.
    """
    cap = 256 if max_step_num is None else int(max_step_num)
    inputs, states, finished = decoder.initialize(inits)
    finished_v = _unwrap(finished)
    seq_len = jnp.zeros(finished_v.shape, jnp.int64)
    step_outputs = []
    final_outputs = None
    step = 0
    while step <= cap and not bool(jnp.all(finished_v)):
        out, next_states, next_inputs, next_finished = decoder.step(
            jnp.asarray(step), inputs, states, **kwargs)
        next_finished_v = _unwrap(next_finished)
        if not decoder.tracks_own_finished:
            next_finished_v = next_finished_v | finished_v
            next_seq_len = seq_len + (~finished_v).astype(jnp.int64)
            if impute_finished:
                next_states = jax.tree_util.tree_map(
                    lambda new, old: jnp.where(
                        _bcast(finished_v, jnp.asarray(new)),
                        jnp.asarray(old), jnp.asarray(new)),
                    next_states, states)
        else:
            next_seq_len = getattr(next_states, "lengths", seq_len)
        step_outputs.append(_map(_unwrap, out))
        inputs, states = next_inputs, next_states
        finished_v, seq_len = next_finished_v, next_seq_len
        step += 1

    if not step_outputs:
        raise ValueError("dynamic_decode: decoder finished before the "
                         "first step — check initialize()")
    # stack along time, keeping the output namedtuple structure
    outputs = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs, axis=0), *step_outputs)
    try:
        outputs, final_states = decoder.finalize(outputs, states, seq_len)
        final_outputs = outputs
    except NotImplementedError:
        final_outputs, final_states = outputs, states

    if not output_time_major:
        final_outputs = _map(
            lambda x: jnp.swapaxes(jnp.asarray(_unwrap(x)), 0, 1),
            final_outputs)
    final_outputs = _map(lambda x: _wrap(jnp.asarray(x)), final_outputs)
    if return_length:
        return final_outputs, final_states, _wrap(seq_len)
    return final_outputs, final_states


def _bcast(mask, ref):
    m = mask
    while m.ndim < ref.ndim:
        m = m[..., None]
    return m


class DecodeHelper:
    """Sampling protocol for BasicDecoder (reference rnn.py:1399)."""

    def initialize(self):
        raise NotImplementedError

    def sample(self, time, outputs, states):
        raise NotImplementedError

    def next_inputs(self, time, outputs, states, sample_ids):
        raise NotImplementedError


class TrainingHelper(DecodeHelper):
    """Teacher forcing: feed ground-truth inputs step by step
    (reference rnn.py:1468). inputs: (batch, time, ...) (or time-major);
    sequence_length: (batch,)."""

    def __init__(self, inputs, sequence_length, time_major=False):
        self.sequence_length = _unwrap(sequence_length)
        self.time_major = time_major
        # transpose to time-major ONCE — next_inputs slices a step per
        # call and must not move the whole tensor every step
        t = (lambda x: _unwrap(x)) if time_major else \
            (lambda x: jnp.swapaxes(_unwrap(x), 0, 1))
        self._tm_inputs = _map(t, inputs)

    def initialize(self):
        first = _map(lambda x: x[0], self._tm_inputs)
        finished = self.sequence_length <= 0
        return _map(_wrap, first), _wrap(finished)

    def sample(self, time, outputs, states):
        return _wrap(jnp.argmax(_unwrap(outputs), axis=-1))

    def next_inputs(self, time, outputs, states, sample_ids):
        t = int(time) + 1
        length = jax.tree_util.tree_leaves(self._tm_inputs)[0].shape[0]
        nxt = _map(lambda x: x[min(t, length - 1)], self._tm_inputs)
        finished = self.sequence_length <= t
        return _wrap(finished), _map(_wrap, nxt), states


class GreedyEmbeddingHelper(DecodeHelper):
    """Greedy argmax sampling fed back through an embedding
    (reference rnn.py:1599)."""

    def __init__(self, embedding_fn, start_tokens, end_token):
        self.embedding_fn = embedding_fn
        self.start_tokens = _unwrap(start_tokens).astype(jnp.int64)
        self.end_token = int(end_token)

    def initialize(self):
        finished = jnp.zeros(self.start_tokens.shape, bool)
        return self.embedding_fn(_wrap(self.start_tokens)), _wrap(finished)

    def sample(self, time, outputs, states):
        return _wrap(jnp.argmax(_unwrap(outputs), axis=-1))

    def next_inputs(self, time, outputs, states, sample_ids):
        ids = _unwrap(sample_ids)
        finished = ids == self.end_token
        return _wrap(finished), self.embedding_fn(_wrap(ids)), states


class SampleEmbeddingHelper(GreedyEmbeddingHelper):
    """Categorical sampling from the softmax (reference rnn.py:1700)."""

    def __init__(self, embedding_fn, start_tokens, end_token,
                 softmax_temperature=None, seed=None):
        super().__init__(embedding_fn, start_tokens, end_token)
        self.temperature = softmax_temperature
        self._key = jax.random.key(0 if seed is None else seed)

    def sample(self, time, outputs, states):
        logits = _unwrap(outputs)
        if self.temperature is not None:
            logits = logits / self.temperature
        self._key, sub = jax.random.split(self._key)
        return _wrap(jax.random.categorical(sub, logits, axis=-1))


class BasicDecoder(Decoder):
    """cell + helper decoder (reference rnn.py:1770): each step runs
    the cell, samples via the helper, and emits
    (cell_outputs, sample_ids)."""

    OutputWrapper = collections.namedtuple(
        "OutputWrapper", ("cell_outputs", "sample_ids"))

    def __init__(self, cell, helper, output_fn=None):
        self.cell = cell
        self.helper = helper
        self.output_fn = output_fn

    def initialize(self, initial_cell_states):
        initial_inputs, initial_finished = self.helper.initialize()
        return initial_inputs, initial_cell_states, initial_finished

    def step(self, time, inputs, states, **kwargs):
        cell_outputs, cell_states = self.cell(inputs, states, **kwargs)
        if self.output_fn is not None:
            cell_outputs = self.output_fn(cell_outputs)
        sample_ids = self.helper.sample(time, cell_outputs, cell_states)
        finished, next_inputs, next_states = self.helper.next_inputs(
            time, cell_outputs, cell_states, sample_ids)
        out = self.OutputWrapper(_unwrap(cell_outputs), _unwrap(sample_ids))
        return out, next_states, next_inputs, finished

    def finalize(self, outputs, final_states, sequence_lengths):
        raise NotImplementedError
