"""nn functional ops.

Parity with the reference NN operator set (/root/reference/paddle/fluid/
operators/: activation_op.cc, conv_op.cc, pool_op.cc, batch_norm_op.cc,
layer_norm_op.cc, softmax_op.cc, cross_entropy_op.cc, dropout_op.cc,
lookup_table_v2_op.cc, interpolate_op.cc ...). Convs/matmuls lower to MXU
via lax.conv_general_dilated / dot_general; everything else is fusable
elementwise work for the VPU.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtype_mod
from ..framework.op import primitive
from ..framework.random import next_rng_key
from ..framework.tensor import Tensor, unwrap

# ---------------------------------------------------------------------------
# activations (activation_op.cc)
# ---------------------------------------------------------------------------


@primitive("relu")
def relu(x, name=None):
    return jax.nn.relu(x)


@primitive("relu6")
def relu6(x, name=None):
    return jnp.clip(x, 0.0, 6.0)


@primitive("leaky_relu")
def leaky_relu(x, negative_slope=0.01, name=None):
    return jnp.where(x >= 0, x, negative_slope * x)


@primitive("prelu_fn")
def prelu(x, weight, data_format="NCHW", name=None):
    if weight.size > 1:
        shape = [1] * x.ndim
        axis = 1 if data_format == "NCHW" else x.ndim - 1
        shape[axis] = weight.size
        weight = weight.reshape(shape)
    return jnp.where(x >= 0, x, weight * x)


@primitive("elu")
def elu(x, alpha=1.0, name=None):
    return jax.nn.elu(x, alpha)


@primitive("selu")
def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))


@primitive("celu")
def celu(x, alpha=1.0, name=None):
    return jax.nn.celu(x, alpha)


@primitive("gelu")
def gelu(x, approximate=False, name=None):
    return jax.nn.gelu(x, approximate=approximate)


@primitive("silu")
def silu(x, name=None):
    return jax.nn.silu(x)


swish = silu


@primitive("hardswish")
def hardswish(x, name=None):
    return x * jnp.clip(x + 3.0, 0.0, 6.0) / 6.0


@primitive("hardsigmoid")
def hardsigmoid(x, slope=1.0 / 6.0, offset=0.5, name=None):
    return jnp.clip(slope * x + offset, 0.0, 1.0)


@primitive("hardtanh")
def hardtanh(x, min=-1.0, max=1.0, name=None):
    return jnp.clip(x, min, max)


@primitive("hardshrink")
def hardshrink(x, threshold=0.5, name=None):
    return jnp.where(jnp.abs(x) > threshold, x, 0.0)


@primitive("softshrink")
def softshrink(x, threshold=0.5, name=None):
    return jnp.where(x > threshold, x - threshold,
                     jnp.where(x < -threshold, x + threshold, 0.0))


@primitive("tanhshrink")
def tanhshrink(x, name=None):
    return x - jnp.tanh(x)


@primitive("softplus")
def softplus(x, beta=1.0, threshold=20.0, name=None):
    scaled = beta * x
    return jnp.where(scaled > threshold, x, jnp.log1p(jnp.exp(scaled)) / beta)


@primitive("softsign")
def softsign(x, name=None):
    return x / (1.0 + jnp.abs(x))


@primitive("mish")
def mish(x, name=None):
    return x * jnp.tanh(jax.nn.softplus(x))


@primitive("thresholded_relu")
def thresholded_relu(x, threshold=1.0, name=None):
    return jnp.where(x > threshold, x, 0.0)


@primitive("maxout")
def maxout(x, groups, axis=1, name=None):
    c = x.shape[axis]
    axis = axis % x.ndim
    new_shape = x.shape[:axis] + (c // groups, groups) + x.shape[axis + 1:]
    return jnp.max(x.reshape(new_shape), axis=axis + 1)


@primitive("softmax")
def softmax(x, axis=-1, dtype=None, name=None):
    if dtype is not None:
        x = x.astype(dtype_mod.convert_dtype(dtype))
    return jax.nn.softmax(x, axis=axis)


@primitive("log_softmax")
def log_softmax(x, axis=-1, dtype=None, name=None):
    if dtype is not None:
        x = x.astype(dtype_mod.convert_dtype(dtype))
    return jax.nn.log_softmax(x, axis=axis)


@primitive("gumbel_softmax")
def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    g = jax.random.gumbel(next_rng_key(), x.shape, x.dtype)
    y = jax.nn.softmax((x + g) / temperature, axis=axis)
    if hard:
        idx = jnp.argmax(y, axis=axis, keepdims=True)
        hard_y = jnp.zeros_like(y).at[...].set(0.0)
        hard_y = jnp.where(
            jnp.arange(y.shape[axis]).reshape(
                [-1 if i == axis % y.ndim else 1 for i in range(y.ndim)]) == idx,
            1.0, 0.0)
        # straight-through estimator
        y = hard_y - jax.lax.stop_gradient(y) + y
    return y


@primitive("sigmoid_fn")
def sigmoid(x, name=None):
    return jax.nn.sigmoid(x)


# ---------------------------------------------------------------------------
# linear / embedding (mul_op.cc fc, lookup_table_v2_op.cc)
# ---------------------------------------------------------------------------


@primitive("linear")
def linear(x, weight, bias=None, name=None):
    if x.ndim < 1 or weight.ndim != 2 or x.shape[-1] != weight.shape[0]:
        from ..framework.errors import InvalidArgumentError

        raise InvalidArgumentError(
            f"linear: input features {tuple(x.shape)}[-1] must match "
            f"weight rows {tuple(weight.shape)} — W is (in_features, "
            "out_features) in this framework (reference fc/mul op)")
    out = jnp.matmul(x, weight)
    if bias is not None:
        out = out + bias
    return out


@primitive("embedding_fn")
def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    if not jnp.issubdtype(x.dtype, jnp.integer):
        from ..framework.errors import InvalidArgumentError

        raise InvalidArgumentError(
            f"embedding: ids must be an integer tensor, got {x.dtype} "
            f"shape {tuple(x.shape)} (cast labels/ids with "
            ".astype('int64'))")
    out = jnp.take(weight, x, axis=0)
    if padding_idx is not None:
        mask = (x == padding_idx)[..., None]
        out = jnp.where(mask, 0.0, out)
    return out


@primitive("one_hot")
def one_hot(x, num_classes, name=None):
    return jax.nn.one_hot(x, num_classes, dtype=dtype_mod.get_default_dtype())


# ---------------------------------------------------------------------------
# dropout family (dropout_op.cc)
# ---------------------------------------------------------------------------


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return _scale_only(x, factor=1.0 - p)
        return x if isinstance(x, Tensor) else Tensor(x)
    return _dropout(x, p=p, axis=axis, mode=mode, key=next_rng_key())


@primitive("dropout")
def _dropout(x, p, axis, mode, key):
    if axis is None:
        shape = x.shape
    else:
        axes = [axis] if isinstance(axis, int) else list(axis)
        shape = tuple(s if i in axes else 1 for i, s in enumerate(x.shape))
    keep = jax.random.bernoulli(key, 1.0 - p, shape)
    if mode == "upscale_in_train":
        return jnp.where(keep, x / (1.0 - p), 0.0)
    return jnp.where(keep, x, 0.0)


@primitive("scale_only")
def _scale_only(x, factor):
    return x * factor


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p=p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p=p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x if isinstance(x, Tensor) else Tensor(x)
    return _alpha_dropout(x, p=p, key=next_rng_key())


@primitive("alpha_dropout")
def _alpha_dropout(x, p, key):
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
    a = (1.0 / math.sqrt((1.0 - p) * (1.0 + p * alpha_p ** 2))) if p < 1 else 0.0
    b = -a * alpha_p * p
    return a * jnp.where(keep, x, alpha_p) + b


# ---------------------------------------------------------------------------
# convolutions (conv_op.cc / conv_transpose_op.cc) — MXU path
# ---------------------------------------------------------------------------


def _tuple_n(v, n):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    return tuple(int(x) for x in v)


def _conv_padding(padding, n, strides=None):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, (int, np.integer)):
        return [(int(padding),) * 2] * n
    padding = list(padding)
    if len(padding) == n:
        if isinstance(padding[0], (list, tuple)):
            return [tuple(int(v) for v in p) for p in padding]
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    raise ValueError(f"Bad padding {padding}")


def _conv(x, weight, bias, stride, padding, dilation, groups, n, channel_last):
    from ..framework.errors import InvalidArgumentError

    if x.ndim != n + 2 or weight.ndim != n + 2:
        raise InvalidArgumentError(
            f"conv{n}d: expected rank-{n + 2} input and weight, got "
            f"input {tuple(x.shape)} and weight {tuple(weight.shape)}")
    cin = x.shape[-1] if channel_last else x.shape[1]
    if cin != weight.shape[1] * groups:
        raise InvalidArgumentError(
            f"conv{n}d: input {tuple(x.shape)} "
            f"({'channel-last' if channel_last else 'channel-first'}, "
            f"C_in={cin}) is incompatible with weight "
            f"{tuple(weight.shape)} — weight layout is (C_out, "
            f"C_in/groups, *kernel) and needs C_in == "
            f"{weight.shape[1]} * groups({groups})")
    stride = _tuple_n(stride, n)
    dilation = _tuple_n(dilation, n)
    pad = _conv_padding(padding, n)
    if channel_last:
        spatial = "DHW"[-n:]
        lhs_spec = "N" + spatial + "C"
    else:
        spatial = "DHW"[-n:]
        lhs_spec = "NC" + spatial
    rhs_spec = "OI" + spatial
    dn = jax.lax.conv_dimension_numbers(x.shape, weight.shape,
                                        (lhs_spec, rhs_spec, lhs_spec))
    # no preferred_element_type here: the TPU MXU accumulates bf16 convs
    # in f32 natively, and requesting an f32 output makes the conv
    # transpose rule see an f32 cotangent against bf16 operands (dtype
    # mismatch at trace time under value_and_grad)
    out = jax.lax.conv_general_dilated(
        x, weight, window_strides=stride, padding=pad,
        rhs_dilation=dilation, dimension_numbers=dn,
        feature_group_count=groups)
    if bias is not None:
        bshape = [1] * out.ndim
        bshape[-1 if channel_last else 1] = bias.shape[0]
        out = out + bias.reshape(bshape)
    return out


@primitive("conv1d")
def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1,
                 channel_last=data_format == "NLC")


@primitive("conv2d")
def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2,
                 channel_last=data_format == "NHWC")


@primitive("conv3d")
def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3,
                 channel_last=data_format == "NDHWC")


def _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                    groups, n, channel_last):
    stride = _tuple_n(stride, n)
    dilation = _tuple_n(dilation, n)
    output_padding = _tuple_n(output_padding, n)
    if isinstance(padding, str):
        raise ValueError("string padding unsupported for conv_transpose")
    pad = _conv_padding(padding, n)
    if channel_last:
        spatial = "DHW"[-n:]
        lhs_spec = "N" + spatial + "C"
    else:
        spatial = "DHW"[-n:]
        lhs_spec = "NC" + spatial
    rhs_spec = "IO" + spatial  # paddle stores transpose weight as (Cin, Cout/g, K...)
    dn = jax.lax.conv_dimension_numbers(x.shape, weight.shape,
                                        (lhs_spec, rhs_spec, lhs_spec))
    # gradient-of-conv formulation: lhs_dilation=stride
    k = [(weight.shape[2 + i] - 1) * dilation[i] for i in range(n)]
    tpad = [(k[i] - pad[i][0], k[i] - pad[i][1] + output_padding[i])
            for i in range(n)]
    if groups > 1:
        # weight (Cin, Cout/g, K) -> grouped transpose conv via reshape
        cin = weight.shape[0]
        w = weight.reshape(groups, cin // groups, *weight.shape[1:])
        w = jnp.flip(w, axis=tuple(range(3, 3 + n)))
        w = jnp.swapaxes(w, 1, 2)  # (g, Cout/g, Cin/g, K)
        w = w.reshape(groups * w.shape[1], *w.shape[2:])  # (Cout, Cin/g, K)
        dn2 = jax.lax.conv_dimension_numbers(
            x.shape, w.shape, (lhs_spec, "OI" + spatial, lhs_spec))
        out = jax.lax.conv_general_dilated(
            x, w, window_strides=(1,) * n, padding=tpad,
            lhs_dilation=stride, rhs_dilation=dilation, dimension_numbers=dn2,
            feature_group_count=groups)
    else:
        w = jnp.flip(weight, axis=tuple(range(2, 2 + n)))
        w = jnp.swapaxes(w, 0, 1)  # (Cout, Cin, K)
        dn2 = jax.lax.conv_dimension_numbers(
            x.shape, w.shape, (lhs_spec, "OI" + spatial, lhs_spec))
        out = jax.lax.conv_general_dilated(
            x, w, window_strides=(1,) * n, padding=tpad,
            lhs_dilation=stride, rhs_dilation=dilation, dimension_numbers=dn2)
    if bias is not None:
        bshape = [1] * out.ndim
        bshape[-1 if channel_last else 1] = bias.shape[0]
        out = out + bias.reshape(bshape)
    return out


@primitive("conv1d_transpose")
def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     data_format="NCL", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 1, data_format == "NLC")


@primitive("conv2d_transpose")
def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     data_format="NCHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 2, data_format == "NHWC")


@primitive("conv3d_transpose")
def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     data_format="NCDHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 3, data_format == "NDHWC")


# ---------------------------------------------------------------------------
# pooling (pool_op.cc)
# ---------------------------------------------------------------------------


def _pool(x, kernel, stride, padding, n, channel_last, op, ceil_mode=False,
          count_include_pad=True):
    kernel = _tuple_n(kernel, n)
    stride = _tuple_n(stride if stride is not None else kernel, n)
    pad = _conv_padding(padding, n)
    if channel_last:
        window = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
        pads = [(0, 0)] + (pad if isinstance(pad, list) else pad) + [(0, 0)] if not isinstance(pad, str) else pad
    else:
        window = (1, 1) + kernel
        strides = (1, 1) + stride
        pads = [(0, 0), (0, 0)] + pad if not isinstance(pad, str) else pad
    if isinstance(pads, str):
        pads = jax.lax.padtype_to_pads(x.shape, window, strides, pads)
    if ceil_mode:
        pads = list(pads)
        spatial_off = 1 if channel_last else 2
        for i in range(n):
            dim = spatial_off + i
            size = x.shape[dim] + pads[dim][0] + pads[dim][1]
            rem = (size - kernel[i]) % stride[i]
            if rem != 0:
                pads[dim] = (pads[dim][0], pads[dim][1] + stride[i] - rem)
    if op == "max":
        init = -jnp.inf if dtype_mod.is_floating(x.dtype) else jnp.iinfo(x.dtype).min
        return jax.lax.reduce_window(x, init, jax.lax.max, window, strides, pads)
    ssum = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides, pads)
    if count_include_pad:
        denom = float(np.prod(kernel))
        return ssum / denom
    ones = jnp.ones_like(x)
    counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides, pads)
    return ssum / counts


@primitive("max_pool1d")
def max_pool1d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCL", name=None):
    return _pool(x, kernel_size, stride, padding, 1, data_format == "NLC",
                 "max", ceil_mode)


@primitive("max_pool2d")
def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, data_format == "NHWC",
                 "max", ceil_mode)


@primitive("max_pool3d")
def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, data_format == "NDHWC",
                 "max", ceil_mode)


@primitive("avg_pool1d")
def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    return _pool(x, kernel_size, stride, padding, 1, data_format == "NLC",
                 "avg", ceil_mode, count_include_pad=not exclusive)


@primitive("avg_pool2d")
def avg_pool2d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 2, data_format == "NHWC",
                 "avg", ceil_mode, count_include_pad=not exclusive)


@primitive("avg_pool3d")
def avg_pool3d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, data_format == "NDHWC",
                 "avg", ceil_mode, count_include_pad=not exclusive)


def _adaptive_pool(x, output_size, n, op, channel_last=False):
    out_sizes = _tuple_n(output_size, n)
    spatial_off = 1 if channel_last else 2
    out = x
    for i in range(n):
        dim = spatial_off + i
        in_size = out.shape[dim]
        o = out_sizes[i] if out_sizes[i] is not None else in_size
        if in_size % o == 0:
            k = in_size // o
            shape = out.shape[:dim] + (o, k) + out.shape[dim + 1:]
            r = out.reshape(shape)
            out = jnp.max(r, axis=dim + 1) if op == "max" else jnp.mean(r, axis=dim + 1)
        else:
            # general adaptive: gather variable windows
            starts = (np.arange(o) * in_size) // o
            ends = ((np.arange(o) + 1) * in_size + o - 1) // o
            segs = []
            for s, e in zip(starts, ends):
                sl = jax.lax.slice_in_dim(out, int(s), int(e), axis=dim)
                red = jnp.max(sl, axis=dim, keepdims=True) if op == "max" \
                    else jnp.mean(sl, axis=dim, keepdims=True)
                segs.append(red)
            out = jnp.concatenate(segs, axis=dim)
    return out


@primitive("adaptive_avg_pool1d")
def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, "avg")


@primitive("adaptive_avg_pool2d")
def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, "avg", data_format == "NHWC")


@primitive("adaptive_avg_pool3d")
def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, "avg", data_format == "NDHWC")


@primitive("adaptive_max_pool1d")
def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 1, "max")


@primitive("adaptive_max_pool2d")
def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 2, "max")


@primitive("adaptive_max_pool3d")
def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 3, "max")


# ---------------------------------------------------------------------------
# normalization (batch_norm_op.cc, layer_norm_op.cc, group_norm_op.cc,
# instance_norm_op.cc, norm_op.cc)
# ---------------------------------------------------------------------------


@primitive("layer_norm")
def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5,
               name=None):
    if isinstance(normalized_shape, (int, np.integer)):
        normalized_shape = (int(normalized_shape),)
    axes = tuple(range(x.ndim - len(normalized_shape), x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=axes, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + epsilon)
    if weight is not None:
        out = out * weight
    if bias is not None:
        out = out + bias
    return out


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5,
               data_format="NCHW", use_global_stats=None, name=None):
    """Stateful wrapper: updates running stats in training mode (eager)."""
    axis = _bn_axis(unwrap(x).ndim, data_format)
    use_stats = (not training) if use_global_stats is None else use_global_stats
    if use_stats:
        return _batch_norm_infer(x, running_mean, running_var, weight, bias,
                                 epsilon=epsilon, axis=axis)
    out, mean, var = _batch_norm_train(x, weight, bias, epsilon=epsilon,
                                       axis=axis)
    if isinstance(running_mean, Tensor):
        m = unwrap(mean)
        v = unwrap(var)
        running_mean._value = momentum * running_mean._value + (1 - momentum) * m
        running_var._value = momentum * running_var._value + (1 - momentum) * v
    return out


def _bn_axis(ndim, data_format):
    if data_format in ("NCHW", "NCL", "NCDHW", "NC"):
        return 1
    return ndim - 1


@primitive("batch_norm_infer")
def _batch_norm_infer(x, mean, var, weight, bias, epsilon, axis):
    shape = [1] * x.ndim
    shape[axis] = x.shape[axis]
    out = (x - mean.reshape(shape)) * jax.lax.rsqrt(var.reshape(shape) + epsilon)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out


@primitive("batch_norm_train")
def _batch_norm_train(x, weight, bias, epsilon, axis):
    axes = tuple(i for i in range(x.ndim) if i != axis)
    mean = jnp.mean(x, axis=axes)
    var = jnp.var(x, axis=axes)
    shape = [1] * x.ndim
    shape[axis] = x.shape[axis]
    out = (x - mean.reshape(shape)) * jax.lax.rsqrt(var.reshape(shape) + epsilon)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out, mean, var


@primitive("group_norm_fn")
def group_norm(x, num_groups, weight=None, bias=None, epsilon=1e-5,
               data_format="NCHW", name=None):
    if data_format != "NCHW" and x.ndim == 4:
        x = jnp.moveaxis(x, -1, 1)
    n, c = x.shape[0], x.shape[1]
    g = num_groups
    r = x.reshape(n, g, c // g, *x.shape[2:])
    axes = tuple(range(2, r.ndim))
    mean = jnp.mean(r, axis=axes, keepdims=True)
    var = jnp.var(r, axis=axes, keepdims=True)
    out = ((r - mean) * jax.lax.rsqrt(var + epsilon)).reshape(x.shape)
    shape = [1, c] + [1] * (x.ndim - 2)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    if data_format != "NCHW" and out.ndim == 4:
        out = jnp.moveaxis(out, 1, -1)
    return out


@primitive("instance_norm_fn")
def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW", name=None):
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + eps)
    if weight is not None:
        shape = [1, x.shape[1]] + [1] * (x.ndim - 2)
        out = out * weight.reshape(shape)
    if bias is not None:
        shape = [1, x.shape[1]] + [1] * (x.ndim - 2)
        out = out + bias.reshape(shape)
    return out


@primitive("local_response_norm")
def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    chan_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    sq = jnp.square(x)
    half = size // 2
    pads = [(0, 0)] * x.ndim
    pads[chan_axis] = (half, size - half - 1)
    sq = jnp.pad(sq, pads)
    window = [1] * x.ndim
    window[chan_axis] = size
    ssum = jax.lax.reduce_window(sq, 0.0, jax.lax.add, tuple(window),
                                 (1,) * x.ndim, [(0, 0)] * x.ndim)
    return x / jnp.power(k + alpha * ssum, beta)


@primitive("normalize")
def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    nrm = jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=True) ** (1.0 / p)
    return x / jnp.maximum(nrm, epsilon)


l2_normalize = normalize


@primitive("rms_norm")
def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    out = x * jax.lax.rsqrt(var + epsilon)
    if weight is not None:
        out = out * weight
    return out


# ---------------------------------------------------------------------------
# losses (cross_entropy_op.cc, softmax_with_cross_entropy_op.cc, ...)
# ---------------------------------------------------------------------------


def _reduce_loss(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


@primitive("fused_linear_cross_entropy", nondiff=("label",))
def fused_linear_cross_entropy(h, weight, bias, label, ignore_index=-100,
                               name=None):
    """mean softmax-xent of (h @ weight^T + bias) without materialising
    the (rows, vocab) logits in HBM: the Pallas kernel streams vocab
    tiles with an online logsumexp (ops/pallas/fused_xent.py — the MLM
    head's ~1 GB logits round-trips were the top non-MXU cost at
    bert512). weight: (V, H) (embedding layout, tied-decoder ready);
    falls back to the equivalent XLA computation off-TPU."""
    from ..ops.pallas.fused_xent import fused_linear_cross_entropy as core

    return core(h, weight, bias, label, ignore_index=ignore_index)


@primitive("softmax_with_cross_entropy")
def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None):
    if use_softmax:
        logp = jax.nn.log_softmax(input, axis=axis)
    else:
        logp = jnp.log(jnp.maximum(input, 1e-30))
    n_classes = input.shape[axis]
    if soft_label:
        loss = -jnp.sum(label * logp, axis=axis)
        if reduction == "mean":
            return jnp.mean(loss)
        return _reduce_loss(loss, reduction)
    lbl = label
    if not jnp.issubdtype(lbl.dtype, jnp.integer):
        from ..framework.errors import InvalidArgumentError

        raise InvalidArgumentError(
            f"cross_entropy: hard labels must be integer class ids, got "
            f"{lbl.dtype} {tuple(lbl.shape)}; pass soft_label=True for "
            "probability targets")
    if lbl.ndim == logp.ndim:
        lbl = jnp.squeeze(lbl, axis=axis)
    elif lbl.ndim != logp.ndim - 1:
        from ..framework.errors import InvalidArgumentError

        raise InvalidArgumentError(
            f"cross_entropy: label shape {tuple(label.shape)} must be "
            f"logits shape {tuple(input.shape)} without the class axis "
            f"(or with a trailing 1)")
    if label_smoothing > 0.0:
        onehot = jax.nn.one_hot(lbl, n_classes, dtype=logp.dtype, axis=axis)
        soft = onehot * (1 - label_smoothing) + label_smoothing / n_classes
        loss = -jnp.sum(soft * logp, axis=axis)
    else:
        safe_lbl = jnp.where(lbl == ignore_index, 0, lbl)
        picked = jnp.take_along_axis(
            logp, jnp.expand_dims(safe_lbl, axis), axis=axis)
        loss = -jnp.squeeze(picked, axis=axis)
    valid = lbl != ignore_index
    loss = jnp.where(valid, loss, 0.0)
    if weight is not None:
        w = jnp.take(weight, jnp.where(valid, lbl, 0))
        loss = loss * w
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(jnp.where(valid, w, 0.0)), 1e-12)
    if reduction == "mean":
        return jnp.sum(loss) / jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
    return _reduce_loss(loss, reduction)


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, axis=-1,
                               return_softmax=False):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    from ..framework.tensor import Tensor as _T

    loss_nd = loss
    if not soft_label:
        lu = unwrap(label)
        if lu.ndim < unwrap(logits).ndim:
            from . import functional as F  # noqa

            loss_nd = _unsqueeze_like(loss, axis=axis)
    if return_softmax:
        return loss_nd, softmax(logits, axis=axis)
    return loss_nd


@primitive("unsqueeze_like")
def _unsqueeze_like(x, axis):
    return jnp.expand_dims(x, axis)


@primitive("nll_loss")
def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    picked = jnp.take_along_axis(input, label[..., None], axis=-1)[..., 0]
    loss = -picked
    valid = label != ignore_index
    loss = jnp.where(valid, loss, 0.0)
    if weight is not None:
        w = jnp.take(weight, jnp.where(valid, label, 0))
        loss = loss * w
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(jnp.where(valid, w, 0.0)), 1e-12)
    if reduction == "mean":
        return jnp.sum(loss) / jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
    return _reduce_loss(loss, reduction)


@primitive("binary_cross_entropy")
def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    eps = 1e-12
    loss = -(label * jnp.log(jnp.maximum(input, eps)) +
             (1 - label) * jnp.log(jnp.maximum(1 - input, eps)))
    if weight is not None:
        loss = loss * weight
    return _reduce_loss(loss, reduction)


@primitive("sigmoid_cross_entropy_with_logits")
def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    neg_abs = -jnp.abs(logit)
    base = jnp.maximum(logit, 0) - logit * label + jnp.log1p(jnp.exp(neg_abs))
    if pos_weight is not None:
        log_weight = 1 + (pos_weight - 1) * label
        base = jnp.maximum(logit, 0) - logit * label + \
            log_weight * jnp.log1p(jnp.exp(neg_abs)) + \
            (log_weight - 1) * jnp.maximum(-logit, 0)
    if weight is not None:
        base = base * weight
    return _reduce_loss(base, reduction)


@primitive("mse_loss")
def mse_loss(input, label, reduction="mean", name=None):
    return _reduce_loss(jnp.square(input - label), reduction)


@primitive("l1_loss")
def l1_loss(input, label, reduction="mean", name=None):
    return _reduce_loss(jnp.abs(input - label), reduction)


@primitive("smooth_l1_loss")
def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    d = jnp.abs(input - label)
    loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
    return _reduce_loss(loss, reduction)


@primitive("huber_loss")
def huber_loss(input, label, delta=1.0, reduction="mean", name=None):
    d = jnp.abs(input - label)
    loss = jnp.where(d <= delta, 0.5 * d * d, delta * (d - 0.5 * delta))
    return _reduce_loss(loss, reduction)


@primitive("kl_div")
def kl_div(input, label, reduction="mean", name=None):
    loss = label * (jnp.log(jnp.maximum(label, 1e-12)) - input)
    if reduction == "batchmean":
        return jnp.sum(loss) / input.shape[0]
    return _reduce_loss(loss, reduction)


@primitive("margin_ranking_loss")
def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    return _reduce_loss(jnp.maximum(0.0, -label * (input - other) + margin),
                        reduction)


@primitive("hinge_embedding_loss")
def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    loss = jnp.where(label == 1.0, input, jnp.maximum(0.0, margin - input))
    return _reduce_loss(loss, reduction)


@primitive("cosine_embedding_loss")
def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean",
                          name=None):
    cos = jnp.sum(input1 * input2, axis=-1) / jnp.maximum(
        jnp.linalg.norm(input1, axis=-1) * jnp.linalg.norm(input2, axis=-1),
        1e-12)
    loss = jnp.where(label == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
    return _reduce_loss(loss, reduction)


@primitive("triplet_margin_loss")
def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    def pdist(a, b):
        return jnp.sum(jnp.abs(a - b + epsilon) ** p, axis=-1) ** (1.0 / p)

    dp = pdist(input, positive)
    dn = pdist(input, negative)
    if swap:
        dn = jnp.minimum(dn, pdist(positive, negative))
    return _reduce_loss(jnp.maximum(dp - dn + margin, 0.0), reduction)


@primitive("square_error_cost")
def square_error_cost(input, label):
    return jnp.square(input - label)


@primitive("log_loss")
def log_loss(input, label, epsilon=1e-4, name=None):
    return -label * jnp.log(input + epsilon) - \
        (1 - label) * jnp.log(1 - input + epsilon)


@primitive("ctc_loss_fn")
def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False, name=None):
    """CTC forward (operators/warpctc_op.cc parity) as a lax.scan DP."""
    # log_probs: (T, B, C) log-softmax scores; labels: (B, L)
    T, B, C = log_probs.shape
    L = labels.shape[1]
    S = 2 * L + 1
    ext = jnp.full((B, S), blank, dtype=labels.dtype)
    ext = ext.at[:, 1::2].set(labels)
    neg_inf = jnp.asarray(-1e30, log_probs.dtype)

    emit = jnp.take_along_axis(
        jnp.transpose(log_probs, (1, 0, 2)),  # (B, T, C)
        jnp.broadcast_to(ext[:, None, :], (B, T, S)), axis=2)  # (B,T,S)
    emit = jnp.transpose(emit, (1, 0, 2))  # (T, B, S)

    can_skip = jnp.concatenate(
        [jnp.zeros((B, 2), bool),
         (ext[:, 2:] != ext[:, :-2]) & (ext[:, 2:] != blank)], axis=1)

    alpha0 = jnp.full((B, S), neg_inf)
    alpha0 = alpha0.at[:, 0].set(emit[0, :, 0])
    alpha0 = alpha0.at[:, 1].set(jnp.where(L > 0, emit[0, :, 1], neg_inf))

    def step(alpha, e):
        shift1 = jnp.concatenate([jnp.full((B, 1), neg_inf), alpha[:, :-1]], 1)
        shift2 = jnp.concatenate([jnp.full((B, 2), neg_inf), alpha[:, :-2]], 1)
        shift2 = jnp.where(can_skip, shift2, neg_inf)
        new = jnp.logaddexp(jnp.logaddexp(alpha, shift1), shift2) + e
        return new, new

    _, alphas = jax.lax.scan(step, alpha0, emit[1:])
    alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # (T, B, S)

    t_idx = jnp.clip(input_lengths - 1, 0, T - 1)
    final = alphas[t_idx, jnp.arange(B)]  # (B, S)
    s_last = 2 * label_lengths  # blank after last label
    ll = jnp.logaddexp(
        jnp.take_along_axis(final, s_last[:, None], axis=1)[:, 0],
        jnp.take_along_axis(final, jnp.maximum(s_last - 1, 0)[:, None], axis=1)[:, 0])
    loss = -ll
    if norm_by_times:
        loss = loss / input_lengths.astype(loss.dtype)
    if reduction == "mean":
        return jnp.mean(loss / jnp.maximum(label_lengths, 1).astype(loss.dtype))
    return _reduce_loss(loss, reduction)


# ---------------------------------------------------------------------------
# attention — see ops/pallas/flash_attention.py for the fused TPU kernel
# (reference fused op: operators/fused/multihead_matmul_op.cu)
# ---------------------------------------------------------------------------


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    """query/key/value: (B, L, H, D) paddle layout."""
    use_dropout = dropout_p > 0.0 and training
    return _sdpa(query, key, value, attn_mask,
                 dropout_p=dropout_p if use_dropout else 0.0,
                 is_causal=is_causal,
                 key_rng=next_rng_key() if use_dropout else None)


@primitive("sdpa")
def _sdpa(q, k, v, mask, dropout_p, is_causal, key_rng):
    from ..ops.pallas.flash_attention import flash_attention_or_fallback

    return flash_attention_or_fallback(q, k, v, mask, dropout_p, is_causal,
                                       key_rng)


# ---------------------------------------------------------------------------
# misc nn (interpolate_op.cc, pixel_shuffle_op.cc, pad ops, ...)
# ---------------------------------------------------------------------------


@primitive("interpolate")
def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    if not channel_last:
        perm = (0,) + tuple(range(2, x.ndim)) + (1,)
        xcl = jnp.transpose(x, perm)
    else:
        xcl = x
    spatial = xcl.shape[1:-1]
    if size is None:
        if isinstance(scale_factor, (int, float)):
            scale_factor = [scale_factor] * len(spatial)
        size = [int(s * f) for s, f in zip(spatial, scale_factor)]
    size = tuple(int(s) for s in size)
    jmode = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
             "trilinear": "linear", "bicubic": "cubic", "area": "linear"}[mode]
    if align_corners and jmode == "linear":
        # jax.image.resize is half-pixel-center only; do per-dim linear
        # interp with endpoint-preserving src = i*(in-1)/(out-1) sampling.
        out = xcl
        for d, o in enumerate(size):
            dim = 1 + d
            n = out.shape[dim]
            if n == o:
                continue
            if o == 1 or n == 1:
                src = jnp.zeros((o,))
            else:
                src = jnp.arange(o) * (n - 1) / (o - 1)
            lo = jnp.clip(jnp.floor(src).astype(jnp.int32), 0, n - 1)
            hi = jnp.clip(lo + 1, 0, n - 1)
            w = (src - lo).astype(out.dtype)
            shape = [1] * out.ndim
            shape[dim] = o
            w = w.reshape(shape)
            out = (jnp.take(out, lo, axis=dim) * (1 - w) +
                   jnp.take(out, hi, axis=dim) * w)
    else:
        out = jax.image.resize(
            xcl, (xcl.shape[0],) + size + (xcl.shape[-1],), method=jmode)
    if not channel_last:
        inv = (0, x.ndim - 1) + tuple(range(1, x.ndim - 1))
        out = jnp.transpose(out, inv)
    return out


upsample = interpolate


@primitive("pixel_shuffle")
def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor
    if data_format == "NCHW":
        n, c, h, w = x.shape
        out = x.reshape(n, c // (r * r), r, r, h, w)
        out = jnp.transpose(out, (0, 1, 4, 2, 5, 3))
        return out.reshape(n, c // (r * r), h * r, w * r)
    n, h, w, c = x.shape
    out = x.reshape(n, h, w, r, r, c // (r * r))
    out = jnp.transpose(out, (0, 1, 3, 2, 4, 5))
    return out.reshape(n, h * r, w * r, c // (r * r))


@primitive("pixel_unshuffle")
def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor
    n, c, h, w = x.shape
    out = x.reshape(n, c, h // r, r, w // r, r)
    out = jnp.transpose(out, (0, 1, 3, 5, 2, 4))
    return out.reshape(n, c * r * r, h // r, w // r)


@primitive("channel_shuffle")
def channel_shuffle(x, groups, data_format="NCHW", name=None):
    n, c, h, w = x.shape
    out = x.reshape(n, groups, c // groups, h, w)
    out = jnp.swapaxes(out, 1, 2)
    return out.reshape(n, c, h, w)


@primitive("affine_grid")
def affine_grid(theta, out_shape, align_corners=True, name=None):
    n, _, h, w = int(out_shape[0]), out_shape[1], int(out_shape[2]), int(out_shape[3])
    if align_corners:
        ys = jnp.linspace(-1, 1, h)
        xs = jnp.linspace(-1, 1, w)
    else:
        ys = (jnp.arange(h) * 2 + 1) / h - 1
        xs = (jnp.arange(w) * 2 + 1) / w - 1
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=-1).reshape(1, h * w, 3)
    grid = jnp.matmul(jnp.tile(base, (theta.shape[0], 1, 1)),
                      jnp.swapaxes(theta, 1, 2))
    return grid.reshape(theta.shape[0], h, w, 2)


@primitive("grid_sample")
def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    n, c, h, w = x.shape
    gx, gy = grid[..., 0], grid[..., 1]
    if align_corners:
        fx = (gx + 1) * (w - 1) / 2
        fy = (gy + 1) * (h - 1) / 2
    else:
        fx = ((gx + 1) * w - 1) / 2
        fy = ((gy + 1) * h - 1) / 2

    x0 = jnp.floor(fx)
    y0 = jnp.floor(fy)
    x1 = x0 + 1
    y1 = y0 + 1

    def gather(yy, xx):
        valid = (yy >= 0) & (yy < h) & (xx >= 0) & (xx < w)
        yc = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
        xc = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
        flat = x.reshape(n, c, h * w)
        idx = (yc * w + xc).reshape(n, 1, -1)
        out = jnp.take_along_axis(flat, jnp.broadcast_to(idx, (n, c, idx.shape[-1])), axis=2)
        out = out.reshape(n, c, *yy.shape[1:])
        if padding_mode == "zeros":
            out = out * valid[:, None].astype(out.dtype)
        return out

    wa = ((x1 - fx) * (y1 - fy))[:, None]
    wb = ((fx - x0) * (y1 - fy))[:, None]
    wc = ((x1 - fx) * (fy - y0))[:, None]
    wd = ((fx - x0) * (fy - y0))[:, None]
    if mode == "nearest":
        return gather(jnp.round(fy), jnp.round(fx))
    return (gather(y0, x0) * wa + gather(y0, x1) * wb +
            gather(y1, x0) * wc + gather(y1, x1) * wd)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    from ..ops.manipulation import pad as _pad_nd

    return _pad_nd(x, pad, mode=mode, value=value, data_format=data_format)


@primitive("temporal_shift")
def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW", name=None):
    nt, c, h, w = x.shape
    n = nt // seg_num
    r = x.reshape(n, seg_num, c, h, w)
    fold = int(c * shift_ratio)
    left = jnp.concatenate([r[:, 1:, :fold], jnp.zeros_like(r[:, :1, :fold])], 1)
    right = jnp.concatenate([jnp.zeros_like(r[:, :1, fold:2 * fold]),
                             r[:, :-1, fold:2 * fold]], 1)
    rest = r[:, :, 2 * fold:]
    return jnp.concatenate([left, right, rest], axis=2).reshape(nt, c, h, w)


@primitive("label_smooth")
def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    k = label.shape[-1]
    if prior_dist is not None:
        return (1 - epsilon) * label + epsilon * prior_dist
    return (1 - epsilon) * label + epsilon / k


@primitive("npair_loss")
def npair_loss(anchor, positive, labels, l2_reg=0.002):
    sim = jnp.matmul(anchor, positive.T)
    lbl = labels.reshape(-1, 1)
    target = (lbl == lbl.T).astype(sim.dtype)
    target = target / jnp.sum(target, axis=1, keepdims=True)
    logp = jax.nn.log_softmax(sim, axis=1)
    ce = -jnp.mean(jnp.sum(target * logp, axis=1))
    reg = l2_reg * (jnp.mean(jnp.sum(jnp.square(anchor), 1)) +
                    jnp.mean(jnp.sum(jnp.square(positive), 1))) * 0.25
    return ce + reg


@primitive("fused_bias_act")
def fused_bias_act(x, bias=None, act="gelu"):
    if bias is not None:
        x = x + bias
    if act == "gelu":
        return jax.nn.gelu(x)
    if act == "relu":
        return jax.nn.relu(x)
    return x


def sequence_mask(lengths, maxlen=None, dtype="int64", name=None):
    lengths_arr = unwrap(lengths)
    if maxlen is None:
        maxlen = int(np.asarray(lengths_arr).max())
    return _sequence_mask(lengths, maxlen=int(maxlen),
                          dtype=dtype_mod.convert_dtype(dtype))


@primitive("sequence_mask")
def _sequence_mask(lengths, maxlen, dtype):
    steps = jnp.arange(maxlen)
    return (steps[None, :] < lengths[..., None]).astype(dtype)


@primitive("diag_embed")
def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):
    n = input.shape[-1] + abs(offset)
    out = jnp.zeros(input.shape[:-1] + (n, n), input.dtype)
    idx = jnp.arange(input.shape[-1])
    r = idx + max(0, -offset)
    c = idx + max(0, offset)
    out = out.at[..., r, c].set(input)
    return out


# -- fluid.layers long-tail losses/activations ------------------------------
@primitive("brelu")
def brelu(x, t_min=0.0, t_max=24.0, name=None):
    """Bounded relu (activation_op.cc BRelu)."""
    return jnp.clip(x, t_min, t_max)


@primitive("soft_relu")
def soft_relu(x, threshold=40.0, name=None):
    """log(1+exp(clip(x))) (activation_op.cc SoftRelu)."""
    return jnp.log1p(jnp.exp(jnp.clip(x, -threshold, threshold)))


@primitive("dice_loss")
def dice_loss(input, label, epsilon=1e-5, name=None):
    """Dice coefficient loss for segmentation (layers/nn.py dice_loss):
    input (N, ..., C) probabilities, label (N, ..., 1) int."""
    label_oh = jax.nn.one_hot(jnp.squeeze(label, -1), input.shape[-1],
                              dtype=input.dtype)
    reduce_dims = tuple(range(1, input.ndim))
    inter = jnp.sum(input * label_oh, axis=reduce_dims)
    union = jnp.sum(input, axis=reduce_dims) + \
        jnp.sum(label_oh, axis=reduce_dims)
    dice = (2 * inter + epsilon) / (union + epsilon)
    return jnp.mean(1 - dice)


@primitive("bpr_loss", nondiff=("label",))
def bpr_loss(input, label, name=None):
    """Bayesian personalized ranking loss (bpr_loss_op.cc): input
    (N, C) raw scores, label (N, 1) the positive class."""
    label = jnp.reshape(label, (-1,))
    pos = jnp.take_along_axis(input, label[:, None], axis=1)
    # -mean over negatives of log sigmoid(pos - neg)
    diff = pos - input
    logsig = jax.nn.log_sigmoid(diff)
    n = input.shape[1]
    mask = jax.nn.one_hot(label, n, dtype=input.dtype)
    return jnp.mean(-jnp.sum(logsig * (1 - mask), axis=1) / (n - 1))


@primitive("rank_loss")
def rank_loss(label, left, right, name=None):
    """RankNet pairwise loss (rank_loss_op.cc)."""
    diff = left - right
    return jnp.mean(-label * diff + jnp.log1p(jnp.exp(diff)))


@primitive("margin_rank_loss")
def margin_rank_loss(label, left, right, margin=0.1, name=None):
    """max(0, -label*(left-right)+margin) (margin_rank_loss_op.cc)."""
    return jnp.maximum(0.0, -label * (left - right) + margin)


@primitive("teacher_student_sigmoid_loss")
def teacher_student_sigmoid_loss(input, label, soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0, name=None):
    """Distillation CTR loss (teacher_student_sigmoid_loss_op.cc):
    label in [0,1] teacher or {0,1} click."""
    x = jnp.clip(input, soft_max_lower_bound, soft_max_up_bound)
    return jnp.mean(x - x * label + jnp.log1p(jnp.exp(-jnp.abs(x))))


@primitive("sigmoid_focal_loss", nondiff=("normalizer",))
def sigmoid_focal_loss_fluid(input, label, fg_num=None, gamma=2.0,
                             alpha=0.25, normalizer=None, name=None):
    """RetinaNet focal loss (sigmoid_focal_loss_op.cc), summed form."""
    p = jax.nn.sigmoid(input)
    ce = -(label * jnp.log(jnp.maximum(p, 1e-12)) +
           (1 - label) * jnp.log(jnp.maximum(1 - p, 1e-12)))
    pt = label * p + (1 - label) * (1 - p)
    w = (label * alpha + (1 - label) * (1 - alpha)) * (1 - pt) ** gamma
    loss = w * ce
    denom = normalizer if normalizer is not None else fg_num
    if denom is not None:
        loss = loss / jnp.maximum(jnp.asarray(denom, loss.dtype), 1.0)
    return loss


@primitive("center_loss", nondiff=("label", "update_center", "alpha"))
def center_loss(input, label, centers, alpha=0.1, update_center=False,
                name=None):
    """Distance to per-class centers (center_loss_op.cc). Functional:
    returns the loss; center updates are the caller's optimizer's job
    (pass centers as a Parameter and let autograd update it)."""
    label = jnp.reshape(label, (-1,))
    c = jnp.take(centers, label, axis=0)
    return 0.5 * jnp.sum(jnp.square(input - c), axis=1, keepdims=True)


@primitive("bilinear_tensor_product")
def bilinear_tensor_product_fn(x, y, weight, bias=None, name=None):
    """out[:, i] = x W_i y^T (bilinear_tensor_product_op.cc);
    weight: (size, dx, dy)."""
    out = jnp.einsum("bi,oij,bj->bo", x, weight, y)
    if bias is not None:
        out = out + bias
    return out


@primitive("affine_channel")
def affine_channel(x, scale, bias, data_layout="NCHW", name=None):
    """Per-channel scale+bias (affine_channel_op.cc; folded-BN form)."""
    if data_layout == "NCHW":
        shape = (1, -1) + (1,) * (x.ndim - 2)
    else:
        shape = (1,) * (x.ndim - 1) + (-1,)
    return x * scale.reshape(shape) + bias.reshape(shape)


@primitive("fsp_matrix")
def fsp_matrix(x, y, name=None):
    """Flow-of-solution-procedure matrix for distillation
    (fsp_op.cc): (N,C1,H,W),(N,C2,H,W) -> (N,C1,C2)."""
    n, c1, h, w = x.shape
    c2 = y.shape[1]
    return jnp.einsum("nchw,ndhw->ncd", x, y) / (h * w)


@primitive("row_conv")
def row_conv(input, weight, name=None):
    """Lookahead row convolution (row_conv_op.cc): input (B, T, D),
    weight (future_context, D)."""
    k = weight.shape[0]
    pads = ((0, 0), (0, k - 1), (0, 0))
    xp = jnp.pad(input, pads)
    out = jnp.zeros_like(input)
    for i in range(k):
        out = out + xp[:, i:i + input.shape[1], :] * weight[i][None, None, :]
    return out


@primitive("nce", nondiff=("label", "num_neg_samples", "seed"))
def nce(input, label, weight, bias=None, num_neg_samples=5,
        sampler="uniform", seed=None, name=None):
    """Noise-contrastive estimation loss (nce_op.cc): input (B, D),
    label (B, 1) positive class, weight (num_classes, D). Uniform
    negative sampling; returns (B, 1) losses."""
    num_classes = weight.shape[0]
    b = input.shape[0]
    from ..framework import random as random_mod
    from ..framework.random import next_rng_key

    # fresh negatives each step unless the caller pins a seed
    key = random_mod.make_key(seed) if seed else next_rng_key()
    neg = jax.random.randint(key, (b, num_neg_samples), 0, num_classes)
    label = jnp.reshape(label, (-1, 1))

    def score(cls):
        w = jnp.take(weight, cls, axis=0)          # (B, K, D)
        s = jnp.einsum("bd,bkd->bk", input, w)
        if bias is not None:
            s = s + jnp.take(bias, cls, axis=0)
        return s

    s_pos = score(label)                           # (B, 1)
    s_neg = score(neg)                             # (B, K)
    # log-odds vs uniform noise: q = K/num_classes
    log_q = jnp.log(jnp.asarray(num_neg_samples / num_classes,
                                input.dtype))
    pos_loss = -jax.nn.log_sigmoid(s_pos - log_q)
    neg_loss = -jnp.sum(jax.nn.log_sigmoid(-(s_neg - log_q)), axis=1,
                        keepdims=True)
    return pos_loss + neg_loss


@primitive("sampled_softmax_with_cross_entropy",
           nondiff=("label", "num_samples", "seed"))
def sampled_softmax_with_cross_entropy(logits_weight, input, label,
                                       num_samples, seed=None, name=None):
    """Sampled-softmax CE (sample_logits_op.cc + layers
    sampled_softmax_with_cross_entropy): full softmax over
    [true class, num_samples uniform negatives] only. logits_weight
    (num_classes, D), input (B, D), label (B, 1)."""
    from ..framework import random as random_mod

    num_classes = logits_weight.shape[0]
    b = input.shape[0]
    from ..framework.random import next_rng_key

    key = random_mod.make_key(seed) if seed else next_rng_key()
    neg = jax.random.randint(key, (b, num_samples), 0, num_classes)
    label = jnp.reshape(label, (-1, 1))
    cls = jnp.concatenate([label, neg], axis=1)    # (B, 1+S)
    w = jnp.take(logits_weight, cls, axis=0)       # (B, 1+S, D)
    logits = jnp.einsum("bd,bkd->bk", input, w)
    # subtract expected sampling correction log q (uniform)
    logq = jnp.log(jnp.asarray(num_samples / num_classes, logits.dtype))
    logits = logits - logq
    # mask accidental hits of the true class among negatives
    hit = cls[:, 1:] == label
    logits = logits.at[:, 1:].set(
        jnp.where(hit, -1e9, logits[:, 1:]))
    return -jax.nn.log_softmax(logits, axis=1)[:, :1]


@primitive("fused_embedding_seq_pool", nondiff=("ids",))
def fused_embedding_seq_pool(table, ids, combiner="sum", padding_idx=None,
                             name=None):
    """Fused lookup_table + sequence_pool — the (B, S, D) gathered
    intermediate never reaches HBM (reference fused/
    fused_embedding_seq_pool_op.cc; Pallas scalar-prefetch kernel on TPU,
    XLA fallback elsewhere). table (V, D); ids (B, S) with padding_idx /
    negative entries ignored; combiner sum|mean|sqrtn. Returns (B, D)."""
    from ..ops.pallas.fused_embedding import fused_embedding_seq_pool as fe

    return fe(table, ids, combiner=combiner, padding_idx=padding_idx)


# ---------------------------------------------------------------------------
# 2.0-alpha functional surface completion (reference
# python/paddle/nn/functional/__init__.py __all__): names whose
# implementations live in the op/layer library are re-exported lazily via
# PEP 562 so the static layer surface is not imported at module load.
# Audited by tests/test_namespace_freeze.py.
# ---------------------------------------------------------------------------

# fluid-surface names keep their fluid semantics/signatures (e.g.
# hard_sigmoid slope=0.2, not Hardsigmoid's 1/6 — the v1.8 functional
# namespace aliases the fluid ops)
_LAYER_ALIASES = (
    "add_position_encoding", "continuous_value_model", "filter_by_instag",
    "multiclass_nms", "polygon_box_transform", "random_crop",
    "rpn_target_assign", "similarity_focus", "target_assign", "warpctc",
    "pad_constant_like", "pad2d", "unfold", "assign", "pool2d", "pool3d",
    "adaptive_pool2d", "adaptive_pool3d", "edit_distance",
    "iou_similarity", "sigmoid_cross_entropy_with_logits",
    "sigmoid_focal_loss", "smooth_l1", "ssd_loss", "hsigmoid",
    "hard_sigmoid", "hard_swish", "tanh",
)

_LOCAL_ALIASES = {
    "conv_transpose1d": "conv1d_transpose",
    "conv_transpose2d": "conv2d_transpose",
    "conv_transpose3d": "conv3d_transpose",
}


def __getattr__(name):
    import sys

    mod = sys.modules[__name__]
    if name in _LOCAL_ALIASES:
        return getattr(mod, _LOCAL_ALIASES[name])
    if name in ("erf", "logsigmoid"):
        from .. import ops as _ops

        return getattr(_ops, {"logsigmoid": "log_sigmoid"}.get(name, name))
    if name in _LAYER_ALIASES:
        from ..static import layers as _L

        return getattr(_L, name)
    raise AttributeError(name)


from ..framework.op import primitive as _primitive  # noqa: E402


@_primitive(name="bilinear")
def bilinear(x1, x2, weight, bias=None, name=None):
    """paddle.nn.functional.bilinear (reference nn/functional/common.py):
    out[b, k] = x1[b, i] W[k, i, j] x2[b, j] (+ bias)."""
    out = jnp.einsum("bi,kij,bj->bk", x1, weight, x2)
    if bias is not None:
        out = out + bias
    return out


@_primitive(name="cosine_similarity")
def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    """paddle.nn.functional.cosine_similarity (reference
    nn/functional/common.py): cos of the angle along ``axis``."""
    num = jnp.sum(x1 * x2, axis=axis)
    den = jnp.sqrt(jnp.sum(x1 * x1, axis=axis)) * \
        jnp.sqrt(jnp.sum(x2 * x2, axis=axis))
    return num / jnp.maximum(den, eps)
