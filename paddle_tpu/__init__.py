"""paddle_tpu — a TPU-native deep-learning framework.

Brand-new design with the capabilities of the reference PaddlePaddle Fluid
(v1.8 era, see SURVEY.md): eager (dygraph-parity) execution with tape
autograd over jax ops, whole-step jit compilation for the fast path, SPMD
parallelism over jax.sharding meshes, and paddle-flavored user APIs
(Tensor / nn.Layer / optimizer / io / fleet).
"""
from __future__ import annotations

__version__ = "0.1.0"

from .framework import (  # noqa: F401
    Tensor, to_tensor, is_tensor, no_grad, enable_grad, seed,
    set_default_dtype, get_default_dtype, set_device, get_device,
    device_count, CPUPlace, TPUPlace, CUDAPlace, CUDAPinnedPlace, XPUPlace,
    is_compiled_with_tpu, is_compiled_with_cuda, get_flags, set_flags,
    rng_scope, LoDTensor, create_lod_tensor, create_random_int_lodtensor,
)
from .framework.dtype import (  # noqa: F401
    bool_, uint8, int8, int16, int32, int64, float16, bfloat16, float32,
    float64, complex64, complex128,
)
from .framework import math_op_patch  # noqa: F401  (installs Tensor dunders)
from .ops import *  # noqa: F401,F403
from . import ops  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import regularizer  # noqa: F401
from . import distribution  # noqa: F401
from . import io  # noqa: F401
from . import metric  # noqa: F401
from . import amp  # noqa: F401
from . import jit  # noqa: F401
from . import autograd  # noqa: F401
from .autograd import grad  # noqa: F401
from . import vision  # noqa: F401
from . import text  # noqa: F401
from . import utils  # noqa: F401
from . import hapi  # noqa: F401
from .hapi import Model  # noqa: F401
from .framework.tape import no_grad as no_grad  # noqa: F401
from . import profiler  # noqa: F401
from . import observability  # noqa: F401  (metrics registry, step trace, flight recorder)
from . import fault  # noqa: F401  (retry/backoff + fault injection)
from . import inference  # noqa: F401
from . import incubate  # noqa: F401
from . import quantization  # noqa: F401
from . import contrib  # noqa: F401  (fluid.contrib parity surface)
from . import dataset  # noqa: F401  (legacy paddle.dataset readers)


def save(obj, path, **kwargs):
    from .io.serialization import save as _save

    return _save(obj, path, **kwargs)


def load(path, **kwargs):
    from .io.serialization import load as _load

    return _load(path, **kwargs)


def summary(net, input_size=None, dtypes=None):
    from .hapi.summary import summary as _summary

    return _summary(net, input_size, dtypes)
from . import reader  # noqa: F401
from .reader import batch  # noqa: F401
from . import install_check  # noqa: F401


def __getattr__(name):
    """Top-level 2.0-alpha aliases (reference python/paddle/__init__.py
    DEFINE_ALIAS rows), resolved lazily so package import stays light.
    Audited by tests/test_namespace_freeze.py ("paddle")."""
    _tensor_names = {
        "t", "reduce_all", "reduce_any", "reduce_max", "reduce_min",
        "reduce_prod", "reduce_sum", "reduce_mean", "sums",
        "elementwise_sum", "elementwise_floordiv", "addcmul",
        "standard_normal", "shuffle", "numel",
    }
    if name in _tensor_names:
        from . import tensor as _T

        return getattr(_T, name)
    if name == "manual_seed":
        return seed
    if name == "to_variable":
        from .dygraph import to_variable as _tv

        return _tv
    if name in ("enable_static", "disable_static", "in_dynamic_mode",
                "in_dygraph_mode", "enable_imperative",
                "disable_imperative"):
        from .framework import mode as _mode

        return getattr(_mode, name)
    if name in ("Variable", "data"):
        from . import static as _S

        return getattr(_S, name)
    if name in ("create_parameter", "create_global_var"):
        from .static import layers as _L

        return getattr(_L, name)
    if name == "ParamAttr":
        from .nn.layer import ParamAttr as _PA

        return _PA
    if name in ("BackwardStrategy", "prepare_context", "ParallelEnv",
                "DataParallel", "NoamDecay", "PiecewiseDecay",
                "NaturalExpDecay", "ExponentialDecay",
                "InverseTimeDecay", "PolynomialDecay", "CosineDecay"):
        from . import dygraph as _dg

        return getattr(_dg, name)
    if name == "get_cudnn_version":
        # no cuDNN on this stack — the reference returns None when not
        # compiled with it
        return lambda: None
    raise AttributeError(name)
