"""Communicator: background gradient send/param sync threads.

Parity with the reference Communicator family
(/root/reference/paddle/fluid/operators/distributed/communicator.h:180 —
:253 AsyncCommunicator (queue + send thread), :326 HalfAsync (batched
merge), :365 Sync, :396 GeoCommunicator (send param deltas every k
steps)). The TPU build keeps the same modes but over the TCP PSClient;
"merge before send" is a numpy groupby-add instead of SelectedRows
merge."""
from __future__ import annotations

import queue
import threading
from typing import Optional

import numpy as np

from .service import PSClient


def _merge_dups(ids, grads):
    """Sum gradients of duplicate ids (communicator MergeVars parity)."""
    uniq, inv = np.unique(ids, return_inverse=True)
    merged = np.zeros((uniq.size, grads.shape[1]), grads.dtype)
    np.add.at(merged, inv, grads)
    return uniq, merged


class AsyncCommunicator:
    """Queue + background send thread (communicator.h:253). Trainer calls
    push_sparse_grad and keeps going; the send thread batches
    send_queue_size entries, merges duplicates, and pushes."""

    def __init__(self, client: PSClient, dim: int, table_id: int = 0,
                 lr: float = 0.01, send_queue_size: int = 16):
        self._client = client
        self._dim = dim
        self._table = table_id
        self._lr = lr
        self._q: queue.Queue = queue.Queue(maxsize=max(send_queue_size, 1))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def push_sparse_grad(self, ids, grads, lr: Optional[float] = None):
        self._q.put((np.asarray(ids, np.int64).ravel(),
                     np.asarray(grads, np.float32),
                     self._lr if lr is None else lr))

    def _loop(self):
        while not self._stop.is_set() or not self._q.empty():
            try:
                ids, grads, lr = self._q.get(timeout=0.1)
            except queue.Empty:
                continue
            ids, grads = _merge_dups(ids, grads.reshape(ids.size, self._dim))
            self._client.push(self._table, ids, grads, self._dim, lr)
            self._q.task_done()

    def flush(self):
        self._q.join()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)


class GeoCommunicator:
    """GEO-SGD (communicator.h:396 + geo_sgd_transpiler.py): the trainer
    keeps a local SparseTable replica, trains on it for k steps, then
    sends the param DELTAS (local - base) and pulls the merged params."""

    def __init__(self, client: PSClient, local_table, table_id: int = 0,
                 k_steps: int = 4):
        self._client = client
        self._local = local_table
        self._table = table_id
        self._k = max(1, k_steps)
        self._step = 0
        self._base = {}    # id -> row value at last sync

    def snapshot(self, ids):
        """Record base values for ids about to be trained."""
        ids = np.asarray(ids, np.int64).ravel()
        vals = self._local.pull(ids)
        for i, v in zip(ids, vals):
            self._base.setdefault(int(i), v.copy())

    def step(self):
        self._step += 1
        if self._step % self._k == 0:
            self.sync()

    def sync(self):
        if not self._base:
            return
        ids = np.fromiter(self._base.keys(), np.int64, len(self._base))
        base = np.stack([self._base[int(i)] for i in ids])
        cur = self._local.pull(ids)
        delta = cur - base
        self._client.merge_add(self._table, ids, delta, self._local.dim)
        merged = self._client.pull(self._table, ids, self._local.dim)
        self._local.assign(ids, merged)
        self._base.clear()
