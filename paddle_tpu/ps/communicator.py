"""Communicator: background gradient send/param sync threads.

Parity with the reference Communicator family
(/root/reference/paddle/fluid/operators/distributed/communicator.h:180 —
:253 AsyncCommunicator (queue + send thread), :326 HalfAsync (batched
merge), :365 Sync, :396 GeoCommunicator (send param deltas every k
steps)). The TPU build keeps the same modes but over the TCP PSClient;
"merge before send" is a numpy groupby-add instead of SelectedRows
merge."""
from __future__ import annotations

import queue
import threading
from typing import Optional

import numpy as np

from .service import PSClient


def _merge_dups(ids, grads):
    """Sum gradients of duplicate ids (communicator MergeVars parity)."""
    uniq, inv = np.unique(ids, return_inverse=True)
    merged = np.zeros((uniq.size, grads.shape[1]), grads.dtype)
    np.add.at(merged, inv, grads)
    return uniq, merged


class AsyncCommunicator:
    """Queue + background send thread (communicator.h:253). Trainer calls
    push_sparse_grad and keeps going; the send thread batches
    send_queue_size entries, merges duplicates, and pushes.

    Bounded drain: ``flush`` used to be an unbounded ``Queue.join()`` —
    a pserver death killed the send thread and wedged the trainer in
    flush forever. Now the send thread parks its error instead of dying
    silently, and ``flush(timeout)`` polls a pending counter on an
    injectable clock, raising typed ``distributed.elastic.WorkerLost``
    when the sender is dead (or its parked error re-raised as the
    cause) and ``TimeoutError`` when it is merely too slow. When the
    parked error is a :class:`~paddle_tpu.ps.replication.PSError` —
    the PSERVER died, typed by the client's bounded retries, not the
    send thread — flush re-raises it (``PSUnavailable`` etc.) instead
    of mislabeling a server death as a lost worker."""

    def __init__(self, client: PSClient, dim: int, table_id: int = 0,
                 lr: float = 0.01, send_queue_size: int = 16,
                 flush_timeout: float = 60.0,
                 clock=None, sleep=None):
        import time

        # public identity: SparseEmbedding validates its pulls route to
        # the same table/server this communicator pushes to
        self.client = client
        self.dim = int(dim)
        self.table_id = int(table_id)
        self._lr = lr
        self._q: queue.Queue = queue.Queue(maxsize=max(send_queue_size, 1))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._flush_timeout = float(flush_timeout)
        self._clock = clock or time.monotonic
        self._sleep = sleep or time.sleep
        self._pending = 0
        self._pending_lock = threading.Lock()
        self._error: Optional[BaseException] = None

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def push_sparse_grad(self, ids, grads, lr: Optional[float] = None):
        item = (np.asarray(ids, np.int64).ravel(),
                np.asarray(grads, np.float32),
                self._lr if lr is None else lr)
        with self._pending_lock:
            self._pending += 1
        # the bounded queue must not become an unbounded wait: with the
        # send thread dead nothing ever drains it, so a blocking put()
        # would wedge the trainer in the push hot path before it even
        # reaches flush()'s typed error
        while True:
            if self._sender_failed():
                with self._pending_lock:
                    self._pending -= 1
                self._raise_worker_lost("push_sparse_grad")
            try:
                self._q.put(item, timeout=0.05)
                return
            except queue.Full:
                continue

    def _loop(self):
        while not self._stop.is_set() or not self._q.empty():
            try:
                ids, grads, lr = self._q.get(timeout=0.1)
            except queue.Empty:
                continue
            try:
                ids, grads = _merge_dups(
                    ids, grads.reshape(ids.size, self.dim))
                self.client.push(self.table_id, ids, grads, self.dim, lr)
            except BaseException as e:   # noqa: B036 (parked for flush)
                # the failed batch stays PENDING: flush must report the
                # loss (WorkerLost), not count the batch as delivered
                self._error = e
                self._q.task_done()
                return
            with self._pending_lock:
                self._pending -= 1
            self._q.task_done()

    def _sender_dead(self) -> bool:
        return (self._error is not None
                or self._thread is None
                or not self._thread.is_alive())

    def _sender_failed(self) -> bool:
        """Dead-after-start only: queueing before start() stays legal
        (the reference lets trainers push before the communicator runs),
        so a None thread is not a failure here — unlike flush(), where
        waiting on a never-started sender would hang forever."""
        return (self._error is not None
                or (self._thread is not None
                    and not self._thread.is_alive()))

    def _raise_worker_lost(self, op: str):
        from ..distributed.elastic import WorkerLost
        from ..fault.injector import _bump
        from .replication import PSError

        with self._pending_lock:
            pending = self._pending
        if isinstance(self._error, PSError):
            # the PSERVER died (typed PSUnavailable/ShardMapStale after
            # the client's bounded retries), not the send thread itself:
            # surface the server-side verdict — WorkerLost would point
            # operators at the wrong process
            raise self._error
        _bump("worker_lost")
        raise WorkerLost(
            f"communicator send thread is dead ({op}) with {pending} "
            "unsent gradient batches"
            + (f" (cause: {self._error!r})" if self._error
               else "")) from self._error

    def flush(self, timeout: Optional[float] = None):
        """Block until every pushed gradient reached the pserver, the
        sender died (WorkerLost), or ``timeout`` seconds passed
        (TimeoutError). Never hangs on a dead peer."""
        deadline = self._clock() + (self._flush_timeout
                                    if timeout is None else float(timeout))
        while True:
            with self._pending_lock:
                pending = self._pending
            if pending <= 0:
                return
            if self._sender_dead():
                self._raise_worker_lost("flush")
            if self._clock() >= deadline:
                raise TimeoutError(
                    f"communicator flush timed out with {pending} "
                    "gradient batches still unsent — pserver too slow "
                    "or unreachable")
            self._sleep(0.01)

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)


class GeoCommunicator:
    """GEO-SGD (communicator.h:396 + geo_sgd_transpiler.py): the trainer
    keeps a local SparseTable replica, trains on it for k steps, then
    sends the param DELTAS (local - base) and pulls the merged params."""

    def __init__(self, client: PSClient, local_table, table_id: int = 0,
                 k_steps: int = 4):
        self._client = client
        self._local = local_table
        self._table = table_id
        self._k = max(1, k_steps)
        self._step = 0
        self._base = {}    # id -> row value at last sync

    def snapshot(self, ids):
        """Record base values for ids about to be trained."""
        ids = np.asarray(ids, np.int64).ravel()
        vals = self._local.pull(ids)
        for i, v in zip(ids, vals):
            self._base.setdefault(int(i), v.copy())

    def step(self):
        self._step += 1
        if self._step % self._k == 0:
            self.sync()

    def sync(self):
        if not self._base:
            return
        ids = np.fromiter(self._base.keys(), np.int64, len(self._base))
        base = np.stack([self._base[int(i)] for i in ids])
        cur = self._local.pull(ids)
        delta = cur - base
        self._client.merge_add(self._table, ids, delta, self._local.dim)
        merged = self._client.pull(self._table, ids, self._local.dim)
        self._local.assign(ids, merged)
        self._base.clear()
