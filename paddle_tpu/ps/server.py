"""Server entrypoint: env-driven pserver bootstrap (fleet.run_server).

Parity with the reference pserver startup
(/root/reference/python/paddle/fluid/incubate/fleet/parameter_server and
listen_and_serv_op.cc): endpoints/roles come from the PADDLE_* env the
launcher sets (launch_utils.py), tables are declared via
PADDLE_PS_TABLES ("id:dim:optimizer,..." — the TrainerDesc/table-config
analogue).

Fault-tolerant mode (ps/replication.py) switches on when
``PADDLE_PS_KV_ENDPOINT`` names the coordination KV server:

    PADDLE_PS_KV_ENDPOINT   host:port of the http_kv KVServer
    PADDLE_PS_JOB           shard-map namespace (default "ps")
    PADDLE_PS_SYNC          1 = synchronous primary→backup replication
                            (bitwise-deterministic acks; default),
                            0 = async with a bounded lag watermark
    PADDLE_PS_REPLICAS      backups per shard R — consumed by whoever
                            publishes the shard map (publish_from_env /
                            the chaos drill), not by the server itself
    PADDLE_PS_SNAPSHOT_DIR  SnapshotStore root for crash-safe
                            shard_<k>/seq_<n>/ table snapshots
    PADDLE_PS_SNAPSHOT_EVERY  commit a snapshot every N applied writes
    PADDLE_PS_LEASE_TTL     liveness-lease seconds (default 10)
    PADDLE_PS_ADVERTISE     endpoint to register as (defaults to the
                            bound host:port — set it when the bind host
                            differs from the reachable one)

A replicated server restores its newest valid snapshot and rejoins its
group (delta-log catch-up from the most advanced live peer) before
serving — the supervised-relaunch recovery path. SIGTERM drains
gracefully (stop serving, exit 0) so launch.Supervisor's bounded drain
window works on pservers exactly like on trainers.
"""
from __future__ import annotations

import os
import signal
import sys
from typing import Dict, List, Sequence

from .service import PSServer
from .table import SparseTable


def _tables_from_env() -> Dict[int, SparseTable]:
    spec = os.environ.get("PADDLE_PS_TABLES", "0:8:sgd")
    tables = {}
    for part in spec.split(","):
        tid, dim, opt = (part.split(":") + ["sgd"])[:3]
        tables[int(tid)] = SparseTable(int(dim), optimizer=opt)
    return tables


def groups_from_env(endpoints: Sequence[str]) -> List[List[str]]:
    """Slice a flat endpoint list into replica groups of 1 primary +
    ``PADDLE_PS_REPLICAS`` backups each: with R=1, [a, b, c, d] becomes
    [[a, b], [c, d]] — 2 shards, 2-replica groups."""
    r = int(os.environ.get("PADDLE_PS_REPLICAS", "0"))
    size = r + 1
    eps = list(endpoints)
    if len(eps) % size:
        raise ValueError(
            f"{len(eps)} endpoints do not divide into groups of "
            f"{size} (PADDLE_PS_REPLICAS={r})")
    return [eps[i:i + size] for i in range(0, len(eps), size)]


def publish_from_env(kv, endpoints: Sequence[str], job=None):
    """Publish the initial shard map from the launcher env (the
    coordinator-less bring-up path: one process — usually rank 0 or the
    launch driver — calls this once)."""
    from .replication import ShardMap, publish_shard_map

    m = ShardMap(groups_from_env(endpoints),
                 sync=os.environ.get("PADDLE_PS_SYNC", "1") != "0",
                 job=job or os.environ.get("PADDLE_PS_JOB", "ps"))
    publish_shard_map(kv, m)
    return m


def run_server(block: bool = True):
    """Start serving on PADDLE_PORT (reference listen_and_serv main
    loop); replicated + crash-safe when PADDLE_PS_KV_ENDPOINT is set."""
    port = int(os.environ.get("PADDLE_PORT", "0"))
    num_trainers = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    kv_ep = os.environ.get("PADDLE_PS_KV_ENDPOINT")
    # pserver scrape surface: the PS wire protocol is raw sockets, so
    # /metrics rides a sidecar HTTP listener on PADDLE_METRICS_PORT
    from ..observability.server import maybe_start_metrics_server

    metrics_server = maybe_start_metrics_server()
    if metrics_server is not None:
        print(f"paddle_tpu pserver /metrics on 127.0.0.1:"
              f"{metrics_server.port}")
    if kv_ep:
        from .replication import ReplicatedPSServer

        server = ReplicatedPSServer(
            _tables_from_env(), kv_ep,
            job=os.environ.get("PADDLE_PS_JOB", "ps"),
            port=port,
            advertise=os.environ.get("PADDLE_PS_ADVERTISE") or None,
            snapshot_dir=os.environ.get("PADDLE_PS_SNAPSHOT_DIR") or None,
            snapshot_every=int(
                os.environ.get("PADDLE_PS_SNAPSHOT_EVERY", "0")),
            lease_ttl=float(os.environ.get("PADDLE_PS_LEASE_TTL", "10")),
            sync=(None if "PADDLE_PS_SYNC" not in os.environ
                  else os.environ["PADDLE_PS_SYNC"] != "0"),
            num_trainers=num_trainers)
        # supervised-relaunch recovery BEFORE serving or leasing: a
        # fast-relaunched primary must not answer pulls from its empty
        # tables, and must not renew the lease that would suppress the
        # promotion clients are waiting on. The listener is bound (the
        # backlog queues early connections) but nothing is accepted and
        # no lease is published until restore + catch-up finish.
        source = server.rejoin(timeout=float(
            os.environ.get("PADDLE_PS_REJOIN_TIMEOUT", "30")))
        server.start()
        print(f"paddle_tpu pserver listening on {server.endpoint} "
              f"(job={server.job}, role={server.role}, "
              f"epoch={server.epoch}, seq={server.seq}, "
              f"caught_up_from={source})")
    else:
        server = PSServer(_tables_from_env(), port=port,
                          num_trainers=num_trainers).start()
        print(f"paddle_tpu pserver listening on {server.endpoint}")
    server.metrics_server = metrics_server
    if block:
        def _drain(signum, frame):
            server.stop()
            try:
                from ..observability.flight_recorder import \
                    flight_recorder

                fr = flight_recorder()
                fr.record("sigterm_drain", role="pserver")
                fr.dump(reason="sigterm_drain")
            except Exception:
                pass
            sys.exit(0)

        try:
            signal.signal(signal.SIGTERM, _drain)
        except (ValueError, OSError):
            pass   # non-main thread: caller owns signal policy
        server.join()
    return server
