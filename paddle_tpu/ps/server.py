"""Server entrypoint: env-driven pserver bootstrap (fleet.run_server).

Parity with the reference pserver startup
(/root/reference/python/paddle/fluid/incubate/fleet/parameter_server and
listen_and_serv_op.cc): endpoints/roles come from the PADDLE_* env the
launcher sets (launch_utils.py), tables are declared via
PADDLE_PS_TABLES ("id:dim:optimizer,..." — the TrainerDesc/table-config
analogue)."""
from __future__ import annotations

import os
from typing import Dict

from .service import PSServer
from .table import SparseTable


def _tables_from_env() -> Dict[int, SparseTable]:
    spec = os.environ.get("PADDLE_PS_TABLES", "0:8:sgd")
    tables = {}
    for part in spec.split(","):
        tid, dim, opt = (part.split(":") + ["sgd"])[:3]
        tables[int(tid)] = SparseTable(int(dim), optimizer=opt)
    return tables


def run_server(block: bool = True):
    """Start serving on PADDLE_PORT (reference listen_and_serv main loop)."""
    port = int(os.environ.get("PADDLE_PORT", "0"))
    num_trainers = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    server = PSServer(_tables_from_env(), port=port,
                      num_trainers=num_trainers).start()
    print(f"paddle_tpu pserver listening on {server.endpoint}")
    if block:
        server.join()
    return server
