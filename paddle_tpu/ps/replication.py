"""Fault-tolerant parameter server: replica groups, shard-map epochs,
crash-safe shard recovery.

The reference PS data plane (listen_and_serv + parameter_send/recv, the
Downpour pull/push cycle) loses a hash-shard of every SparseTable the
moment one pserver dies, and a relaunched server comes back empty. This
module gives the ``paddle_tpu.ps`` port the same discipline PRs 2/6/7
gave checkpointing, serving, and elastic DP training:

**Replica groups.** Shard ``k`` is served by a *group* — a primary plus
``R`` backups. Writes land on the primary and forward primary→backup:
synchronously in ``sync`` mode (the ack means every replica applied it —
bitwise-deterministic for CI), or through a bounded queue with a lag
watermark in ``async`` mode (gauge ``ps_replication_lag``).

**Epoch-versioned shard map.** Group membership lives in the
coordination KV store (``distributed.http_kv``) under
``ps/<job>/map/<epoch>`` with a ``ps/<job>/epoch`` pointer — immutable
per epoch, so readers never see a torn map. Every client request carries
its map epoch; a demoted or stale server replies a typed error frame and
the client refreshes instead of hanging.

**Promotion.** Each server renews a heartbeat lease
(``ps/<job>/lease/<endpoint>``). The :class:`ReplicaCoordinator`
observes lease expiry, promotes the first live backup (epoch bump,
counter ``ps_promotions``); clients discover the promotion via the map,
fail over (counter ``ps_failovers``), and REPLAY the in-flight request —
write frames carry (client, seq) so an update the dead primary already
replicated is deduplicated, never double-applied: in sync mode the final
table state is bitwise identical to a never-killed run.

**Crash-safe shard recovery.** Servers commit their tables through the
PR 2 :class:`~paddle_tpu.io.snapshot.SnapshotStore` (manifest-verified
``shard_<k>/seq_<n>/`` dirs, atomic commit, keep-N; counter
``ps_snapshot_commits``) and keep a sequence-numbered :class:`DeltaLog`
of applied writes. A killed pserver relaunches, restores the newest
valid snapshot (corrupt ones are skipped — the PR 2 fallback), and
catches up by replaying the delta log of a group peer (full state
transfer when the log rotated past its snapshot).

Typed failures: :class:`PSUnavailable` (endpoint, shard),
:class:`ShardMapStale` (expected_epoch, observed),
:class:`ReplicaDiverged` (digest mismatch inside a group),
:class:`PSRequestError` (server-side rejection, e.g. unknown table).
Every blocking path is bounded and runs on injectable clocks.
"""
from __future__ import annotations

import json
import os
import queue
import socket
import struct
import tempfile
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..fault.injector import _bump
from ..fault import injector as _fault
from ..observability import tracing
from .service import (
    ERR_IO, ERR_LOG_TRUNCATED, ERR_NOT_PRIMARY, ERR_STALE_EPOCH,
    ERR_UNSUPPORTED, OP_DELTA_SINCE, OP_DIGEST, OP_LOAD, OP_REPL_APPLY,
    OP_SEQ, OP_SNAPSHOT, OP_STATE, PSReplyError, PSServer, WriteRejected,
    _HDR, _read_reply, _recv_exact, _send_err, _send_ok, table_digest,
)
from .table import SparseTable

__all__ = [
    "PSError", "PSUnavailable", "ShardMapStale", "ReplicaDiverged",
    "PSRequestError", "ShardMap", "publish_shard_map", "fetch_shard_map",
    "wait_shard_map", "DeltaLog", "Replicator", "ReplicatedPSServer",
    "ReplicaCoordinator", "verify_replicas",
]


# ---------------------------------------------------------------------------
# typed failures — every PS blocking path exits through one of these
# ---------------------------------------------------------------------------
class PSError(RuntimeError):
    """Base of the parameter-server failure taxonomy. A verdict for the
    operation that raised it — the client's Retrier never blind-retries
    these; callers decide whether to fail over, refresh, or surface."""


class PSUnavailable(PSError):
    """A pserver stayed unreachable past the retry budget (and, in
    replicated mode, past the bounded failover window). ``endpoint``
    names the dead server, ``shard`` the hash-shard it owned."""

    def __init__(self, message: str, endpoint: str = "", shard: int = -1):
        super().__init__(message)
        self.endpoint = endpoint
        self.shard = int(shard)


class ShardMapStale(PSError):
    """The shard map this client (or server) holds is behind the epoch
    the cluster moved to, and the bounded refresh couldn't catch up."""

    def __init__(self, message: str, expected_epoch: int = -1,
                 observed: int = -1):
        super().__init__(message)
        self.expected_epoch = int(expected_epoch)
        self.observed = int(observed)


class ReplicaDiverged(PSError):
    """Replicas of one shard disagree on table content (digest
    mismatch): replication lost a write or applied out of order.
    ``digests`` maps endpoint -> hex digest for the offending shard."""

    def __init__(self, message: str, shard: int = -1,
                 digests: Optional[Dict[str, str]] = None):
        super().__init__(message)
        self.shard = int(shard)
        self.digests = dict(digests or {})


class PSRequestError(PSError):
    """The server rejected the request itself (unknown table id, dim
    mismatch, save/load IO failure) — retrying the same frame cannot
    succeed. ``code`` is the wire error code."""

    def __init__(self, message: str, code: int = 0, endpoint: str = ""):
        super().__init__(message)
        self.code = int(code)
        self.endpoint = endpoint


# ---------------------------------------------------------------------------
# the epoch-versioned shard map
# ---------------------------------------------------------------------------
class ShardMap:
    """Immutable-per-epoch assignment of shards to replica groups.

    ``groups[k]`` lists shard ``k``'s endpoints, primary FIRST. Epochs
    start at 1 (0 on the wire means "not epoch-aware" — the legacy
    static client) and only ever grow; every promotion bumps the epoch.
    """

    def __init__(self, groups: Sequence[Sequence[str]], epoch: int = 1,
                 sync: bool = True, job: str = "ps"):
        if not groups or any(not g for g in groups):
            raise ValueError("shard map needs >=1 endpoint per group")
        if int(epoch) < 1:
            raise ValueError("shard-map epochs start at 1")
        self.groups: List[List[str]] = [list(g) for g in groups]
        self.epoch = int(epoch)
        self.sync = bool(sync)
        self.job = str(job)

    @property
    def num_shards(self) -> int:
        return len(self.groups)

    def primary(self, shard: int) -> str:
        return self.groups[shard][0]

    def backups(self, shard: int) -> List[str]:
        return list(self.groups[shard][1:])

    def endpoints(self) -> List[str]:
        return [ep for g in self.groups for ep in g]

    def role_of(self, endpoint: str) -> Tuple[Optional[str], int]:
        """("primary"|"backup", shard) for an endpoint, (None, -1) when
        it is not in the map."""
        for k, group in enumerate(self.groups):
            if endpoint in group:
                return ("primary" if group[0] == endpoint else "backup", k)
        return (None, -1)

    def to_json(self) -> str:
        return json.dumps({"epoch": self.epoch, "sync": self.sync,
                           "job": self.job, "groups": self.groups},
                          sort_keys=True)

    @classmethod
    def from_json(cls, raw) -> "ShardMap":
        if isinstance(raw, (bytes, bytearray)):
            raw = raw.decode("utf-8")
        d = json.loads(raw)
        return cls(d["groups"], epoch=d["epoch"], sync=d.get("sync", True),
                   job=d.get("job", "ps"))


def _map_key(job: str, epoch: int) -> str:
    return f"ps/{job}/map/{int(epoch)}"


def _epoch_key(job: str) -> str:
    return f"ps/{job}/epoch"


def _lease_key(job: str, endpoint: str) -> str:
    return f"ps/{job}/lease/{endpoint}"


def publish_shard_map(kv, m: ShardMap) -> None:
    """Commit a map: the versioned body first (immutable per epoch),
    then the epoch pointer — readers following the pointer can never
    see a torn map."""
    kv.put(_map_key(m.job, m.epoch), m.to_json())
    kv.put(_epoch_key(m.job), str(m.epoch))


def fetch_shard_map(kv, job: str) -> Optional[ShardMap]:
    """Current map, or None while none is published."""
    raw_epoch = kv.get(_epoch_key(job))
    if raw_epoch is None:
        return None
    raw = kv.get(_map_key(job, int(raw_epoch)))
    return ShardMap.from_json(raw) if raw is not None else None


def wait_shard_map(kv, job: str, min_epoch: int = 1, timeout: float = 30.0,
                   clock: Callable[[], float] = time.monotonic,
                   sleep: Callable[[float], None] = time.sleep,
                   poll: float = 0.05) -> ShardMap:
    """Block (bounded, backoff-paced via ``KVClient.wait_until``) until
    a map with epoch >= ``min_epoch`` is published; ShardMapStale past
    the deadline."""
    def _reached(raw) -> bool:
        try:
            return int(raw) >= int(min_epoch)
        except (TypeError, ValueError):
            return False

    try:
        kv.wait_until(_epoch_key(job), _reached, timeout=float(timeout),
                      poll=poll, clock=clock, sleep=sleep)
    except TimeoutError:
        m = fetch_shard_map(kv, job)
        observed = m.epoch if m is not None else -1
        raise ShardMapStale(
            f"shard map for job {job!r} never reached epoch "
            f"{min_epoch} within {timeout}s (observed "
            f"{'none' if observed < 0 else observed})",
            expected_epoch=min_epoch, observed=observed) from None
    m = fetch_shard_map(kv, job)
    if m is None or m.epoch < int(min_epoch):
        # the pointer advanced but the (immutable) map body is missing:
        # a torn publish — treat as not-yet-available
        raise ShardMapStale(
            f"shard map body for job {job!r} missing at the published "
            f"epoch", expected_epoch=min_epoch,
            observed=m.epoch if m is not None else -1)
    return m


def publish_lease(kv, job: str, endpoint: str, ttl: float,
                  clock: Callable[[], float] = time.time,
                  token: Optional[str] = None) -> float:
    """Renew a server's liveness lease: stores the wall-clock expiry (the
    coordinator compares against ITS wall clock — same convention as the
    elastic agent's worker leases). ``token`` is the server's PROCESS
    INCARNATION (random per construction): a crashed primary whose
    supervised relaunch republishes a fresh lease BEFORE the TTL sweep
    notices the expiry gap would otherwise look continuously alive —
    the coordinator sees the token change and promotes anyway, closing
    the relaunch-vs-promotion race on the injectable clock instead of
    widening wall sleeps."""
    expiry = clock() + float(ttl)
    val = repr(expiry) if token is None else f"{expiry!r}:{token}"
    kv.put(_lease_key(job, endpoint), val)
    return expiry


def read_lease(kv, job: str, endpoint: str) -> Optional[float]:
    return read_lease_token(kv, job, endpoint)[0]


def read_lease_token(kv, job: str, endpoint: str):
    """(expiry, incarnation_token) — token None for tokenless leases
    (pre-incarnation writers keep working)."""
    raw = kv.get(_lease_key(job, endpoint))
    if raw is None:
        return None, None
    s = raw.decode() if isinstance(raw, bytes) else str(raw)
    expiry_s, _, token = s.partition(":")
    try:
        return float(expiry_s), (token or None)
    except ValueError:
        return None, None


# ---------------------------------------------------------------------------
# the delta log (catch-up replay source)
# ---------------------------------------------------------------------------
# op codec table seq client cseq lr n vlen — codec is the VALUE payload
# encoding (ps/codec.py ids): a quantized client push forwards its RAW
# ENCODED bytes, so every backup decodes the identical payload the
# primary applied (bitwise replica parity under quantization)
_DELTA_HDR = struct.Struct("<BBIQIQfQQ")


class DeltaEntry:
    __slots__ = ("seq", "op", "table_id", "client", "client_seq", "lr",
                 "ids", "vals", "codec")

    def __init__(self, seq, op, table_id, client, client_seq, lr, ids,
                 vals, codec: int = 0):
        self.seq = int(seq)
        self.op = int(op)
        self.table_id = int(table_id)
        self.client = int(client)
        self.client_seq = int(client_seq)
        self.lr = float(lr)
        self.ids = bytes(ids)
        self.vals = bytes(vals)
        self.codec = int(codec)

    def values(self, dim: Optional[int] = None) -> np.ndarray:
        """The f32 values this entry applies (decoding ``vals`` per the
        entry codec) — every replica applies THIS, never the raw bytes.
        Pass the table ``dim`` when known (apply sites do); without it
        the element count is inverted from the byte length (exact — the
        elems→bytes map is strictly increasing)."""
        from .codec import codec_name, np_decode

        if not self.codec:
            return np.frombuffer(self.vals, np.float32)
        elems = ((len(self.ids) // 8) * int(dim) if dim
                 else self._elems())
        return np_decode(self.vals, elems, codec_name(self.codec))

    def _elems(self) -> int:
        from .codec import QUANT_BLOCK

        if self.codec == 1:       # bf16: 2 bytes/elem
            return len(self.vals) // 2
        # int8: vlen = elems + 4 * nblocks, nblocks = ceil(elems/BLOCK)
        # → invert exactly: try the candidate implied by vlen
        vlen = len(self.vals)
        est = vlen * QUANT_BLOCK // (QUANT_BLOCK + 4)
        for cand in range(max(0, est - QUANT_BLOCK), est + QUANT_BLOCK + 1):
            if cand + 4 * (-(-cand // QUANT_BLOCK)) == vlen:
                return cand
        raise ValueError(f"undecodable int8 delta payload ({vlen} bytes)")

    def encode(self) -> bytes:
        n = len(self.ids) // 8
        return (_DELTA_HDR.pack(self.op, self.codec, self.table_id,
                                self.seq, self.client, self.client_seq,
                                self.lr, n, len(self.vals))
                + self.ids + self.vals)


def decode_deltas(raw: bytes) -> List[DeltaEntry]:
    out, off = [], 0
    while off < len(raw):
        op, codec, table_id, seq, client, cseq, lr, n, vlen = \
            _DELTA_HDR.unpack_from(raw, off)
        off += _DELTA_HDR.size
        ids = raw[off:off + 8 * n]
        off += 8 * n
        vals = raw[off:off + vlen]
        off += vlen
        out.append(DeltaEntry(seq, op, table_id, client, cseq, lr, ids,
                              vals, codec))
    return out


class DeltaLog:
    """Bounded in-memory log of applied writes, sequence-ordered. A
    rejoining replica replays ``since(seq)``; ``None`` means the log
    rotated past that point (ERR_LOG_TRUNCATED on the wire → the
    rejoiner falls back to a full state transfer)."""

    def __init__(self, capacity: int = 4096):
        self.capacity = max(1, int(capacity))
        self._entries: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()

    def append(self, entry: DeltaEntry) -> None:
        with self._lock:
            self._entries.append(entry)

    def since(self, seq: int) -> Optional[List[DeltaEntry]]:
        with self._lock:
            if self._entries and self._entries[0].seq > seq + 1:
                return None          # rotated past the requested point
            return [e for e in self._entries if e.seq > seq]

    def last_seq(self) -> int:
        with self._lock:
            return self._entries[-1].seq if self._entries else 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


# ---------------------------------------------------------------------------
# raw peer channel (replication/admin traffic; not the sharded client)
# ---------------------------------------------------------------------------
class _RawPeer:
    """One socket to one endpoint speaking the service.py wire protocol
    directly — what the primary's Replicator and a rejoiner's catch-up
    use. Reconnects on any error (the desynced-stream rule)."""

    def __init__(self, endpoint: str, timeout: float = 10.0,
                 connect_timeout: Optional[float] = None):
        self.endpoint = endpoint
        self.timeout = float(timeout)
        # connects are bounded tighter than data: a down-peer reprobe
        # runs on the primary's write path (under its replication
        # lock), and a black-holed host must not stall every shard
        # write for the full data timeout
        self.connect_timeout = (min(self.timeout, 2.0)
                                if connect_timeout is None
                                else float(connect_timeout))
        self._sock: Optional[socket.socket] = None

    def _connect(self) -> socket.socket:
        if self._sock is None:
            host, port = self.endpoint.rsplit(":", 1)
            s = socket.create_connection((host, int(port)),
                                         timeout=self.connect_timeout)
            s.settimeout(self.timeout)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = s
        return self._sock

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def call(self, op: int, table_id: int = 0, n: int = 0, lr: float = 0.0,
             epoch: int = 0, client: int = 0, seq: int = 0, dim: int = 0,
             payload: bytes = b"", reader=None):
        try:
            s = self._connect()
            ctx = tracing.current_context()
            w_trace, w_span = ctx.to_wire() if ctx is not None \
                else (0, 0)
            s.sendall(_HDR.pack(op, table_id, n, lr, epoch, client, seq,
                                dim, w_trace, w_span, 0) + payload)
            _read_reply(s, endpoint=self.endpoint)
            return reader(s) if reader is not None else None
        except PSReplyError:
            raise
        except (ConnectionError, OSError):
            self.close()
            raise

    def call_frame(self, frame: bytes) -> None:
        """Send a pre-built frame and consume its ack (the Replicator
        forward hot path)."""
        try:
            s = self._connect()
            s.sendall(frame)
            _read_reply(s, endpoint=self.endpoint)
        except PSReplyError:
            raise
        except (ConnectionError, OSError):
            self.close()
            raise

    # -- admin helpers ------------------------------------------------------
    def seq_epoch(self) -> Tuple[int, int]:
        raw = self.call(OP_SEQ, reader=lambda s: _recv_exact(s, 12))
        return struct.unpack("<QI", raw)

    def delta_since(self, seq: int) -> List[DeltaEntry]:
        def read(s):
            total = struct.unpack("<Q", _recv_exact(s, 8))[0]
            return _recv_exact(s, total)

        raw = self.call(OP_DELTA_SINCE, n=8,
                        payload=struct.pack("<Q", int(seq)), reader=read)
        return decode_deltas(raw)

    def state(self) -> Tuple[int, Dict[int, int], Dict[int, bytes]]:
        """(seq, applied_map, {table_id: blob}) — full state transfer."""
        def read(s):
            seq, jlen = struct.unpack("<QI", _recv_exact(s, 12))
            applied = {int(k): int(v) for k, v in
                       json.loads(_recv_exact(s, jlen).decode()).items()}
            ntab = struct.unpack("<I", _recv_exact(s, 4))[0]
            blobs = {}
            for _ in range(ntab):
                tid, blen = struct.unpack("<IQ", _recv_exact(s, 12))
                blobs[tid] = _recv_exact(s, blen)
            return seq, applied, blobs

        return self.call(OP_STATE, reader=read)

    def digest(self, table_id: int) -> bytes:
        return self.call(OP_DIGEST, table_id=table_id,
                         reader=lambda s: _recv_exact(s, 32))


# ---------------------------------------------------------------------------
# table state blobs (SnapshotStore payloads / full state transfer)
# ---------------------------------------------------------------------------
def _table_blob(table: SparseTable) -> bytes:
    """Full table state (values + optimizer accumulators) as bytes, via
    the table's own save format so native and python backends both
    round-trip."""
    fd, path = tempfile.mkstemp(suffix=".pstable")
    os.close(fd)
    try:
        table.save(path)
        with open(path, "rb") as f:
            return f.read()
    finally:
        try:
            os.remove(path)
        except OSError:
            pass


def _load_table_blob(table: SparseTable, blob: bytes,
                     replace: bool = True) -> None:
    if replace:
        table.clear()
    fd, path = tempfile.mkstemp(suffix=".pstable")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(blob)
        table.load(path)
    finally:
        try:
            os.remove(path)
        except OSError:
            pass


# ---------------------------------------------------------------------------
# primary -> backup forwarding
# ---------------------------------------------------------------------------
class _StalePeerEpoch(Exception):
    """A backup rejected a sync forward because the SENDER's epoch is
    stale: this 'primary' has been demoted and doesn't know it yet. The
    server turns this into a typed client rejection (fencing)."""

    def __init__(self, endpoint: str, epoch: int):
        super().__init__(f"{endpoint} reports epoch {epoch}")
        self.endpoint = endpoint
        self.epoch = int(epoch)


class Replicator:
    """Forwards applied writes to a group's backups.

    ``sync=True``: ``forward`` blocks until every live backup acked —
    the primary's ack to the client then means "replicated", and a
    promoted backup serves a bitwise-identical table. ``sync=False``:
    frames ride a BOUNDED queue drained by a forwarder thread; the queue
    depth is the replication-lag watermark (gauge
    ``ps_replication_lag``), and a full queue back-pressures the write
    path (``max_lag`` frames) instead of growing without bound.

    A backup that stops answering is marked down and skipped; it is
    re-probed after ``peer_retry_s`` (its recovery path is the delta-log
    catch-up, not this hot path). ``dropped`` counts frames each down
    peer missed — the honest "how far behind is that replica" signal.
    """

    def __init__(self, peers: Sequence[str], sync: bool = True,
                 max_lag: int = 1024, rpc_timeout: float = 10.0,
                 peer_retry_s: float = 1.0,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 on_stale: Optional[Callable[[int], None]] = None):
        self.sync = bool(sync)
        # async mode can't fence the already-acked write, but a typed
        # STALE reject is definitive demotion evidence: surface it so
        # the owning server refreshes its role immediately instead of
        # acking more writes for the rest of the role_ttl window
        self._on_stale = on_stale
        self.max_lag = max(1, int(max_lag))
        self._rpc_timeout = float(rpc_timeout)
        self._peers: Dict[str, _RawPeer] = {
            ep: _RawPeer(ep, timeout=rpc_timeout) for ep in peers}
        self._down: Dict[str, float] = {}      # endpoint -> retry-at
        self.dropped: Dict[str, int] = {ep: 0 for ep in peers}
        self._peer_retry_s = float(peer_retry_s)
        self._clock = clock
        self._sleep = sleep
        self._q: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._killed = False
        if not self.sync:
            self._q = queue.Queue(maxsize=self.max_lag)
            self._thread = threading.Thread(target=self._drain_loop,
                                            daemon=True)
            self._thread.start()

    @property
    def peers(self) -> List[str]:
        return list(self._peers)

    def set_peers(self, peers: Sequence[str]) -> None:
        """Adopt a new backup set (promotion / rejoin reshuffles)."""
        for ep in list(self._peers):
            if ep not in peers:
                self._peers.pop(ep).close()
                self._down.pop(ep, None)
        for ep in peers:
            if ep not in self._peers:
                self._peers[ep] = _RawPeer(ep, timeout=self._rpc_timeout)
                self.dropped.setdefault(ep, 0)

    def lag(self) -> int:
        """Frames accepted but not yet replicated (async queue depth)."""
        return self._q.qsize() if self._q is not None else 0

    def _set_lag_gauge(self) -> None:
        from .. import profiler

        profiler.set_counter("ps_replication_lag", self.lag())

    def _send_one(self, ep: str, frame: bytes) -> bool:
        peer = self._peers.get(ep)
        if peer is None:
            return False       # set_peers raced the drain thread
        retry_at = self._down.get(ep)
        if retry_at is not None and self._clock() < retry_at:
            self.dropped[ep] = self.dropped.get(ep, 0) + 1
            return False
        try:
            peer.call_frame(frame)
            self._down.pop(ep, None)
            return True
        except PSReplyError as e:
            if e.code == ERR_STALE_EPOCH:
                if self.sync:
                    # the peer is at a NEWER epoch than this sender: we
                    # are a demoted primary that hasn't noticed — fence
                    # the in-flight client write instead of silently
                    # losing it
                    raise _StalePeerEpoch(ep, e.epoch) from e
                if self._on_stale is not None:
                    self._on_stale(e.epoch)
            self._down[ep] = self._clock() + self._peer_retry_s
            self.dropped[ep] = self.dropped.get(ep, 0) + 1
            return False
        except (ConnectionError, OSError):
            self._down[ep] = self._clock() + self._peer_retry_s
            self.dropped[ep] = self.dropped.get(ep, 0) + 1
            return False

    def _send_all(self, frame: bytes) -> None:
        for ep in list(self._peers):
            self._send_one(ep, frame)

    def forward(self, frame: bytes) -> None:
        """Called by the primary under its replication lock, once per
        applied write, with the fully-built OP_REPL_APPLY frame."""
        if self.sync:
            self._send_all(frame)
            self._set_lag_gauge()
            return
        while True:
            try:
                self._q.put(frame, timeout=0.5)
                break
            except queue.Full:
                # bounded lag: back-pressure the write path rather than
                # let an unbounded backlog hide a dead forwarder — but
                # never spin on a queue nobody will ever drain
                if self._stop.is_set() or self._killed or (
                        self._thread is not None
                        and not self._thread.is_alive()):
                    return
        self._set_lag_gauge()

    def _drain_loop(self) -> None:
        while not self._stop.is_set() or not self._q.empty():
            try:
                frame = self._q.get(timeout=0.1)
            except queue.Empty:
                continue
            try:
                if not self._killed:
                    self._send_all(frame)
            except Exception:      # noqa: BLE001 (forwarder must live)
                pass   # a down peer heals via gap-reject + catch-up
            finally:
                self._q.task_done()
            self._set_lag_gauge()

    def flush(self, timeout: float = 30.0) -> None:
        """Async mode: block (bounded) until every accepted frame is
        fully forwarded — polls the queue's unfinished-task count, not
        qsize, so a frame the drain thread popped but is still sending
        counts as pending (flush == replicated, not merely dequeued)."""
        if self._q is None:
            return
        deadline = self._clock() + float(timeout)
        while self._q.unfinished_tasks > 0:
            if self._clock() >= deadline:
                raise TimeoutError(
                    f"replication queue still holds "
                    f"{self._q.unfinished_tasks} frames after {timeout}s")
            self._sleep(0.01)

    def stop(self) -> None:
        self._stop.set()
        if (self._thread is not None
                and self._thread is not threading.current_thread()):
            # on_stale can trigger a demotion that stops this replicator
            # FROM the drain thread itself — joining yourself raises
            self._thread.join(timeout=5)
        for peer in self._peers.values():
            peer.close()

    def kill(self) -> None:
        """Crash-fidelity stop: DROP queued frames instead of draining
        them — a SIGKILL'd primary would never have sent them, and the
        in-process chaos simulation must not replicate state a real
        crash loses."""
        self._killed = True
        self.stop()


# ---------------------------------------------------------------------------
# the replicated server
# ---------------------------------------------------------------------------
class ReplicatedPSServer(PSServer):
    """A PSServer that participates in a replica group.

    On top of the base server it: validates every client request against
    its role/epoch (typed STALE/NOT_PRIMARY error frames — a client
    talking to a demoted server refreshes instead of split-braining),
    assigns a sequence number to every applied write, dedups replays by
    (client, client_seq), appends to the :class:`DeltaLog`, forwards to
    its backups through a :class:`Replicator`, renews a liveness lease
    in the coordination KV, commits crash-safe SnapshotStore snapshots
    (``snapshot_every`` writes, plus on demand via the client's
    ``snapshot_shards``), and — after a crash — ``rejoin()``s its group:
    restore newest valid snapshot, replay a peer's delta log (or full
    state transfer), resume serving as whatever the current map says it
    is.

    The primary re-validates its role against the KV map at most every
    ``role_ttl`` seconds (and immediately when a request carries a
    newer epoch) — the bounded split-brain fencing window.
    """

    def __init__(self, tables: Dict[int, SparseTable], kv, job: str = "ps",
                 host: str = "127.0.0.1", port: int = 0,
                 advertise: Optional[str] = None,
                 snapshot_dir: Optional[str] = None,
                 num_trainers: int = 1, lease_ttl: float = 10.0,
                 role_ttl: float = 5.0, snapshot_every: int = 0,
                 keep_snapshots: int = 3, max_lag: int = 1024,
                 sync: Optional[bool] = None,
                 clock: Callable[[], float] = time.time,
                 request_timeout: Optional[float] = None,
                 heartbeat_timeout_s: float = 120.0):
        from ..distributed.http_kv import KVClient

        super().__init__(tables, host=host, port=port,
                         num_trainers=num_trainers,
                         heartbeat_timeout_s=heartbeat_timeout_s,
                         request_timeout=request_timeout)
        self._kv = KVClient(kv) if isinstance(kv, str) else kv
        self.job = str(job)
        self.advertise = advertise or self.endpoint
        self._snapshot_dir = snapshot_dir
        self._keep_snapshots = max(1, int(keep_snapshots))
        self.snapshot_every = max(0, int(snapshot_every))
        self._lease_ttl = float(lease_ttl)
        self._role_ttl = float(role_ttl)
        self._max_lag = int(max_lag)
        self._sync_override = sync
        self._sync_effective: Optional[bool] = None
        self._clock = clock
        self._repl_lock = threading.RLock()
        self.seq = 0
        self._applied: Dict[int, int] = {}     # client -> last client_seq
        self._dlog = DeltaLog(capacity=max(64, self._max_lag * 4))
        self._replicator: Optional[Replicator] = None
        self._epoch = 0
        self._role: Optional[str] = None
        self._shard = 0
        self._last_role_check = -1e18
        self._lease_stop = threading.Event()
        self._lease_thread: Optional[threading.Thread] = None
        self._catchup_running = threading.Event()
        # set when this server was demoted primary→backup: it may hold
        # locally-applied writes the group never replicated, so its
        # state (and seq) cannot be trusted until a FULL resync from
        # the current primary — replication traffic is rejected typed
        # in the meantime (a seq collision would otherwise dup-ack the
        # new primary's forwards without applying them: silent
        # permanent divergence)
        self._state_suspect = False
        # process incarnation, stamped into every lease renewal: a
        # relaunch of this endpoint carries a fresh token, which is how
        # the coordinator distinguishes "still alive" from "died and
        # came back fast" (the promotion-race fix)
        self._incarnation = os.urandom(8).hex()

    # -- properties ---------------------------------------------------------
    @property
    def role(self) -> Optional[str]:
        return self._role

    @property
    def epoch(self) -> int:
        return self._epoch

    @property
    def shard(self) -> int:
        return self._shard

    @property
    def sync_mode(self) -> bool:
        """Effective replication mode: the constructor override, else
        the adopted shard map's ``sync`` flag (True before any map is
        seen). Callers gate bitwise-parity assumptions on this — it
        must not claim sync while the map said async."""
        if self._sync_override is not None:
            return bool(self._sync_override)
        if self._sync_effective is not None:
            return bool(self._sync_effective)
        return True

    def _store(self):
        from ..io.snapshot import SnapshotStore

        if self._snapshot_dir is None:
            return None
        root = os.path.join(self._snapshot_dir, f"shard_{self._shard}")
        return SnapshotStore(root, keep_last=self._keep_snapshots,
                             prefix="seq_")

    # -- lifecycle ----------------------------------------------------------
    def start(self):
        super().start()
        self.refresh_role(force=True)
        self._publish_lease()
        self._lease_thread = threading.Thread(target=self._lease_loop,
                                              daemon=True)
        self._lease_thread.start()
        return self

    def stop(self):
        self._lease_stop.set()
        if self._replicator is not None:
            self._replicator.stop()
        super().stop()

    def crash(self):
        # a crashed process renews nothing and forwards nothing: stop
        # the lease thread so the coordinator sees the lease expire on
        # schedule, and KILL the replicator (dropping queued frames — a
        # real SIGKILL would never have sent them)
        self._lease_stop.set()
        if self._replicator is not None:
            self._replicator.kill()
        super().crash()

    # -- leases -------------------------------------------------------------
    def _publish_lease(self) -> None:
        try:
            publish_lease(self._kv, self.job, self.advertise,
                          self._lease_ttl, clock=self._clock,
                          token=self._incarnation)
        except (ConnectionError, OSError, RuntimeError):
            pass   # KV briefly down: next renewal retries

    def _lease_loop(self) -> None:
        interval = max(0.05, self._lease_ttl / 3.0)
        while not self._lease_stop.wait(interval):
            if self._stop.is_set():
                return
            self._publish_lease()
            # role refresh rides the renewal beat (role_ttl-paced
            # inside): without it a demoted primary that receives NO
            # traffic — e.g. a crash-relaunch that resumed serving a
            # heartbeat before the coordinator's promotion landed —
            # would zombie at the old epoch forever, since every other
            # refresh path is request-driven
            try:
                self.refresh_role()
            except Exception:  # noqa: BLE001 (KV blip: next beat retries)
                pass
            try:
                self._anti_entropy_check()
            except Exception:  # noqa: BLE001 (next beat retries)
                pass

    def _anti_entropy_check(self) -> None:
        """Backup-side idle-divergence repair, role_ttl-paced on the
        lease beat: compare our applied seq with the primary's and
        schedule a catch-up when behind. The forward path alone cannot
        close this — a backup that was down-listed by the primary's
        replicator during its own resync misses the tail forwards, and
        with no further traffic there is no gap-reject left to trigger
        the heal (the "last writes before idle" divergence window)."""
        if self._role != "backup" or self._catchup_running.is_set():
            return
        now = self._clock()
        last = getattr(self, "_last_entropy_check", -1e18)
        if now - last < self._role_ttl:
            return
        self._last_entropy_check = now
        m = fetch_shard_map(self._kv, self.job)
        if m is None:
            return
        _role, shard = m.role_of(self.advertise)
        if shard < 0:
            return
        primary = m.groups[shard][0]
        if primary == self.advertise:
            return
        # this probe runs ON the lease-renewal thread: bound it well
        # under the TTL, or a hung (not crashed) primary — SIGSTOP,
        # black-holed network — would stall our OWN renewals past
        # expiry and cascade a false promotion over a healthy backup
        t = max(0.2, self._lease_ttl / 6.0)
        peer = _RawPeer(primary, timeout=t, connect_timeout=t)
        try:
            pseq, _ = peer.seq_epoch()
        except (ConnectionError, OSError, PSReplyError):
            return
        finally:
            peer.close()
        if pseq > self.seq or self._state_suspect:
            self._schedule_catch_up()

    # -- role management ----------------------------------------------------
    def refresh_role(self, force: bool = False) -> None:
        """Re-read the shard map and adopt role/epoch/peers. Paced by
        ``role_ttl`` unless forced (a request carrying a newer epoch
        forces — promotion must be adoptable the moment a client shows
        up with the new map)."""
        now = self._clock()
        if not force and now - self._last_role_check < self._role_ttl:
            return
        self._last_role_check = now
        try:
            m = fetch_shard_map(self._kv, self.job)
        except (ConnectionError, OSError, RuntimeError):
            return
        if m is None or m.epoch <= self._epoch:
            return
        self._adopt(m)

    def _adopt(self, m: ShardMap) -> None:
        demoted = False
        with self._repl_lock:
            was_primary = self._role == "primary"
            role, shard = m.role_of(self.advertise)
            self._epoch = m.epoch
            self._role = role
            if shard >= 0:
                self._shard = shard
            sync = (m.sync if self._sync_override is None
                    else bool(self._sync_override))
            self._sync_effective = m.sync
            peers = ([ep for ep in m.groups[shard] if ep != self.advertise]
                     if role == "primary" else [])
            if role == "primary" and peers:
                if self._replicator is None:
                    self._replicator = Replicator(
                        peers, sync=sync, max_lag=self._max_lag,
                        clock=time.monotonic,
                        on_stale=lambda _e: self.refresh_role(force=True))
                else:
                    self._replicator.set_peers(peers)
            elif self._replicator is not None:
                self._replicator.stop()
                self._replicator = None
            if was_primary and role != "primary" and self.seq > 0:
                # demotion: any write applied here but not replicated
                # is now orphaned state — quarantine until fully
                # resynced from the authoritative primary
                demoted = True
                self._state_suspect = True
        if demoted:
            self._schedule_catch_up()

    # -- request validation (PSServer hook) ---------------------------------
    def _access_error(self, base_op: int, epoch: int):
        self.refresh_role(force=epoch > self._epoch)
        if self._epoch < 1:
            return None          # no map published: plain-server mode
        if base_op == OP_LOAD:
            # a raw table load would mutate state with no seq, no delta
            # entry, and no forward — backups would silently diverge;
            # replicated recovery goes through snapshots + catch-up
            return (ERR_UNSUPPORTED,
                    f"{self.advertise} is replicated: OP_LOAD bypasses "
                    "the replication stream — restore via snapshots "
                    "and catch-up instead")
        if epoch and epoch < self._epoch:
            return (ERR_STALE_EPOCH,
                    f"request epoch {epoch} is behind {self.advertise} "
                    f"(epoch {self._epoch}) — refresh the shard map")
        if self._role != "primary":
            return (ERR_NOT_PRIMARY,
                    f"{self.advertise} is "
                    f"{self._role or 'unassigned'} for shard "
                    f"{self._shard} at epoch {self._epoch} — only the "
                    "primary serves clients")
        return None

    # -- the write path -----------------------------------------------------
    def _apply_write(self, base_op: int, table: SparseTable, table_id: int,
                     ids: np.ndarray, vals: np.ndarray, lr: float,
                     client: int, cseq: int, forwarded: bool,
                     codec: int = 0, raw=None) -> None:
        with self._repl_lock:
            if client and cseq and self._applied.get(client, 0) >= cseq:
                return           # failover replay of an applied write
            _fault.point("ps.apply")
            super()._apply_write(base_op, table, table_id, ids, vals, lr,
                                 client, cseq, forwarded)
            if client and cseq:
                self._applied[client] = cseq
            self.seq += 1
            # a quantized push logs/forwards its RAW ENCODED payload:
            # backups decode the identical bytes the primary applied,
            # so replica digests stay bitwise equal under quantization
            # (and the delta log holds the true wire-sized entry)
            entry = DeltaEntry(
                self.seq, base_op, table_id, client, cseq, lr,
                ids.tobytes(),
                raw if (codec and raw is not None) else vals.tobytes(),
                codec if raw is not None else 0)
            self._dlog.append(entry)
            if not forwarded and self._replicator is not None:
                # forward the encoded delta entry: it carries THIS
                # replication seq, so backups apply strictly in primary
                # order (a gap is a typed reject + catch-up, never a
                # silent out-of-order apply)
                blob = entry.encode()
                _ctx = tracing.current_context()
                _wt, _ws = _ctx.to_wire() if _ctx is not None else (0, 0)
                # the primary's server-side ps_rpc span is ambient here,
                # so the replication forward links the backup's apply
                # into the same trace
                frame = _HDR.pack(OP_REPL_APPLY, 0, len(blob), 0.0,
                                  self._epoch, 0, 0, 0, _wt, _ws,
                                  0) + blob
                try:
                    self._replicator.forward(frame)
                except _StalePeerEpoch as e:
                    # a peer at a NEWER epoch rejected our forward: we
                    # were demoted mid-write. Fence: adopt the new map
                    # and reject the client's write typed — our local
                    # apply is on a stale replica whose state the rejoin
                    # catch-up discards, and the client's replay against
                    # the real primary applies it exactly once.
                    self.refresh_role(force=True)
                    raise WriteRejected(
                        ERR_NOT_PRIMARY,
                        f"{self.advertise} was demoted during the write "
                        f"(peer {e.endpoint} is at epoch {e.epoch}) — "
                        "refresh the shard map and replay") from e
            if (self.snapshot_every and self._snapshot_dir
                    and self.seq % self.snapshot_every == 0):
                self._save_snapshot_locked()

    # -- admin channel (PSServer hook) --------------------------------------
    def _admin_reply(self, base_op: int, conn, table_id: int, n: int,
                     payload: bytes, epoch: int = 0) -> None:
        if base_op == OP_SEQ:
            _send_ok(conn, struct.pack("<QI", self.seq, self._epoch))
        elif base_op == OP_DELTA_SINCE:
            if len(payload) < 8:
                _send_err(conn, ERR_LOG_TRUNCATED, self._epoch,
                          "malformed DELTA_SINCE request (no seq)")
                return
            if self._state_suspect:
                _send_err(conn, ERR_LOG_TRUNCATED, self._epoch,
                          f"{self.advertise} holds quarantined "
                          "post-demotion state — not a catch-up source")
                return
            since = struct.unpack("<Q", payload)[0]
            entries = self._dlog.since(since)
            # the log must COVER since+1..self.seq — an empty log on a
            # snapshot-restored server (seq ahead, nothing retained)
            # would otherwise reply "0 entries" and leave the rejoiner
            # believing it is caught up while silently diverged
            if entries is None or since + len(entries) < self.seq:
                _send_err(conn, ERR_LOG_TRUNCATED, self._epoch,
                          f"delta log on {self.advertise} does not cover "
                          f"seq {since + 1}..{self.seq} — full state "
                          "transfer required")
                return
            blob = b"".join(e.encode() for e in entries)
            _send_ok(conn, struct.pack("<Q", len(blob)) + blob)
        elif base_op == OP_STATE:
            if self._state_suspect:
                _send_err(conn, ERR_LOG_TRUNCATED, self._epoch,
                          f"{self.advertise} holds quarantined "
                          "post-demotion state — not a sync source")
                return
            with self._repl_lock:
                applied = json.dumps(
                    {str(k): v for k, v in self._applied.items()}).encode()
                blobs = {tid: _table_blob(t)
                         for tid, t in self.tables.items()}
                seq = self.seq
            out = [struct.pack("<QI", seq, len(applied)), applied,
                   struct.pack("<I", len(blobs))]
            for tid, blob in sorted(blobs.items()):
                out.append(struct.pack("<IQ", tid, len(blob)))
                out.append(blob)
            _send_ok(conn, b"".join(out))
        elif base_op == OP_SNAPSHOT:
            try:
                seq = self.save_snapshot()
            except (OSError, ValueError, RuntimeError) as e:
                _send_err(conn, ERR_IO, self._epoch,
                          f"snapshot on {self.advertise} failed: {e}")
                return
            _send_ok(conn, struct.pack("<Q", seq))
        elif base_op == OP_REPL_APPLY:
            if self._state_suspect:
                # quarantined post-demotion state: a seq collision with
                # the new primary's stream would dup-ack a DIFFERENT
                # write — reject everything until the full resync lands
                _send_err(conn, ERR_LOG_TRUNCATED, self._epoch,
                          f"{self.advertise} is resyncing after "
                          "demotion — retry after catch-up")
                self._schedule_catch_up()
                return
            if epoch and self._epoch and epoch < self._epoch:
                # a forward from a demoted primary that doesn't know it
                # yet: rejecting typed (instead of a silent duplicate
                # ack when its seq collides with ours) is what lets the
                # stale sender fence ITS client's write
                _send_err(conn, ERR_STALE_EPOCH, self._epoch,
                          f"forward from epoch {epoch} but "
                          f"{self.advertise} is at {self._epoch}")
                return
            if epoch and epoch > self._epoch and self.seq > 0:
                # first forward from a NEW epoch's primary: our tail was
                # fed by the old primary and may differ from the new
                # one's by the writes that raced the promotion — a seq
                # collision would dup-ack a different write. Quarantine
                # and fully resync before accepting the new stream.
                self.refresh_role(force=True)
                with self._repl_lock:
                    self._state_suspect = True
                _send_err(conn, ERR_LOG_TRUNCATED, self._epoch,
                          f"{self.advertise} crossed into epoch "
                          f"{epoch} with a pre-promotion tail — "
                          "resyncing")
                self._schedule_catch_up()
                return
            try:
                entries = decode_deltas(payload)
            except (struct.error, IndexError):
                entries = []
            if not entries:
                _send_err(conn, ERR_IO, self._epoch,
                          "malformed replication frame")
                return
            entry = entries[0]
            with self._repl_lock:
                if entry.seq <= self.seq:
                    _send_ok(conn)        # duplicate forward: acked
                    return
                if entry.seq != self.seq + 1:
                    # a gap means this replica missed forwards while it
                    # was down — applying out of order would silently
                    # diverge; reject typed and self-heal: a background
                    # catch-up replays the primary's delta log, after
                    # which retried forwards line up again
                    _send_err(conn, ERR_LOG_TRUNCATED, self._epoch,
                              f"replica {self.advertise} is at seq "
                              f"{self.seq}, got forward seq {entry.seq} "
                              "— delta catch-up required")
                    self._schedule_catch_up()
                    return
                table = self.tables.get(entry.table_id)
                if table is None:
                    _send_err(conn, ERR_IO, self._epoch,
                              f"forwarded write names unknown table "
                              f"{entry.table_id}")
                    return
                PSServer._apply_write(
                    self, entry.op, table, entry.table_id,
                    np.frombuffer(entry.ids, np.int64),
                    entry.values(table.dim), entry.lr,
                    entry.client, entry.client_seq, True)
                if entry.client and entry.client_seq:
                    self._applied[entry.client] = max(
                        self._applied.get(entry.client, 0),
                        entry.client_seq)
                self.seq = entry.seq
                self._dlog.append(entry)
                if (self.snapshot_every and self._snapshot_dir
                        and self.seq % self.snapshot_every == 0):
                    # backups snapshot on the same cadence as primaries:
                    # a promoted backup must restore from ITS OWN disk,
                    # not hope the dead primary's survives
                    self._save_snapshot_locked()
            _send_ok(conn)
        else:
            super()._admin_reply(base_op, conn, table_id, n, payload)

    # -- crash-safe snapshots -----------------------------------------------
    def save_snapshot(self) -> int:
        """Commit all tables through SnapshotStore (atomic, manifest-
        verified, keep-N). Returns the applied seq the snapshot covers.
        Counter: ``ps_snapshot_commits``."""
        with self._repl_lock:
            return self._save_snapshot_locked()

    def _save_snapshot_locked(self) -> int:
        store = self._store()
        if store is None:
            raise ValueError(
                f"{self.advertise} has no snapshot_dir configured")
        meta = {"seq": self.seq, "epoch": self._epoch,
                "applied": {str(k): v for k, v in self._applied.items()},
                "tables": {str(t): {"dim": tab.dim}
                           for t, tab in self.tables.items()}}
        files: Dict[str, object] = {
            "meta.json": json.dumps(meta, sort_keys=True).encode()}
        for tid, tab in self.tables.items():
            files[f"table_{tid}.bin"] = _table_blob(tab)
        store.save(self.seq, files)
        _bump("ps_snapshot_commits")
        return self.seq

    def restore(self) -> Optional[int]:
        """Load the newest VALID snapshot (corrupt/torn ones are skipped
        with the PR 2 fallback counters). Returns the restored seq, or
        None when no usable snapshot exists (fresh start)."""
        store = self._store()
        if store is None:
            return None
        loaded = store.load_latest()
        if loaded is None:
            return None
        _tag, files = loaded
        meta = json.loads(files["meta.json"].decode())
        with self._repl_lock:
            for tid, tab in self.tables.items():
                blob = files.get(f"table_{tid}.bin")
                if blob is not None:
                    _load_table_blob(tab, blob, replace=True)
            self.seq = int(meta["seq"])
            self._applied = {int(k): int(v)
                             for k, v in meta.get("applied", {}).items()}
        return self.seq

    # -- catch-up / rejoin --------------------------------------------------
    def _schedule_catch_up(self) -> None:
        """One-shot background heal for a live backup that missed
        forwards (gap-rejected an OP_REPL_APPLY): replay the current
        primary's delta log, then retried forwards line up."""
        if self._catchup_running.is_set():
            return
        self._catchup_running.set()

        def run():
            try:
                m = fetch_shard_map(self._kv, self.job)
                if m is None:
                    return
                if m.epoch > self._epoch:
                    self._adopt(m)
                _role, shard = m.role_of(self.advertise)
                if shard < 0:
                    return
                primary = m.groups[shard][0]
                if primary == self.advertise:
                    return
                try:
                    if self._state_suspect:
                        # quarantined: delta replay can't help (our seq
                        # itself is untrustworthy) — full state only
                        self._full_resync(primary)
                    else:
                        self.catch_up(primary)
                except (ConnectionError, OSError, PSReplyError, PSError):
                    pass   # next gap rejection schedules another round
            finally:
                self._catchup_running.clear()

        threading.Thread(target=run, daemon=True).start()

    def _replay(self, entries: List[DeltaEntry]) -> int:
        applied = 0
        with self._repl_lock:
            for e in entries:
                if e.seq <= self.seq:
                    continue
                table = self.tables.get(e.table_id)
                if table is None:
                    # a table this replica doesn't host (mismatched
                    # PADDLE_PS_TABLES): consume the seq so catch-up
                    # progresses instead of a KeyError killing the
                    # heal thread in a crash loop
                    self.seq = e.seq
                    continue
                ids = np.frombuffer(e.ids, np.int64)
                vals = e.values(table.dim)
                PSServer._apply_write(self, e.op, table, e.table_id, ids,
                                      vals, e.lr, e.client, e.client_seq,
                                      True)
                if e.client and e.client_seq:
                    self._applied[e.client] = max(
                        self._applied.get(e.client, 0), e.client_seq)
                self.seq = e.seq
                applied += 1
        return applied

    def _full_resync(self, peer_endpoint: str) -> int:
        """Replace local state wholesale with the peer's (tables, seq,
        dedup map) and reset the delta log; clears the post-demotion
        quarantine. The recovery of last resort — and the only correct
        one when our own seq can't be trusted."""
        peer = _RawPeer(peer_endpoint)
        try:
            seq, applied, blobs = peer.state()
        finally:
            peer.close()
        with self._repl_lock:
            for tid, blob in blobs.items():
                if tid in self.tables:
                    _load_table_blob(self.tables[tid], blob,
                                     replace=True)
            self.seq = int(seq)
            self._applied = dict(applied)
            self._dlog = DeltaLog(self._dlog.capacity)
            self._state_suspect = False
        return len(blobs)

    def catch_up(self, peer_endpoint: str) -> int:
        """Replay the peer's delta log from our applied seq; on
        ERR_LOG_TRUNCATED fall back to a full state transfer. Returns
        the number of entries (or tables, for a full sync) applied."""
        peer = _RawPeer(peer_endpoint)
        try:
            try:
                entries = peer.delta_since(self.seq)
                return self._replay(entries)
            except PSReplyError as e:
                if e.code != ERR_LOG_TRUNCATED:
                    raise
        finally:
            peer.close()
        return self._full_resync(peer_endpoint)

    def rejoin(self, timeout: float = 30.0) -> Optional[str]:
        """The supervised-relaunch recovery path: adopt the current map,
        restore the newest valid snapshot, catch up from the most
        advanced live group peer, and resume serving under whatever role
        the map assigns. Returns the sync-source endpoint (None when
        nothing to catch up from)."""
        try:
            m = wait_shard_map(self._kv, self.job, timeout=timeout,
                               clock=time.monotonic)
        except ShardMapStale:
            return None
        self._adopt(m)
        self.restore()
        _role, shard = m.role_of(self.advertise)
        if shard < 0:
            return None
        # probe group peers for the most advanced seq. A transiently
        # unreachable-but-lease-live peer is RETRIED (bounded): serving
        # from a stale snapshot because one probe raced a busy peer
        # would hand out old values (as primary) or set up a seq
        # collision (as backup). Peers with expired leases are truly
        # gone — no point waiting on them.
        deadline = time.monotonic() + min(10.0, float(timeout))
        while True:
            best_ep, best_seq = None, self.seq
            flaky = []
            for ep in m.groups[shard]:
                if ep == self.advertise:
                    continue
                probe = _RawPeer(ep)
                try:
                    seq, _ = probe.seq_epoch()
                except (ConnectionError, OSError, PSReplyError):
                    lease = read_lease(self._kv, self.job, ep)
                    if lease is not None and lease > self._clock():
                        flaky.append(ep)
                    continue
                finally:
                    probe.close()
                if seq > best_seq:
                    best_ep, best_seq = ep, seq
            if (best_ep is not None or not flaky
                    or time.monotonic() >= deadline):
                break
            time.sleep(0.2)
        if best_ep is not None:
            self.catch_up(best_ep)
        if _role == "backup":
            # an async-mode crash can leave a restored snapshot holding
            # writes the group never saw, with a seq that LOOKS caught
            # up (or ahead) — digest-verify against the live primary
            # and full-resync on any mismatch; seq comparison alone
            # cannot see divergent content at equal seq
            primary = m.groups[shard][0]
            if primary != self.advertise:
                probe = _RawPeer(primary)
                try:
                    for tid, tab in self.tables.items():
                        if probe.digest(tid) != table_digest(tab):
                            self._full_resync(primary)
                            break
                except (ConnectionError, OSError, PSReplyError):
                    pass   # primary unreachable: forwards will gap-heal
                finally:
                    probe.close()
        self._publish_lease()
        return best_ep


# ---------------------------------------------------------------------------
# the coordinator (promotion on lease expiry)
# ---------------------------------------------------------------------------
class ReplicaCoordinator:
    """Publishes the shard map and promotes backups when a primary's
    lease expires.

    ``check_now()`` is one sweep on the injected clock (tests drive
    expiry with a fake clock, zero real sleeps); ``start()`` runs it on
    a daemon thread every ``interval`` for real deployments/drills. A
    promotion reorders the dead primary to the TAIL of its group (it
    rejoins as a backup after relaunch) and bumps the epoch; counter
    ``ps_promotions``. A shard whose every member is lease-dead is left
    alone — there is nothing correct to promote, and clients keep
    getting typed PSUnavailable until an operator intervenes.
    """

    def __init__(self, kv, job: str = "ps", lease_ttl: float = 10.0,
                 interval: float = 1.0, boot_grace: Optional[float] = None,
                 clock: Callable[[], float] = time.time,
                 on_promote: Optional[Callable[[int, str], None]] = None):
        from ..distributed.http_kv import KVClient

        self._kv = KVClient(kv) if isinstance(kv, str) else kv
        self.job = str(job)
        self._ttl = float(lease_ttl)
        self._interval = float(interval)
        self._clock = clock
        self._boot_grace = (2 * self._ttl if boot_grace is None
                            else float(boot_grace))
        self._boot_deadline = clock() + self._boot_grace
        self._on_promote = on_promote
        self._seen_lease: set = set()
        # endpoint -> last seen lease incarnation token: a PRIMARY
        # whose token changes died and relaunched between sweeps — it
        # must be promoted over even though its (fresh) lease is live
        self._tokens: Dict[str, str] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.promotions = 0

    # -- map management -----------------------------------------------------
    def publish(self, groups: Sequence[Sequence[str]],
                sync: bool = True, epoch: Optional[int] = None) -> ShardMap:
        """Publish the initial (or a hand-edited) map. Epoch defaults to
        one past the current map's."""
        cur = fetch_shard_map(self._kv, self.job)
        e = (epoch if epoch is not None
             else (cur.epoch + 1 if cur is not None else 1))
        m = ShardMap(groups, epoch=e, sync=sync, job=self.job)
        publish_shard_map(self._kv, m)
        # restart the grace window at the CONFIGURED width — resetting
        # to a hardcoded 2*ttl here would silently defeat a generous
        # boot_grace (slow server imports would read as dead primaries
        # and promote before the cluster even came up)
        self._boot_deadline = self._clock() + self._boot_grace
        return m

    def map(self) -> Optional[ShardMap]:
        return fetch_shard_map(self._kv, self.job)

    def leases(self) -> Dict[str, Optional[float]]:
        m = self.map()
        if m is None:
            return {}
        return {ep: read_lease(self._kv, self.job, ep)
                for ep in m.endpoints()}

    def _alive(self, ep: str, now: float,
               track_incarnation: bool = False) -> bool:
        expiry, token = read_lease_token(self._kv, self.job, ep)
        if expiry is None:
            # no lease yet: grant boot grace, then treat as dead — a
            # server that never came up is as gone as a crashed one
            return ep not in self._seen_lease and now < self._boot_deadline
        self._seen_lease.add(ep)
        relaunched = False
        if token is not None:
            prev = self._tokens.get(ep)
            relaunched = prev is not None and prev != token
            self._tokens[ep] = token
        if track_incarnation and relaunched:
            # the endpoint died and came back between sweeps: its fresh
            # lease must NOT read as continuity — for a primary this is
            # exactly the relaunch-beats-the-TTL-sweep race, and the
            # correct answer is a promotion (the relaunch rejoins as a
            # backup, per the group contract)
            return False
        return expiry > now

    # -- the sweep ----------------------------------------------------------
    def check_now(self) -> List[int]:
        """One promotion sweep; returns the shard indices promoted."""
        m = self.map()
        if m is None:
            return []
        now = self._clock()
        promoted: List[int] = []
        new_groups = [list(g) for g in m.groups]
        for k, group in enumerate(m.groups):
            if self._alive(group[0], now, track_incarnation=True):
                continue
            live_backup = next((ep for ep in group[1:]
                                if self._alive(ep, now)), None)
            if live_backup is None:
                continue   # whole group dark: nothing correct to promote
            rest = [ep for ep in group if ep not in (group[0], live_backup)]
            new_groups[k] = [live_backup] + rest + [group[0]]
            promoted.append(k)
        if promoted:
            nm = ShardMap(new_groups, epoch=m.epoch + 1, sync=m.sync,
                          job=self.job)
            publish_shard_map(self._kv, nm)
            for k in promoted:
                self.promotions += 1
                _bump("ps_promotions")
                if self._on_promote is not None:
                    self._on_promote(k, new_groups[k][0])
        return promoted

    # -- monitor thread -----------------------------------------------------
    def start(self) -> "ReplicaCoordinator":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self.check_now()
            except (ConnectionError, OSError, RuntimeError):
                continue   # KV hiccup: sweep again next interval

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


# ---------------------------------------------------------------------------
# divergence check
# ---------------------------------------------------------------------------
def verify_replicas(m: ShardMap, table_ids: Sequence[int] = (0,),
                    timeout: float = 10.0) -> Dict[int, Dict[str, str]]:
    """Compare table digests across every group's live members; returns
    {shard: {endpoint: hexdigest}} on agreement and raises
    :class:`ReplicaDiverged` naming the first disagreeing shard.
    Unreachable members are skipped (they are the failover/rejoin
    story, not the divergence one)."""
    out: Dict[int, Dict[str, str]] = {}
    for k, group in enumerate(m.groups):
        for tid in table_ids:
            digests: Dict[str, str] = {}
            for ep in group:
                probe = _RawPeer(ep, timeout=timeout)
                try:
                    digests[ep] = probe.digest(tid).hex()
                except (ConnectionError, OSError, PSReplyError):
                    continue
                finally:
                    probe.close()
            if len(set(digests.values())) > 1:
                raise ReplicaDiverged(
                    f"shard {k} table {tid} diverged across replicas: "
                    + ", ".join(f"{ep}={d[:12]}..."
                                for ep, d in sorted(digests.items())),
                    shard=k, digests=digests)
            out.setdefault(k, {}).update(digests)
    return out


def local_digest(table: SparseTable) -> str:
    """Hex digest of one local table (pairs with verify_replicas)."""
    return table_digest(table).hex()
