"""SparseEmbedding: embedding layer backed by a parameter-server table.

The distributed_lookup_table path (reference
operators/distributed_ops/distributed_lookup_table_op.cc + the pslib
DownpourWorker cycle downpour_worker.cc:726: pull sparse before forward,
push grads after backward). TPU-native shape: forward pulls the touched
rows into a dense (n, dim) Tensor that joins the autodiff tape like any
activation; after loss.backward(), push_gradients() reads the pulled
tensor's grad and pushes it (optimizer applies server-side). The dense
compute stays on-chip; only the touched rows cross host<->server."""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..framework.tensor import Tensor
from ..nn.layer import Layer
from .table import SparseTable


class SparseEmbedding(Layer):
    """``table=`` serves locally, ``client=`` pulls/pushes through a
    PSClient (typed failures + failover ride the client — a pull during
    a primary death fails over to the promoted backup transparently),
    and ``communicator=`` routes ``push_gradients`` through an
    AsyncCommunicator so the backward path never blocks on the pserver
    round-trip (call ``communicator.flush()`` at the sync points)."""

    def __init__(self, embedding_dim: int, table: Optional[SparseTable] = None,
                 client=None, table_id: int = 0, optimizer: str = "sgd",
                 init_range: float = 0.01, seed: int = 0, name=None,
                 communicator=None):
        super().__init__()
        self.embedding_dim = int(embedding_dim)
        self._table = table
        self._client = client          # PSClient for remote mode
        self._comm = communicator      # AsyncCommunicator for async push
        self._table_id = table_id
        if communicator is not None:
            # the async push path and the pull path must agree on where
            # the rows live — a mismatched table/dim would silently
            # train a table the forward never reads (or crash the send
            # thread and surface later as a misleading WorkerLost)
            if communicator.dim != self.embedding_dim:
                raise ValueError(
                    f"communicator dim {communicator.dim} != "
                    f"embedding_dim {self.embedding_dim}")
            if communicator.table_id != table_id:
                raise ValueError(
                    f"communicator pushes table {communicator.table_id} "
                    f"but this embedding reads table {table_id}")
            if self._client is None:
                # communicator-only construction: pulls must hit the
                # SAME pserver the async pushes land on, not a fresh
                # local table that would never see an update
                self._client = communicator.client
        if self._table is None and self._client is None:
            self._table = SparseTable(embedding_dim, optimizer=optimizer,
                                      init_range=init_range, seed=seed)
        self._pending = []             # (ids, pulled Tensor) since last push

    def _pull(self, ids: np.ndarray) -> np.ndarray:
        if self._client is not None:
            return self._client.pull(self._table_id, ids,
                                     self.embedding_dim)
        return self._table.pull(ids)

    def _push(self, ids: np.ndarray, grads: np.ndarray, lr: float):
        if self._comm is not None:
            self._comm.push_sparse_grad(ids, grads, lr)
        elif self._client is not None:
            self._client.push(self._table_id, ids, grads,
                              self.embedding_dim, lr)
        else:
            self._table.push(ids, grads, lr)

    def forward(self, ids):
        """ids: int Tensor/array of any shape -> (*, dim) embeddings."""
        ids_np = np.asarray(ids.numpy() if hasattr(ids, "numpy") else ids,
                            np.int64)
        flat = ids_np.ravel()
        pulled = Tensor(self._pull(flat), stop_gradient=False)
        if self.training:
            self._pending.append((flat, pulled))
        from .. import ops

        out = ops.reshape(pulled, list(ids_np.shape) +
                          [self.embedding_dim])
        return out

    def push_gradients(self, lr: float):
        """Push grads of all pulls since the last call (DownpourWorker's
        PushSparseVarsWithLabelAsync moment). Call after loss.backward()."""
        for flat, pulled in self._pending:
            g = pulled.grad
            if g is None:
                continue
            self._push(flat, np.asarray(g.numpy()
                                        if hasattr(g, "numpy") else g), lr)
        self._pending.clear()

    def clear_pending(self):
        self._pending.clear()
