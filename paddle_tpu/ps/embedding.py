"""SparseEmbedding: embedding layer backed by a parameter-server table.

The distributed_lookup_table path (reference
operators/distributed_ops/distributed_lookup_table_op.cc + the pslib
DownpourWorker cycle downpour_worker.cc:726: pull sparse before forward,
push grads after backward). TPU-native shape: forward pulls the touched
rows into a dense (n, dim) Tensor that joins the autodiff tape like any
activation; after loss.backward(), push_gradients() reads the pulled
tensor's grad and pushes it (optimizer applies server-side). The dense
compute stays on-chip; only the touched rows cross host<->server."""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..framework.tensor import Tensor
from ..nn.layer import Layer
from .table import SparseTable


class SparseEmbedding(Layer):
    def __init__(self, embedding_dim: int, table: Optional[SparseTable] = None,
                 client=None, table_id: int = 0, optimizer: str = "sgd",
                 init_range: float = 0.01, seed: int = 0, name=None):
        super().__init__()
        self.embedding_dim = int(embedding_dim)
        self._table = table
        self._client = client          # PSClient for remote mode
        self._table_id = table_id
        if self._table is None and self._client is None:
            self._table = SparseTable(embedding_dim, optimizer=optimizer,
                                      init_range=init_range, seed=seed)
        self._pending = []             # (ids, pulled Tensor) since last push

    def _pull(self, ids: np.ndarray) -> np.ndarray:
        if self._client is not None:
            return self._client.pull(self._table_id, ids,
                                     self.embedding_dim)
        return self._table.pull(ids)

    def _push(self, ids: np.ndarray, grads: np.ndarray, lr: float):
        if self._client is not None:
            self._client.push(self._table_id, ids, grads,
                              self.embedding_dim, lr)
        else:
            self._table.push(ids, grads, lr)

    def forward(self, ids):
        """ids: int Tensor/array of any shape -> (*, dim) embeddings."""
        ids_np = np.asarray(ids.numpy() if hasattr(ids, "numpy") else ids,
                            np.int64)
        flat = ids_np.ravel()
        pulled = Tensor(self._pull(flat), stop_gradient=False)
        if self.training:
            self._pending.append((flat, pulled))
        from .. import ops

        out = ops.reshape(pulled, list(ids_np.shape) +
                          [self.embedding_dim])
        return out

    def push_gradients(self, lr: float):
        """Push grads of all pulls since the last call (DownpourWorker's
        PushSparseVarsWithLabelAsync moment). Call after loss.backward()."""
        for flat, pulled in self._pending:
            g = pulled.grad
            if g is None:
                continue
            self._push(flat, np.asarray(g.numpy()
                                        if hasattr(g, "numpy") else g), lr)
        self._pending.clear()

    def clear_pending(self):
        self._pending.clear()
