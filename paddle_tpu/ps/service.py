"""Parameter-server RPC: TCP pull/push service over SparseTables.

TPU-native replacement for the reference PS data plane
(/root/reference/paddle/fluid/operators/distributed/ — gRPC/BRPC
send_recv.proto.in SendVariable/GetVariable,
distributed_ops/listen_and_serv_op.cc server loop, parameter_send.cc /
parameter_recv.cc sharded send/recv). Design notes: the wire protocol is
a fixed little-endian header + raw float/int64 payloads (numpy buffers
straight onto the socket — no proto marshalling on the hot path), ids are
hash-sharded across server endpoints by the client exactly like the
reference splits parameter blocks across pservers, and each connection
gets a server thread (the listen_and_serv thread-per-handler model).

Wire format: [op:u8][table:u32][n:u64][lr:f32] then op-dependent arrays.
  PULL:  ids[n]i64            -> values[n*dim]f32
  PUSH:  ids[n]i64 grads f32  -> ack u8
  MERGE: ids[n]i64 deltas f32 -> ack u8   (geo delta add)
  SAVE/LOAD: path bytes[n]    -> rc u8
  ROWS:                       -> count u64
  BARRIER/STOP:               -> ack u8
"""
from __future__ import annotations

import socket
import struct
import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

from .table import SparseTable

OP_PULL, OP_PUSH, OP_MERGE, OP_SAVE, OP_LOAD, OP_ROWS, OP_BARRIER, \
    OP_STOP, OP_HEARTBEAT = range(9)

_HDR = struct.Struct("<BIQf")


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError("peer closed")
        got += r
    return bytes(buf)


class PSServer:
    """One parameter-server process/thread (listen_and_serv_op parity)."""

    def __init__(self, tables: Dict[int, SparseTable], host="127.0.0.1",
                 port: int = 0, num_trainers: int = 1,
                 heartbeat_timeout_s: float = 120.0):
        from .heartbeat import HeartBeatMonitor

        self.tables = tables
        self.monitor = HeartBeatMonitor(num_trainers, heartbeat_timeout_s)
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(64)
        self.host, self.port = self._srv.getsockname()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._barrier = threading.Barrier(max(num_trainers, 1))

    @property
    def endpoint(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self):
        self.monitor.start()
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)
        return self

    def _accept_loop(self):
        self._srv.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _serve(self, conn: socket.socket):
        try:
            while not self._stop.is_set():
                hdr = _recv_exact(conn, _HDR.size)
                op, table_id, n, lr = _HDR.unpack(hdr)
                if op == OP_STOP:
                    conn.sendall(b"\x01")
                    self._stop.set()
                    return
                if op == OP_HEARTBEAT:
                    # trainer_id rides the table field, status the count
                    self.monitor.update(table_id, int(n))
                    conn.sendall(b"\x01")
                    continue
                if op == OP_BARRIER:
                    try:
                        self._barrier.wait(timeout=60)
                    except threading.BrokenBarrierError:
                        pass
                    conn.sendall(b"\x01")
                    continue
                table = self.tables[table_id]
                if op == OP_PULL:
                    ids = np.frombuffer(_recv_exact(conn, 8 * n), np.int64)
                    conn.sendall(table.pull(ids).tobytes())
                elif op in (OP_PUSH, OP_MERGE):
                    ids = np.frombuffer(_recv_exact(conn, 8 * n), np.int64)
                    vals = np.frombuffer(
                        _recv_exact(conn, 4 * n * table.dim), np.float32)
                    if op == OP_PUSH:
                        table.push(ids, vals, lr)
                    else:
                        table.merge_add(ids, vals)
                    conn.sendall(b"\x01")
                elif op in (OP_SAVE, OP_LOAD):
                    path = _recv_exact(conn, n).decode()
                    try:
                        (table.save if op == OP_SAVE else table.load)(path)
                        conn.sendall(b"\x01")
                    except IOError:
                        conn.sendall(b"\x00")
                elif op == OP_ROWS:
                    conn.sendall(struct.pack("<Q", table.rows()))
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    def stop(self):
        self._stop.set()
        self.monitor.stop()
        try:
            self._srv.close()
        except OSError:
            pass

    def join(self, timeout: Optional[float] = None):
        self._stop.wait(timeout)


class PSClient:
    """Trainer-side client: shards ids across endpoints by hash
    (parameter_send.cc splits param blocks the same way)."""

    def __init__(self, endpoints: Sequence[str]):
        self._eps = list(endpoints)
        self._socks: List[Optional[socket.socket]] = [None] * len(self._eps)
        self._locks = [threading.Lock() for _ in self._eps]
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None

    def _sock(self, i: int) -> socket.socket:
        if self._socks[i] is None:
            host, port = self._eps[i].rsplit(":", 1)
            s = socket.create_connection((host, int(port)), timeout=30)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._socks[i] = s
        return self._socks[i]

    def _shard(self, ids: np.ndarray):
        srv = (ids * np.int64(0x9E3779B1) % np.int64(2**31)) % len(self._eps)
        return [np.nonzero(srv == k)[0] for k in range(len(self._eps))]

    def pull(self, table_id: int, ids, dim: int) -> np.ndarray:
        ids = np.ascontiguousarray(ids, np.int64).ravel()
        out = np.empty((ids.size, dim), np.float32)
        for k, sel in enumerate(self._shard(ids)):
            if sel.size == 0:
                continue
            with self._locks[k]:
                s = self._sock(k)
                s.sendall(_HDR.pack(OP_PULL, table_id, sel.size, 0.0))
                s.sendall(ids[sel].tobytes())
                vals = np.frombuffer(
                    _recv_exact(s, 4 * sel.size * dim),
                    np.float32).reshape(sel.size, dim)
            out[sel] = vals
        return out

    def _send_vals(self, op: int, table_id: int, ids, vals, dim: int,
                   lr: float):
        ids = np.ascontiguousarray(ids, np.int64).ravel()
        vals = np.ascontiguousarray(vals, np.float32).reshape(ids.size, dim)
        for k, sel in enumerate(self._shard(ids)):
            if sel.size == 0:
                continue
            with self._locks[k]:
                s = self._sock(k)
                s.sendall(_HDR.pack(op, table_id, sel.size, lr))
                s.sendall(ids[sel].tobytes())
                s.sendall(vals[sel].tobytes())
                _recv_exact(s, 1)

    def push(self, table_id: int, ids, grads, dim: int, lr: float):
        self._send_vals(OP_PUSH, table_id, ids, grads, dim, lr)

    def merge_add(self, table_id: int, ids, deltas, dim: int):
        self._send_vals(OP_MERGE, table_id, ids, deltas, dim, 0.0)

    def rows(self, table_id: int) -> int:
        total = 0
        for k in range(len(self._eps)):
            with self._locks[k]:
                s = self._sock(k)
                s.sendall(_HDR.pack(OP_ROWS, table_id, 0, 0.0))
                total += struct.unpack("<Q", _recv_exact(s, 8))[0]
        return total

    def save(self, table_id: int, path_prefix: str):
        for k in range(len(self._eps)):
            p = f"{path_prefix}.shard{k}".encode()
            with self._locks[k]:
                s = self._sock(k)
                s.sendall(_HDR.pack(OP_SAVE, table_id, len(p), 0.0))
                s.sendall(p)
                if _recv_exact(s, 1) != b"\x01":
                    raise IOError(f"save failed on {self._eps[k]}")

    def barrier(self):
        def one(k):
            with self._locks[k]:
                s = self._sock(k)
                s.sendall(_HDR.pack(OP_BARRIER, 0, 0, 0.0))
                _recv_exact(s, 1)
        threads = [threading.Thread(target=one, args=(k,))
                   for k in range(len(self._eps))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    def heartbeat(self, trainer_id: int, status: int = 0):
        """Beat every pserver (reference HeartbeatRPC; status 0=running,
        1=completed — see ps/heartbeat.py)."""
        for k in range(len(self._eps)):
            with self._locks[k]:
                s = self._sock(k)
                s.sendall(_HDR.pack(OP_HEARTBEAT, trainer_id, status, 0.0))
                _recv_exact(s, 1)

    def start_heartbeat(self, trainer_id: int, interval_s: float = 10.0):
        """Background beat thread (the reference Communicator's send
        thread beats as a side effect; here it is explicit)."""
        if self._hb_thread is not None:
            return

        def loop():
            while not self._hb_stop.wait(interval_s):
                try:
                    self.heartbeat(trainer_id)
                except (ConnectionError, OSError):
                    return

        self.heartbeat(trainer_id)
        self._hb_thread = threading.Thread(target=loop, daemon=True)
        self._hb_thread.start()

    def stop_heartbeat(self, trainer_id: Optional[int] = None,
                       completed: bool = True):
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5)
            self._hb_thread = None
        self._hb_stop = threading.Event()
        if trainer_id is not None and completed:
            try:
                self.heartbeat(trainer_id, status=1)
            except (ConnectionError, OSError):
                pass

    def stop_servers(self):
        for k in range(len(self._eps)):
            try:
                with self._locks[k]:
                    s = self._sock(k)
                    s.sendall(_HDR.pack(OP_STOP, 0, 0, 0.0))
                    _recv_exact(s, 1)
            except (ConnectionError, OSError):
                pass

    def close(self):
        for s in self._socks:
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
        self._socks = [None] * len(self._eps)
