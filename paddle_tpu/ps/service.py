"""Parameter-server RPC: TCP pull/push service over SparseTables.

TPU-native replacement for the reference PS data plane
(/root/reference/paddle/fluid/operators/distributed/ — gRPC/BRPC
send_recv.proto.in SendVariable/GetVariable,
distributed_ops/listen_and_serv_op.cc server loop, parameter_send.cc /
parameter_recv.cc sharded send/recv). Design notes: the wire protocol is
a fixed little-endian header + raw float/int64 payloads (numpy buffers
straight onto the socket — no proto marshalling on the hot path), ids are
hash-sharded across server endpoints by the client exactly like the
reference splits parameter blocks across pservers, and each connection
gets a server thread (the listen_and_serv thread-per-handler model).

Wire format v2 (fault-tolerant revision; trace-context + codec
extensions)::

    request  = [op:u8][table:u32][n:u64][lr:f32]
               [epoch:u32][client:u32][seq:u64][dim:u32]
               [trace:u64][span:u64][codec:u8]             + payload
    reply    = [0x01] + payload                            (OK)
             | [0x00][code:u8][srv_epoch:u32][len:u32][msg]  (typed error)

``codec`` selects the VALUE payload encoding for PULL/PUSH (ps/codec.py:
0 = f32, 1 = bf16, 2 = blocked-scaled int8 — the same encodings the
quantized all-reduce uses): a quantized push carries
``encoded_nbytes(n*dim, codec)`` value bytes which the primary decodes
to f32 before applying, and a pull request asks the server to encode
its reply the same way. The RAW ENCODED bytes ride the replication
stream (DeltaEntry carries the codec), so primary and every backup
decode identical bytes — replica digests stay bitwise equal under
quantization. MERGE/ASSIGN/admin traffic is always codec 0 (an ASSIGN
is a raw overwrite — quantizing it would corrupt catch-up state).

``trace``/``span`` are the caller's compact trace context
(observability/tracing.py — zero = untraced): when set, the server
wraps the request in a server-side ``ps_rpc`` span parented to the
caller's span, so a PS pull issued inside a traced region appears in
the caller's tree even across the process boundary.

``epoch`` is the client's shard-map epoch (0 = not epoch-aware — the
legacy static-endpoint client), ``client``/``seq`` identify a write for
replay dedup (a failover replays the *same* frame, so an update that was
already applied-and-replicated is acked instead of double-applied), and
``dim`` is the client's row width so the server can always drain a
value-carrying payload before reporting an error (unknown table, dim
mismatch) without desyncing the stream. Primary→backup replication
traffic rides the seq-validated ``OP_REPL_APPLY`` admin op — there is
deliberately NO wire-level "trusted" flag that would exempt a frame
from role checks.

The v1 protocol acked every reply with a bare ``\\x01`` and had no error
channel at all: an unknown ``table_id`` raised KeyError past the
``(ConnectionError, OSError)`` handler, killing the connection thread
while the client blocked on a reply forever, and a timed-out barrier
still acked success. Every reply now starts with a status byte and every
failure is a typed error frame the client surfaces as a typed exception
(see ps/replication.py for the taxonomy).

Ops:
  PULL:  ids[n]i64             -> values[n*dim]f32
  PUSH:  ids[n]i64 grads f32   -> ack        (server-side optimizer step)
  MERGE: ids[n]i64 deltas f32  -> ack        (geo delta add)
  ASSIGN:ids[n]i64 values f32  -> ack        (raw overwrite, catch-up)
  SAVE/LOAD: path bytes[n]     -> ack / ERR_IO
  ROWS:                        -> count u64
  SEQ:                         -> [applied_seq u64][epoch u32]
  KEYS:                        -> [count u64][ids i64...]
  DIGEST:                      -> sha256(sorted ids + values) 32 bytes
  DELTA_SINCE / STATE / SNAPSHOT: replication admin (ReplicatedPSServer)
  BARRIER/STOP/HEARTBEAT:      -> ack
"""
from __future__ import annotations

import hashlib
import itertools
import os
import socket
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..fault import injector as _fault
from ..fault.injector import _bump  # shared lazy counter shim
from ..fault.retry import Backoff, Retrier, env_backoff, env_max_attempts
from ..observability import tracing
from ..observability.flight_recorder import note_typed_error
from ..observability.metrics import default_registry as _obs_registry
from .codec import CODEC_IDS, codec_name, encoded_nbytes, np_decode, \
    np_encode

_RPC_HIST = None


def _rpc_hist():
    """Cached ps_rpc_ms histogram handle — the per-RPC hot path must
    not re-take the registry declaration lock on every round trip."""
    global _RPC_HIST
    if _RPC_HIST is None:
        _RPC_HIST = _obs_registry().histogram("ps_rpc_ms",
                                              labels=("op",))
    return _RPC_HIST
from .table import SparseTable

(OP_PULL, OP_PUSH, OP_MERGE, OP_SAVE, OP_LOAD, OP_ROWS, OP_BARRIER,
 OP_STOP, OP_HEARTBEAT, OP_ASSIGN, OP_SEQ, OP_DELTA_SINCE, OP_DIGEST,
 OP_KEYS, OP_SNAPSHOT, OP_STATE, OP_REPL_APPLY) = range(17)

_MAX_OP = OP_REPL_APPLY

# op table n lr epoch client seq dim trace span codec — trace/span are
# the caller's compact trace context (0 = untraced; tracing.SpanContext),
# codec the value-payload encoding (ps/codec.py ids; 0 = plain f32)
_HDR = struct.Struct("<BIQfIIQIQQB")
_ERR_HDR = struct.Struct("<BII")    # code srv_epoch msg_len

_OP_NAMES = {
    OP_PULL: "pull", OP_PUSH: "push", OP_MERGE: "merge",
    OP_SAVE: "save", OP_LOAD: "load", OP_ROWS: "rows",
    OP_BARRIER: "barrier", OP_STOP: "stop", OP_HEARTBEAT: "heartbeat",
    OP_ASSIGN: "assign", OP_SEQ: "seq", OP_DELTA_SINCE: "delta_since",
    OP_DIGEST: "digest", OP_KEYS: "keys", OP_SNAPSHOT: "snapshot",
    OP_STATE: "state", OP_REPL_APPLY: "repl_apply",
}

# typed error-frame codes (client maps them to the ps.replication taxonomy)
(ERR_UNKNOWN_TABLE, ERR_BARRIER_TIMEOUT, ERR_STALE_EPOCH, ERR_NOT_PRIMARY,
 ERR_LOG_TRUNCATED, ERR_BAD_REQUEST, ERR_IO, ERR_UNSUPPORTED) = range(1, 9)

#: a request larger than these bounds is a malformed/hostile header, not
#: a real batch — reject before allocating buffers for it (the payload
#: read is n*dim floats: both factors AND the product must be sane)
_MAX_IDS = 1 << 28
_MAX_DIM = 1 << 20
_MAX_ELEMS = 1 << 28
#: admin ops (DELTA_SINCE reply cursors, REPL_APPLY entry blobs) carry a
#: BYTE length in ``n`` — bound it by the largest legal encoded write
#: (ids + values at the element caps) rather than the ids-count caps, or
#: a legal large write would forward as a "malformed" frame the backup
#: rejects, silently breaking the sync-replication ack invariant
_MAX_BLOB = 8 * _MAX_IDS + 4 * _MAX_ELEMS + 64


class WriteRejected(Exception):
    """Raised by an _apply_write hook to reject an already-drained write
    with a typed error frame (e.g. a primary that discovered mid-write it
    was demoted). Internal to the server loop."""

    def __init__(self, code: int, msg: str):
        super().__init__(msg)
        self.code = int(code)
        self.msg = msg


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError("peer closed")
        got += r
    return bytes(buf)


def _send_ok(conn: socket.socket, payload: bytes = b"") -> None:
    conn.sendall(b"\x01" + payload)


def _send_err(conn: socket.socket, code: int, epoch: int, msg: str) -> None:
    data = msg.encode("utf-8", "replace")
    conn.sendall(b"\x00" + _ERR_HDR.pack(code, max(0, int(epoch)),
                                         len(data)) + data)


class PSReplyError(Exception):
    """Wire-level typed error frame from a pserver. Internal: PSClient
    maps it onto the ps.replication exception taxonomy (or handles it —
    a stale-epoch frame triggers a shard-map refresh, not a raise)."""

    def __init__(self, code: int, epoch: int, message: str,
                 endpoint: str = ""):
        super().__init__(f"[err {code}] {message}")
        self.code = int(code)
        self.epoch = int(epoch)
        self.message = message
        self.endpoint = endpoint


def _read_reply(sock: socket.socket, endpoint: str = "") -> None:
    """Consume the status byte; raise PSReplyError on an error frame.
    On OK the caller reads its op-specific payload next."""
    status = _recv_exact(sock, 1)
    if status == b"\x01":
        return
    code, epoch, mlen = _ERR_HDR.unpack(_recv_exact(sock, _ERR_HDR.size))
    msg = _recv_exact(sock, mlen).decode("utf-8", "replace")
    raise PSReplyError(code, epoch, msg, endpoint=endpoint)


def table_digest(table: SparseTable) -> bytes:
    """Deterministic sha256 over (sorted ids, their values): the
    replica-divergence check. Values only (not optimizer accumulators) so
    native and python table backends hash identically."""
    ids = np.sort(table.keys())
    h = hashlib.sha256()
    h.update(ids.tobytes())
    if ids.size:
        h.update(np.ascontiguousarray(table.pull(ids)).tobytes())
    return h.digest()


class PSServer:
    """One parameter-server process/thread (listen_and_serv_op parity).

    Hardened against misbehaving peers: every reply carries a status
    byte, an unknown ``table_id`` or a dim mismatch is a typed error
    frame (the connection thread survives — v1 died on the KeyError with
    the client blocked forever), a broken barrier replies failure AND
    resets so one timeout doesn't poison every later barrier, and each
    connection carries an idle ``request_timeout`` (counter
    ``ps_conn_timeouts``, mirroring the KVHTTPServer hardening) — safe
    now that the client transparently reconnects on any socket error.
    """

    def __init__(self, tables: Dict[int, SparseTable], host="127.0.0.1",
                 port: int = 0, num_trainers: int = 1,
                 heartbeat_timeout_s: float = 120.0,
                 request_timeout: Optional[float] = None,
                 barrier_timeout_s: float = 60.0):
        from .heartbeat import HeartBeatMonitor

        self.tables = tables
        self.monitor = HeartBeatMonitor(num_trainers, heartbeat_timeout_s)
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(64)
        self.host, self.port = self._srv.getsockname()
        self.request_timeout = (
            request_timeout if request_timeout is not None
            else _env_float("PADDLE_PS_CONN_TIMEOUT", 300.0)) or None
        self.barrier_timeout_s = float(barrier_timeout_s)
        self._stop = threading.Event()
        self.crashed = False
        self._threads: List[threading.Thread] = []
        self._accept_thread: Optional[threading.Thread] = None
        self._barrier = threading.Barrier(max(num_trainers, 1))
        self._barrier_lock = threading.Lock()
        self._applied: Dict[int, int] = {}   # client -> last write seq
        self._applied_lock = threading.Lock()
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        # SystemExit from a fault point (PADDLE_FAULT_SPEC chaos kill)
        # exits the whole process when this env flag is set — a server
        # subprocess dies like a real crash; in-process test servers
        # default to crash() (stop serving, drop connections) instead
        self._exit_on_crash = os.environ.get(
            "PADDLE_PS_EXIT_ON_CRASH", "0") not in ("0", "")

    @property
    def endpoint(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self):
        self.monitor.start()
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._accept_thread = t
        self._threads.append(t)
        return self

    def _accept_loop(self):
        self._srv.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            with self._conns_lock:
                self._conns.add(conn)
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    # -- subclass hooks (ps/replication.py ReplicatedPSServer) --------------
    def _access_error(self, base_op: int, epoch: int):
        """Role/epoch validation for table data ops; (code, msg) to
        reject, None to serve. The base server serves everyone.
        Replication traffic never reaches this: it rides the
        seq-validated OP_REPL_APPLY admin channel."""
        return None

    def _apply_write(self, base_op: int, table: SparseTable, table_id: int,
                     ids: np.ndarray, vals: np.ndarray, lr: float,
                     client: int, cseq: int, forwarded: bool,
                     codec: int = 0, raw: Optional[bytes] = None) -> None:
        """Apply one write, exactly once per (client, seq): the client's
        retry loop replays a frame whose ack was lost (connection died
        between apply and reply), and without dedup a plain server would
        double-apply the gradient. The replicated subclass wraps this
        with sequence numbering, the delta log, and primary→backup
        forwarding (its own dedup runs under the replication lock).
        ``codec``/``raw`` carry a quantized push's wire encoding so the
        replicated subclass can forward the ENCODED bytes — backups
        decode the same payload the primary did, bitwise."""
        if client and cseq:
            with self._applied_lock:
                if self._applied.get(client, 0) >= cseq:
                    return           # replayed write: already applied
        if base_op == OP_PUSH:
            table.push(ids, vals, lr)
        elif base_op == OP_MERGE:
            table.merge_add(ids, vals)
        else:
            table.assign(ids, vals)
        if client and cseq:
            # watermark advances only AFTER a successful apply: set
            # earlier, a failed apply would make the client's replay
            # read as "already applied" and the write would be acked
            # but never land
            with self._applied_lock:
                self._applied[client] = max(
                    self._applied.get(client, 0), cseq)

    def _admin_reply(self, base_op: int, conn: socket.socket,
                     table_id: int, n: int, payload: bytes,
                     epoch: int = 0) -> None:
        """SEQ/DELTA_SINCE/STATE/SNAPSHOT — replication admin channel.
        The base server only knows SEQ (always 0: nothing replicated)."""
        if base_op == OP_SEQ:
            _send_ok(conn, struct.pack("<QI", 0, 0))
        else:
            _send_err(conn, ERR_UNSUPPORTED, 0,
                      f"op {base_op} needs a ReplicatedPSServer")

    # -- the connection loop ------------------------------------------------
    def _serve(self, conn: socket.socket):
        if self.request_timeout:
            conn.settimeout(self.request_timeout)
        try:
            while not self._stop.is_set():
                hdr = _recv_exact(conn, _HDR.size)
                (op, table_id, n, lr, epoch, client, seq, dim,
                 w_trace, w_span, codec) = _HDR.unpack(hdr)
                ctx = tracing.SpanContext.from_wire(w_trace, w_span)
                if ctx is None:
                    if not self._serve_one(conn, op, table_id, n, lr,
                                           epoch, client, seq, dim,
                                           codec):
                        return
                    continue
                # server-side ps_rpc span parented to the CALLER's
                # span over the wire: a PS pull inside a traced region
                # lands in the caller's tree across the process
                # boundary. Activated, so replication forwards carry
                # it one hop further (primary -> backup).
                sp = tracing.Span("ps_rpc", parent=ctx,
                                  op=_OP_NAMES.get(op, str(op)),
                                  table=table_id,
                                  endpoint=self.endpoint)
                try:
                    with sp.activate():
                        keep = self._serve_one(conn, op, table_id, n,
                                               lr, epoch, client, seq,
                                               dim, codec)
                except BaseException as e:
                    sp.fail(e)
                    raise
                sp.end()
                if not keep:
                    return
        except socket.timeout:
            # idle/stalled peer: close its connection, count it —
            # the hardened client reconnects transparently on next use
            _bump("ps_conn_timeouts")
        except (ConnectionError, OSError):
            pass
        except SystemExit:
            # a chaos fault point (PADDLE_FAULT_SPEC ... :SystemExit)
            # fired inside a handler: die like a crashed pserver.
            # _exit FIRST — crash() sets the stop event, and the main
            # thread's join() would win the race and exit 0 (a "clean"
            # death the supervisor would never relaunch)
            if self._exit_on_crash:
                os._exit(17)
            self.crash()
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _serve_one(self, conn: socket.socket, op: int, table_id: int,
                   n: int, lr: float, epoch: int, client: int,
                   seq: int, dim: int, codec: int = 0) -> bool:
        """Handle ONE framed request (header already consumed). Returns
        True to keep the connection loop serving, False to close it."""
        # no wire-level "trusted" flag: replication traffic is the
        # OP_REPL_APPLY admin op (seq-validated), so an op with any
        # reserved bit set is simply malformed — a flag that exempted
        # role checks would let any client desync a backup's
        # replication stream
        base = op
        oversized = (
            n > _MAX_BLOB
            if base in (OP_DELTA_SINCE, OP_REPL_APPLY)
            else (n > _MAX_IDS or dim > _MAX_DIM
                  or n * max(dim, 1) > _MAX_ELEMS))
        if base > _MAX_OP or oversized or codec > 2 or (
                codec and base not in (OP_PULL, OP_PUSH)):
            # unparseable header (an unknown codec makes the payload
            # length uncomputable — the stream cannot be resynced, and
            # a quantized MERGE/ASSIGN would corrupt catch-up state):
            # reply typed, then drop the connection
            _send_err(conn, ERR_BAD_REQUEST, 0,
                      f"malformed request (op={op}, n={n}, "
                      f"dim={dim}, codec={codec})")
            return False
        if base == OP_STOP:
            _send_ok(conn)
            self._stop.set()
            return False
        if base == OP_HEARTBEAT:
            # trainer_id rides the table field, status the count
            self.monitor.update(table_id, int(n))
            _send_ok(conn)
            return True
        if base == OP_BARRIER:
            self._serve_barrier(conn, epoch)
            return True
        if base in (OP_SEQ, OP_DELTA_SINCE, OP_STATE, OP_SNAPSHOT,
                    OP_REPL_APPLY):
            # DELTA_SINCE and REPL_APPLY carry n payload bytes
            body = (_recv_exact(conn, n)
                    if base in (OP_DELTA_SINCE, OP_REPL_APPLY)
                    else b"")
            self._admin_reply(base, conn, table_id, n, body,
                              epoch=epoch)
            return True
        table = self.tables.get(table_id)
        if base == OP_PULL:
            ids = np.frombuffer(_recv_exact(conn, 8 * n), np.int64)
            err = self._table_error(table, table_id, dim, epoch,
                                    base)
            if err:
                _send_err(conn, err[0], err[1], err[2])
                return True
            vals = table.pull(ids)
            if codec:
                _send_ok(conn, np_encode(vals, codec_name(codec)))
            else:
                _send_ok(conn, vals.tobytes())
        elif base in (OP_PUSH, OP_MERGE, OP_ASSIGN):
            # drain ids AND values by the client-declared dim + codec
            # BEFORE any error reply, so a rejected write leaves
            # the stream in sync for the next request
            ids = np.frombuffer(_recv_exact(conn, 8 * n), np.int64)
            raw = _recv_exact(
                conn, encoded_nbytes(n * dim, codec_name(codec)))
            err = self._table_error(table, table_id, dim, epoch,
                                    base)
            if err:
                _send_err(conn, err[0], err[1], err[2])
                return True
            if codec:
                vals = np_decode(raw, n * dim, codec_name(codec))
            else:
                vals = np.frombuffer(raw, np.float32)
            try:
                self._apply_write(base, table, table_id, ids,
                                  vals, lr, client, seq, False,
                                  codec=codec, raw=raw)
            except WriteRejected as e:
                _send_err(conn, e.code,
                          getattr(self, "_epoch", 0), e.msg)
                return True
            except (ValueError, KeyError, OSError,
                    RuntimeError) as e:
                # a failed apply must reply typed (the client
                # replays; the dedup watermark only advances on
                # success) — dying here would leave the client
                # blocked and the retry silently swallowed
                _send_err(conn, ERR_IO,
                          getattr(self, "_epoch", 0),
                          f"write failed: {e}")
                return True
            _send_ok(conn)
        elif base in (OP_SAVE, OP_LOAD):
            path = _recv_exact(conn, n).decode()
            if table is None:
                _send_err(conn, ERR_UNKNOWN_TABLE, 0,
                          f"unknown table_id {table_id}")
                return True
            acc = self._access_error(base, epoch)
            if acc is not None:
                # SAVE/LOAD fence like data ops: a LOAD onto a
                # demoted server (or a backup) would mutate
                # state outside the replication stream
                _send_err(conn, acc[0],
                          getattr(self, "_epoch", 0), acc[1])
                return True
            try:
                (table.save if base == OP_SAVE else
                 table.load)(path)
                _send_ok(conn)
            except (IOError, OSError, ValueError) as e:
                _send_err(conn, ERR_IO, 0,
                          f"{'save' if base == OP_SAVE else 'load'}"
                          f"({path}) failed: {e}")
        elif base == OP_ROWS:
            if table is None:
                _send_err(conn, ERR_UNKNOWN_TABLE, 0,
                          f"unknown table_id {table_id}")
                return True
            _send_ok(conn, struct.pack("<Q", table.rows()))
        elif base == OP_KEYS:
            if table is None:
                _send_err(conn, ERR_UNKNOWN_TABLE, 0,
                          f"unknown table_id {table_id}")
                return True
            keys = np.sort(table.keys())
            _send_ok(conn, struct.pack("<Q", keys.size)
                     + keys.tobytes())
        elif base == OP_DIGEST:
            if table is None:
                _send_err(conn, ERR_UNKNOWN_TABLE, 0,
                          f"unknown table_id {table_id}")
                return True
            _send_ok(conn, table_digest(table))
        else:
            _send_err(conn, ERR_BAD_REQUEST, 0,
                      f"unhandled op {base}")
            return False
        return True

    def _table_error(self, table, table_id: int, dim: Optional[int],
                     epoch: int, base_op: int):
        if table is None:
            return (ERR_UNKNOWN_TABLE, 0,
                    f"unknown table_id {table_id} on {self.endpoint} "
                    f"(serving {sorted(self.tables)})")
        if dim is not None and dim != table.dim:
            return (ERR_BAD_REQUEST, 0,
                    f"dim mismatch for table {table_id}: client sent "
                    f"{dim}, table is {table.dim}-wide")
        acc = self._access_error(base_op, epoch)
        if acc is not None:
            code, msg = acc
            return (code, getattr(self, "_epoch", 0), msg)
        return None

    def _serve_barrier(self, conn: socket.socket, epoch: int) -> None:
        """Bounded barrier: a timeout/broken barrier replies a TYPED
        failure (v1 acked success) and resets the barrier so the next
        round starts clean instead of inheriting the broken state."""
        try:
            self._barrier.wait(timeout=self.barrier_timeout_s)
        except threading.BrokenBarrierError:
            with self._barrier_lock:
                if self._barrier.broken:
                    self._barrier.reset()
            _send_err(conn, ERR_BARRIER_TIMEOUT, epoch,
                      f"barrier on {self.endpoint} timed out after "
                      f"{self.barrier_timeout_s}s (or was broken by a "
                      "peer timeout) — barrier has been reset")
            return
        _send_ok(conn)

    def crash(self):
        """Simulate this pserver's process dying, in-process: stop
        accepting, sever every live connection mid-whatever, stop
        renewing liveness — clients see raw socket errors, exactly like
        a SIGKILL'd server. The chaos-drill seam (no graceful replies)."""
        self.crashed = True
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        with self._conns_lock:
            conns, self._conns = list(self._conns), set()
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        self.monitor.stop()
        self._join_acceptor()

    def _join_acceptor(self):
        """CPython defers the real close of the listening fd while the
        accept thread is blocked in accept(); join it (bounded by its
        0.2s accept timeout) so the port is actually free when
        crash()/stop() return — a relaunch rebinds deterministically."""
        t = self._accept_thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=1.0)

    def stop(self):
        self._stop.set()
        self.monitor.stop()
        try:
            self._srv.close()
        except OSError:
            pass
        self._join_acceptor()

    def join(self, timeout: Optional[float] = None):
        self._stop.wait(timeout)


def _fresh_client_id() -> int:
    """Random nonzero 32-bit write identity. It must be unique across
    HOSTS and trainer RESTARTS: pids collide in containers (everything
    is pid 1) and a relaunched trainer restarts its write seq at 1 — a
    reused id would collide with the server's persisted high watermark
    and every replayed-looking write would be silently dropped."""
    while True:
        cid = int.from_bytes(os.urandom(4), "little")
        if cid:
            return cid


class PSClient:
    """Trainer-side client: shards ids across endpoints by hash
    (parameter_send.cc splits param blocks the same way).

    Fault-tolerant revision: every RPC runs with socket deadlines
    (``PADDLE_PS_RPC_TIMEOUT``), passes a named fault point
    (``ps.pull`` / ``ps.push`` / ``ps.barrier`` / ``ps.save``), retries
    transient socket failures with the repo-wide backoff policy
    (counters ``ps_rpc_retries`` + ``retry_attempts``), and exits TYPED:
    :class:`~paddle_tpu.ps.replication.PSUnavailable` naming the
    endpoint and shard when a server stays unreachable,
    :class:`~paddle_tpu.ps.replication.ShardMapStale` when the shard map
    can't catch up to the epoch a server demands, TimeoutError naming
    the endpoint on a barrier timeout. A failed RPC always DROPS its
    socket — v1 cached the half-written stream and the next call read
    garbage from the desynced connection.

    Replicated mode (``kv=`` + ``job=``): endpoints come from the
    epoch-versioned shard map published in the coordination KV store;
    on a primary failure the client refreshes the map (bounded), fails
    over to the promoted backup, and REPLAYS the in-flight request —
    write frames carry (client, seq) so a replay of an update the dead
    primary already replicated is deduplicated server-side, never
    double-applied. Counter: ``ps_failovers``.
    """

    def __init__(self, endpoints: Optional[Sequence[str]] = None, *,
                 kv=None, job: str = "ps",
                 rpc_timeout: Optional[float] = None,
                 connect_timeout: float = 5.0,
                 max_attempts: Optional[int] = None,
                 failover_timeout: float = 30.0,
                 client_id: Optional[int] = None,
                 codec: Optional[str] = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep):
        from ..distributed.http_kv import KVClient
        from .replication import fetch_shard_map

        if endpoints is None and kv is None:
            raise ValueError("PSClient needs endpoints= or kv=")
        # quantized wire codec for PUSH/PULL value payloads ("int8" |
        # "bf16" | "f32"): ctor arg, else PADDLE_PS_QUANT, else f32 —
        # PADDLE_QUANT_ALLREDUCE=0 pins the escape leg here too (ONE
        # switch restores the whole f32 baseline, DP step + PS wire)
        if codec is None:
            codec = os.environ.get("PADDLE_PS_QUANT", "f32").strip() \
                .lower() or "f32"
            if codec in ("0", "off", "false"):
                codec = "f32"
        if os.environ.get("PADDLE_QUANT_ALLREDUCE", "").strip() in (
                "0", "off", "false"):
            codec = "f32"
        if codec not in CODEC_IDS:
            raise ValueError(f"PSClient codec {codec!r}: expected "
                             "f32|bf16|int8")
        self._codec = codec
        self._codec_id = CODEC_IDS[codec]
        self._kv = (KVClient(kv, sleep=sleep) if isinstance(kv, str)
                    else kv)
        self._job = str(job)
        self._clock = clock
        self._sleep = sleep
        self._connect_timeout = float(connect_timeout)
        self._rpc_timeout = (rpc_timeout if rpc_timeout is not None
                             else _env_float("PADDLE_PS_RPC_TIMEOUT", 30.0))
        self._failover_timeout = float(failover_timeout)
        self._max_attempts = (max_attempts if max_attempts is not None
                              else env_max_attempts(3))
        # the repo-wide retry policy object: transient socket failures
        # only — typed error frames (PSReplyError) are verdicts, never
        # blind-retried
        self._retrier = Retrier(
            max_attempts=self._max_attempts,
            retry_on=(ConnectionError, OSError),
            backoff=env_backoff(0.05, 1.0), sleep=sleep, name="ps")
        self._map = None
        if endpoints is not None:
            self._eps = list(endpoints)
            self._epoch = 0
        else:
            self._map = fetch_shard_map(self._kv, self._job)
            if self._map is None:
                from .replication import wait_shard_map
                self._map = wait_shard_map(
                    self._kv, self._job, timeout=self._failover_timeout,
                    clock=clock, sleep=sleep)
            self._eps = [g[0] for g in self._map.groups]
            self._epoch = self._map.epoch
        self._socks: List[Optional[socket.socket]] = [None] * len(self._eps)
        self._locks = [threading.Lock() for _ in self._eps]
        self._client_id = int(client_id if client_id is not None
                              else _fresh_client_id())
        self._wseq = itertools.count(1)
        self._wseq_lock = threading.Lock()
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        self._hb_error: Optional[BaseException] = None

    # -- topology -----------------------------------------------------------
    @property
    def endpoints(self) -> List[str]:
        return list(self._eps)

    @property
    def epoch(self) -> int:
        """Shard-map epoch this client is acting in (0 = static mode)."""
        return self._epoch

    @property
    def replicated(self) -> bool:
        return self._kv is not None

    def _adopt_map(self, m) -> None:
        if m.num_shards != len(self._eps):
            raise ValueError(
                f"shard map epoch {m.epoch} has {m.num_shards} shards, "
                f"client was built for {len(self._eps)} — shard count is "
                "fixed for a job's lifetime")
        self._map, self._epoch = m, m.epoch
        for k, group in enumerate(m.groups):
            if group[0] != self._eps[k]:
                self._eps[k] = group[0]
                self._drop(k)

    def refresh_shard_map(self, min_epoch: int = 0,
                          timeout: Optional[float] = None) -> int:
        """Re-read the shard map, blocking (bounded) until its epoch is
        at least ``min_epoch``; returns the adopted epoch. Raises
        ShardMapStale when the map can't catch up in time."""
        from .replication import wait_shard_map

        if self._kv is None:
            from .replication import ShardMapStale
            raise ShardMapStale(
                "static-endpoint PSClient has no shard map to refresh",
                expected_epoch=min_epoch, observed=self._epoch)
        m = wait_shard_map(
            self._kv, self._job, min_epoch=min_epoch,
            timeout=self._failover_timeout if timeout is None else timeout,
            clock=self._clock, sleep=self._sleep)
        self._adopt_map(m)
        return self._epoch

    # -- sockets ------------------------------------------------------------
    def _sock(self, i: int) -> socket.socket:
        if self._socks[i] is None:
            host, port = self._eps[i].rsplit(":", 1)
            s = socket.create_connection((host, int(port)),
                                         timeout=self._connect_timeout)
            s.settimeout(self._rpc_timeout or None)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._socks[i] = s
        return self._socks[i]

    def _drop(self, i: int) -> None:
        s = self._socks[i]
        self._socks[i] = None
        if s is not None:
            try:
                s.close()
            except OSError:
                pass

    # -- core exchange ------------------------------------------------------
    def _shard(self, ids: np.ndarray):
        srv = (ids * np.int64(0x9E3779B1) % np.int64(2**31)) % len(self._eps)
        return [np.nonzero(srv == k)[0] for k in range(len(self._eps))]

    def _next_wseq(self) -> int:
        with self._wseq_lock:
            return next(self._wseq)

    @property
    def codec(self) -> str:
        """Wire codec for PUSH/PULL value payloads."""
        return self._codec

    def _frame(self, op: int, table_id: int, n: int, lr: float,
               dim: int, seq: int, payload: bytes,
               codec: int = 0) -> bytes:
        # the ambient trace context rides every frame (0s = untraced):
        # read at build time, so a failover replay re-stamps the SAME
        # caller identity onto the fresh primary's frame
        ctx = tracing.current_context()
        w_trace, w_span = ctx.to_wire() if ctx is not None else (0, 0)
        return _HDR.pack(op, table_id, n, lr, self._epoch,
                         self._client_id, seq, dim, w_trace,
                         w_span, codec) + payload

    def _exchange_once(self, k: int, frame: bytes, reader, fp_name: str):
        _fault.point(fp_name)
        s = self._sock(k)
        t0 = time.perf_counter()
        try:
            s.sendall(frame)
            _read_reply(s, endpoint=self._eps[k])
            out = reader(s) if reader is not None else None
            # RPC round-trip histogram, per successful attempt, labeled
            # by fault-point name (ps.pull/ps.push/...): the PS latency
            # truth the /metrics scrape derives p50/p99 from
            _rpc_hist().observe((time.perf_counter() - t0) * 1e3,
                                op=fp_name)
            return out
        except PSReplyError:
            raise          # semantic error frame: stream is still in sync
        except (ConnectionError, OSError):
            # any transport failure poisons the stream: drop the socket
            # so the retry/replay runs on a fresh connection
            self._drop(k)
            raise

    def _exchange(self, k: int, frame: bytes, reader, fp_name: str,
                  retry: bool = True):
        """One RPC through the repo ``fault.Retrier`` (its counters
        ``retry_attempts``/``retry_giveups`` plus the PS-scoped
        ``ps_rpc_retries`` per re-attempt); transport exhaustion exits
        typed as PSUnavailable naming the endpoint and shard."""
        from .replication import PSUnavailable

        first = True

        def once():
            nonlocal first
            if not first:
                _bump("ps_rpc_retries")
            first = False
            return self._exchange_once(k, frame, reader, fp_name)

        try:
            return self._retrier.call(once) if retry else once()
        except PSReplyError:
            raise
        except (ConnectionError, OSError) as e:
            attempts = self._retrier.max_attempts if retry else 1
            err = PSUnavailable(
                f"pserver {self._eps[k]} (shard {k}) unreachable after "
                f"{attempts} attempt(s): {e!r}",
                endpoint=self._eps[k], shard=k)
            note_typed_error(err, where=fp_name)
            raise err from e

    def _shard_call(self, k: int, build, reader, fp_name: str,
                    retry: bool = True, failover: bool = True):
        """One logical RPC against shard ``k``: ``build()`` re-packs the
        frame with the CURRENT epoch (the write seq inside it is fixed,
        so a replay after failover dedups server-side). Chases at most a
        few promotions before giving up typed."""
        from .replication import (PSRequestError, PSUnavailable,
                                  ShardMapStale)

        with self._locks[k]:
            for _hop in range(4):
                try:
                    return self._exchange(k, build(), reader, fp_name,
                                          retry=retry)
                except PSReplyError as e:
                    if e.code in (ERR_STALE_EPOCH, ERR_NOT_PRIMARY) \
                            and self.replicated:
                        # the server is ahead (promotion happened) or we
                        # reached a demoted backup: adopt the newer map
                        # and replay against the current primary
                        self._drop(k)
                        self.refresh_shard_map(
                            min_epoch=max(e.epoch, self._epoch + 1))
                        continue
                    if e.code == ERR_STALE_EPOCH:
                        raise ShardMapStale(
                            f"pserver {self._eps[k]} is at epoch "
                            f"{e.epoch}, this client at {self._epoch} "
                            "with no shard map to refresh",
                            expected_epoch=e.epoch,
                            observed=self._epoch) from e
                    if e.code == ERR_BARRIER_TIMEOUT:
                        raise TimeoutError(
                            f"ps barrier timed out at {self._eps[k]}: "
                            f"{e.message}") from e
                    raise PSRequestError(
                        f"pserver {self._eps[k]} rejected the request: "
                        f"{e.message}", code=e.code,
                        endpoint=self._eps[k]) from e
                except PSUnavailable as e:
                    if self.replicated and failover:
                        self._failover(k, e)
                        continue
                    raise
            raise ShardMapStale(
                f"shard {k} kept moving (epoch now {self._epoch}) — "
                "gave up chasing promotions",
                expected_epoch=self._epoch + 1, observed=self._epoch)

    def _failover(self, k: int, cause: BaseException) -> None:
        """Primary for shard ``k`` is gone: wait (bounded) for the
        coordinator to publish a map that moves the shard off the dead
        endpoint, adopt it, and let the caller replay."""
        from .replication import PSUnavailable, fetch_shard_map

        _bump("ps_failovers")
        dead = self._eps[k]
        deadline = self._clock() + self._failover_timeout
        backoff = Backoff(base=0.05, factor=1.5, cap=1.0, jitter=0.25)
        attempt = 0
        while True:
            m = fetch_shard_map(self._kv, self._job)
            if m is not None and (m.epoch > self._epoch
                                  or m.groups[k][0] != dead):
                self._adopt_map(m)
                return
            if self._clock() >= deadline:
                err = PSUnavailable(
                    f"pserver {dead} (shard {k}) died and no promotion "
                    f"was published within {self._failover_timeout}s",
                    endpoint=dead, shard=k)
                note_typed_error(err, where="ps.failover")
                raise err from cause
            self._sleep(min(backoff.delay(attempt),
                            max(0.0, deadline - self._clock())))
            attempt += 1

    # -- data-plane API -----------------------------------------------------
    def pull(self, table_id: int, ids, dim: int) -> np.ndarray:
        ids = np.ascontiguousarray(ids, np.int64).ravel()
        out = np.empty((ids.size, dim), np.float32)
        cid, cname = self._codec_id, self._codec
        for k, sel in enumerate(self._shard(ids)):
            if sel.size == 0:
                continue
            payload = ids[sel].tobytes()

            def build(k=k, sel=sel, payload=payload):
                return self._frame(OP_PULL, table_id, sel.size, 0.0,
                                   dim, 0, payload, codec=cid)

            nb = encoded_nbytes(sel.size * dim, cname)
            raw = self._shard_call(
                k, build, lambda s, m=nb: _recv_exact(s, m), "ps.pull")
            if cid:
                self._count_quant(sel.size * dim, nb)
                out[sel] = np_decode(raw, sel.size * dim,
                                     cname).reshape(sel.size, dim)
            else:
                out[sel] = np.frombuffer(raw, np.float32).reshape(
                    sel.size, dim)
        return out

    @staticmethod
    def _count_quant(n_elems: int, encoded: int) -> None:
        _bump("comm_quant_bytes_sent", encoded)
        _bump("comm_quant_bytes_saved", 4 * n_elems - encoded)

    def _send_vals(self, op: int, table_id: int, ids, vals, dim: int,
                   lr: float):
        ids = np.ascontiguousarray(ids, np.int64).ravel()
        vals = np.ascontiguousarray(vals, np.float32).reshape(ids.size, dim)
        # only PUSH payloads quantize: a MERGE/ASSIGN is state transfer
        # (geo deltas / catch-up overwrites), not a gradient
        cid = self._codec_id if op == OP_PUSH else 0
        cname = self._codec if cid else "f32"
        for k, sel in enumerate(self._shard(ids)):
            if sel.size == 0:
                continue
            if cid:
                enc = np_encode(vals[sel], cname)
                self._count_quant(sel.size * dim, len(enc))
                payload = ids[sel].tobytes() + enc
            else:
                payload = ids[sel].tobytes() + vals[sel].tobytes()
            # seq is drawn on the FIRST build() call — inside the shard
            # lock — so allocation order matches send order: drawing it
            # out here would let a concurrent pusher send a higher seq
            # first and the server's high-watermark dedup silently drop
            # this write as a "replay". Fixed across failover replays.
            state = {"seq": None}

            def build(k=k, sel=sel, payload=payload, state=state):
                if state["seq"] is None:
                    state["seq"] = self._next_wseq()
                return self._frame(op, table_id, sel.size, lr, dim,
                                   state["seq"], payload, codec=cid)

            self._shard_call(k, build, None, "ps.push")

    def push(self, table_id: int, ids, grads, dim: int, lr: float):
        self._send_vals(OP_PUSH, table_id, ids, grads, dim, lr)

    def merge_add(self, table_id: int, ids, deltas, dim: int):
        self._send_vals(OP_MERGE, table_id, ids, deltas, dim, 0.0)

    def assign(self, table_id: int, ids, values, dim: int):
        self._send_vals(OP_ASSIGN, table_id, ids, values, dim, 0.0)

    def rows(self, table_id: int) -> int:
        total = 0
        for k in range(len(self._eps)):
            def build(k=k):
                return self._frame(OP_ROWS, table_id, 0, 0.0, 0, 0, b"")

            raw = self._shard_call(k, build,
                                   lambda s: _recv_exact(s, 8), "ps.pull")
            total += struct.unpack("<Q", raw)[0]
        return total

    def keys(self, table_id: int, shard: int) -> np.ndarray:
        """All ids held by one shard (replication catch-up / tooling)."""
        def build():
            return self._frame(OP_KEYS, table_id, 0, 0.0, 0, 0, b"")

        def read(s):
            count = struct.unpack("<Q", _recv_exact(s, 8))[0]
            return np.frombuffer(_recv_exact(s, 8 * count), np.int64)

        return self._shard_call(shard, build, read, "ps.pull")

    def save(self, table_id: int, path_prefix: str):
        for k in range(len(self._eps)):
            p = f"{path_prefix}.shard{k}".encode()

            def build(k=k, p=p):
                return self._frame(OP_SAVE, table_id, len(p), 0.0, 0,
                                   0, p)

            self._shard_call(k, build, None, "ps.save")

    def snapshot_shards(self, timeout: Optional[float] = None) -> List[int]:
        """Ask every shard's primary to commit a crash-safe SnapshotStore
        snapshot of its tables (ReplicatedPSServer only). Returns the
        committed sequence number per shard."""
        seqs = []
        for k in range(len(self._eps)):
            def build(k=k):
                return self._frame(OP_SNAPSHOT, 0, 0, 0.0, 0, 0, b"")

            raw = self._shard_call(k, build,
                                   lambda s: _recv_exact(s, 8), "ps.save")
            seqs.append(struct.unpack("<Q", raw)[0])
        return seqs

    def shard_seq(self, shard: int):
        """(applied_seq, epoch) of one shard's server — replication lag /
        catch-up introspection."""
        def build():
            return self._frame(OP_SEQ, 0, 0, 0.0, 0, 0, b"")

        raw = self._shard_call(shard, build,
                               lambda s: _recv_exact(s, 12), "ps.pull",
                               failover=False)
        return struct.unpack("<QI", raw)

    def barrier(self):
        """All-trainer barrier on every pserver. Single attempt per
        endpoint (a blind retry would double-count this trainer and
        desync the barrier for everyone); a timed-out barrier raises
        TimeoutError NAMING the endpoint — and the server has reset the
        barrier, so the next round starts clean."""
        errors: List[BaseException] = []

        def one(k):
            try:
                def build():
                    return self._frame(OP_BARRIER, 0, 0, 0.0, 0, 0, b"")

                self._shard_call(k, build, None, "ps.barrier",
                                 retry=False, failover=False)
            except BaseException as e:   # noqa: B036 (re-raised below)
                errors.append(e)

        threads = [threading.Thread(target=one, args=(k,))
                   for k in range(len(self._eps))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]

    # -- liveness -----------------------------------------------------------
    def heartbeat(self, trainer_id: int, status: int = 0):
        """Beat every pserver (reference HeartbeatRPC; status 0=running,
        1=completed — see ps/heartbeat.py)."""
        for k in range(len(self._eps)):
            def build(k=k):
                return self._frame(OP_HEARTBEAT, trainer_id, status,
                                   0.0, 0, 0, b"")

            self._shard_call(k, build, None, "ps.heartbeat", retry=False,
                             failover=False)

    @property
    def heartbeat_error(self) -> Optional[BaseException]:
        """Last parked beat failure (None while beats land). A beat loop
        never dies silently — it backs off and keeps trying."""
        return self._hb_error

    def start_heartbeat(self, trainer_id: int, interval_s: float = 10.0):
        """Background beat thread. The loop retries with capped
        exponential backoff on transient failures instead of silently
        exiting on the first ConnectionError — a dead beat thread gets
        the trainer flagged lost by the pserver monitor even though the
        trainer is healthy (the PR 7 elastic lesson). Errors park on
        ``heartbeat_error`` and clear on the next successful beat."""
        if self._hb_thread is not None and self._hb_thread.is_alive():
            return
        backoff = Backoff(base=min(1.0, interval_s), factor=1.5,
                          cap=max(interval_s, 1.0), jitter=0.25)

        def loop():
            fails = 0
            while True:
                delay = (interval_s if fails == 0
                         else backoff.delay(fails - 1))
                if self._hb_stop.wait(delay):
                    return
                try:
                    self.heartbeat(trainer_id)
                    fails = 0
                    self._hb_error = None
                except (ConnectionError, OSError) as e:
                    fails += 1
                    self._hb_error = e
                    _bump("ps_rpc_retries")
                except BaseException as e:  # noqa: B036 (parked, typed)
                    # typed verdicts (PSUnavailable after retries, ...)
                    # park too: the beat loop survives a failover window
                    # and resumes against the promoted primary
                    fails += 1
                    self._hb_error = e

        self.heartbeat(trainer_id)
        self._hb_thread = threading.Thread(target=loop, daemon=True)
        self._hb_thread.start()

    def stop_heartbeat(self, trainer_id: Optional[int] = None,
                       completed: bool = True):
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5)
            self._hb_thread = None
        self._hb_stop = threading.Event()
        if trainer_id is not None and completed:
            try:
                self.heartbeat(trainer_id, status=1)
            except BaseException:  # noqa: B036 (best-effort farewell)
                pass

    # -- lifecycle ----------------------------------------------------------
    def stop_servers(self):
        for k in range(len(self._eps)):
            try:
                def build(k=k):
                    return self._frame(OP_STOP, 0, 0, 0.0, 0, 0, b"")

                self._shard_call(k, build, None, "ps.stop", retry=False,
                                 failover=False)
            except BaseException:  # noqa: B036 (best-effort shutdown)
                pass

    def close(self):
        for k in range(len(self._socks)):
            self._drop(k)
