"""Trainer liveness tracking for the parameter server.

Parity with /root/reference/paddle/fluid/operators/distributed/
heart_beat_monitor.{h,cc}: every trainer beats periodically; a monitor
thread on the pserver walks the table and flags trainers whose last beat
is older than the timeout (the reference logs ERROR and, for the chief
trainer 0, aborts the job). Here the policy is injectable via `on_dead`.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

RUNNING = 0
COMPLETED = 1


class HeartBeatMonitor:
    """Tracks last-beat timestamps per trainer (heart_beat_monitor.cc:60
    Update / :80 LostWorkerMonitor loop)."""

    def __init__(self, num_trainers: int, timeout_s: float = 120.0,
                 check_interval_s: float = 1.0,
                 on_dead: Optional[Callable[[int], None]] = None,
                 clock: Callable[[], float] = time.monotonic):
        self._timeout = float(timeout_s)
        self._interval = float(check_interval_s)
        self._on_dead = on_dead
        # injectable clock: every timestamp and expiry comparison runs
        # on it, so tests (and distributed.elastic, which mirrors KV
        # lease observations here) drive expiry with a fake clock and
        # check_now() — no real sleeps, no monitor thread needed
        self._clock = clock
        self._lock = threading.Lock()
        self._beats: Dict[int, float] = {}
        self._status: Dict[int, int] = {}
        self._dead: set = set()
        # supervisor integration (attach_supervisor): re-fire on_dead
        # every timeout period while a rank stays silent, so a
        # relaunched incarnation that hangs before its first beat is
        # not lost. Plain on_dead users keep the one-shot contract.
        self._refire = False
        self._last_fired: Dict[int, float] = {}
        self._num_trainers = int(num_trainers)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- updates ------------------------------------------------------------
    def update(self, trainer_id: int, status: int = RUNNING):
        with self._lock:
            self._beats[trainer_id] = self._clock()
            self._status[trainer_id] = status
            self._dead.discard(trainer_id)
            self._last_fired.pop(trainer_id, None)

    def attach_supervisor(self, supervisor) -> "HeartBeatMonitor":
        """Route dead-trainer events into a distributed.launch
        Supervisor: a trainer whose beat lapses past the timeout is
        terminated and relaunched under the supervisor's restart budget
        (the reference heart_beat_monitor.cc only logs, and aborts the
        whole job for the chief; here recovery is the default policy).

        Two-way wiring: re-firing is enabled (a relaunched incarnation
        that hangs before its first beat gets flagged again after a
        fresh timeout), and every supervisor (re)launch refreshes the
        rank's beat so the new process has a full timeout of grace —
        without that, a re-fire racing a slow relaunch would SIGTERM the
        fresh incarnation and drain the restart budget on a healthy
        job."""
        self._on_dead = supervisor.notify_dead
        self._refire = True
        register = getattr(supervisor, "on_relaunch", None)
        if register is not None:
            register(self.update)
        return self

    # -- queries ------------------------------------------------------------
    def alive(self, trainer_id: int) -> bool:
        with self._lock:
            if self._status.get(trainer_id) == COMPLETED:
                return True
            t = self._beats.get(trainer_id)
            return t is not None and \
                self._clock() - t <= self._timeout and \
                trainer_id not in self._dead

    def dead_trainers(self) -> List[int]:
        with self._lock:
            return sorted(self._dead)

    def leases(self) -> Dict[int, float]:
        """Liveness view as lease expiries: trainer -> the clock value
        past which it counts as dead (last beat + timeout). The shape
        distributed.elastic's KV leases use, so the agent's monitor and
        a pserver-side monitor read identically."""
        with self._lock:
            return {tid: t + self._timeout
                    for tid, t in self._beats.items()}

    def completed_trainers(self) -> List[int]:
        with self._lock:
            return sorted(t for t, s in self._status.items()
                          if s == COMPLETED)

    def all_completed(self) -> bool:
        with self._lock:
            done = sum(1 for s in self._status.values() if s == COMPLETED)
            return done >= self._num_trainers

    # -- monitor loop --------------------------------------------------------
    def start(self):
        if self._thread is not None:
            return
        # a restarted monitor must actually sweep: stop() left the event
        # set, and _loop's first wait() would exit the thread immediately
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while not self._stop.wait(self._interval):
            self.check_now()

    def check_now(self) -> List[int]:
        """One expiry sweep (the _loop body, callable without the
        thread): flag trainers whose last beat is older than the
        timeout and fire on_dead for each. Returns the newly-flagged
        ids. Tests and injectable-clock users drive this directly —
        advance the clock, call check_now(), observe the policy."""
        now = self._clock()
        newly_dead = []
        with self._lock:
            for tid, t in self._beats.items():
                if self._status.get(tid) == COMPLETED:
                    continue
                flagged = tid in self._dead
                if flagged and not self._refire:
                    continue   # one-shot contract for plain users
                since = max(t, self._last_fired.get(tid, t))
                if now - since > self._timeout:
                    self._dead.add(tid)
                    self._last_fired[tid] = now
                    newly_dead.append(tid)
        for tid in newly_dead:
            if self._on_dead is not None:
                self._on_dead(tid)
        return newly_dead

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
