"""SparseTable: host-RAM sparse parameter table.

Python face of native/src/sparse_kv.cc (reference large_scale_kv.h
SparseVariable + pslib tables — see the .cc header for the mapping).
Falls back to a pure-numpy dict implementation when no C++ toolchain is
available; both paths share deterministic init so mixed deployments
agree.
"""
from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional

import numpy as np

SGD = 0
ADAGRAD = 1

_OPT_NAMES = {"sgd": SGD, "adagrad": ADAGRAD}


def _kv_lib():
    from ..native import load_library

    lib = load_library("sparse_kv")
    if lib is not None and not getattr(lib, "_pt_typed", False):
        c = ctypes
        lib.kv_create.restype = c.c_void_p
        lib.kv_create.argtypes = [c.c_int64, c.c_int, c.c_float, c.c_uint64]
        lib.kv_destroy.argtypes = [c.c_void_p]
        lib.kv_dim.restype = c.c_int64
        lib.kv_dim.argtypes = [c.c_void_p]
        lib.kv_rows.restype = c.c_int64
        lib.kv_rows.argtypes = [c.c_void_p]
        ptr_i64 = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
        ptr_f32 = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
        lib.kv_pull.argtypes = [c.c_void_p, ptr_i64, c.c_int64, ptr_f32]
        lib.kv_push.argtypes = [c.c_void_p, ptr_i64, c.c_int64, ptr_f32,
                                c.c_float]
        lib.kv_assign.argtypes = [c.c_void_p, ptr_i64, c.c_int64, ptr_f32]
        lib.kv_merge_add.argtypes = [c.c_void_p, ptr_i64, c.c_int64, ptr_f32]
        lib.kv_keys.restype = c.c_int64
        lib.kv_keys.argtypes = [c.c_void_p, ptr_i64, c.c_int64]
        lib.kv_save.restype = c.c_int
        lib.kv_save.argtypes = [c.c_void_p, c.c_char_p]
        lib.kv_load.restype = c.c_int
        lib.kv_load.argtypes = [c.c_void_p, c.c_char_p]
        lib._pt_typed = True
    return lib


def _splitmix64(x):
    x = (x + 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
    return x ^ (x >> 31)


class SparseTable:
    """dim-wide rows keyed by int64 id; rows materialize on first pull with
    deterministic uniform(-init_range, init_range) values; push applies the
    entry optimizer (sgd / adagrad)."""

    def __init__(self, dim: int, optimizer: str = "sgd",
                 init_range: float = 0.01, seed: int = 0,
                 force_python: bool = False):
        self.dim = int(dim)
        self.optimizer = _OPT_NAMES[optimizer.lower()]
        self.init_range = float(init_range)
        self.seed = int(seed)
        self._lock = threading.Lock()
        self._lib = None if force_python else _kv_lib()
        if self._lib is not None:
            self._h = self._lib.kv_create(self.dim, self.optimizer,
                                          self.init_range, self.seed)
        else:
            self._rows = {}   # id -> np row (value [+ adagrad accum])

    # -- python fallback helpers --------------------------------------------
    def _py_width(self):
        return 2 * self.dim if self.optimizer == ADAGRAD else self.dim

    def _py_row(self, i):
        row = self._rows.get(i)
        if row is None:
            row = np.zeros(self._py_width(), np.float32)
            for j in range(self.dim):
                r = _splitmix64(self.seed ^ _splitmix64(
                    (i * 1315423911 + j) & 0xFFFFFFFFFFFFFFFF))
                u = float(r >> 40) / float(1 << 24)
                row[j] = (2.0 * u - 1.0) * self.init_range
            self._rows[i] = row
        return row

    # -- API -----------------------------------------------------------------
    def pull(self, ids) -> np.ndarray:
        # native branch locks too: clear() swaps the C handle, and a
        # pull racing it would execute against freed memory
        ids = np.ascontiguousarray(ids, np.int64).ravel()
        out = np.empty((ids.size, self.dim), np.float32)
        if self._lib is not None:
            with self._lock:
                self._lib.kv_pull(self._h, ids, ids.size, out)
            return out
        with self._lock:
            for k, i in enumerate(ids):
                out[k] = self._py_row(int(i))[: self.dim]
        return out

    def push(self, ids, grads, lr: float):
        ids = np.ascontiguousarray(ids, np.int64).ravel()
        grads = np.ascontiguousarray(grads, np.float32).reshape(
            ids.size, self.dim)
        if self._lib is not None:
            with self._lock:
                self._lib.kv_push(self._h, ids, ids.size, grads,
                                  float(lr))
            return
        with self._lock:
            for k, i in enumerate(ids):
                row = self._py_row(int(i))
                g = grads[k]
                if self.optimizer == ADAGRAD:
                    row[self.dim:] += g * g
                    row[: self.dim] -= (lr * g /
                                        np.sqrt(row[self.dim:] + 1e-6))
                else:
                    row[: self.dim] -= lr * g

    def assign(self, ids, values):
        ids = np.ascontiguousarray(ids, np.int64).ravel()
        values = np.ascontiguousarray(values, np.float32).reshape(
            ids.size, self.dim)
        if self._lib is not None:
            with self._lock:
                self._lib.kv_assign(self._h, ids, ids.size, values)
            return
        with self._lock:
            for k, i in enumerate(ids):
                self._py_row(int(i))[: self.dim] = values[k]

    def merge_add(self, ids, deltas):
        """w[id] += delta — the geo-SGD server-side merge."""
        ids = np.ascontiguousarray(ids, np.int64).ravel()
        deltas = np.ascontiguousarray(deltas, np.float32).reshape(
            ids.size, self.dim)
        if self._lib is not None:
            with self._lock:
                self._lib.kv_merge_add(self._h, ids, ids.size, deltas)
            return
        with self._lock:
            for k, i in enumerate(ids):
                self._py_row(int(i))[: self.dim] += deltas[k]

    def keys(self) -> np.ndarray:
        if self._lib is not None:
            with self._lock:   # one lock scope: rows() would re-lock
                n = int(self._lib.kv_rows(self._h))
                out = np.empty(max(n, 1), np.int64)
                got = self._lib.kv_keys(self._h, out, out.size)
            return out[:got]
        with self._lock:
            return np.fromiter(self._rows.keys(), np.int64,
                               len(self._rows))

    def rows(self) -> int:
        if self._lib is not None:
            with self._lock:
                return int(self._lib.kv_rows(self._h))
        return len(self._rows)

    def clear(self):
        """Drop every materialized row (replication full-state transfer
        replaces the table rather than merging into it — a stale row the
        source never held must not survive the sync). The native branch
        swaps the C handle under the lock: a concurrent pull/digest on
        the just-destroyed handle would be a use-after-free."""
        with self._lock:
            if self._lib is not None:
                self._lib.kv_destroy(self._h)
                self._h = self._lib.kv_create(self.dim, self.optimizer,
                                              self.init_range, self.seed)
                return
            self._rows.clear()

    def save(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        if self._lib is not None:
            with self._lock:
                rc = self._lib.kv_save(self._h, path.encode())
            if rc != 0:
                raise IOError(f"kv_save({path}) failed rc={rc}")
            return
        with self._lock, open(path, "wb") as f:
            np.savez(f, dim=self.dim, width=self._py_width(),
                     ids=np.fromiter(self._rows, np.int64,
                                     len(self._rows)),
                     vals=np.stack(list(self._rows.values()))
                     if self._rows else np.zeros((0, self._py_width()),
                                                 np.float32))

    def load(self, path: str):
        if self._lib is not None:
            with self._lock:
                rc = self._lib.kv_load(self._h, path.encode())
            if rc != 0:
                raise IOError(f"kv_load({path}) failed rc={rc}")
            return
        with self._lock, open(path, "rb") as f:
            data = np.load(f)
            for i, v in zip(data["ids"], data["vals"]):
                self._rows[int(i)] = v.astype(np.float32)

    def __del__(self):
        lib = getattr(self, "_lib", None)
        if lib is not None and getattr(self, "_h", None):
            try:
                lib.kv_destroy(self._h)
            except Exception:
                pass
            self._h = None
