"""Quantized wire codecs shared by the PS data plane and the
parallel/collectives quantized all-reduce (which re-exports them).

stdlib + numpy ONLY — ps/ must stay importable without jax (the PR 9
contract: fault/http_kv/ps serve on boxes that never load XLA). The
jnp trace-time encoders in parallel/collectives.py implement the SAME
layout; ``encoded_nbytes`` is the ONE closed form the cost model, the
wire readers on both ends, and the bench probe's comm_bytes_saved_pct
all share.

Layouts (all little-endian, deterministic):
  f32   raw float32 payload (codec id 0 — the pre-codec wire bytes)
  bf16  round-to-nearest-even upper 16 bits of each float32 (id 1)
  int8  per-block symmetric scales: ``nblocks`` float32 scales
        (max-abs/127 over each QUANT_BLOCK-element block, final block
        zero-padded) followed by the int8 payload (id 2)
"""
from __future__ import annotations

import numpy as np

__all__ = [
    "QUANT_BLOCK", "CODEC_IDS", "CODEC_NAMES", "codec_name",
    "encoded_nbytes", "ring_nbytes", "np_encode", "np_decode",
    "jnp_encode_kv_rows", "jnp_decode_kv_rows",
]

#: elements covered by one f32 scale in the blocked int8 encoding —
#: 512 keeps scale overhead at 4/(512*4) < 0.2% of the f32 payload
QUANT_BLOCK = 512

#: wire/codec ids (the PS v2 header's codec byte; 0 keeps the
#: pre-codec frames' zero-filled byte meaning "plain f32")
CODEC_IDS = {"f32": 0, "bf16": 1, "int8": 2}
CODEC_NAMES = {v: k for k, v in CODEC_IDS.items()}


def codec_name(codec_id: int) -> str:
    name = CODEC_NAMES.get(int(codec_id))
    if name is None:
        raise ValueError(f"unknown wire codec id {codec_id}")
    return name


def _nblocks(n: int, block: int = QUANT_BLOCK) -> int:
    return -(-int(n) // int(block))


def encoded_nbytes(n_elems: int, codec: str,
                   block: int = QUANT_BLOCK) -> int:
    """Wire bytes of ``n_elems`` f32 values under ``codec`` — payload
    plus per-block scales."""
    n = int(n_elems)
    if codec == "int8":
        return n + 4 * _nblocks(n, block)
    if codec == "bf16":
        return 2 * n
    if codec == "f32":
        return 4 * n
    raise ValueError(f"unknown codec {codec!r}")


def ring_nbytes(n_elems: int, group: int, codec: str,
                block: int = QUANT_BLOCK) -> int:
    """Per-device wire bytes of a ring all-reduce of ``n_elems`` over
    ``group`` devices: reduce-scatter + all-gather each move
    ``(g-1)/g`` of the encoded payload."""
    g = max(1, int(group))
    if g <= 1:
        return 0
    return int(2 * (g - 1) * encoded_nbytes(n_elems, codec, block) // g)


def np_encode(values: np.ndarray, codec: str,
              block: int = QUANT_BLOCK) -> bytes:
    """Encode a float32 array for the wire; byte count is exactly
    ``encoded_nbytes(values.size, codec)``."""
    vals = np.ascontiguousarray(values, np.float32).reshape(-1)
    if codec == "f32":
        return vals.tobytes()
    if codec == "bf16":
        # bf16 = f32's upper 16 bits, round-to-nearest-even (portable,
        # no ml_dtypes dependency on the jax-free PS side)
        u = vals.view(np.uint32)
        rounded = (u.astype(np.uint64) + 0x7FFF + ((u >> 16) & 1)) >> 16
        return rounded.astype(np.uint16).tobytes()
    if codec != "int8":
        raise ValueError(f"unknown codec {codec!r}")
    n = vals.size
    nb = _nblocks(n, block)
    padded = np.zeros(nb * block, np.float32)
    padded[:n] = vals
    xb = padded.reshape(nb, block)
    amax = np.max(np.abs(xb), axis=1)
    scale = (amax / 127.0).astype(np.float32)
    safe = np.where(scale > 0, scale, 1.0)
    q = np.clip(np.rint(xb / safe[:, None]), -127, 127).astype(np.int8)
    return scale.tobytes() + q.reshape(-1)[:n].tobytes()


def jnp_encode_kv_rows(x):
    """Trace-time int8 encode for KV page writes: one symmetric scale
    per TOKEN ROW — the blocked int8 layout with ``block`` = one row's
    ``H * D`` elements, so ``encoded_nbytes(n, "int8", block=H*D)`` is
    the page's exact byte cost. ``x`` is (..., H, D); returns the int8
    payload (same shape) and the f32 scales (...,). jnp.rint matches
    np_encode's half-even rounding bit for bit.

    Lazy jax import: the module itself stays importable on jax-free PS
    boxes (the PR 9 contract)."""
    import jax.numpy as jnp

    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=(-2, -1))
    scale = (amax / 127.0).astype(jnp.float32)
    safe = jnp.where(scale > 0, scale, 1.0)
    q = jnp.clip(jnp.rint(xf / safe[..., None, None]),
                 -127, 127).astype(jnp.int8)
    return q, scale


def jnp_decode_kv_rows(q, scale):
    """Trace-time dequant twin of :func:`jnp_encode_kv_rows`: int8
    payload (..., H, D) × per-row scales (...,) → f32."""
    import jax.numpy as jnp

    return q.astype(jnp.float32) * scale.astype(jnp.float32)[..., None,
                                                             None]


def np_decode(raw: bytes, n_elems: int, codec: str,
              block: int = QUANT_BLOCK) -> np.ndarray:
    """Decode ``np_encode`` output back to a 1-D float32 array."""
    n = int(n_elems)
    if codec == "f32":
        return np.frombuffer(raw, np.float32, count=n).copy()
    if codec == "bf16":
        u = np.frombuffer(raw, np.uint16, count=n).astype(np.uint32)
        return (u << 16).view(np.float32).copy()
    if codec != "int8":
        raise ValueError(f"unknown codec {codec!r}")
    nb = _nblocks(n, block)
    scale = np.frombuffer(raw, np.float32, count=nb)
    q = np.frombuffer(raw, np.int8, count=n, offset=4 * nb)
    padded = np.zeros(nb * block, np.float32)
    padded[:n] = q.astype(np.float32)
    out = (padded.reshape(nb, block) * scale[:, None]).reshape(-1)
    return out[:n].astype(np.float32)
