"""Parameter-server / large-scale sparse subsystem (SURVEY §2.6: the PS
sync/async/geo family, large_scale_kv, FleetWrapper pull/push). See each
module's docstring for the reference mapping; ps/replication.py for the
fault-tolerance layer (replica groups, shard-map epochs, crash-safe
shard recovery)."""
from .communicator import AsyncCommunicator, GeoCommunicator  # noqa: F401
from .heartbeat import HeartBeatMonitor  # noqa: F401
from .embedding import SparseEmbedding  # noqa: F401
from .replication import (  # noqa: F401
    DeltaLog, PSError, PSRequestError, PSUnavailable, ReplicaCoordinator,
    ReplicaDiverged, ReplicatedPSServer, Replicator, ShardMap,
    ShardMapStale, fetch_shard_map, publish_shard_map, verify_replicas,
    wait_shard_map,
)
from .server import run_server  # noqa: F401
from .service import PSClient, PSServer  # noqa: F401
from .table import SparseTable  # noqa: F401
