"""Parameter-server / large-scale sparse subsystem (SURVEY §2.6: the PS
sync/async/geo family, large_scale_kv, FleetWrapper pull/push). See each
module's docstring for the reference mapping."""
from .communicator import AsyncCommunicator, GeoCommunicator  # noqa: F401
from .heartbeat import HeartBeatMonitor  # noqa: F401
from .embedding import SparseEmbedding  # noqa: F401
from .server import run_server  # noqa: F401
from .service import PSClient, PSServer  # noqa: F401
from .table import SparseTable  # noqa: F401
