"""Fleet-scale decode serving: a router fronting N ``DecodeEngine``
replicas, built so that ONE ENGINE DYING MID-GENERATION IS NOT AN
OUTAGE.

The core trick is chunked dispatch + greedy replay (the PR 13
preemption move, lifted to the fleet): the router asks an engine for at
most ``chunk_tokens`` tokens at a time, folding everything already
emitted into the prompt of the next chunk. Every chunk therefore
either *returned* (tokens are safely router-side) or *failed* (no
tokens surfaced) — so when a replica dies, the bounded
``fault.Retrier`` re-dispatches the chunk on a healthy replica, whose
prefill regenerates the exact same KV (deterministic params, greedy
argmax) and continues the sequence BYTE-IDENTICAL to an unkilled run:
zero tokens lost, zero doubled. The engines' prefix caches make the
replayed prefill cheap (full pages of the folded context share), and
adopted/migrated pages (serving/disagg.py) make it nearly free.

Routing policy, in order:

- **admission** — the ``ServingEngine`` typed taxonomy: ``Overloaded``
  at the in-flight bound (counted ``router_sheds``), ``EngineStopped``
  after drain begins, ``DeadlineExceeded`` pre-checked;
- **health gating** — a replica is routable only while its ``/readyz``
  probe is green (PR 9 probes; local engines answer ``engine.ready``
  directly) and it is not in a post-failure cooldown;
- **SLO shed/scale signal** — an optional :class:`FleetSLOSignal`
  (per-engine burn rates federated through
  ``observability/federation.py``) deprioritizes burning replicas:
  they only serve when every healthy replica burns;
- **session affinity** — requests carrying the same session key (the
  trace id by default) stick to their replica while it stays routable
  (``router_affinity_hits``), keeping the folded-context prefix cache
  hot;
- **least-loaded** — otherwise the replica with the smallest
  ``kv_pages_in_use + queue_weight * queue_depth`` wins.

Everything lands in the declared ``router_*`` counters and the
``router_e2e_ms`` histogram, scraped through every /metrics listener.
"""
from __future__ import annotations

import json
import threading
import time
from collections import Counter as _Counter
from typing import Dict, List, Optional, Sequence, Set

from ..fault import Backoff, Retrier
from ..inference.serving import (DeadlineExceeded, EngineStopped,
                                 Overloaded, RequestFailed, ServingError,
                                 _DualHist)
from ..observability import tracing
from ..observability.flight_recorder import (flight_recorder,
                                             note_typed_error)
from ..observability.metrics import MetricsRegistry

__all__ = [
    "DecodeEngineServer", "FleetRouter", "FleetSLOSignal",
    "HTTPReplica", "LocalReplica", "ReplicaUnroutable",
]

#: typed-error name <-> HTTP status for the engine server wire; the
#: name also travels in the X-Paddle-Error header so the client
#: re-raises the exact type (status codes alone are ambiguous)
_ERROR_STATUS = {
    "Overloaded": 429,
    "DeadlineExceeded": 504,
    "EngineStopped": 503,
    "RequestFailed": 500,
    "MalformedPageFrame": 400,
    "ValueError": 400,
}
_ERROR_TYPES = {
    "Overloaded": Overloaded,
    "DeadlineExceeded": DeadlineExceeded,
    "EngineStopped": EngineStopped,
    "RequestFailed": RequestFailed,
}


class ReplicaUnroutable(RuntimeError):
    """Transport-level replica failure (connection refused/reset, a
    half-written response): the router fails over — never user-visible
    unless every replica is gone."""


# ---------------------------------------------------------------------------
# the engine-side HTTP surface
# ---------------------------------------------------------------------------
class DecodeEngineServer:
    """One decode engine's fleet-facing HTTP listener, riding the
    hardened ``KVHTTPServer`` scaffolding (body cap, per-connection
    timeout, free GET /metrics):

    - GET ``/healthz`` — 200 while the process serves at all;
    - GET ``/readyz`` — 200 only while the engine is warmed and
      admitting (503 while warming or draining);
    - GET ``/stats`` — live load for least-loaded dispatch
      (``kv_pages_in_use``, ``queue_depth``) plus geometry;
    - PUT ``/generate`` — JSON ``{prompt, max_new_tokens, deadline_s}``
      → ``{tokens, ttft_ms}``; typed admission errors map to status
      codes (429/503/504/500) with the type name in ``X-Paddle-Error``;
    - PUT ``/adopt`` — a raw disagg page frame → adoption report
      (400 + ``MalformedPageFrame`` on a bad frame).
    """

    def __init__(self, engine, port: int = 0, host: str = "127.0.0.1",
                 request_timeout: Optional[float] = 30.0,
                 max_body_bytes: int = 64 << 20,
                 result_timeout_s: float = 120.0):
        from ..distributed.http_kv import KVHandler, KVHTTPServer

        def _send_json(handler, code: int, payload: dict,
                       error: Optional[str] = None):
            body = json.dumps(payload).encode("utf-8")
            handler.send_response(code)
            handler.send_header("Content-Type", "application/json")
            handler.send_header("Content-Length", str(len(body)))
            if error is not None:
                handler.send_header("X-Paddle-Error", error)
            handler.end_headers()
            handler.wfile.write(body)

        def _send_typed(handler, e: BaseException):
            name = type(e).__name__
            code = _ERROR_STATUS.get(name, 500)
            _send_json(handler, code,
                       {"error": name, "message": str(e)}, error=name)

        def _read_body(handler) -> Optional[bytes]:
            try:
                n = int(handler.headers.get("Content-Length"))
            except (TypeError, ValueError):
                handler.send_status_code(411)
                handler.close_connection = True
                return None
            if n < 0 or (self._server.max_body_bytes is not None
                         and n > self._server.max_body_bytes):
                handler.send_status_code(413 if n >= 0 else 400)
                handler.close_connection = True
                return None
            return handler.rfile.read(n) if n else b""

        def _generate(handler):
            body = _read_body(handler)
            if body is None:
                return
            try:
                req = json.loads(body.decode("utf-8"))
                prompt = req["prompt"]
                max_new = int(req.get("max_new_tokens", 16))
                deadline_s = req.get("deadline_s")
            except (ValueError, KeyError, TypeError) as e:
                _send_json(handler, 400,
                           {"error": "ValueError",
                            "message": f"bad generate body: {e}"},
                           error="ValueError")
                return
            try:
                h = engine.submit(prompt, max_new, deadline_s=deadline_s)
                timeout = result_timeout_s if deadline_s is None \
                    else float(deadline_s) + 5.0
                tokens = h.result(timeout=timeout)
            except (ServingError, ValueError) as e:
                _send_typed(handler, e)
                return
            except TimeoutError:
                # unresolved handle: a stopped engine never flushes it
                e = EngineStopped("engine stopped mid-request") \
                    if not engine.ready else \
                    RequestFailed("generation timed out in-engine")
                _send_typed(handler, e)
                return
            _send_json(handler, 200,
                       {"tokens": tokens,
                        "ttft_ms": h.meta.get("ttft_ms")})

        def _adopt(handler):
            from .disagg import MalformedPageFrame

            body = _read_body(handler)
            if body is None:
                return
            try:
                report = engine.adopt_pages(body)
            except (MalformedPageFrame, ValueError) as e:
                _send_typed(handler, e)
                return
            _send_json(handler, 200, report)

        def _stats(handler):
            pool = engine.pool
            ctr = engine.counters
            # kv_pages_in_use is HBM-RESIDENT pages only: parked
            # sessions release their device pages into the free list,
            # so an engine with a deep host tier legitimately looks
            # light to the router's load signal — that is the point
            # of the offload tier.
            _send_json(handler, 200, {
                "ready": bool(engine.ready),
                "kv_pages_in_use": pool.pages_in_use,
                "queue_depth": engine.queue_depth,
                "page_size": pool.page_size,
                "max_pages_per_seq": pool.max_pages_per_seq,
                "vocab_size": engine.config.vocab_size,
                "kv_pages_host": int(ctr.get("kv_pages_host", 0)),
                "kv_offload_bytes": int(ctr.get("kv_offload_bytes", 0)),
                "kv_page_restores": int(ctr.get("kv_page_restores", 0)),
                "kv_restore_wait_p99_ms": float(
                    engine.engine_latency_stats().get(
                        "restore_wait_p99_ms", 0.0)),
            })

        class _Handler(KVHandler):
            def do_GET(handler):  # noqa: N805 (handler-local self)
                if handler.path == "/healthz":
                    handler.send_response(200)
                    handler.send_header("Content-Length", "2")
                    handler.end_headers()
                    handler.wfile.write(b"ok")
                    return
                if handler.path == "/readyz":
                    code = 200 if engine.ready else 503
                    msg = b"ready" if code == 200 else b"not ready"
                    handler.send_response(code)
                    handler.send_header("Content-Length",
                                        str(len(msg)))
                    handler.end_headers()
                    handler.wfile.write(msg)
                    return
                if handler.path == "/stats":
                    return _stats(handler)
                KVHandler.do_GET(handler)

            def do_PUT(handler):  # noqa: N805
                if handler.path == "/generate":
                    return _generate(handler)
                if handler.path == "/adopt":
                    return _adopt(handler)
                KVHandler.do_PUT(handler)

        self.engine = engine
        self._server = KVHTTPServer(port, _Handler, host=host,
                                    max_body_bytes=max_body_bytes,
                                    request_timeout=request_timeout)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    @property
    def endpoint(self) -> str:
        host, port = self._server.server_address[:2]
        return f"{host}:{port}"

    def start(self) -> "DecodeEngineServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="decode-engine-server")
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is not None:
            self._server.shutdown()
            self._thread.join()
            self._thread = None
        self._server.server_close()


# ---------------------------------------------------------------------------
# replicas: the router's uniform view of an engine
# ---------------------------------------------------------------------------
class LocalReplica:
    """An in-process ``DecodeEngine`` behind the replica interface —
    what tests, the bench probe, and ``load_gen --fleet`` route to."""

    def __init__(self, engine, name: Optional[str] = None):
        self.engine = engine
        self.name = name or f"local:{id(engine) & 0xFFFF:04x}"

    def ready(self) -> bool:
        return bool(self.engine.ready)

    def load(self) -> Optional[tuple]:
        return (self.engine.pool.pages_in_use, self.engine.queue_depth)

    def generate_chunk(self, prompt: Sequence[int], max_new: int,
                       deadline_s: Optional[float]) -> List[int]:
        h = self.engine.submit(prompt, max_new, deadline_s=deadline_s)
        limit = time.monotonic() + (120.0 if deadline_s is None
                                    else float(deadline_s) + 5.0)
        while True:
            try:
                return h.result(timeout=0.05)
            except TimeoutError:
                if not self.engine.ready and not h.done():
                    # a stopped/draining engine never flushes the
                    # handle — surface it as the typed death the
                    # router fails over on
                    raise EngineStopped(
                        f"engine behind {self.name} stopped "
                        "mid-chunk") from None
                if time.monotonic() >= limit:
                    raise RequestFailed(
                        f"chunk timed out on {self.name}") from None

    def adopt(self, frame: bytes) -> dict:
        return self.engine.adopt_pages(frame)

    def drain(self, timeout: Optional[float] = None) -> bool:
        return self.engine.drain(timeout=timeout)

    def stop(self) -> None:
        self.engine.stop()


class HTTPReplica:
    """A remote engine behind its :class:`DecodeEngineServer`, with the
    readiness probe result cached for ``probe_ttl_s`` so per-chunk
    dispatch doesn't double every request's HTTP round-trips."""

    def __init__(self, endpoint: str, timeout_s: float = 30.0,
                 probe_ttl_s: float = 0.5, clock=time.monotonic):
        endpoint = endpoint.replace("http://", "").rstrip("/")
        host, _, port = endpoint.rpartition(":")
        self.host, self.port = host or "127.0.0.1", int(port)
        self.name = f"{self.host}:{self.port}"
        self.timeout_s = float(timeout_s)
        self._probe_ttl = float(probe_ttl_s)
        self._clock = clock
        self._probe: Optional[tuple] = None   # (t, ready)

    def _request(self, method: str, path: str,
                 body: Optional[bytes] = None,
                 timeout: Optional[float] = None):
        import http.client

        conn = http.client.HTTPConnection(
            self.host, self.port,
            timeout=self.timeout_s if timeout is None else timeout)
        try:
            conn.request(method, path, body=body)
            resp = conn.getresponse()
            return resp.status, resp.read(), \
                resp.getheader("X-Paddle-Error")
        except (OSError, http.client.HTTPException) as e:
            raise ReplicaUnroutable(
                f"{self.name}: {type(e).__name__}: {e}") from e
        finally:
            conn.close()

    def _raise_typed(self, status: int, data: bytes,
                     err: Optional[str]):
        try:
            msg = json.loads(data.decode("utf-8")).get("message", "")
        except (ValueError, AttributeError):
            msg = data.decode("utf-8", "replace")[:200]
        cls = _ERROR_TYPES.get(err or "")
        if cls is None:
            cls = {429: Overloaded, 503: EngineStopped,
                   504: DeadlineExceeded}.get(status, RequestFailed)
        raise cls(f"{self.name}: {msg or f'HTTP {status}'}")

    def ready(self) -> bool:
        now = self._clock()
        if self._probe is not None \
                and now - self._probe[0] < self._probe_ttl:
            return self._probe[1]
        try:
            status, _, _ = self._request("GET", "/readyz", timeout=2.0)
            up = status == 200
        except ReplicaUnroutable:
            up = False
        self._probe = (now, up)
        return up

    def load(self) -> Optional[tuple]:
        try:
            status, data, _ = self._request("GET", "/stats",
                                            timeout=2.0)
            if status != 200:
                return None
            stats = json.loads(data.decode("utf-8"))
            return (int(stats.get("kv_pages_in_use", 0)),
                    int(stats.get("queue_depth", 0)))
        except (ReplicaUnroutable, ValueError):
            return None

    def generate_chunk(self, prompt: Sequence[int], max_new: int,
                       deadline_s: Optional[float]) -> List[int]:
        body = json.dumps({
            "prompt": [int(t) for t in prompt],
            "max_new_tokens": int(max_new),
            "deadline_s": deadline_s,
        }).encode("utf-8")
        status, data, err = self._request(
            "PUT", "/generate", body=body,
            timeout=self.timeout_s if deadline_s is None
            else float(deadline_s) + 10.0)
        if status != 200:
            self._raise_typed(status, data, err)
        try:
            return [int(t) for t in
                    json.loads(data.decode("utf-8"))["tokens"]]
        except (ValueError, KeyError, TypeError) as e:
            raise ReplicaUnroutable(
                f"{self.name}: unparseable generate response: "
                f"{e}") from e

    def adopt(self, frame: bytes) -> dict:
        from .disagg import MalformedPageFrame

        status, data, err = self._request("PUT", "/adopt", body=frame)
        if status != 200:
            if err == "MalformedPageFrame":
                raise MalformedPageFrame(
                    data.decode("utf-8", "replace")[:200])
            self._raise_typed(status, data, err)
        return json.loads(data.decode("utf-8"))

    def drain(self, timeout: Optional[float] = None) -> bool:
        # the remote process owns its lifecycle (SIGTERM drain); the
        # router draining itself only needs its OWN in-flight flushed
        return True

    def stop(self) -> None:
        pass


# ---------------------------------------------------------------------------
# SLO-burn shed/scale signal
# ---------------------------------------------------------------------------
class FleetSLOSignal:
    """Per-engine burn rates as the router's shed/scale signal: each
    engine's /metrics endpoint is federated through
    ``FederatedMetrics`` (instance labels injected), one latency + one
    error-rate objective per engine evaluate over the merged scrapes,
    and :meth:`burning` names the endpoints whose error budget is
    burning — the router deprioritizes them, and :meth:`scale_hint`
    is the autoscaler-facing summary."""

    def __init__(self, targets: Sequence[str],
                 threshold_ms: float = 2500.0,
                 max_error_ratio: float = 0.05,
                 windows=None, clock=time.time, fetch=None):
        from ..observability.federation import FederatedMetrics
        from ..observability.slo import (DEFAULT_WINDOWS, Objective,
                                         SLOEvaluator)

        self.targets = [str(t) for t in targets]
        self._fed = FederatedMetrics(self.targets, clock=clock,
                                     fetch=fetch)
        objectives = []
        self._by_objective: Dict[str, str] = {}
        for t in self.targets:
            o_lat = Objective(f"decode_e2e_p99@{t}",
                              hist="decode_e2e_ms", percentile=99.0,
                              threshold_ms=threshold_ms, instance=t)
            o_err = Objective(f"decode_errors@{t}",
                              numerator="decode_failed",
                              denominator="decode_requests",
                              max_ratio=max_error_ratio, instance=t)
            objectives += [o_lat, o_err]
            self._by_objective[o_lat.name] = t
            self._by_objective[o_err.name] = t
        self._eval = SLOEvaluator(
            objectives,
            windows=windows if windows is not None else DEFAULT_WINDOWS,
            clock=clock)
        self._clock = clock
        self._burning: Set[str] = set()
        self._last_refresh: Optional[float] = None
        self._lock = threading.Lock()

    def refresh(self) -> Set[str]:
        """Scrape every engine, snapshot, evaluate; returns the burning
        endpoint set (dead members go stale, not failed — staleness is
        the health gate's job, not the SLO's)."""
        self._fed.scrape_once()
        self._eval.add_snapshot(self._fed.merged_samples())
        burning: Set[str] = set()
        for verdict in self._eval.evaluate():
            if verdict.burning:
                target = self._by_objective.get(verdict.objective)
                if target is not None:
                    burning.add(target)
        with self._lock:
            self._burning = burning
            self._last_refresh = self._clock()
        return set(burning)

    def maybe_refresh(self, min_interval_s: float = 1.0) -> None:
        with self._lock:
            last = self._last_refresh
        if last is not None \
                and self._clock() - last < min_interval_s:
            return
        try:
            self.refresh()
        except Exception:
            pass   # a broken scrape must never take dispatch down

    def burning(self) -> Set[str]:
        with self._lock:
            return set(self._burning)

    def scale_hint(self) -> dict:
        """The autoscaler-facing summary: which engines burn, how many
        are clean, and the resulting action — plus the KV tier view.
        ``kv_pages_in_use`` is HBM-RESIDENT by construction (parked
        sessions live in each engine's host tier), so ``kv_pages_host``
        is the pressure the fleet absorbed WITHOUT scaling: a high
        host-page count with a clean burn set means the offload tier is
        doing its job; a high count WITH burn means the fleet is out of
        headroom and paging cost is leaking into latency — scale up."""
        burning = self.burning()
        clean = [t for t in self.targets if t not in burning]
        action = "steady"
        if burning:
            action = "scale_up" if len(clean) <= len(burning) \
                else "shift_load"
        samples = self._fed.merged_samples()
        pages_host = 0.0
        restores = 0.0
        for key, v in samples.items():
            if key.startswith("kv_pages_host"):
                pages_host += v
            elif key.startswith("kv_page_restores"):
                restores += v
        return {"burning": sorted(burning), "clean": len(clean),
                "targets": len(self.targets), "action": action,
                "kv_pages_host": int(pages_host),
                "kv_page_restores": int(restores)}


# ---------------------------------------------------------------------------
# the router
# ---------------------------------------------------------------------------
class FleetRouter:
    """Route generation requests across engine replicas with health
    gating, session affinity, least-loaded dispatch, and chunked
    retry-with-failover (module docstring has the policy order).

    ``replicas`` mixes raw ``DecodeEngine`` objects (wrapped into
    :class:`LocalReplica`), :class:`LocalReplica` and
    :class:`HTTPReplica` freely. The router satisfies the engine duck
    type ``load_gen``/``install_sigterm_drain`` expect: ``submit`` →
    handle, ``generate``, ``counters``, ``engine_latency_stats``,
    ``ready``, ``drain``."""

    def __init__(self, replicas: Sequence, chunk_tokens: int = 8,
                 max_inflight: int = 64, max_attempts: int = 4,
                 dispatch_timeout_s: float = 120.0,
                 backoff: Optional[Backoff] = None,
                 affinity: bool = True, config=None,
                 default_deadline_s: Optional[float] = None,
                 slo_signal: Optional[FleetSLOSignal] = None,
                 shed_on_burn: bool = False, queue_weight: int = 4,
                 cooldown_s: float = 1.0,
                 clock=time.monotonic, sleep=time.sleep):
        if not replicas:
            raise ValueError("FleetRouter needs at least one replica")
        self.replicas: List = []
        for i, r in enumerate(replicas):
            if hasattr(r, "generate_chunk"):
                self.replicas.append(r)
            else:
                self.replicas.append(LocalReplica(r, name=f"local:{i}"))
        self.config = config
        if self.config is None:
            for r in self.replicas:
                eng = getattr(r, "engine", None)
                if eng is not None and hasattr(eng, "config"):
                    self.config = eng.config
                    break
        self.chunk_tokens = int(chunk_tokens)
        if self.chunk_tokens < 1:
            raise ValueError("chunk_tokens must be >= 1")
        self.max_inflight = int(max_inflight)
        self.max_attempts = int(max_attempts)
        self.dispatch_timeout_s = float(dispatch_timeout_s)
        self.affinity = bool(affinity)
        self.default_deadline_s = default_deadline_s
        self.shed_on_burn = bool(shed_on_burn)
        self.queue_weight = int(queue_weight)
        self.cooldown_s = float(cooldown_s)
        self.slo = slo_signal
        self._backoff = backoff if backoff is not None \
            else Backoff(base=0.02, factor=2.0, cap=0.25, jitter=0.0)
        self._clock = clock
        self._sleep = sleep

        self._lock = threading.Condition()
        self._accepting = True
        self._inflight = 0
        self._affinity_map: Dict[str, object] = {}
        self._cooldown: Dict[str, float] = {}
        self._stats_lock = threading.Lock()
        self._counters: _Counter = _Counter()
        self._hist_reg = MetricsRegistry()
        self._h_e2e = _DualHist("router_e2e_ms", self._hist_reg)

    # -- counters ---------------------------------------------------------
    def _count(self, name: str, n: int = 1) -> None:
        from .. import profiler

        with self._stats_lock:
            self._counters[name] += n
        profiler.bump_counter(name, n)

    def _gauge(self, name: str, value) -> None:
        from .. import profiler

        with self._stats_lock:
            self._counters[name] = value
        profiler.set_counter(name, value)

    @property
    def counters(self) -> Dict[str, int]:
        from .. import profiler

        with self._stats_lock:
            out = dict(self._counters)
        snap = profiler.counters_snapshot()
        for name in profiler.FAULT_COUNTER_NAMES:
            if name in snap:
                out[name] = snap[name]
        return out

    def engine_latency_stats(self) -> Dict[str, float]:
        """Router-side e2e latency in the engine's stats shape (step
        and prefill are engine-internal — zero here)."""
        snap = self._h_e2e._local.snapshot()
        return {
            "n": snap.get("count", 0),
            "e2e_p50_ms": round(self._h_e2e.percentile(50), 3),
            "e2e_p99_ms": round(self._h_e2e.percentile(99), 3),
            "step_p50_ms": 0.0, "step_p99_ms": 0.0,
            "prefill_p50_ms": 0.0, "prefill_p99_ms": 0.0,
        }

    # -- gating + choice --------------------------------------------------
    def _routable(self) -> List:
        now = self._clock()
        with self._lock:
            cooled = dict(self._cooldown)
        out = []
        for r in self.replicas:
            if cooled.get(r.name, 0.0) > now:
                continue
            try:
                if not r.ready():
                    continue
            except Exception:
                continue
            out.append(r)
        self._gauge("router_engines_routable", len(out))
        return out

    def _pick(self, session: str):
        if self.slo is not None:
            self.slo.maybe_refresh()
        cands = self._routable()
        if not cands:
            return None
        burning = self.slo.burning() if self.slo is not None else set()
        if burning:
            clean = [r for r in cands if r.name not in burning]
            if clean:           # burning replicas serve only as a
                cands = clean   # last resort
        if self.affinity:
            with self._lock:
                aff = self._affinity_map.get(session)
            if aff is not None and aff in cands:
                return aff
        def score(r):
            ld = r.load()
            if ld is None:
                return (float("inf"),)
            pages, depth = ld
            return (pages + self.queue_weight * depth,)
        return min(cands, key=score)

    def _is_routable(self, replica) -> bool:
        with self._lock:
            if self._cooldown.get(replica.name, 0.0) > self._clock():
                return False
        try:
            return bool(replica.ready())
        except Exception:
            return False

    def _mark_failed(self, replica, e: BaseException) -> None:
        if isinstance(e, (ReplicaUnroutable, EngineStopped)):
            with self._lock:
                self._cooldown[replica.name] = \
                    self._clock() + self.cooldown_s
                self._affinity_map = {
                    s: r for s, r in self._affinity_map.items()
                    if r is not replica}
            # the dead engine can't dump its own flight recorder after
            # SIGKILL — the router names the kill from its side
            flight_recorder().record(
                "replica_dead", replica=replica.name,
                error=type(e).__name__, detail=str(e)[:200])
            note_typed_error(e)

    # -- dispatch ---------------------------------------------------------
    def _dispatch(self, session: str, ctx: List[int], chunk: int,
                  deadline: Optional[float],
                  has_emitted: bool) -> List[int]:
        state = {"failed": False}

        def attempt() -> List[int]:
            replica = self._pick(session)
            if replica is None:
                raise Overloaded("no routable engine replica")
            chunk_deadline = None
            if deadline is not None:
                chunk_deadline = max(0.01, deadline - self._clock())
            try:
                tokens = replica.generate_chunk(ctx, chunk,
                                                chunk_deadline)
            except DeadlineExceeded:
                raise
            except (ReplicaUnroutable, ServingError) as e:
                state["failed"] = True
                self._mark_failed(replica, e)
                raise
            with self._lock:
                prev = self._affinity_map.get(session)
                self._affinity_map[session] = replica
            self._count("router_dispatches")
            if prev is replica:
                self._count("router_affinity_hits")
            # a failover is a session landing away from its replica
            # because that replica FAILED — either an attempt in this
            # very dispatch died on it, or the health gate caught the
            # death first and steered around it
            if state["failed"] or (prev is not None
                                   and prev is not replica
                                   and not self._is_routable(prev)):
                self._count("router_failovers")
                if has_emitted:
                    self._count("router_replays")
                    flight_recorder().record(
                        "router_replay", session=session,
                        replica=replica.name, ctx_tokens=len(ctx))
            return tokens

        budget = self.dispatch_timeout_s
        if deadline is not None:
            budget = max(0.01, deadline - self._clock())
        retrier = Retrier(max_attempts=self.max_attempts,
                          deadline=budget, backoff=self._backoff,
                          retry_on=(ServingError, ReplicaUnroutable,
                                    ConnectionError, OSError),
                          giveup_on=(DeadlineExceeded,),
                          sleep=self._sleep, name="router.dispatch")
        return retrier.call(attempt)

    def _run(self, handle, prompt: List[int], max_new: int,
             deadline: Optional[float], session: str, span,
             on_chunk, t_submit: float) -> None:
        emitted: List[int] = []
        token_times: List[float] = []
        err: Optional[BaseException] = None
        try:
            while len(emitted) < max_new:
                if deadline is not None \
                        and self._clock() >= deadline:
                    raise DeadlineExceeded(
                        f"deadline passed mid-generation after "
                        f"{len(emitted)} tokens")
                chunk = min(self.chunk_tokens, max_new - len(emitted))
                tokens = self._dispatch(session, prompt + emitted,
                                        chunk, deadline, bool(emitted))
                now = self._clock()
                emitted.extend(int(t) for t in tokens)
                token_times.extend(now for _ in tokens)
                if on_chunk is not None:
                    on_chunk(list(emitted))
                if len(tokens) < chunk:
                    break   # engine finished early (eos)
        except ServingError as e:
            err = e
        except BaseException as e:
            err = RequestFailed(
                f"router dispatch failed: {type(e).__name__}: {e}")
        finally:
            with self._lock:
                self._inflight -= 1
                self._lock.notify_all()
        if token_times:
            handle.meta["ttft_ms"] = round(
                (token_times[0] - t_submit) * 1e3, 3)
            handle.meta["token_times"] = token_times
        if span is not None:
            span.set("tokens", len(emitted))
            if err is not None:
                span.fail(err)
            else:
                span.end()
        if err is not None:
            handle._resolve(error=err)
            return
        self._h_e2e.observe((self._clock() - t_submit) * 1e3)
        handle._resolve(value=emitted)

    # -- the engine duck type --------------------------------------------
    def submit(self, prompt: Sequence[int], max_new_tokens: int = 16,
               deadline_s: Optional[float] = None,
               session: Optional[str] = None, on_chunk=None):
        """Admit one fleet request; returns the familiar decode handle
        (``result()`` → tokens, ``stats()`` → ttft/token times).
        ``session`` keys affinity (defaults to the request's trace id);
        ``on_chunk`` is the streaming hook — called with the tokens
        emitted so far after every chunk lands router-side."""
        from ..inference.decode.scheduler import _DecodeHandle

        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if deadline_s is None:
            deadline_s = self.default_deadline_s
        with self._lock:
            if not self._accepting:
                raise EngineStopped("router is draining; not admitting")
            if self._inflight >= self.max_inflight:
                self._count("router_sheds")
                raise Overloaded(
                    f"router at max_inflight={self.max_inflight}")
            if self.shed_on_burn and self.slo is not None:
                burning = self.slo.burning()
                if burning and all(r.name in burning
                                   for r in self.replicas):
                    self._count("router_sheds")
                    raise Overloaded(
                        "every engine replica is burning its SLO "
                        "budget; shedding new work")
            self._inflight += 1
        self._count("router_requests")
        t_submit = self._clock()
        deadline = None if deadline_s is None \
            else t_submit + float(deadline_s)
        span = tracing.Span("router.request", root=True,
                            clock=self._clock,
                            tokens_requested=int(max_new_tokens))
        handle = _DecodeHandle()
        handle.meta["trace_id"] = format(span.trace_id, "016x")
        key = str(session) if session is not None \
            else handle.meta["trace_id"]
        threading.Thread(
            target=self._run,
            args=(handle, prompt, int(max_new_tokens), deadline, key,
                  span, on_chunk, t_submit),
            daemon=True, name="fleet-router-req").start()
        return handle

    def generate(self, prompt: Sequence[int], max_new_tokens: int = 16,
                 deadline_s: Optional[float] = None,
                 session: Optional[str] = None, on_chunk=None,
                 timeout: Optional[float] = None) -> List[int]:
        """Blocking convenience: submit + wait for the token list."""
        return self.submit(prompt, max_new_tokens,
                           deadline_s=deadline_s, session=session,
                           on_chunk=on_chunk).result(timeout)

    @property
    def ready(self) -> bool:
        with self._lock:
            if not self._accepting:
                return False
        return bool(self._routable())

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return self._inflight

    def session_replica(self, session: str) -> Optional[str]:
        """The replica name a session is currently pinned to (None
        before its first dispatch) — drills use this to aim the kill."""
        with self._lock:
            r = self._affinity_map.get(str(session))
        return None if r is None else r.name

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Stop admitting, flush the router's in-flight requests, then
        drain every local replica — the duck-typed contract
        ``install_sigterm_drain`` runs on SIGTERM. True when everything
        flushed inside the budget."""
        deadline = None if timeout is None \
            else self._clock() + float(timeout)
        with self._lock:
            self._accepting = False
            while self._inflight > 0:
                left = None if deadline is None \
                    else deadline - self._clock()
                if left is not None and left <= 0:
                    return False
                self._lock.wait(timeout=0.05 if left is None
                                else min(0.05, left))
        ok = True
        for r in self.replicas:
            left = None if deadline is None \
                else max(0.1, deadline - self._clock())
            try:
                ok = bool(r.drain(timeout=left)) and ok
            except Exception:
                ok = False
        return ok

    def stop(self) -> None:
        with self._lock:
            self._accepting = False
            self._lock.notify_all()
        for r in self.replicas:
            try:
                r.stop()
            except Exception:
                pass
