"""Prefill/decode disaggregation: compute prompt KV on a PREFILL pool,
ship the full pages over the wire, adopt them into a DECODE engine's
``PageTableManager``.

Why split: prefill is a compute-bound batched matmul burst, decode is a
latency-bound one-token-per-step loop — co-locating them makes prefill
bursts stall every resident decode stream. The split only pays if the
shipped state is cheaper than recomputing it, which is exactly what the
PS v2 page codec buys: ``ps/codec.py`` int8 with ``block = H * D`` (one
f32 scale per token row — the same layout the int8 KV pool stores), so
a page travels at ~26% of its f32 bytes and, on serving-scale models,
orders of magnitude under the prefill-recompute FLOP-equivalent
(:func:`migration_cost` is the closed form both the chaos drill and the
bench probe assert against).

The wire unit is a PAGE FRAME: a fixed header (magic, version, codec
byte from ``CODEC_IDS``, pool geometry, token count), the covered
tokens (chain-hash inputs — the decode side re-derives the prefix-cache
keys from content, so shipped pages dedupe against locally prefilled
ones by construction), then the K and V planes ``np_encode``-d
per-token-row. Anything short, mis-magicked, mis-versioned or
mis-geometried raises :class:`MalformedPageFrame` — the typed reject
the PS wire taught us (never guess at half a frame).

Migration is an OPTIMIZATION, never a correctness dependency:
:class:`MigrationClient` gives the ship RPC a deadline and a bounded
``fault.Retrier`` budget, and when the budget is spent it DEGRADES —
the decode engine simply prefills locally, ``kv_migration_fallbacks``
ticks, and the user sees nothing.
"""
from __future__ import annotations

import struct
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..fault import Backoff, Retrier
from ..ps.codec import CODEC_IDS, codec_name, encoded_nbytes, np_encode

__all__ = [
    "FRAME_MAGIC", "FRAME_VERSION", "MalformedPageFrame", "PageFrame",
    "PrefillShipment", "PrefillWorker", "MigrationClient",
    "decode_frame", "encode_frame", "migration_cost", "quantize_rows",
]

FRAME_MAGIC = b"KVPG"
FRAME_VERSION = 1

# magic, version, codec, n_layers, n_pages, page_size, heads, head_dim,
# n_tokens — little-endian like the codec payloads
_HEADER = struct.Struct("<4sBBHHHHHI")

#: FLOPs one wire byte is worth when deciding ship-vs-recompute: peak
#: matmul throughput over inter-host network bandwidth (machine
#: balance). ~400 TFLOP/s bf16 against ~25 GB/s DCN per host ≈ 16k
#: FLOPs/byte — the v5e-class numbers the cost model's device peaks
#: table carries. Overridable per call for other fabrics (ICI-attached
#: prefill pools are ~40x cheaper per byte).
FLOPS_PER_WIRE_BYTE = 16000.0


class MalformedPageFrame(RuntimeError):
    """A page frame the decoder refuses to guess at: bad magic, unknown
    version or codec byte, or a body shorter than its header promises."""


def quantize_rows(rows: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Per-token-row symmetric int8 quantization of ``(..., H, D)``
    float32 rows — identical rounding to ``np_encode``/
    ``jnp_encode_kv_rows`` (amax/127 scale, half-even rint, clip), so
    every producer of an int8 page row agrees bit for bit. Public
    because it is THE row codec of every KV tier: the wire frames
    below, the int8 pool's prefill path, and the decode engine's
    host-RAM offload records (kv_cache.HostKVPool) all quantize
    through this one rule — which is what makes a page parked to host
    RAM re-encode IDEMPOTENTLY (the amax element quantizes to ±127
    exactly, so decode → re-encode reproduces the same bytes)."""
    xf = np.asarray(rows, np.float32)
    amax = np.max(np.abs(xf), axis=(-2, -1))
    scale = (amax / 127.0).astype(np.float32)
    safe = np.where(scale > 0, scale, 1.0)
    q = np.clip(np.rint(xf / safe[..., None, None]),
                -127, 127).astype(np.int8)
    return q, scale


def encode_frame(tokens: Sequence[int], ks: np.ndarray, vs: np.ndarray,
                 page_size: int, codec: str = "int8") -> bytes:
    """Encode full prefill pages for the wire. ``ks``/``vs`` are the
    dense-forward KV stacks ``(n_layers, T, H, D)`` (float32) covering
    exactly ``T = len(tokens)`` positions; ``T`` must be a whole number
    of pages — partial tail pages never ship (the adopter's suffix
    prefill covers them)."""
    ks = np.ascontiguousarray(ks, np.float32)
    vs = np.ascontiguousarray(vs, np.float32)
    if ks.ndim != 4 or ks.shape != vs.shape:
        raise ValueError(f"expected matching (n_layers, T, H, D) KV "
                         f"stacks, got {ks.shape} and {vs.shape}")
    n_layers, T, heads, head_dim = ks.shape
    toks = [int(t) for t in tokens]
    n_pages, rem = divmod(len(toks), int(page_size))
    if len(toks) != T or rem or n_pages <= 0:
        raise ValueError(
            f"frame covers whole pages only: {len(toks)} tokens, "
            f"{T} KV rows, page_size {page_size}")
    if codec not in CODEC_IDS:
        raise ValueError(f"unknown codec {codec!r}")
    header = _HEADER.pack(FRAME_MAGIC, FRAME_VERSION, CODEC_IDS[codec],
                          n_layers, n_pages, int(page_size), heads,
                          head_dim, len(toks))
    tok_bytes = np.asarray(toks, np.uint32).tobytes()
    row = heads * head_dim
    k_raw = np_encode(ks, codec, block=row)
    v_raw = np_encode(vs, codec, block=row)
    return header + tok_bytes + k_raw + v_raw


class PageFrame:
    """A decoded page frame: geometry + tokens + the two encoded KV
    planes, with row-layout accessors for both pool dtypes."""

    def __init__(self, codec: str, n_layers: int, n_pages: int,
                 page_size: int, heads: int, head_dim: int,
                 tokens: List[int], k_raw: bytes, v_raw: bytes):
        self.codec = codec
        self.n_layers = n_layers
        self.n_pages = n_pages
        self.page_size = page_size
        self.heads = heads
        self.head_dim = head_dim
        self.tokens = tokens
        self._raw = {"k": k_raw, "v": v_raw}

    @property
    def n_rows(self) -> int:
        return self.n_layers * self.n_pages * self.page_size

    @property
    def n_elems(self) -> int:
        return self.n_rows * self.heads * self.head_dim

    def f32_rows(self, which: str) -> np.ndarray:
        """One plane as float32 ``(n_layers, n_pages, S, H, D)``."""
        from ..ps.codec import np_decode

        flat = np_decode(self._raw[which], self.n_elems, self.codec,
                         block=self.heads * self.head_dim)
        return flat.reshape(self.n_layers, self.n_pages, self.page_size,
                            self.heads, self.head_dim)

    def int8_rows(self, which: str
                  ) -> Tuple[np.ndarray, np.ndarray]:
        """One plane as the int8 pool's storage pair: quantized rows
        ``(n_layers, n_pages, S, H, D)`` int8 + per-row f32 scales
        ``(n_layers, n_pages, S)``. An int8 frame parses its payload
        directly (zero requantization — bitwise what a local int8
        prefill would have written); other codecs requantize with the
        same per-row rule."""
        shape = (self.n_layers, self.n_pages, self.page_size,
                 self.heads, self.head_dim)
        if self.codec == "int8":
            raw = self._raw[which]
            scales = np.frombuffer(raw, np.float32, count=self.n_rows)
            q = np.frombuffer(raw, np.int8, count=self.n_elems,
                              offset=4 * self.n_rows)
            return (q.reshape(shape).copy(),
                    scales.reshape(shape[:3]).copy())
        q, scales = quantize_rows(self.f32_rows(which))
        return q, scales


def decode_frame(frame: bytes) -> PageFrame:
    """Parse a page frame; :class:`MalformedPageFrame` on anything that
    does not parse EXACTLY (short body, trailing junk, bad magic or
    codec byte) — a migration wire never guesses."""
    if len(frame) < _HEADER.size:
        raise MalformedPageFrame(
            f"frame of {len(frame)} bytes is shorter than the "
            f"{_HEADER.size}-byte header")
    (magic, version, codec_id, n_layers, n_pages, page_size, heads,
     head_dim, n_tokens) = _HEADER.unpack_from(frame)
    if magic != FRAME_MAGIC:
        raise MalformedPageFrame(f"bad frame magic {magic!r}")
    if version != FRAME_VERSION:
        raise MalformedPageFrame(f"unknown frame version {version}")
    try:
        codec = codec_name(codec_id)
    except ValueError as e:
        raise MalformedPageFrame(str(e)) from None
    if n_tokens != n_pages * page_size or n_tokens == 0:
        raise MalformedPageFrame(
            f"{n_tokens} tokens do not cover {n_pages} pages of "
            f"{page_size}")
    n_elems = n_layers * n_tokens * heads * head_dim
    plane = encoded_nbytes(n_elems, codec, block=heads * head_dim)
    want = _HEADER.size + 4 * n_tokens + 2 * plane
    if len(frame) != want:
        raise MalformedPageFrame(
            f"frame is {len(frame)} bytes, header promises {want}")
    off = _HEADER.size
    tokens = np.frombuffer(frame, np.uint32, count=n_tokens,
                           offset=off).astype(int).tolist()
    off += 4 * n_tokens
    k_raw = frame[off:off + plane]
    v_raw = frame[off + plane:off + 2 * plane]
    return PageFrame(codec, n_layers, n_pages, page_size, heads,
                     head_dim, tokens, k_raw, v_raw)


def migration_cost(config, n_tokens: int, codec: str = "int8",
                   flops_per_byte: float = FLOPS_PER_WIRE_BYTE) -> dict:
    """Ship-vs-recompute closed form for an ``n_tokens`` prefix of a
    ``DecodeModelConfig``-shaped model: encoded wire bytes of the KV
    pages against the FLOP cost of recomputing the prefill locally,
    expressed in wire-byte equivalents through the machine balance
    (``flops_per_byte``). ``cheaper_to_ship`` is the drill's gate."""
    E = config.n_heads * config.head_dim
    n = int(n_tokens)
    row = config.n_heads * config.head_dim
    n_elems = config.n_layers * n * row
    encoded = 2 * encoded_nbytes(n_elems, codec, block=row)
    f32 = 2 * encoded_nbytes(n_elems, "f32", block=row)
    # dense prefill: per-layer QKVO projections (4 E^2) + MLP (2 E F),
    # x2 multiply-add, plus the causal attention term and the LM head
    matmul = config.n_layers * (4 * E * E + 2 * E * config.ffn_dim)
    flops = 2 * matmul * n + 4 * config.n_layers * E * n * n \
        + 2 * E * config.vocab_size * n
    flops_equiv_bytes = flops / float(flops_per_byte)
    return {
        "n_tokens": n,
        "codec": codec,
        "encoded_bytes": int(encoded),
        "f32_bytes": int(f32),
        "bytes_saved_pct": round(100.0 * (1 - encoded / f32), 2),
        "reprefill_flops": int(flops),
        "flops_equiv_bytes": int(flops_equiv_bytes),
        "cheaper_to_ship": encoded < flops_equiv_bytes,
    }


class PrefillShipment:
    """One prompt's prefill product: the encoded frame for its FULL
    pages (None when the prompt spans less than one page), plus the
    byte accounting the migration counters publish."""

    __slots__ = ("prompt", "frame", "n_pages", "next_token",
                 "encoded_bytes", "f32_bytes")

    def __init__(self, prompt, frame, n_pages, next_token,
                 encoded_bytes, f32_bytes):
        self.prompt = prompt
        self.frame = frame
        self.n_pages = n_pages
        self.next_token = next_token
        self.encoded_bytes = encoded_bytes
        self.f32_bytes = f32_bytes


class PrefillWorker:
    """The prefill half of the split: computes prompt KV with the dense
    forward — no page pool, no decode slots, none of the decode
    engine's compiled-step cache pressure — and packages the full pages
    as wire frames. Deterministic params (``init_decode_params`` is
    seed-reproducible across processes), so a shipped page holds
    exactly what the decode engine's own prefill would have written."""

    def __init__(self, config, params: Optional[Dict] = None,
                 seed: int = 0, page_size: int = 16,
                 codec: str = "int8"):
        from ..inference.decode.model import init_decode_params

        if codec not in CODEC_IDS:
            raise ValueError(f"unknown codec {codec!r}")
        self.config = config
        self.params = params if params is not None \
            else init_decode_params(config, seed)
        self.page_size = int(page_size)
        self.codec = codec

    def prefill(self, prompt: Sequence[int]) -> PrefillShipment:
        from ..inference.decode.model import dense_forward

        toks = [int(t) for t in prompt]
        if not toks:
            raise ValueError("empty prompt")
        arr = np.asarray(toks, np.int32)[None, :]
        logits, ks, vs = dense_forward(self.config, self.params, arr,
                                       collect_kv=True)
        next_token = int(np.asarray(
            np.argmax(np.asarray(logits)[0, len(toks) - 1])))
        n_full = len(toks) // self.page_size
        if n_full == 0:
            return PrefillShipment(toks, None, 0, next_token, 0, 0)
        cover = n_full * self.page_size
        k_np = np.asarray(ks)[:, 0, :cover]
        v_np = np.asarray(vs)[:, 0, :cover]
        frame = encode_frame(toks[:cover], k_np, v_np, self.page_size,
                             self.codec)
        row = self.config.n_heads * self.config.head_dim
        n_elems = self.config.n_layers * cover * row
        return PrefillShipment(
            toks, frame, n_full, next_token,
            2 * encoded_nbytes(n_elems, self.codec, block=row),
            2 * encoded_nbytes(n_elems, "f32", block=row))


class MigrationClient:
    """Ships page frames to a decode engine with deadlines, bounded
    retries, and the degrade leg.

    ``send`` is the transport: ``callable(frame_bytes) -> report
    dict`` — ``DecodeEngine.adopt_pages`` for an in-process engine,
    ``HTTPReplica.adopt`` for a remote one. Transport failures burn the
    ``fault.Retrier`` budget; an exhausted budget (or a pool-full
    adoption) is a FALLBACK, not an error: :meth:`migrate` returns
    ``ok=False``, ``kv_migration_fallbacks`` ticks, and the caller's
    normal submit path recomputes the prefill locally."""

    def __init__(self, send, max_attempts: int = 3,
                 deadline_s: float = 5.0,
                 backoff: Optional[Backoff] = None,
                 sleep=time.sleep, name: str = "kv_migrate"):
        self._send = send
        self._max_attempts = int(max_attempts)
        self._deadline_s = float(deadline_s)
        self._backoff = backoff if backoff is not None \
            else Backoff(base=0.05, factor=2.0, cap=0.5, jitter=0.0)
        self._sleep = sleep
        self._name = name

    def migrate(self, shipment: PrefillShipment) -> dict:
        from .. import profiler

        if shipment.frame is None:
            profiler.bump_counter("kv_migration_fallbacks")
            return {"ok": False, "reason": "no_full_pages",
                    "adopted": 0, "shared": 0, "pages": 0}
        retrier = Retrier(max_attempts=self._max_attempts,
                          deadline=self._deadline_s,
                          backoff=self._backoff,
                          retry_on=(ConnectionError, OSError,
                                    TimeoutError),
                          giveup_on=(MalformedPageFrame,),
                          sleep=self._sleep, name=self._name)
        try:
            report = retrier.call(self._send, shipment.frame)
        except Exception as e:
            profiler.bump_counter("kv_migration_fallbacks")
            return {"ok": False,
                    "reason": f"{type(e).__name__}: {e}",
                    "adopted": 0, "shared": 0, "pages": 0}
        if not report.get("ok"):
            profiler.bump_counter("kv_migration_fallbacks")
            return report
        profiler.bump_counter("kv_migration_bytes", len(shipment.frame))
        profiler.bump_counter(
            "kv_migration_bytes_saved",
            max(0, shipment.f32_bytes - shipment.encoded_bytes))
        report = dict(report)
        report["frame_bytes"] = len(shipment.frame)
        report["encoded_bytes"] = shipment.encoded_bytes
        report["f32_bytes"] = shipment.f32_bytes
        return report
