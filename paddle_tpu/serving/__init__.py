"""paddle_tpu.serving — the fleet plane over ``DecodeEngine``:
COMPOSE engines, don't grow inside one.

A single decode engine (inference/decode/) is a process: one pool, one
scheduler thread, one /metrics listener. This package is everything
that only exists BETWEEN engines:

- :mod:`router` — ``FleetRouter``: health-gated, session-affine,
  least-loaded dispatch over N replicas with chunked
  retry-with-failover — an engine SIGKILLed mid-generation is replayed
  on a healthy replica with its emitted tokens folded into the prompt,
  byte-identical to an unkilled run. ``DecodeEngineServer`` is the
  per-engine HTTP surface (healthz/readyz/stats/metrics/generate/
  adopt); ``FleetSLOSignal`` federates per-engine burn rates into the
  router's shed/scale signal.
- :mod:`disagg` — prefill/decode disaggregation: ``PrefillWorker``
  computes prompt KV pool-free, ships FULL pages as int8 page frames
  (the PS v2 codec, per-token-row scales), and a decode engine adopts
  them through ``PageTableManager.adopt_pages`` with prefix-cache
  hashes preserved — shipped pages dedupe exactly like local ones.
  ``MigrationClient`` wraps the ship in deadlines + bounded retries
  with a local-recompute degrade leg (``kv_migration_fallbacks``).

Quickstart (three engines, one router)::

    from paddle_tpu.inference.decode import DecodeEngine, DecodeModelConfig
    from paddle_tpu.serving import DecodeEngineServer, FleetRouter

    cfg = DecodeModelConfig()
    engines = [DecodeEngine(cfg, seed=11).start() for _ in range(3)]
    for e in engines:
        e.warm()
    router = FleetRouter(engines)           # in-process replicas
    tokens = router.generate([1, 2, 3], max_new_tokens=32)

    # or remote: DecodeEngineServer(engine, port=8101).start() per
    # process, then FleetRouter([HTTPReplica("127.0.0.1:8101"), ...])

``tools/chaos_drill.py --fleet`` is the proof: 3 engine processes
under live load, one SIGKILLed mid-generation, outputs asserted
bitwise against a never-killed oracle.
"""
from .disagg import (  # noqa: F401
    MalformedPageFrame, MigrationClient, PageFrame, PrefillShipment,
    PrefillWorker, decode_frame, encode_frame, migration_cost,
)
from .router import (  # noqa: F401
    DecodeEngineServer, FleetRouter, FleetSLOSignal, HTTPReplica,
    LocalReplica, ReplicaUnroutable,
)

__all__ = [
    "DecodeEngineServer", "FleetRouter", "FleetSLOSignal",
    "HTTPReplica", "LocalReplica", "ReplicaUnroutable",
    "MalformedPageFrame", "MigrationClient", "PageFrame",
    "PrefillShipment", "PrefillWorker", "decode_frame", "encode_frame",
    "migration_cost",
]
