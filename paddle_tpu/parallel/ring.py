"""Sequence/context parallelism: ring attention and Ulysses (all-to-all).

The reference framework predates sequence parallelism entirely (SURVEY §2.6:
TP/SP/CP/ring attention absent; long sequences were handled only via
recompute, /root/reference/python/paddle/fluid/backward.py:629, and pipeline
micro-batching, /root/reference/paddle/fluid/framework/section_worker.cc).
This module is the TPU-first design for that gap: the sequence axis of
q/k/v is sharded over a named mesh axis, and

- **ring attention**: every device keeps its local Q block resident and
  streams K/V blocks around the ring with `lax.ppermute` (ICI
  neighbour-exchange), combining partial results with a numerically stable
  online softmax — flash attention across chips.
- **Ulysses**: `lax.all_to_all` re-shards (seq-sharded, all heads) ->
  (full seq, head-sharded), runs ordinary attention locally per head group,
  and re-shards back. Cheaper for moderate sequence lengths when
  num_heads % axis_size == 0.

Both are plain collectives inside `shard_map`, so they compose with data /
tensor parallel axes of the same mesh and with `jax.grad` (XLA
differentiates ppermute/all_to_all natively).

NOTE on tracing: the `sequence_parallel()` context is consulted at TRACE
time. A function jitted outside the context keeps its non-ring executable
in jax's cache even if later called inside the context (and vice versa).
For the training hot path, prefer the explicit
`jit.TrainStep(..., sequence_parallel="sp")` knob, which bakes the ring
path into the compiled step deterministically.
"""
from __future__ import annotations

import functools
import math
from contextlib import contextmanager
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

from ..framework.flags import define_flag, get_flag
from .mesh import get_mesh

_NEG_INF = -1e30

define_flag("ring_flash", True,
            "Route each ring-attention step's local block compute through "
            "the Pallas flash kernel (SURVEY hard part f). Eligible shapes "
            "only; False keeps the einsum online-softmax walk everywhere "
            "(the A/B arm for tools/live_tpu_session.py)")


def _axis_size(axis_name):
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


# Tests flip this to run interpret-mode Pallas under shard_map: the hlo
# interpreter evaluates kernel bodies as jax ops, where kernel-internal
# constants carry empty vma and trip check_vma (jax 0.9 rough edge).
# Real Mosaic lowering never vma-types kernel internals.
_SHARD_MAP_CHECK_VMA = [True]


def _shard_map(fn, mesh, in_specs, out_specs):
    from .collectives import shard_map_fn

    sm = shard_map_fn()  # jax.shard_map, or the pre-0.6 experimental home
    if _SHARD_MAP_CHECK_VMA[0]:
        return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    try:
        return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=False)
    except TypeError:   # pre-vma jax spells it check_rep
        return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=False)


# ---------------------------------------------------------------------------
# flash-ring: the ring walk's local block compute routed through the Pallas
# flash kernels (SURVEY.md hard part f: "ring attention as a Pallas
# flash-attention kernel with ppermute KV rotation"). Forward runs the
# streaming flash FORWARD kernel on each arriving KV block and merges the
# normalized block outputs by their logsumexp; backward re-walks the ring
# calling the flash dq/dkv kernels with the GLOBAL lse (the standard flash
# decomposition: p = exp(s - lse_global) is the true probability, so each
# block's dq/dk/dv contribution is exact), rotating each block's dk/dv
# accumulators around the ring WITH the block so they arrive home after a
# full circle.
# ---------------------------------------------------------------------------


def _ring_flash_eligible(q, k, is_causal):
    """Static-shape gate for the flash-ring path (per-device shards)."""
    from ..framework.bringup import pallas_enabled

    # FLAGS_ring_flash is defined at this module's import, so a plain
    # lookup is safe
    if not get_flag("ring_flash") or not pallas_enabled():
        return False
    b, lq, h, d = q.shape
    lk = k.shape[1]
    # kernel tile modulus 128, head_dim lane modulus 64; causal block
    # classification below (before/diagonal/after) assumes equal shards
    return (lq % 128 == 0 and lk % 128 == 0 and lq >= 128 and lk >= 128
            and d % 64 == 0 and d <= 256 and (not is_causal or lq == lk))


def _ring_branch(origin, idx, is_causal, bias, masked):
    """0 = skip, 1 = full block, 2 = diagonal (in-block causal mask).

    With equal shards, block `origin` is entirely before the local Q
    block iff origin < idx (full), entirely after iff origin > idx
    (skip under causal). Mask-empty blocks are skipped outright."""
    if is_causal:
        branch = jnp.where(origin > idx, 0,
                           jnp.where(origin == idx, 2, 1))
    else:
        branch = jnp.ones((), jnp.int32)
    if masked:
        branch = jnp.where(jnp.any(bias > -1e29), branch, 0)
    return branch


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _ring_flash(q, k, v, kv_bias, axis_name, axis_size, is_causal, masked):
    out, _ = _ring_flash_fwd(q, k, v, kv_bias, axis_name, axis_size,
                             is_causal, masked)
    return out


def _ring_flash_fwd(q, k, v, kv_bias, axis_name, axis_size, is_causal,
                    masked):
    from ..ops.pallas.flash_attention import (_fwd_call, _mergeheads,
                                              _pick_blocks, _splitheads)

    size = axis_size
    idx = jax.lax.axis_index(axis_name)
    b, lq, h, d = q.shape
    lk = k.shape[1]
    sm_scale = 1.0 / math.sqrt(d)
    bq, bkv = _pick_blocks(lq, lk, 512, 512)
    qm, km, vm = _mergeheads(q), _mergeheads(k), _mergeheads(v)
    perm = [(i, (i + 1) % size) for i in range(size)]

    def merge(acc, lse, out_b, lse_b):
        # both partials are normalized over disjoint key sets: combine
        # with logsumexp weights (numerically the online-softmax rescale)
        new = jnp.logaddexp(lse, lse_b)                  # (bh, 1, lq)
        w_old = jnp.exp(lse - new)[:, 0, :, None]        # (bh, lq, 1)
        w_new = jnp.exp(lse_b - new)[:, 0, :, None]
        return acc * w_old + out_b.astype(jnp.float32) * w_new, new

    def step_update(s, acc, lse, kc, vc, bc):
        origin = jnp.mod(idx - s, size)

        def compute(causal):
            mb = bc[:, None, :] if masked else None
            out_b, lse_b = _fwd_call(qm, kc, vc, causal, bq, bkv,
                                     sm_scale, mask_bias=mb, heads=h)
            return merge(acc, lse, out_b, lse_b)

        branch = _ring_branch(origin, idx, is_causal, bc, masked)
        return jax.lax.switch(branch, (lambda: (acc, lse),
                                       lambda: compute(False),
                                       lambda: compute(True)))

    def body(s, carry):
        acc, lse, kc, vc, bc = carry
        acc, lse = step_update(s, acc, lse, kc, vc, bc)
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        if masked:
            bc = jax.lax.ppermute(bc, axis_name, perm)
        return acc, lse, kc, vc, bc

    # carries derive from inputs (0*x) for shard_map's vma typing; lse in
    # f32 at the kernels' -1e30 floor (finite: logaddexp/exp stay NaN-free
    # even for fully-masked rows)
    acc0 = (0.0 * qm).astype(jnp.float32)
    lse0 = (0.0 * qm[..., 0]).astype(jnp.float32)[:, None, :] + _NEG_INF
    bc0 = kv_bias if masked else jnp.zeros((), jnp.float32)
    # last block needs no rotation afterwards: size-1 rotations, final
    # fold outside the loop (saves one ICI hop)
    acc, lse, kc, vc, bc = jax.lax.fori_loop(
        0, size - 1, body, (acc0, lse0, km, vm, bc0))
    acc, lse = step_update(size - 1, acc, lse, kc, vc, bc)
    out_m = acc.astype(q.dtype)
    return (_splitheads(out_m, b, h),
            (qm, km, vm, out_m, lse, kv_bias, b, h))


def _ring_flash_bwd(axis_name, axis_size, is_causal, masked, res, dout):
    from ..ops.pallas.flash_attention import (_bwd_call, _mergeheads,
                                              _pick_blocks, _splitheads)

    qm, km, vm, out_m, lse, kv_bias, b, h = res
    size = axis_size
    idx = jax.lax.axis_index(axis_name)
    bh, lq, d = qm.shape
    lk = km.shape[1]
    sm_scale = 1.0 / math.sqrt(d)
    bq, bkv = _pick_blocks(lq, lk, 512, 512)
    # constant-cotangent Mosaic guard, as in the single-device bwd paths
    dom = _mergeheads(jax.lax.optimization_barrier(dout))
    delta = jnp.sum(dom.astype(jnp.float32) * out_m.astype(jnp.float32),
                    axis=-1)[:, None, :]                 # (bh, 1, lq)
    perm = [(i, (i + 1) % size) for i in range(size)]

    def step(s, dq, dkc, dvc, kc, vc, bc):
        origin = jnp.mod(idx - s, size)

        def compute(causal):
            mb = bc[:, None, :] if masked else None
            dqb, dkb, dvb = _bwd_call(qm, kc, vc, dom, lse, delta, causal,
                                      bq, bkv, sm_scale, mask_bias=mb,
                                      heads=h)
            return (dq + dqb.astype(jnp.float32),
                    dkc + dkb.astype(jnp.float32),
                    dvc + dvb.astype(jnp.float32))

        branch = _ring_branch(origin, idx, is_causal, bc, masked)
        return jax.lax.switch(branch, (lambda: (dq, dkc, dvc),
                                       lambda: compute(False),
                                       lambda: compute(True)))

    def body(s, carry):
        dq, dkc, dvc, kc, vc, bc = carry
        dq, dkc, dvc = step(s, dq, dkc, dvc, kc, vc, bc)
        # each block's grad accumulators travel WITH the block: after a
        # full circle (size process+rotate iterations) dk/dv are home
        dkc = jax.lax.ppermute(dkc, axis_name, perm)
        dvc = jax.lax.ppermute(dvc, axis_name, perm)
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        if masked:
            bc = jax.lax.ppermute(bc, axis_name, perm)
        return dq, dkc, dvc, kc, vc, bc

    dq0 = (0.0 * qm).astype(jnp.float32)
    dk0 = (0.0 * km).astype(jnp.float32)
    dv0 = (0.0 * vm).astype(jnp.float32)
    bc0 = kv_bias if masked else jnp.zeros((), jnp.float32)
    dq, dk, dv, _, _, _ = jax.lax.fori_loop(
        0, size, body, (dq0, dk0, dv0, km, vm, bc0))
    return (_splitheads(dq.astype(qm.dtype), b, h),
            _splitheads(dk.astype(km.dtype), b, h),
            _splitheads(dv.astype(vm.dtype), b, h),
            jnp.zeros_like(kv_bias))


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


# ---------------------------------------------------------------------------
# ring attention (inside shard_map; q/k/v local blocks (B, L_local, H, D))
# ---------------------------------------------------------------------------


def ring_attention_local(q, k, v, axis_name: str, is_causal: bool = False,
                         axis_size: Optional[int] = None, kv_mask=None):
    """Ring attention over `axis_name`; call inside shard_map.

    q/k/v: (B, L_local, H, D) — this device's sequence shard. Returns the
    attention output for the local Q block, (B, L_local, H, D). The KV ring
    walk is a `fori_loop`, so HLO size stays O(1) in the axis size.

    kv_mask: optional (B, L_local) bool — this device's key-padding shard
    (True = attend). It rides the ring with its K/V block, so padded keys
    are masked at block granularity without materialising a global
    (B, L, L) mask. Rows whose every key is padded produce zeros.

    Eligible shapes route each block's compute through the Pallas flash
    kernels (_ring_flash, FLAGS_ring_flash); the einsum online-softmax
    walk below is the exact fallback for everything else.
    """
    size = axis_size if axis_size is not None else _axis_size(axis_name)
    if _ring_flash_eligible(q, k, is_causal):
        from ..ops.pallas.counters import bump

        try:
            bias = (jnp.where(kv_mask.astype(jnp.bool_), 0.0,
                              _NEG_INF).astype(jnp.float32)
                    if kv_mask is not None else jnp.zeros((), jnp.float32))
            out = _ring_flash(q, k, v, bias, axis_name, size, is_causal,
                              kv_mask is not None)
            bump("ring_attention", "pallas")
            return out
        except Exception as e:  # trace/lowering failure: exact fallback
            bump("ring_attention", "xla",
                 f"flash-ring error {type(e).__name__}: {e}")
    else:
        from ..ops.pallas.counters import bump

        bump("ring_attention", "xla",
             f"dispatch ineligible (q {tuple(q.shape)}, causal="
             f"{is_causal}; modulus/shape gate in _ring_flash_eligible)")
    idx = jax.lax.axis_index(axis_name)

    orig_dtype = q.dtype
    # MXU einsums run in the INPUT dtype (bf16 under AMP = 2x throughput);
    # softmax statistics and the accumulator stay f32 (flash-standard
    # mixed precision: scores/acc accumulate via preferred_element_type)
    qh = jnp.swapaxes(q, 1, 2)                       # (b, h, lq, d)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    b, h, lq, d = qh.shape
    lk = kh.shape[2]
    scale = 1.0 / math.sqrt(d)
    has_mask = kv_mask is not None
    mh = kv_mask.astype(jnp.bool_) if has_mask else None  # (b, lk)

    perm = [(i, (i + 1) % size) for i in range(size)]
    # causal alignment matches _xla_attention's bottom-right tril(k=kl-ql):
    # the last lq*size query positions align with the end of the kv axis
    causal_offset = (lk - lq) * size

    def block_update(s, m, l, acc, kc, vc, mc):
        # after s rotations this device holds the block that originated on
        # device (idx - s) mod size
        origin = jnp.mod(idx - s, size)
        scores = jnp.einsum("bhqd,bhkd->bhqk", qh, kc,
                            preferred_element_type=jnp.float32) * scale
        valid = None
        if is_causal:
            q_pos = idx * lq + jnp.arange(lq)[:, None] + causal_offset
            k_pos = origin * lk + jnp.arange(lk)[None, :]
            valid = jnp.broadcast_to(q_pos >= k_pos, (1, 1, lq, lk))
        if has_mask:
            kvalid = mc[:, None, None, :]              # (b, 1, 1, lk)
            valid = kvalid if valid is None else (valid & kvalid)
        if valid is not None:
            scores = jnp.where(valid, scores, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
        p = jnp.exp(scores - m_new[..., None])
        if valid is not None:
            # fully-masked rows have scores == m_new == _NEG_INF and would
            # otherwise contribute exp(0) = 1
            p = jnp.where(valid, p, 0.0)
        alpha = jnp.exp(m - m_new)
        l = alpha * l + jnp.sum(p, axis=-1)
        acc = acc * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p.astype(vc.dtype), vc,
            preferred_element_type=jnp.float32)
        return m_new, l, acc

    def guarded_update(s, m, l, acc, kc, vc, mc):
        """block_update behind a lax.cond that skips whole KV blocks:
        causal blocks entirely above the diagonal (the ~2x win at long
        sequence — the classic ring walk computes then discards them)
        and fully-padded blocks. The ppermute always runs; only the
        einsum pair is skipped."""
        needed = None
        if is_causal:
            origin = jnp.mod(idx - s, size)
            # intersects the causal triangle iff the local Q block's last
            # position can see the arriving KV block's first position
            q_last = idx * lq + (lq - 1) + causal_offset
            needed = q_last >= origin * lk
        if has_mask:
            any_valid = jnp.any(mc)
            needed = any_valid if needed is None else (needed & any_valid)
        if needed is None:
            return block_update(s, m, l, acc, kc, vc, mc)
        return jax.lax.cond(
            needed,
            lambda: block_update(s, m, l, acc, kc, vc, mc),
            lambda: (m, l, acc))

    def body(s, carry):
        m, l, acc, kc, vc, mc = carry
        m, l, acc = guarded_update(s, m, l, acc, kc, vc, mc)
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        if has_mask:
            mc = jax.lax.ppermute(mc, axis_name, perm)
        return m, l, acc, kc, vc, mc

    # derive initial carries from the inputs (0*q) so they carry the same
    # varying-manual-axes type as the loop outputs (shard_map vma check);
    # f32 regardless of input dtype — they are the softmax statistics
    zero_q = (0.0 * qh[..., 0]).astype(jnp.float32)  # (b, h, lq)
    m0 = zero_q + _NEG_INF
    l0 = zero_q
    acc0 = zero_q[..., None] * vh[..., :1, :].astype(jnp.float32)
    # a dummy all-True mask keeps the carry structure static when unmasked
    mc0 = mh if has_mask else jnp.zeros((), jnp.bool_)
    # the last block needs no rotation afterwards: loop size-1 rotations,
    # then fold in the final kv block outside the loop (saves one ICI hop)
    m, l, acc, kc, vc, mc = jax.lax.fori_loop(
        0, size - 1, body, (m0, l0, acc0, kh, vh, mc0))
    m, l, acc = guarded_update(size - 1, m, l, acc, kc, vc, mc)

    # fully-masked rows: l == 0 -> output 0 (not NaN)
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return jnp.swapaxes(out, 1, 2).astype(orig_dtype)


# ---------------------------------------------------------------------------
# Ulysses attention (all-to-all head/sequence reshuffle)
# ---------------------------------------------------------------------------


def ulysses_attention_local(q, k, v, axis_name: str, is_causal: bool = False,
                            axis_size: Optional[int] = None, kv_mask=None):
    """Ulysses sequence parallelism; call inside shard_map.

    q/k/v: (B, L_local, H, D), H divisible by the axis size. all_to_all to
    (B, L_full, H/size, D), local full attention, all_to_all back.
    kv_mask: optional (B, L_full) bool key-padding mask, replicated over
    the axis (after the all-to-all every device sees the full kv axis).

    The post-all-to-all local attention sees the FULL sequence with a
    head subset — exactly the flash kernel's sweet spot at the long
    lengths Ulysses exists for — so the mask-free path dispatches
    through _local_attention (Pallas when eligible; NOT
    flash_attention_or_fallback, which would re-enter the active
    sequence_parallel context and recurse).
    """
    from ..ops.pallas.flash_attention import _local_attention, _xla_attention

    def a2a_fwd(x):   # seq-sharded -> head-sharded
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def a2a_bwd(x):   # head-sharded -> seq-sharded
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    qa, ka, va = a2a_fwd(q), a2a_fwd(k), a2a_fwd(v)
    if kv_mask is None:
        out = _local_attention(qa, ka, va, is_causal)
    else:
        out = _xla_attention(qa, ka, va,
                             kv_mask[:, None, None, :].astype(jnp.bool_),
                             0.0, is_causal, None)
    return a2a_bwd(out)


# ---------------------------------------------------------------------------
# user-facing wrappers (shard_map over a mesh)
# ---------------------------------------------------------------------------


def _log_sp_fallback(reason: str):
    """Sequence-parallel fallbacks are a silent perf cliff (the full
    attention runs replicated); surface them (FLAGS_sp_fallback_warn)."""
    from ..framework.flags import get_flag

    try:
        warn = get_flag("sp_fallback_warn")
    except KeyError:
        warn = True
    if warn:
        import warnings

        warnings.warn(
            f"sequence-parallel attention fell back to the local/XLA "
            f"path: {reason}", RuntimeWarning, stacklevel=3)


def ring_attention(q, k, v, *, mesh: Optional[Mesh] = None,
                   seq_axis: str = "sp", batch_axis: str = "dp",
                   head_axis: str = "tp",
                   is_causal: bool = False, impl: str = "ring",
                   kv_mask=None):
    """Context-parallel attention over `seq_axis` of `mesh`.

    q/k/v: (B, L, H, D) global arrays (or sharded under pjit — specs
    compose). impl: "ring" (ppermute KV rotation) or "ulysses"
    (all-to-all head split). kv_mask: optional (B, L) bool key-padding
    mask (True = attend) — sharded over the sequence axis and streamed
    around the ring with its K/V block. Shapes the sharded path cannot
    handle (sequence/batch/heads not divisible by the relevant axis
    sizes) fall back to plain XLA attention, logged via
    FLAGS_sp_fallback_warn.
    """
    from ..ops.pallas.flash_attention import _local_attention, _xla_attention

    def fallback(reason):
        _log_sp_fallback(reason)
        if kv_mask is None:
            return _local_attention(q, k, v, is_causal)
        return _xla_attention(q, k, v,
                              kv_mask[:, None, None, :].astype(jnp.bool_),
                              0.0, is_causal, None)

    mesh = mesh or get_mesh()
    b, lq, h, d = q.shape
    lk = k.shape[1]
    if mesh is None or seq_axis not in mesh.axis_names:
        return fallback(f"no mesh axis {seq_axis!r}")
    size = mesh.shape[seq_axis]
    if size <= 1:
        return fallback(f"axis {seq_axis!r} has size 1")
    if lq % size != 0 or lk % size != 0:
        return fallback(
            f"sequence lengths ({lq}, {lk}) not divisible by "
            f"{seq_axis}={size}")
    ba = batch_axis if (batch_axis in mesh.axis_names
                        and batch_axis != seq_axis
                        and b % mesh.shape[batch_axis] == 0) else None
    # keep the head axis sharded (e.g. over tp) so attention is not
    # redundantly replicated across tensor-parallel devices
    ha = head_axis if (head_axis in mesh.axis_names
                       and head_axis not in (seq_axis, ba)
                       and h % mesh.shape[head_axis] == 0) else None
    h_local = h // (mesh.shape[ha] if ha else 1)
    if impl == "ulysses" and h_local % size != 0:
        impl = "ring"   # ulysses needs local heads divisible by the sp axis
    spec = PartitionSpec(ba, seq_axis, ha, None)
    local = ring_attention_local if impl == "ring" else ulysses_attention_local
    fn = functools.partial(local, axis_name=seq_axis, is_causal=is_causal,
                           axis_size=size)
    if kv_mask is None:
        return _shard_map(fn, mesh, (spec, spec, spec), spec)(q, k, v)
    kv_mask = jnp.asarray(kv_mask)
    # ring: the mask shard travels with its kv block; ulysses: every
    # device needs the full kv axis after the all-to-all -> replicated
    mspec = (PartitionSpec(ba, seq_axis) if impl == "ring"
             else PartitionSpec(ba, None))
    wrapped = lambda q_, k_, v_, m_: fn(q_, k_, v_, kv_mask=m_)  # noqa: E731
    return _shard_map(wrapped, mesh,
                      (spec, spec, spec, mspec), spec)(q, k, v, kv_mask)


ulysses_attention = functools.partial(ring_attention, impl="ulysses")


# ---------------------------------------------------------------------------
# sequence-parallel context: routes nn.functional.scaled_dot_product_attention
# through ring/ulysses attention when active (trace-time — see module note)
# ---------------------------------------------------------------------------

_SP_STATE = {"axis": None, "impl": "ring", "batch_axis": "dp", "mesh": None}


@contextmanager
def sequence_parallel(seq_axis: str = "sp", impl: str = "ring",
                      batch_axis: str = "dp", mesh: Optional[Mesh] = None):
    """Within this context, scaled_dot_product_attention shards the sequence
    axis over `seq_axis` using ring/Ulysses attention (mask-free paths).

    Pass `mesh` to pin the mesh (TrainStep does); otherwise the global
    mesh at trace time is used. Trace-time semantics: affects code being
    traced/compiled inside the context. Already-compiled executables are
    not retraced — for jitted training steps use
    `TrainStep(..., sequence_parallel=...)` instead.
    """
    prev = dict(_SP_STATE)
    _SP_STATE.update(axis=seq_axis, impl=impl, batch_axis=batch_axis,
                     mesh=mesh)
    try:
        yield
    finally:
        _SP_STATE.update(prev)


def active_sequence_parallel():
    """(axis, impl, batch_axis, mesh) if a usable sp context + mesh axis
    exist; the scope's pinned mesh wins over the global one."""
    axis = _SP_STATE["axis"]
    if axis is None:
        return None
    mesh = _SP_STATE["mesh"] or get_mesh()
    if mesh is None or axis not in mesh.axis_names or mesh.shape[axis] <= 1:
        return None
    return axis, _SP_STATE["impl"], _SP_STATE["batch_axis"], mesh
