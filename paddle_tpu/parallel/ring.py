"""Sequence/context parallelism: ring attention and Ulysses (all-to-all).

The reference framework predates sequence parallelism entirely (SURVEY §2.6:
TP/SP/CP/ring attention absent; long sequences were handled only via
recompute, /root/reference/python/paddle/fluid/backward.py:629, and pipeline
micro-batching, /root/reference/paddle/fluid/framework/section_worker.cc).
This module is the TPU-first design for that gap: the sequence axis of
q/k/v is sharded over a named mesh axis, and

- **ring attention**: every device keeps its local Q block resident and
  streams K/V blocks around the ring with `lax.ppermute` (ICI
  neighbour-exchange), combining partial results with a numerically stable
  online softmax — flash attention across chips.
- **Ulysses**: `lax.all_to_all` re-shards (seq-sharded, all heads) ->
  (full seq, head-sharded), runs ordinary attention locally per head group,
  and re-shards back. Cheaper for moderate sequence lengths when
  num_heads % axis_size == 0.

Both are plain collectives inside `shard_map`, so they compose with data /
tensor parallel axes of the same mesh and with `jax.grad` (XLA
differentiates ppermute/all_to_all natively).

NOTE on tracing: the `sequence_parallel()` context is consulted at TRACE
time. A function jitted outside the context keeps its non-ring executable
in jax's cache even if later called inside the context (and vice versa).
For the training hot path, prefer the explicit
`jit.TrainStep(..., sequence_parallel="sp")` knob, which bakes the ring
path into the compiled step deterministically.
"""
from __future__ import annotations

import functools
import math
from contextlib import contextmanager
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

from .mesh import get_mesh

_NEG_INF = -1e30


def _axis_size(axis_name):
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


# ---------------------------------------------------------------------------
# ring attention (inside shard_map; q/k/v local blocks (B, L_local, H, D))
# ---------------------------------------------------------------------------


def ring_attention_local(q, k, v, axis_name: str, is_causal: bool = False,
                         axis_size: Optional[int] = None):
    """Ring attention over `axis_name`; call inside shard_map.

    q/k/v: (B, L_local, H, D) — this device's sequence shard. Returns the
    attention output for the local Q block, (B, L_local, H, D). The KV ring
    walk is a `fori_loop`, so HLO size stays O(1) in the axis size.
    """
    size = axis_size if axis_size is not None else _axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)

    orig_dtype = q.dtype
    qh = jnp.swapaxes(q, 1, 2).astype(jnp.float32)   # (b, h, lq, d)
    kh = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    vh = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
    b, h, lq, d = qh.shape
    lk = kh.shape[2]
    scale = 1.0 / math.sqrt(d)

    perm = [(i, (i + 1) % size) for i in range(size)]
    # causal alignment matches _xla_attention's bottom-right tril(k=kl-ql):
    # the last lq*size query positions align with the end of the kv axis
    causal_offset = (lk - lq) * size

    def block_update(s, m, l, acc, kc, vc):
        # after s rotations this device holds the block that originated on
        # device (idx - s) mod size
        origin = jnp.mod(idx - s, size)
        scores = jnp.einsum("bhqd,bhkd->bhqk", qh, kc) * scale
        if is_causal:
            q_pos = idx * lq + jnp.arange(lq)[:, None] + causal_offset
            k_pos = origin * lk + jnp.arange(lk)[None, :]
            valid = q_pos >= k_pos                     # (lq, lk)
            scores = jnp.where(valid, scores, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
        p = jnp.exp(scores - m_new[..., None])
        if is_causal:
            # fully-masked rows have scores == m_new == _NEG_INF and would
            # otherwise contribute exp(0) = 1
            p = jnp.where(valid, p, 0.0)
        alpha = jnp.exp(m - m_new)
        l = alpha * l + jnp.sum(p, axis=-1)
        acc = acc * alpha[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, vc)
        return m_new, l, acc

    def body(s, carry):
        m, l, acc, kc, vc = carry
        m, l, acc = block_update(s, m, l, acc, kc, vc)
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        return m, l, acc, kc, vc

    # derive initial carries from the inputs (0*q) so they carry the same
    # varying-manual-axes type as the loop outputs (shard_map vma check)
    zero_q = 0.0 * qh[..., 0]                       # (b, h, lq)
    m0 = zero_q + _NEG_INF
    l0 = zero_q
    acc0 = zero_q[..., None] * vh[..., :1, :]       # (b, h, lq, dv)
    # the last block needs no rotation afterwards: loop size-1 rotations,
    # then fold in the final kv block outside the loop (saves one ICI hop)
    m, l, acc, kc, vc = jax.lax.fori_loop(
        0, size - 1, body, (m0, l0, acc0, kh, vh))
    m, l, acc = block_update(size - 1, m, l, acc, kc, vc)

    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return jnp.swapaxes(out, 1, 2).astype(orig_dtype)


# ---------------------------------------------------------------------------
# Ulysses attention (all-to-all head/sequence reshuffle)
# ---------------------------------------------------------------------------


def ulysses_attention_local(q, k, v, axis_name: str, is_causal: bool = False,
                            axis_size: Optional[int] = None):
    """Ulysses sequence parallelism; call inside shard_map.

    q/k/v: (B, L_local, H, D), H divisible by the axis size. all_to_all to
    (B, L_full, H/size, D), local full attention, all_to_all back.
    """
    from ..ops.pallas.flash_attention import _xla_attention

    def a2a_fwd(x):   # seq-sharded -> head-sharded
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def a2a_bwd(x):   # head-sharded -> seq-sharded
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    qa, ka, va = a2a_fwd(q), a2a_fwd(k), a2a_fwd(v)
    out = _xla_attention(qa, ka, va, None, 0.0, is_causal, None)
    return a2a_bwd(out)


# ---------------------------------------------------------------------------
# user-facing wrappers (shard_map over a mesh)
# ---------------------------------------------------------------------------


def ring_attention(q, k, v, *, mesh: Optional[Mesh] = None,
                   seq_axis: str = "sp", batch_axis: str = "dp",
                   head_axis: str = "tp",
                   is_causal: bool = False, impl: str = "ring"):
    """Context-parallel attention over `seq_axis` of `mesh`.

    q/k/v: (B, L, H, D) global arrays (or sharded under pjit — specs
    compose). impl: "ring" (ppermute KV rotation) or "ulysses"
    (all-to-all head split). Shapes the sharded path cannot handle
    (sequence/batch/heads not divisible by the relevant axis sizes) fall
    back to plain XLA attention instead of erroring.
    """
    from ..ops.pallas.flash_attention import _local_attention

    mesh = mesh or get_mesh()
    b, lq, h, d = q.shape
    lk = k.shape[1]
    if mesh is None or seq_axis not in mesh.axis_names:
        return _local_attention(q, k, v, is_causal)
    size = mesh.shape[seq_axis]
    if size <= 1 or lq % size != 0 or lk % size != 0:
        return _local_attention(q, k, v, is_causal)
    ba = batch_axis if (batch_axis in mesh.axis_names
                        and batch_axis != seq_axis
                        and b % mesh.shape[batch_axis] == 0) else None
    # keep the head axis sharded (e.g. over tp) so attention is not
    # redundantly replicated across tensor-parallel devices
    ha = head_axis if (head_axis in mesh.axis_names
                       and head_axis not in (seq_axis, ba)
                       and h % mesh.shape[head_axis] == 0) else None
    h_local = h // (mesh.shape[ha] if ha else 1)
    if impl == "ulysses" and h_local % size != 0:
        impl = "ring"   # ulysses needs local heads divisible by the sp axis
    spec = PartitionSpec(ba, seq_axis, ha, None)
    local = ring_attention_local if impl == "ring" else ulysses_attention_local
    fn = functools.partial(local, axis_name=seq_axis, is_causal=is_causal,
                           axis_size=size)
    return jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec)(q, k, v)


ulysses_attention = functools.partial(ring_attention, impl="ulysses")


# ---------------------------------------------------------------------------
# sequence-parallel context: routes nn.functional.scaled_dot_product_attention
# through ring/ulysses attention when active (trace-time — see module note)
# ---------------------------------------------------------------------------

_SP_STATE = {"axis": None, "impl": "ring", "batch_axis": "dp", "mesh": None}


@contextmanager
def sequence_parallel(seq_axis: str = "sp", impl: str = "ring",
                      batch_axis: str = "dp", mesh: Optional[Mesh] = None):
    """Within this context, scaled_dot_product_attention shards the sequence
    axis over `seq_axis` using ring/Ulysses attention (mask-free paths).

    Pass `mesh` to pin the mesh (TrainStep does); otherwise the global
    mesh at trace time is used. Trace-time semantics: affects code being
    traced/compiled inside the context. Already-compiled executables are
    not retraced — for jitted training steps use
    `TrainStep(..., sequence_parallel=...)` instead.
    """
    prev = dict(_SP_STATE)
    _SP_STATE.update(axis=seq_axis, impl=impl, batch_axis=batch_axis,
                     mesh=mesh)
    try:
        yield
    finally:
        _SP_STATE.update(prev)


def active_sequence_parallel():
    """(axis, impl, batch_axis, mesh) if a usable sp context + mesh axis
    exist; the scope's pinned mesh wins over the global one."""
    axis = _SP_STATE["axis"]
    if axis is None:
        return None
    mesh = _SP_STATE["mesh"] or get_mesh()
    if mesh is None or axis not in mesh.axis_names or mesh.shape[axis] <= 1:
        return None
    return axis, _SP_STATE["impl"], _SP_STATE["batch_axis"], mesh
