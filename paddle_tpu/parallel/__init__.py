"""SPMD parallelism over TPU meshes (replaces the reference ParallelExecutor
+ transpiler + fleet meta-optimizer machinery — SURVEY.md §2.6)."""
from .mesh import (  # noqa: F401
    create_mesh, get_mesh, set_mesh, replicated, data_sharding, axis_size,
    mesh_for_shape, AXES, DATA_AXIS_NAMES,
)
from .sharding import (  # noqa: F401
    shard_params, place_params, spec_for, TRANSFORMER_TP_RULES,
)
from .pipeline import (  # noqa: F401
    pipeline_apply, pipeline_1f1b_value_and_grad, stack_stage_params,
    gpipe_schedule, gpipe_bubble_fraction, one_f_one_b_schedule,
    interleaved_schedule, pipeline_timeline, schedule_bubble_fraction,
)
from .ring import (  # noqa: F401
    ring_attention, ulysses_attention, ring_attention_local,
    ulysses_attention_local, sequence_parallel, active_sequence_parallel,
)
from .collectives import (  # noqa: F401
    QUANT_BLOCK, all_gather, all_gather_nbytes, allreduce_done,
    allreduce_start, bucketed_allreduce, encoded_nbytes, np_decode,
    np_encode, quant_decode, quant_encode, quantized_allreduce,
    reduce_scatter, reduce_scatter_nbytes, ring_allreduce_local,
    ring_nbytes,
)
