"""Device mesh management.

TPU-native replacement for the reference's NCCL ring/communicator registry
(/root/reference/paddle/fluid/platform/collective_helper.h:62
NCCLCommContext keyed by ring_id, nccl_helper.h:234 InitFlatCtxs /
:265 InitHierarchicalCtxs): instead of rings, a named jax.sharding.Mesh
whose axes ('dp','pp','tp','sp','ep') are what collectives address.
Hierarchical inter/intra-node rings become mesh factorizations with the
DCN axis outermost.
"""
from __future__ import annotations

from contextlib import contextmanager
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..framework.bringup import safe_devices as _safe_devices

_global_mesh: list = [None]

AXES = ("dp", "pp", "tp", "sp", "ep")

# axes a feed's batch dim rides by default (data_sharding / the static
# executor's feed shardings): plain data parallel ('dp') or the classic
# CompiledProgram 'data' axis. Explicit batch axes (e.g. ('dp', 'sp'))
# go through data_sharding(..., axes=...).
DATA_AXIS_NAMES = ("dp", "data")


def create_mesh(mesh_shape: Optional[Dict[str, int]] = None,
                devices: Optional[Sequence] = None) -> Mesh:
    """create_mesh({'dp': 2, 'tp': 4}) over local (or given) devices.

    Axes with size 1 may be omitted; remaining devices fold into 'dp'.
    DCN-reaching axes should be listed first (outermost) so XLA keeps
    high-traffic collectives on ICI.
    """
    devices = list(devices if devices is not None else _safe_devices())
    mesh_shape = dict(mesh_shape or {})
    sized = {k: v for k, v in mesh_shape.items() if v and v > 1}
    total = int(np.prod(list(sized.values()))) if sized else 1
    if total > len(devices):
        raise ValueError(f"mesh needs {total} devices, have {len(devices)}")
    if total < len(devices):
        if "dp" in sized:
            sized["dp"] *= len(devices) // total
        else:
            sized = {"dp": len(devices) // total, **sized}
    names = tuple(sized.keys())
    shape = tuple(sized.values())
    arr = np.asarray(devices[: int(np.prod(shape))]).reshape(shape)
    mesh = Mesh(arr, names)
    _global_mesh[0] = mesh
    return mesh


def get_mesh() -> Optional[Mesh]:
    return _global_mesh[0]


def set_mesh(mesh: Mesh):
    _global_mesh[0] = mesh


# -- trace-time mesh marker -------------------------------------------------
# TrainStep sets this while TRACING its pjit'd step (same trace-time
# pattern as ring.sequence_parallel): kernels whose pallas custom calls
# XLA cannot SPMD-partition (fused_xent — not wrapped in shard_map)
# consult it to self-gate under multi-device traces. The ambient
# _global_mesh is NOT used for that decision: it leaks across tests and
# may differ from the mesh actually governing the trace.

_trace_mesh: list = [(None, ())]


@contextmanager
def trace_mesh(mesh: Optional[Mesh], row_axes: Sequence[str] = ()):
    """row_axes: the mesh axes the BATCH rows are sharded over (from
    TrainStep's data_spec/data_axes) — what a row-parallel kernel needs
    to shard_map itself and psum its reductions."""
    prev = _trace_mesh[0]
    _trace_mesh[0] = (mesh, tuple(row_axes))
    try:
        yield
    finally:
        _trace_mesh[0] = prev


def active_trace_mesh() -> Optional[Mesh]:
    """The mesh of the TrainStep trace currently being built, if any."""
    return _trace_mesh[0][0]


def active_trace_row_axes() -> tuple:
    """The batch-row sharding axes of the current TrainStep trace."""
    return _trace_mesh[0][1]


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def data_sharding(mesh: Mesh, batch_ndim: int = 1,
                  axes: Optional[Sequence[str]] = None) -> NamedSharding:
    """Shard the leading (batch) dim over the mesh's data-like axes.

    ``axes`` names the batch axes explicitly (e.g. ``('dp', 'sp')`` for
    batch rows split over data AND sequence-parallel ranks); names absent
    from the mesh are dropped. With ``axes=None`` the default derives
    from the active mesh's axis names (every :data:`DATA_AXIS_NAMES`
    axis present), so the executor's feed sharding works on any mesh
    shape — 'dp', the classic CompiledProgram 'data' axis, or both."""
    if axes is None:
        axes = [a for a in DATA_AXIS_NAMES if a in mesh.axis_names]
    else:
        axes = [a for a in axes if a in mesh.axis_names]
    spec = [tuple(axes) if axes else None] + [None] * (batch_ndim - 1)
    return NamedSharding(mesh, PartitionSpec(*spec))


# (axis sizes tuple, device ids tuple) -> Mesh: the static executor
# resolves BuildStrategy.mesh_shape through here on every step, so the
# Mesh object must be stable (jax mesh/sharding caches key on identity)
_mesh_cache: Dict[tuple, Mesh] = {}


def mesh_for_shape(mesh_shape: Dict[str, int],
                   devices: Optional[Sequence] = None) -> Mesh:
    """A Mesh of exactly ``mesh_shape`` (no dp-folding of leftover
    devices, unlike :func:`create_mesh`) over the first
    prod(sizes) local (or given) devices, cached — repeated calls with
    the same shape return the SAME Mesh object and never touch the
    ambient global mesh."""
    devices = list(devices if devices is not None else _safe_devices())
    sized = {str(k): int(v) for k, v in (mesh_shape or {}).items()
             if int(v) > 1}
    if not sized:
        raise ValueError(f"mesh_for_shape: no axis with size > 1 in "
                         f"{mesh_shape!r}")
    total = int(np.prod(list(sized.values())))
    if total > len(devices):
        raise ValueError(
            f"mesh_shape {mesh_shape!r} needs {total} devices, have "
            f"{len(devices)} (set XLA_FLAGS="
            f"--xla_force_host_platform_device_count=N for CPU tests)")
    key = (tuple(sized.items()), tuple(id(d) for d in devices[:total]))
    mesh = _mesh_cache.get(key)
    if mesh is None:
        arr = np.asarray(devices[:total]).reshape(tuple(sized.values()))
        mesh = Mesh(arr, tuple(sized.keys()))
        _mesh_cache[key] = mesh
    return mesh


def axis_size(mesh: Mesh, name: str) -> int:
    return mesh.shape[name] if name in mesh.axis_names else 1
