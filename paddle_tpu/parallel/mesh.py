"""Device mesh management.

TPU-native replacement for the reference's NCCL ring/communicator registry
(/root/reference/paddle/fluid/platform/collective_helper.h:62
NCCLCommContext keyed by ring_id, nccl_helper.h:234 InitFlatCtxs /
:265 InitHierarchicalCtxs): instead of rings, a named jax.sharding.Mesh
whose axes ('dp','pp','tp','sp','ep') are what collectives address.
Hierarchical inter/intra-node rings become mesh factorizations with the
DCN axis outermost.
"""
from __future__ import annotations

from contextlib import contextmanager
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..framework.bringup import safe_devices as _safe_devices

_global_mesh: list = [None]

AXES = ("dp", "pp", "tp", "sp", "ep")


def create_mesh(mesh_shape: Optional[Dict[str, int]] = None,
                devices: Optional[Sequence] = None) -> Mesh:
    """create_mesh({'dp': 2, 'tp': 4}) over local (or given) devices.

    Axes with size 1 may be omitted; remaining devices fold into 'dp'.
    DCN-reaching axes should be listed first (outermost) so XLA keeps
    high-traffic collectives on ICI.
    """
    devices = list(devices if devices is not None else _safe_devices())
    mesh_shape = dict(mesh_shape or {})
    sized = {k: v for k, v in mesh_shape.items() if v and v > 1}
    total = int(np.prod(list(sized.values()))) if sized else 1
    if total > len(devices):
        raise ValueError(f"mesh needs {total} devices, have {len(devices)}")
    if total < len(devices):
        if "dp" in sized:
            sized["dp"] *= len(devices) // total
        else:
            sized = {"dp": len(devices) // total, **sized}
    names = tuple(sized.keys())
    shape = tuple(sized.values())
    arr = np.asarray(devices[: int(np.prod(shape))]).reshape(shape)
    mesh = Mesh(arr, names)
    _global_mesh[0] = mesh
    return mesh


def get_mesh() -> Optional[Mesh]:
    return _global_mesh[0]


def set_mesh(mesh: Mesh):
    _global_mesh[0] = mesh


# -- trace-time mesh marker -------------------------------------------------
# TrainStep sets this while TRACING its pjit'd step (same trace-time
# pattern as ring.sequence_parallel): kernels whose pallas custom calls
# XLA cannot SPMD-partition (fused_xent — not wrapped in shard_map)
# consult it to self-gate under multi-device traces. The ambient
# _global_mesh is NOT used for that decision: it leaks across tests and
# may differ from the mesh actually governing the trace.

_trace_mesh: list = [(None, ())]


@contextmanager
def trace_mesh(mesh: Optional[Mesh], row_axes: Sequence[str] = ()):
    """row_axes: the mesh axes the BATCH rows are sharded over (from
    TrainStep's data_spec/data_axes) — what a row-parallel kernel needs
    to shard_map itself and psum its reductions."""
    prev = _trace_mesh[0]
    _trace_mesh[0] = (mesh, tuple(row_axes))
    try:
        yield
    finally:
        _trace_mesh[0] = prev


def active_trace_mesh() -> Optional[Mesh]:
    """The mesh of the TrainStep trace currently being built, if any."""
    return _trace_mesh[0][0]


def active_trace_row_axes() -> tuple:
    """The batch-row sharding axes of the current TrainStep trace."""
    return _trace_mesh[0][1]


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def data_sharding(mesh: Mesh, batch_ndim: int = 1) -> NamedSharding:
    """Shard leading (batch) dim over every data-like axis present."""
    axes = [a for a in ("dp",) if a in mesh.axis_names]
    spec = [tuple(axes) if axes else None] + [None] * (batch_ndim - 1)
    return NamedSharding(mesh, PartitionSpec(*spec))


def axis_size(mesh: Mesh, name: str) -> int:
    return mesh.shape[name] if name in mesh.axis_names else 1
