"""Parameter sharding rules.

TPU-native replacement for the reference multi-device graph builders
(/root/reference/paddle/fluid/framework/ir/multi_devices_graph_pass/
multi_devices_graph_pass.h AllReduce/Reduce/Dist builders): instead of
cloning the graph per device and inserting comm op-handles, parameters get
PartitionSpecs (regex rules over parameter names, t5x-style) and the XLA
SPMD partitioner inserts the collectives.
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

Rules = List[Tuple[str, PartitionSpec]]

# Default tensor-parallel rules for the transformer layer stack
# (megatron-style: column-parallel qkv/ffn-in, row-parallel out/ffn-out).
TRANSFORMER_TP_RULES: Rules = [
    (r".*(q_proj|k_proj|v_proj)\.weight$", PartitionSpec(None, "tp")),
    (r".*(q_proj|k_proj|v_proj)\.bias$", PartitionSpec("tp")),
    (r".*out_proj\.weight$", PartitionSpec("tp", None)),
    (r".*linear1\.weight$", PartitionSpec(None, "tp")),
    (r".*linear1\.bias$", PartitionSpec("tp")),
    (r".*linear2\.weight$", PartitionSpec("tp", None)),
    (r".*(word_)?embedding.*\.weight$", PartitionSpec("tp", None)),
]


def spec_for(name: str, rules: Optional[Rules], mesh: Mesh) -> PartitionSpec:
    if rules:
        for pattern, spec in rules:
            if re.match(pattern, name):
                cleaned = tuple(
                    ax if ax is not None and ax in mesh.axis_names else None
                    for ax in spec)
                return PartitionSpec(*cleaned)
    return PartitionSpec()


def shard_params(params: Dict[str, jax.Array], mesh: Mesh,
                 rules: Optional[Rules] = None) -> Dict[str, NamedSharding]:
    """name->array dict to name->NamedSharding (replicated by default)."""
    out = {}
    for name, arr in params.items():
        spec = spec_for(name, rules, mesh)
        # drop specs that do not divide the dim evenly
        fixed = []
        for i, ax in enumerate(spec):
            if ax is None or i >= arr.ndim:
                fixed.append(None)
                continue
            size = mesh.shape[ax] if isinstance(ax, str) else 1
            fixed.append(ax if arr.shape[i] % max(size, 1) == 0 else None)
        out[name] = NamedSharding(mesh, PartitionSpec(*fixed[: arr.ndim]))
    return out


def place_params(params: Dict[str, jax.Array], shardings) -> Dict[str, jax.Array]:
    return {n: jax.device_put(a, shardings[n]) for n, a in params.items()}
