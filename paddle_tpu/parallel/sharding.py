"""Parameter sharding rules.

TPU-native replacement for the reference multi-device graph builders
(/root/reference/paddle/fluid/framework/ir/multi_devices_graph_pass/
multi_devices_graph_pass.h AllReduce/Reduce/Dist builders): instead of
cloning the graph per device and inserting comm op-handles, parameters get
PartitionSpecs (regex rules over parameter names, t5x-style) and the XLA
SPMD partitioner inserts the collectives.
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

Rules = List[Tuple[str, PartitionSpec]]

# Default tensor-parallel rules for the transformer layer stack
# (megatron-style: column-parallel qkv/ffn-in, row-parallel out/ffn-out).
TRANSFORMER_TP_RULES: Rules = [
    (r".*(q_proj|k_proj|v_proj)\.weight$", PartitionSpec(None, "tp")),
    (r".*(q_proj|k_proj|v_proj)\.bias$", PartitionSpec("tp")),
    (r".*out_proj\.weight$", PartitionSpec("tp", None)),
    (r".*linear1\.weight$", PartitionSpec(None, "tp")),
    (r".*linear1\.bias$", PartitionSpec("tp")),
    (r".*linear2\.weight$", PartitionSpec("tp", None)),
    (r".*(word_)?embedding.*\.weight$", PartitionSpec("tp", None)),
]


def spec_for(name: str, rules: Optional[Rules], mesh: Mesh) -> PartitionSpec:
    if rules:
        for pattern, spec in rules:
            if re.match(pattern, name):
                cleaned = tuple(
                    ax if ax is not None and ax in mesh.axis_names else None
                    for ax in spec)
                return PartitionSpec(*cleaned)
    return PartitionSpec()


def shard_params(params: Dict[str, jax.Array], mesh: Mesh,
                 rules: Optional[Rules] = None) -> Dict[str, NamedSharding]:
    """name->array dict to name->NamedSharding (replicated by default)."""
    out = {}
    for name, arr in params.items():
        spec = spec_for(name, rules, mesh)
        # drop specs that do not divide the dim evenly
        fixed = []
        for i, ax in enumerate(spec):
            if ax is None or i >= arr.ndim:
                fixed.append(None)
                continue
            size = mesh.shape[ax] if isinstance(ax, str) else 1
            fixed.append(ax if arr.shape[i] % max(size, 1) == 0 else None)
        out[name] = NamedSharding(mesh, PartitionSpec(*fixed[: arr.ndim]))
    return out


def device_put_counted(arr, sharding=None):
    """jax.device_put that bumps the profiler's h2d byte counter when the
    source is host-resident (numpy/python scalars). Device-to-device
    reshards of an already-resident array count nothing — re-placing
    state every step is exactly the traffic the executor hot path is
    built to avoid, so only true uploads show up in ``h2d_bytes``."""
    host_resident = not isinstance(arr, jax.Array)
    out = jax.device_put(arr, sharding) if sharding is not None \
        else jax.device_put(arr)
    if host_resident:
        try:
            nb = int(np.asarray(arr).nbytes)
        except Exception:
            nb = 0
        if nb:
            from .. import profiler

            profiler.bump_counter("h2d_bytes", nb)
    return out


def place_params(params: Dict[str, jax.Array], shardings) -> Dict[str, jax.Array]:
    return {n: device_put_counted(a, shardings[n])
            for n, a in params.items()}


# ---------------------------------------------------------------------------
# ZeRO-style sharded optimizer state.
#
# The reference has no ZeRO (SURVEY §2.6: sharding absent in v1.8; fleet's
# DistributedStrategy later grew a sharding config, mirrored in
# distributed/fleet.py). TPU-native design: optimizer slots get
# PartitionSpecs that put their largest divisible dim on the dp axis and
# the XLA SPMD partitioner derives the reduce-scatter / sharded-update /
# all-gather dance — no manual bucketing of parameters into ranks.
# ---------------------------------------------------------------------------


def zero_slot_spec(arr, mesh: Mesh, axis: str = "dp",
                   base_spec: Optional[PartitionSpec] = None) -> PartitionSpec:
    """Spec for one optimizer-slot array: keep the param's own (e.g. tp)
    sharding and additionally shard the largest free dim over `axis`."""
    spec = list(base_spec) if base_spec is not None else []
    spec = spec[: arr.ndim] + [None] * (arr.ndim - len(spec))
    already_used = any(
        axis == ax or (isinstance(ax, (tuple, list)) and axis in ax)
        for ax in spec)
    if axis in mesh.axis_names and not already_used:
        size = mesh.shape[axis]
        for i in sorted(range(arr.ndim), key=lambda i: -arr.shape[i]):
            if spec[i] is None and arr.shape[i] % max(size, 1) == 0:
                spec[i] = axis
                break
    return PartitionSpec(*spec)


def zero_shardings(params: Dict[str, jax.Array], mesh: Mesh,
                   axis: str = "dp", stage: int = 1,
                   rules: Optional[Rules] = None):
    """(param_shardings, slot_spec_fn) for ZeRO stage 1/2 (slots sharded)
    or 3 (params sharded the same way)."""
    pshard = shard_params(params, mesh, rules)
    base_shard = dict(pshard)   # rule-based specs only, pre-ZeRO

    def slot_sharding(param_name: str, slot_arr) -> NamedSharding:
        base = (base_shard[param_name].spec
                if param_name in base_shard else None)
        arr_ndim = getattr(slot_arr, "ndim", 0)
        base = base if (base is not None and len(base) <= arr_ndim) else None
        return NamedSharding(mesh, zero_slot_spec(slot_arr, mesh, axis, base))

    if stage >= 3:
        pshard = {
            n: slot_sharding(n, a) for n, a in params.items()
        }
    return pshard, slot_sharding
