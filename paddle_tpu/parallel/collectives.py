"""Quantized collectives: dynamic-blocked-quantized ring all-reduce.

EQuARX ("Efficient Quantized AllReduce in XLA", PAPERS.md) inside this
repo's shard_map idiom (ring.py precedent): the DP gradient all-reduce
— the interconnect wall at scale-out — runs as an explicit ring
reduce-scatter + all-gather over `lax.ppermute`, with every hop's
payload encoded (per-block scaled int8, or bf16) and every reduce step
ACCUMULATING IN f32 (the PR 5 accumulator discipline, so the accuracy
gates stay provable). int8 wire bytes are ~1/4 of f32 plus one f32
scale per ``QUANT_BLOCK`` elements — ``encoded_nbytes`` is the closed
form the cost model, the PS wire plane, and the bench probe all share.

Determinism: encode is pure jnp arithmetic (round-half-to-even via
``jnp.rint``, max-abs block scales), decode is exact multiply — the
round trip is bitwise deterministic, and the all-gather phase forwards
the QUANTIZED payload unchanged, so every device decodes the identical
bytes and ends with bitwise-identical reduced values (what lets the
executor run the optimizer region replicated inside shard_map).

Overlap split: ``allreduce_start`` runs the reduce-scatter phase and
returns a carry; ``allreduce_done`` runs the all-gather and returns the
reduced tensor. The executor issues start(bucket k+1) before
done(bucket k), so the traced program interleaves the buckets' ring
hops — XLA's latency-hiding scheduler is free to run bucket k's
all-gather while bucket k+1's reduce-scatter (and the surrounding
compute) is in flight, instead of one barrier-shaped reduce at the end.

The numpy codecs at the bottom are the PS data plane's wire encodings
(ps/service.py push/pull payloads + the primary→backup replication
stream) — same layout, same closed form, host-side.

Escape: ``PADDLE_QUANT_ALLREDUCE=0`` pins every consumer back to the
XLA f32 path (resolve_comm in static/passes.py returns None; the PS
client drops to codec f32) — the established kernel-pattern escape leg,
bitwise equal to the pre-quantization baseline.
"""
from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np

# the wire codecs + closed forms live in ps/codec.py (stdlib+numpy
# only: the PS plane must import them without loading jax) — this
# module re-exports them as the one collective-side surface
from ..ps.codec import (  # noqa: F401
    CODEC_IDS, CODEC_NAMES, QUANT_BLOCK, codec_name, encoded_nbytes,
    np_decode, np_encode, ring_nbytes,
)

__all__ = [
    "CODEC_IDS", "CODEC_NAMES", "QUANT_BLOCK",
    "encoded_nbytes", "ring_nbytes",
    "reduce_scatter_nbytes", "all_gather_nbytes",
    "quant_encode", "quant_decode",
    "ring_allreduce_local", "allreduce_start", "allreduce_done",
    "reduce_scatter", "all_gather",
    "quantized_allreduce", "bucketed_allreduce", "padded_len",
    "np_encode", "np_decode",
    "quant_allreduce_escaped", "shard_map_nocheck",
]


def reduce_scatter_nbytes(n_elems: int, group: int, codec: str,
                          block: int = QUANT_BLOCK) -> int:
    """Per-device wire bytes of the reduce-scatter half of the ring:
    ``(g-1)/g`` of the encoded payload (one encoded chunk per hop,
    g-1 hops) — half of :func:`ring_nbytes`."""
    g = max(1, int(group))
    if g <= 1:
        return 0
    return ring_nbytes(n_elems, group, codec, block) // 2


def all_gather_nbytes(n_elems: int, group: int, codec: str,
                      block: int = QUANT_BLOCK) -> int:
    """Per-device wire bytes of the all-gather half of the ring — the
    same ``(g-1)/g`` of the encoded payload as the reduce-scatter half
    (the carried chunk circulates g-1 hops); the two halves sum to
    :func:`ring_nbytes` exactly (this side carries the floor
    remainder)."""
    g = max(1, int(group))
    if g <= 1:
        return 0
    full = ring_nbytes(n_elems, group, codec, block)
    return full - full // 2


def quant_allreduce_escaped() -> bool:
    """True when ``PADDLE_QUANT_ALLREDUCE=0`` pins the escape leg."""
    return os.environ.get("PADDLE_QUANT_ALLREDUCE", "").strip() in (
        "0", "off", "false")


# ---------------------------------------------------------------------------
# jnp codecs (trace-time; used inside shard_map / jit)
# ---------------------------------------------------------------------------


def quant_encode(x, codec: str, block: int = QUANT_BLOCK):
    """Encode a flat f32 vector (length divisible by ``block`` for
    int8 — the collective pads). Returns ``(payload, scales)`` with
    ``scales=None`` for bf16/f32. Deterministic: max-abs block scales,
    ``jnp.rint`` (round-half-to-even), symmetric clamp at ±127."""
    import jax.numpy as jnp

    if codec == "f32":
        return x.astype(jnp.float32), None
    if codec == "bf16":
        return x.astype(jnp.bfloat16), None
    if codec != "int8":
        raise ValueError(f"unknown codec {codec!r} "
                         f"(expected f32|bf16|int8)")
    xb = x.astype(jnp.float32).reshape(-1, block)
    amax = jnp.max(jnp.abs(xb), axis=1, keepdims=True)
    scale = amax / 127.0
    # zero blocks: scale 0 would divide 0/0 — encode exact zeros
    safe = jnp.where(scale > 0, scale, 1.0)
    q = jnp.clip(jnp.rint(xb / safe), -127, 127).astype(jnp.int8)
    return q.reshape(-1), scale.reshape(-1)


def quant_decode(payload, scales, codec: str, block: int = QUANT_BLOCK):
    """Exact inverse transport decode back to f32 (multiply only — the
    lossy step is encode's rounding)."""
    import jax.numpy as jnp

    if codec in ("f32", "bf16"):
        return payload.astype(jnp.float32)
    qb = payload.reshape(-1, block).astype(jnp.float32)
    return (qb * scales.reshape(-1, 1)).reshape(-1)


# ---------------------------------------------------------------------------
# shard_map compat (jax.shard_map landed after 0.4; check_rep/check_vma
# renamed across versions — one resolver, reused by ring.py)
# ---------------------------------------------------------------------------


def shard_map_fn():
    import jax

    fn = getattr(jax, "shard_map", None)
    if fn is None:
        from jax.experimental.shard_map import shard_map as fn
    return fn


def shard_map_nocheck(fn, mesh, in_specs, out_specs):
    """shard_map with replication/vma checking OFF: the quantized ring
    produces outputs that are bitwise-replicated by construction
    (identical decodes of identical forwarded payloads) but not
    PROVABLY replicated to jax's rep/vma type system."""
    sm = shard_map_fn()
    try:
        return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=False)
    except TypeError:
        return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=False)


# ---------------------------------------------------------------------------
# the quantized ring all-reduce (inside shard_map)
# ---------------------------------------------------------------------------


def _axis_size(axis_name) -> int:
    import jax

    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def _pad_to(x, n: int):
    import jax.numpy as jnp

    flat = x.reshape(-1).astype(jnp.float32)
    if flat.shape[0] == n:
        return flat
    return jnp.concatenate(
        [flat, jnp.zeros((n - flat.shape[0],), jnp.float32)])


def padded_len(n_elems: int, group: int, block: int = QUANT_BLOCK) -> int:
    """Flat length the collective pads a bucket to: divisible by
    ``group * block`` so every ring chunk is whole scale blocks."""
    unit = max(1, int(group)) * int(block)
    return -(-int(n_elems) // unit) * unit


def allreduce_start(x, axis_name: str, *, codec: str = "int8",
                    axis_size: Optional[int] = None,
                    block: int = QUANT_BLOCK):
    """Phase 1 (reduce-scatter) of the quantized ring all-reduce; call
    inside shard_map. ``x`` is this device's local contribution (any
    shape). Returns an opaque carry for :func:`allreduce_done`.

    Ring walk: at step s every device sends the f32 partial sum of
    chunk ``(idx - s) % g`` it has accumulated so far, ENCODED
    (quantize per hop), to its +1 neighbour, decodes what arrives, and
    adds its own contribution in f32 — EQuARX's quantize-per-hop /
    accumulate-wide scheme. After g-1 hops device idx holds the fully
    reduced chunk ``(idx + 1) % g``.
    """
    import jax
    import jax.numpy as jnp

    g = axis_size if axis_size is not None else _axis_size(axis_name)
    shape, dtype = x.shape, x.dtype
    n = int(np.prod(shape)) if shape else 1
    total = padded_len(n, g, block)
    flat = _pad_to(x, total).reshape(g, total // g)
    if g == 1:
        return ("done1", flat[0], shape, dtype, codec, block, axis_name, g)
    idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % g) for i in range(g)]

    def hop(s, acc):
        j = jnp.mod(idx - s, g)
        part = acc + jnp.take(flat, j, axis=0)
        q, sc = quant_encode(part, codec, block)
        q = jax.lax.ppermute(q, axis_name, perm)
        if sc is not None:
            sc = jax.lax.ppermute(sc, axis_name, perm)
        return quant_decode(q, sc, codec, block)

    acc = jax.lax.fori_loop(0, g - 1, hop,
                            jnp.zeros((total // g,), jnp.float32))
    mine = acc + jnp.take(flat, jnp.mod(idx + 1, g), axis=0)
    return ("rs", mine, shape, dtype, codec, block, axis_name, g)


def allreduce_done(carry, avg: bool = False):
    """Phase 2 (all-gather) completing :func:`allreduce_start`: the
    reduced chunk is encoded ONCE and circulated g-1 hops; every device
    decodes the identical payload (own chunk included — it goes through
    the same encode/decode), so the output is bitwise-replicated.
    ``avg=True`` divides by g after decode (mean-gradient semantics)."""
    import jax
    import jax.numpy as jnp

    tag, mine, shape, dtype, codec, block, axis_name, g = carry
    if tag == "done1":
        out = mine
    else:
        idx = jax.lax.axis_index(axis_name)
        perm = [(i, (i + 1) % g) for i in range(g)]
        q, sc = quant_encode(mine, codec, block)
        own = quant_decode(q, sc, codec, block)
        chunk = own.shape[0]
        out0 = jnp.zeros((g, chunk), jnp.float32)
        out0 = out0.at[jnp.mod(idx + 1, g)].set(own)

        def hop(s, carry2):
            out, q, sc = carry2
            q = jax.lax.ppermute(q, axis_name, perm)
            if sc is not None:
                sc = jax.lax.ppermute(sc, axis_name, perm)
            # after s+1 rotations the payload originated at idx-s-1,
            # whose reduced chunk position is (idx - s) % g
            out = out.at[jnp.mod(idx - s, g)].set(
                quant_decode(q, sc, codec, block))
            return out, q, sc

        if sc is None:
            sc = jnp.zeros((), jnp.float32)  # static carry structure

            def hop_nosc(s, carry2):
                out, q, _ = carry2
                q = jax.lax.ppermute(q, axis_name, perm)
                out = out.at[jnp.mod(idx - s, g)].set(
                    quant_decode(q, None, codec, block))
                return out, q, sc

            out, _, _ = jax.lax.fori_loop(0, g - 1, hop_nosc,
                                          (out0, q, sc))
        else:
            out, _, _ = jax.lax.fori_loop(0, g - 1, hop, (out0, q, sc))
        out = out.reshape(-1)
    if avg:
        out = out / g
    n = int(np.prod(shape)) if shape else 1
    return out[:n].reshape(shape).astype(dtype)


def reduce_scatter(x, axis_name: str, *, codec: str = "int8",
                   axis_size: Optional[int] = None, avg: bool = False,
                   block: int = QUANT_BLOCK):
    """Public reduce-scatter half of the quantized ring; call inside
    shard_map. ``x`` is this device's local contribution (any shape);
    the result is the flat f32 REDUCED chunk this device owns —
    length ``padded_len(x.size, g, block) // g``, f32-accumulated at
    every hop with the wire payloads encoded per ``codec`` (the
    ``np_encode`` block layout).

    Chunk ownership follows the ring convention: device ``idx`` ends
    holding chunk ``(idx + 1) % g`` of the padded flat buffer —
    :func:`all_gather` undoes exactly that placement, so
    ``all_gather(reduce_scatter(x))`` (avg off, same codec) is
    BITWISE ``quantized_allreduce`` of the same contributions. This is
    the ZeRO decomposition: the optimizer consumes the unquantized f32
    chunk, only the wire moves encoded bytes. ``avg=True`` divides the
    reduced chunk by g (mean-gradient semantics, BEFORE any further
    encode)."""
    carry = allreduce_start(x, axis_name, codec=codec,
                            axis_size=axis_size, block=block)
    mine, g = carry[1], carry[7]
    if avg:
        mine = mine / g
    return mine


def all_gather(chunk, axis_name: str, *, codec: str = "f32",
               axis_size: Optional[int] = None,
               block: int = QUANT_BLOCK):
    """Public all-gather half of the ring; call inside shard_map.
    ``chunk`` is this device's flat owned chunk under the ring
    placement (device ``idx`` owns chunk ``(idx + 1) % g`` — what
    :func:`reduce_scatter` returns); the result is the full flat
    ``(g * chunk.size,)`` f32 buffer in ORIGINAL chunk order, bitwise
    identical on every device (the payload is encoded once and every
    device decodes the same bytes). The default ``codec='f32'`` moves
    raw bytes — the ZeRO parameter all-gather leg (sharded-update
    results must come back exact); pass the grad codec to reproduce
    ``quantized_allreduce``'s gather phase."""
    import jax.numpy as jnp

    g = axis_size if axis_size is not None else _axis_size(axis_name)
    flat = chunk.reshape(-1).astype(jnp.float32)
    n = flat.shape[0] * g
    tag = "done1" if g == 1 else "rs"
    return allreduce_done(
        (tag, flat, (n,), jnp.float32, codec, block, axis_name, g))


def ring_allreduce_local(x, axis_name: str, *, codec: str = "int8",
                         axis_size: Optional[int] = None,
                         avg: bool = False, block: int = QUANT_BLOCK):
    """Full quantized ring all-reduce (start + done); call inside
    shard_map. ``codec='f32'`` is the exact leg (same ring, no
    rounding)."""
    return allreduce_done(
        allreduce_start(x, axis_name, codec=codec, axis_size=axis_size,
                        block=block), avg=avg)


def quantized_allreduce(x, mesh, axis: str = "dp", *,
                        codec: str = "int8", avg: bool = False,
                        block: int = QUANT_BLOCK):
    """shard_map wrapper over a GLOBAL array: per-device partial
    contributions ride ``axis``'s leading dim — ``x`` has shape
    ``(g, ...)`` (one slice per device) and the result is the reduced
    ``(...)`` value, identical on every device. The direct-call surface
    for tests and the PS-side host tooling; the executor's compiled
    step calls the ``_local`` form inside its own shard_map."""
    from jax.sharding import PartitionSpec as P

    g = mesh.shape[axis]

    def local(xs):
        return ring_allreduce_local(xs[0], axis, codec=codec,
                                    axis_size=g, avg=avg, block=block)

    return shard_map_nocheck(
        local, mesh, (P(axis, *([None] * (x.ndim - 1))),),
        P(*([None] * (x.ndim - 1))))(x)


# ---------------------------------------------------------------------------
# bucketed overlap driver (the executor's per-step gradient reduction)
# ---------------------------------------------------------------------------


def bucketed_allreduce(buckets: Sequence, axis_name: str, *,
                       codec: str = "int8",
                       axis_size: Optional[int] = None,
                       avg: bool = False, block: int = QUANT_BLOCK):
    """Reduce a list of flat f32 buckets with start/done interleaving:
    every bucket's reduce-scatter is ISSUED before any bucket's
    all-gather completes, so in the traced program bucket k's collective
    overlaps bucket k+1's — the latency-hiding emission order the
    comm_bucketing pass sets up (bucket order = backward completion
    order)."""
    starts = [allreduce_start(b, axis_name, codec=codec,
                              axis_size=axis_size, block=block)
              for b in buckets]
    return [allreduce_done(c, avg=avg) for c in starts]


