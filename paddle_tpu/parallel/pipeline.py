"""Pipeline parallelism: GPipe fill-drain microbatch schedule on a mesh axis.

TPU-native redesign of the reference pipeline trainer
(/root/reference/paddle/fluid/framework/pipeline_trainer.cc and
section_worker.cc:82 TrainFiles — host threads per stage pushing
micro-batch scopes through a queue; configured by
python/paddle/fluid/optimizer.py:3661 PipelineOptimizer). On TPU there are
no host threads in the loop: the whole fill-drain schedule is ONE compiled
SPMD program — a `lax.scan` over schedule ticks inside `shard_map`, where
each device holds one stage's parameters (stacked pytree sharded over the
`pp` mesh axis) and activations hop stage->stage with `lax.ppermute` over
ICI. Reverse-mode AD through the scan gives the backward pipeline for
free, so a pjit-ed training step differentiates straight through
`pipeline_apply`.

Schedule: classic GPipe. With S stages and M microbatches there are
S+M-1 ticks; at tick t, stage s computes microbatch (t-s) when
0 <= t-s < M (everything else is masked compute — the SPMD trade for
having no data-dependent control flow).
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

from .mesh import get_mesh


def pipeline_apply(stage_fn: Callable, stage_params, x, *,
                   mesh: Optional[Mesh] = None, axis: str = "pp",
                   num_microbatches: Optional[int] = None,
                   batch_axis: str = "dp"):
    """Run homogeneous pipeline stages over the `axis` mesh dimension.

    stage_fn: (params_of_one_layer, h) -> h with h.shape preserved (the
        transformer-block case; put embedding/head outside the pipeline).
    stage_params: pytree whose leaves are stacked along a leading
        num_layers axis (like the carry of a scan-over-layers).
        num_layers must be a multiple of the pp axis size; each stage runs
        its num_layers/num_stages consecutive layers with a local scan.
    x: (batch, ...) activations entering stage 0.
    num_microbatches: defaults to the number of stages (minimum bubble
        fraction (S-1)/(S+M-1) wants M as large as the batch allows).

    Returns stage-(S-1) outputs, (batch, ...), replicated over `axis`.
    """
    mesh = mesh or get_mesh()
    if mesh is None or axis not in mesh.axis_names or mesh.shape[axis] <= 1:
        # degenerate single-stage mesh: plain scan over stages
        def one(h, p):
            return stage_fn(p, h), None
        out, _ = jax.lax.scan(one, x, stage_params)
        return out

    n_stages = mesh.shape[axis]
    n_layers = jax.tree_util.tree_leaves(stage_params)[0].shape[0]
    if n_layers % n_stages != 0:
        raise ValueError(
            f"stacked layer count {n_layers} not divisible by pipeline "
            f"stages {n_stages} (axis '{axis}')")
    mb = num_microbatches or n_stages
    batch = x.shape[0]
    if batch % mb != 0:
        raise ValueError(f"batch {batch} not divisible by "
                         f"num_microbatches {mb}")
    xm = x.reshape(mb, batch // mb, *x.shape[1:])

    # microbatch dim replicated over pp; per-microbatch batch dim may ride dp
    ba = batch_axis if (batch_axis in mesh.axis_names and batch_axis != axis
                        and (batch // mb) % mesh.shape[batch_axis] == 0) else None
    x_spec = PartitionSpec(None, ba)
    p_spec = PartitionSpec(axis)

    send_perm = [(i, i + 1) for i in range(n_stages - 1)]

    def local(params, xm):
        s = jax.lax.axis_index(axis)
        ticks = mb + n_stages - 1

        def run_stage(params, h):
            # this stage's num_layers/num_stages consecutive layers
            def one(h, p):
                return stage_fn(p, h), None
            out, _ = jax.lax.scan(one, h, params)
            return out

        def tick(carry, t):
            recv, outs = carry
            xt = jax.lax.dynamic_index_in_dim(
                xm, jnp.clip(t, 0, mb - 1), axis=0, keepdims=False)
            inp = jnp.where(s == 0, xt, recv)
            h = run_stage(params, inp)
            # hop to the next stage (stage 0 receives zeros: masked anyway)
            recv_next = jax.lax.ppermute(h, axis, send_perm)
            # last stage records microbatch t-(S-1) once it is valid
            widx = jnp.clip(t - (n_stages - 1), 0, mb - 1)
            valid = (t >= n_stages - 1) & (t - (n_stages - 1) < mb)
            cur = jax.lax.dynamic_index_in_dim(outs, widx, 0, keepdims=False)
            new = jnp.where(valid & (s == n_stages - 1), h, cur)
            outs = jax.lax.dynamic_update_index_in_dim(outs, new, widx, 0)
            return (recv_next, outs), None

        # 0*(x,params)-derived carries keep shard_map's varying-axes typing
        # happy: outputs vary over both the data and stage axes
        pzero = 0.0 * jax.tree_util.tree_leaves(params)[0].ravel()[0]
        recv0 = 0.0 * xm[0] + pzero
        outs0 = 0.0 * xm + pzero
        (_, outs), _ = jax.lax.scan(tick, (recv0, outs0), jnp.arange(ticks))
        # replicate the last stage's outputs to every pp rank
        outs = jax.lax.psum(
            jnp.where(s == n_stages - 1, outs, 0.0 * outs), axis)
        return outs

    outs = jax.shard_map(local, mesh=mesh, in_specs=(p_spec, x_spec),
                         out_specs=x_spec)(stage_params, xm)
    return outs.reshape(batch, *x.shape[1:])


def stack_stage_params(per_stage_params):
    """List of per-stage pytrees (same structure) -> stacked pytree with a
    leading num_stages axis, ready for pipeline_apply."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *per_stage_params)
