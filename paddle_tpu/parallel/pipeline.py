"""Pipeline parallelism: GPipe fill-drain microbatch schedule on a mesh axis.

TPU-native redesign of the reference pipeline trainer
(/root/reference/paddle/fluid/framework/pipeline_trainer.cc and
section_worker.cc:82 TrainFiles — host threads per stage pushing
micro-batch scopes through a queue; configured by
python/paddle/fluid/optimizer.py:3661 PipelineOptimizer). On TPU there are
no host threads in the loop: the whole fill-drain schedule is ONE compiled
SPMD program — a `lax.scan` over schedule ticks inside `shard_map`, where
each device holds one stage's parameters (stacked pytree sharded over the
`pp` mesh axis) and activations hop stage->stage with `lax.ppermute` over
ICI. Reverse-mode AD through the scan gives the backward pipeline for
free, so a pjit-ed training step differentiates straight through
`pipeline_apply`.

Two schedules:

- :func:`pipeline_apply` — classic GPipe forward; AD through the scan
  gives the backward. With S stages and M microbatches there are S+M-1
  ticks; at tick t, stage s computes microbatch (t-s) when
  0 <= t-s < M (everything else is masked compute — the SPMD trade for
  having no data-dependent control flow).
- :func:`pipeline_1f1b_value_and_grad` — 1F1B (PipeDream-flush) with
  per-stage activation recomputation and embedding/head *inside* the
  pipeline; backward for a microbatch starts as soon as its cotangent
  can arrive, bounding the activation stash at 2S-1 instead of M.
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

from .mesh import get_mesh


def pipeline_apply(stage_fn: Callable, stage_params, x, *,
                   mesh: Optional[Mesh] = None, axis: str = "pp",
                   num_microbatches: Optional[int] = None,
                   batch_axis: str = "dp"):
    """Run homogeneous pipeline stages over the `axis` mesh dimension.

    stage_fn: (params_of_one_layer, h) -> h with h.shape preserved (the
        transformer-block case; put embedding/head outside the pipeline).
    stage_params: pytree whose leaves are stacked along a leading
        num_layers axis (like the carry of a scan-over-layers).
        num_layers must be a multiple of the pp axis size; each stage runs
        its num_layers/num_stages consecutive layers with a local scan.
    x: (batch, ...) activations entering stage 0.
    num_microbatches: defaults to the number of stages (minimum bubble
        fraction (S-1)/(S+M-1) wants M as large as the batch allows).

    Returns stage-(S-1) outputs, (batch, ...), replicated over `axis`.
    """
    mesh = mesh or get_mesh()
    if mesh is None or axis not in mesh.axis_names or mesh.shape[axis] <= 1:
        # degenerate single-stage mesh: plain scan over stages
        def one(h, p):
            return stage_fn(p, h), None
        out, _ = jax.lax.scan(one, x, stage_params)
        return out

    n_stages = mesh.shape[axis]
    n_layers = jax.tree_util.tree_leaves(stage_params)[0].shape[0]
    if n_layers % n_stages != 0:
        raise ValueError(
            f"stacked layer count {n_layers} not divisible by pipeline "
            f"stages {n_stages} (axis '{axis}')")
    mb = num_microbatches or n_stages
    batch = x.shape[0]
    if batch % mb != 0:
        raise ValueError(f"batch {batch} not divisible by "
                         f"num_microbatches {mb}")
    xm = x.reshape(mb, batch // mb, *x.shape[1:])

    # microbatch dim replicated over pp; per-microbatch batch dim may ride dp
    ba = batch_axis if (batch_axis in mesh.axis_names and batch_axis != axis
                        and (batch // mb) % mesh.shape[batch_axis] == 0) else None
    x_spec = PartitionSpec(None, ba)
    p_spec = PartitionSpec(axis)

    send_perm = [(i, i + 1) for i in range(n_stages - 1)]

    def local(params, xm):
        s = jax.lax.axis_index(axis)
        ticks = mb + n_stages - 1

        def run_stage(params, h):
            # this stage's num_layers/num_stages consecutive layers
            def one(h, p):
                return stage_fn(p, h), None
            out, _ = jax.lax.scan(one, h, params)
            return out

        def tick(carry, t):
            recv, outs = carry
            xt = jax.lax.dynamic_index_in_dim(
                xm, jnp.clip(t, 0, mb - 1), axis=0, keepdims=False)
            inp = jnp.where(s == 0, xt, recv)
            h = run_stage(params, inp)
            # hop to the next stage (stage 0 receives zeros: masked anyway)
            recv_next = jax.lax.ppermute(h, axis, send_perm)
            # last stage records microbatch t-(S-1) once it is valid
            widx = jnp.clip(t - (n_stages - 1), 0, mb - 1)
            valid = (t >= n_stages - 1) & (t - (n_stages - 1) < mb)
            cur = jax.lax.dynamic_index_in_dim(outs, widx, 0, keepdims=False)
            new = jnp.where(valid & (s == n_stages - 1), h, cur)
            outs = jax.lax.dynamic_update_index_in_dim(outs, new, widx, 0)
            return (recv_next, outs), None

        # 0*(x,params)-derived carries keep shard_map's varying-axes typing
        # happy: outputs vary over both the data and stage axes
        pzero = 0.0 * jax.tree_util.tree_leaves(params)[0].ravel()[0]
        recv0 = 0.0 * xm[0] + pzero
        outs0 = 0.0 * xm + pzero
        (_, outs), _ = jax.lax.scan(tick, (recv0, outs0), jnp.arange(ticks))
        # replicate the last stage's outputs to every pp rank
        outs = jax.lax.psum(
            jnp.where(s == n_stages - 1, outs, 0.0 * outs), axis)
        return outs

    from .collectives import shard_map_fn

    outs = shard_map_fn()(local, mesh=mesh, in_specs=(p_spec, x_spec),
                          out_specs=x_spec)(stage_params, xm)
    return outs.reshape(batch, *x.shape[1:])


def stack_stage_params(per_stage_params):
    """List of per-stage pytrees (same structure) -> stacked pytree with a
    leading num_stages axis, ready for pipeline_apply."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *per_stage_params)


def gpipe_schedule(num_stages: int, num_microbatches: int):
    """The GPipe fill-drain tick grid as data: yields
    ``(tick, [(stage, microbatch), ...])`` for every schedule tick.

    With S stages and M microbatches there are S+M-1 ticks; at tick t,
    stage s runs microbatch t-s when 0 <= t-s < M — the same grid
    :func:`pipeline_apply` compiles as a masked scan. Within one tick
    every (stage, microbatch) pair is data-independent (stage s consumes
    what stage s-1 produced at tick t-1), which is what lets a consumer
    run the pairs concurrently — the static executor's pipelined train
    step (the ``pipeline`` plan kind in static/stepplan.py) drives its
    per-stage op ranges off this grid. Stages are yielded in DESCENDING
    order so an in-place consumer never overwrites an activation the
    same tick still reads.
    """
    s_count, m_count = int(num_stages), int(num_microbatches)
    if s_count < 1 or m_count < 1:
        raise ValueError(f"gpipe_schedule: need num_stages >= 1 and "
                         f"num_microbatches >= 1, got ({num_stages}, "
                         f"{num_microbatches})")
    for t in range(s_count + m_count - 1):
        yield t, [(s, t - s) for s in range(s_count - 1, -1, -1)
                  if 0 <= t - s < m_count]


def gpipe_bubble_fraction(num_stages: int, num_microbatches: int) -> float:
    """Analytic GPipe bubble: the idle fraction (S-1)/(S+M-1) of the
    fill-drain schedule — the quantity the MULTICHIP bench probe reports
    as ``pp_bubble_frac`` and that growing M amortises."""
    s_count, m_count = int(num_stages), int(num_microbatches)
    return (s_count - 1) / max(s_count + m_count - 1, 1)


def one_f_one_b_schedule(num_stages: int, num_microbatches: int):
    """The 1F1B (PipeDream-flush) tick grid as data: yields
    ``(tick, [("F"|"B", stage, microbatch), ...])`` for every tick.

    Each stage warms up with forwards until it holds its target
    in-flight depth (S - s microbatches), then strictly alternates
    backward/forward until the drain — so a microbatch's backward
    starts as soon as its cotangent can arrive, and the activation
    stash per stage stays bounded by the warmup depth instead of M
    (GPipe keeps all M in flight through the fill phase).

    The grid is generated by simulating the per-stage state machines
    under the dataflow dependencies (F(s,m) needs F(s-1,m); B(s,m)
    needs F(s,m) and B(s+1,m)), one slot per stage per tick, so any
    consumer that replays the slots in order preserves them by
    construction. Within a tick, stages are yielded in DESCENDING
    order (same contract as :func:`gpipe_schedule`). Microbatches
    retire (run their last-stage forward + backward) in ascending
    order on every schedule — the invariant that keeps merged-gradient
    accumulation order, and therefore the loss, identical across
    gpipe/1f1b/interleaved.
    """
    s_count, m_count = int(num_stages), int(num_microbatches)
    if s_count < 1 or m_count < 1:
        raise ValueError(f"one_f_one_b_schedule: need num_stages >= 1 "
                         f"and num_microbatches >= 1, got "
                         f"({num_stages}, {num_microbatches})")
    f_done = [0] * s_count   # forwards completed per stage
    b_done = [0] * s_count   # backwards completed per stage
    t = 0
    while any(b < m_count for b in b_done):
        prev_f, prev_b = list(f_done), list(b_done)
        slots = []
        for s in range(s_count - 1, -1, -1):
            m_f, m_b = prev_f[s], prev_b[s]
            can_f = m_f < m_count and (s == 0 or prev_f[s - 1] > m_f)
            can_b = m_b < m_f and \
                (s == s_count - 1 or prev_b[s + 1] > m_b)
            # 1F1B discipline: once the stage holds its warmup depth
            # (S - s in-flight microbatches) — or has no forwards left
            # — it drains a backward before admitting another forward
            prefer_b = (m_f - m_b) >= (s_count - s) or m_f == m_count
            if can_b and (prefer_b or not can_f):
                slots.append(("B", s, m_b))
                b_done[s] += 1
            elif can_f:
                slots.append(("F", s, m_f))
                f_done[s] += 1
        yield t, slots
        t += 1


def interleaved_schedule(num_stages: int, num_microbatches: int,
                         interleave: int = 2):
    """Interleaved 1F1B: the ``num_stages`` stamped stages are treated
    as v (= ``interleave``) virtual chunks round-robined over
    S/v physical workers (Megatron-style assignment: worker p owns
    virtual stages p, p + S/v, ...), shrinking the warmup bubble by v
    at the cost of v× the stage-boundary traffic.

    Generated by list-scheduling the plain 1F1B slot stream under the
    same dataflow dependencies plus one-slot-per-worker-per-tick
    occupancy: each slot lands at the earliest tick where its inputs
    are done and its worker is free, preserving both the dependency
    order and the ascending microbatch retirement order. Requires
    ``num_stages % interleave == 0``. Yields the same
    ``(tick, [("F"|"B", stage, m), ...])`` grid as
    :func:`one_f_one_b_schedule`.
    """
    s_count, m_count = int(num_stages), int(num_microbatches)
    v = int(interleave)
    if v < 1 or s_count % v:
        raise ValueError(
            f"interleaved_schedule: num_stages {num_stages} not "
            f"divisible by interleave {interleave}")
    workers = s_count // v
    f_end: dict = {}
    b_end: dict = {}
    busy: dict = {p: set() for p in range(workers)}
    grid: dict = {}
    for _t, tick in one_f_one_b_schedule(s_count, m_count):
        for kind, vs, m in tick:
            if kind == "F":
                ready = f_end.get((vs - 1, m), 0) if vs else 0
            else:
                ready = max(f_end[(vs, m)],
                            b_end.get((vs + 1, m), 0)
                            if vs < s_count - 1 else 0)
            p = vs % workers
            t = ready
            while t in busy[p]:
                t += 1
            busy[p].add(t)
            (f_end if kind == "F" else b_end)[(vs, m)] = t + 1
            grid.setdefault(t, []).append((kind, vs, m))
    for t in sorted(grid):
        yield t, sorted(grid[t], key=lambda slot: (-slot[1], slot[0]))


def pipeline_timeline(schedule: str, num_stages: int,
                      num_microbatches: int, interleave: int = 2):
    """One entry point over the schedule generators: the
    ``(tick, slots)`` stream for ``schedule`` in
    gpipe | 1f1b | interleaved. GPipe's forward-only grid is lifted to
    the slot format with the backward folded into the last-stage
    forward (that is where the compiled GPipe step runs it)."""
    if schedule == "gpipe":
        return ((t, [("F", s, m) for s, m in pairs])
                for t, pairs in gpipe_schedule(num_stages,
                                               num_microbatches))
    if schedule == "1f1b":
        return one_f_one_b_schedule(num_stages, num_microbatches)
    if schedule == "interleaved":
        return interleaved_schedule(num_stages, num_microbatches,
                                    interleave)
    raise ValueError(f"unknown pipeline schedule {schedule!r} "
                     "(expected gpipe|1f1b|interleaved)")


def schedule_bubble_fraction(schedule: str, num_stages: int,
                             num_microbatches: int,
                             interleave: int = 2) -> float:
    """Schedule-aware analytic bubble fraction, one convention across
    the cost model, the gauges and the bench probes.

    The per-microbatch work unit weighs backward at 2× forward
    (B = 2F, the standard roofline for matmul-dominated stages), so a
    full microbatch costs 3 units:

    - ``gpipe``:        (S-1)/(S+M-1) — the classic fill-drain form,
      unchanged from :func:`gpipe_bubble_fraction` (forward grid; the
      monolithic backward rides the last-stage slot)
    - ``1f1b``:         (S-1)/(3M + S-1) — the warmup/drain bubble is
      amortised over the full forward+backward steady state
    - ``interleaved``:  (S-1)/(v·3M + S-1) — v virtual chunks per
      worker shrink the warmup bubble by v
    """
    s_count, m_count = int(num_stages), int(num_microbatches)
    if schedule == "gpipe":
        return gpipe_bubble_fraction(s_count, m_count)
    if schedule == "1f1b":
        return (s_count - 1) / max(3 * m_count + s_count - 1, 1)
    if schedule == "interleaved":
        v = int(interleave)
        return (s_count - 1) / max(3 * v * m_count + s_count - 1, 1)
    raise ValueError(f"unknown pipeline schedule {schedule!r} "
                     "(expected gpipe|1f1b|interleaved)")


# ---------------------------------------------------------------------------
# 1F1B schedule (PipeDream-flush) with activation recomputation
# ---------------------------------------------------------------------------


def pipeline_1f1b_value_and_grad(stage_fn: Callable, first_fn: Callable,
                                 last_fn: Callable, params, x, y, *,
                                 mesh: Optional[Mesh] = None,
                                 axis: str = "pp",
                                 num_microbatches: Optional[int] = None,
                                 batch_axis: str = "dp"):
    """One pipeline-parallel training step on the 1F1B schedule.

    Differences from :func:`pipeline_apply` + AD (the GPipe path):

    - **embedding and head live INSIDE the pipeline**: ``first_fn``
      (params_first, x_mb) -> h runs on stage 0 per microbatch and
      ``last_fn`` (params_last, h, y_mb) -> scalar mean loss on the last
      stage per microbatch, each behind a ``lax.cond`` so only the owning
      stage pays their FLOPs. The GPipe path needs them outside, applied
      to the full batch (pipeline.py:37-44 in round 2).
    - **1F1B ordering with activation recomputation**: each schedule tick
      carries one forward slot and one backward slot. Stage ``s`` runs
      backward for microbatch ``m`` at tick ``2(S-1)-s+m`` — as early as
      its cotangent can arrive — so at most ``2(S-1)+1`` stashed
      activations exist per stage regardless of M (GPipe-through-AD
      stashes all M). The stash holds only each stage's *input* block;
      the stage forward is recomputed inside the backward slot
      (Megatron-style remat — SURVEY's trade-FLOPs-for-HBM rule), which
      is what lets M grow to amortise the bubble without OOM.

    The schedule is still ONE compiled SPMD program: a ``lax.scan`` over
    ``M + 2(S-1)`` ticks inside ``shard_map``; activations hop forward
    and cotangents hop backward with ``lax.ppermute`` each tick.

    stage_fn: (one layer's params, h) -> h; ``params["blocks"]`` is a
    pytree stacked over a leading num_layers axis (num_layers % S == 0).
    params: dict(first=..., blocks=..., last=...). Returns
    ``(loss, grads)`` with grads matching ``params``' structure; loss is
    the mean over microbatches of ``last_fn``'s per-microbatch mean.

    Reference semantics matched: section_worker.cc:111-172 micro-batch
    loop (fill-drain pipeline with per-microbatch backward); schedule
    upgraded from its round-2 GPipe form per VERDICT r2 item 4.
    """
    mesh = mesh or get_mesh()
    if mesh is None or axis not in mesh.axis_names or mesh.shape[axis] <= 1:
        return _sequential_value_and_grad(stage_fn, first_fn, last_fn,
                                          params, x, y,
                                          num_microbatches or 1)

    n_stages = mesh.shape[axis]
    n_layers = jax.tree_util.tree_leaves(params["blocks"])[0].shape[0]
    if n_layers % n_stages != 0:
        raise ValueError(
            f"stacked layer count {n_layers} not divisible by pipeline "
            f"stages {n_stages} (axis '{axis}')")
    mb = num_microbatches or n_stages
    batch = x.shape[0]
    if batch % mb != 0:
        raise ValueError(f"batch {batch} not divisible by "
                         f"num_microbatches {mb}")
    xm = x.reshape(mb, batch // mb, *x.shape[1:])
    ym = y.reshape(mb, batch // mb, *y.shape[1:])

    ba = batch_axis if (batch_axis in mesh.axis_names and batch_axis != axis
                        and (batch // mb) % mesh.shape[batch_axis] == 0) \
        else None

    # one compiled step per configuration: pjit's cache keys on function
    # identity, so rebuilding the shard_map closure per call would
    # retrace+recompile every eager step
    key = (stage_fn, first_fn, last_fn, mesh, axis, mb, ba)
    try:
        step = _1F1B_CACHE.get(key)
    except TypeError:            # unhashable user fn/mesh: build fresh
        key, step = None, None
    if step is None:
        step = _build_1f1b_step(stage_fn, first_fn, last_fn, mesh, axis,
                                mb, ba)
        if key is not None:
            # bounded FIFO: per-step-constructed fns (fresh lambdas)
            # would otherwise pin compiled executables forever
            if len(_1F1B_CACHE) >= _1F1B_CACHE_MAX:
                _1F1B_CACHE.pop(next(iter(_1F1B_CACHE)))
            _1F1B_CACHE[key] = step
    loss, gf, gb, gl = step(params["first"], params["blocks"],
                            params["last"], xm, ym)
    return loss, {"first": gf, "blocks": gb, "last": gl}


_1F1B_CACHE: dict = {}
_1F1B_CACHE_MAX = 32


def _build_1f1b_step(stage_fn, first_fn, last_fn, mesh, axis, mb, ba):
    n_stages = mesh.shape[axis]
    data_spec = PartitionSpec(None, ba)
    blocks_spec = PartitionSpec(axis)
    repl_spec = PartitionSpec()

    send_perm = [(i, i + 1) for i in range(n_stages - 1)]
    back_perm = [(i + 1, i) for i in range(n_stages - 1)]

    def local(p_first, p_blocks, p_last, xm, ym):
        s = jax.lax.axis_index(axis)
        S, M = n_stages, mb
        ticks = M + 2 * (S - 1)
        depth = 2 * (S - 1) + 1     # max stash lifetime + 1

        def run_blocks(pb, h):
            def one(h, p):
                return stage_fn(p, h), None
            out, _ = jax.lax.scan(one, h, pb)
            return out

        # probe the hidden shape via eval_shape (first_fn decides it)
        h_struct = jax.eval_shape(first_fn, p_first, xm[0])

        want_axes = (axis,) + ((ba,) if ba else ())

        def vary(t):
            """Mark a tree as varying over the pp (and dp, when the data
            rides it) axes: cond branches and scan carries must agree on
            shard_map's varying-axes type, and stage-local values
            genuinely differ per rank. Already-varying axes pass through
            (pcast rejects re-casting them)."""
            typeof = getattr(jax, "typeof", None)
            pcast = getattr(jax.lax, "pcast", None)
            if typeof is None or pcast is None:
                # jax < 0.7: no varying-manual-axes typing, so there is
                # nothing to re-cast — values are already usable
                return t

            def one(a):
                have = set(getattr(typeof(a), "vma", ()))
                need = tuple(ax for ax in want_axes if ax not in have)
                return pcast(a, need, to="varying") if need else a
            return jax.tree_util.tree_map(one, t)

        zero_h = vary(jnp.zeros(h_struct.shape, h_struct.dtype))
        # losses and their cotangent seeds stay f32: under bf16
        # activations an M-term bf16 accumulation (and a rounded 1/M
        # seed) would scale every gradient away from the sequential
        # reference; only the h traffic needs the hidden dtype
        zero_s = vary(jnp.zeros((), jnp.float32))

        # CRITICAL: all of local_fwd's inputs are re-typed varying HERE,
        # outside every lax.cond. pcast's transpose is a psum, and
        # local_fwd is vjp'd inside a cond whose predicate differs per
        # stage — a collective materialised inside those branches
        # deadlocks the SPMD program (devices rendezvous at different
        # collectives). With fully-varying inputs the vjp is purely
        # device-local; the only collectives are the per-tick ppermutes
        # and the final psums, all unconditional.
        p_first_v, p_blocks_v, p_last_v, xm_v, ym_v = vary(
            (p_first, p_blocks, p_last, xm, ym))

        def local_fwd(p_first, p_blocks, p_last, h_in, m_idx):
            """Uniform per-stage forward: (h_out, mb mean loss).
            Stage roles are lax.cond'ed so only stage 0 pays first_fn
            and only stage S-1 pays last_fn."""
            x_m = jax.lax.dynamic_index_in_dim(xm_v, m_idx, 0, False)
            y_m = jax.lax.dynamic_index_in_dim(ym_v, m_idx, 0, False)
            inp = jax.lax.cond(
                s == 0,
                lambda: first_fn(p_first, x_m).astype(h_struct.dtype),
                lambda: h_in)
            mid = run_blocks(p_blocks, inp)
            loss = jax.lax.cond(
                s == S - 1,
                lambda: last_fn(p_last, mid, y_m).astype(jnp.float32),
                lambda: zero_s)
            return mid, loss

        gz = vary(jax.tree_util.tree_map(
            jnp.zeros_like, (p_first, p_blocks, p_last)))

        def tick(carry, t):
            recv_h, recv_ct, stash, g_acc, loss_acc = carry

            # ---- forward slot: stage s runs microbatch t - s
            fm = t - s
            f_on = (fm >= 0) & (fm < M)
            fm_c = jnp.clip(fm, 0, M - 1)
            h_out, f_loss = jax.lax.cond(
                f_on,
                lambda: local_fwd(p_first_v, p_blocks_v, p_last_v, recv_h,
                                  fm_c),
                lambda: (zero_h, zero_s))
            # stash this stage's INPUT for the remat backward
            slot_f = jnp.mod(fm_c, depth)
            stash = jnp.where(
                f_on,
                jax.lax.dynamic_update_index_in_dim(stash, recv_h, slot_f,
                                                    0),
                stash)
            loss_acc = loss_acc + jnp.where(f_on & (s == S - 1),
                                            f_loss / M, 0.0)

            # ---- backward slot: stage s runs microbatch t - (2(S-1)-s)
            bm = t - (2 * (S - 1) - s)
            b_on = (bm >= 0) & (bm < M)
            bm_c = jnp.clip(bm, 0, M - 1)
            h_saved = jax.lax.dynamic_index_in_dim(
                stash, jnp.mod(bm_c, depth), 0, False)

            def bwd():
                _, vjp_fn = jax.vjp(
                    lambda a, b, c, h: local_fwd(a, b, c, h, bm_c),
                    p_first_v, p_blocks_v, p_last_v, h_saved)
                # the last stage seeds the loss cotangent (1/M for the
                # microbatch mean); everyone else seeds the arriving h ct
                loss_seed = vary(jnp.where(s == S - 1, 1.0 / M, 0.0)
                                 .astype(jnp.float32))
                gf, gb, gl, ct_h = vjp_fn((recv_ct, loss_seed))
                return (gf, gb, gl), ct_h

            grads_t, ct_out = jax.lax.cond(
                b_on, bwd, lambda: (gz, zero_h))
            g_acc = jax.tree_util.tree_map(jnp.add, g_acc, grads_t)

            # ---- hops (unconditional: collectives stay outside cond)
            recv_h = jax.lax.ppermute(h_out, axis, send_perm)
            recv_ct = jax.lax.ppermute(ct_out, axis, back_perm)
            return (recv_h, recv_ct, stash, g_acc, loss_acc), None

        # vary()-typed carries: scan carry types must match the varying
        # outputs of the tick body
        stash0 = vary(jnp.zeros((depth,) + zero_h.shape, zero_h.dtype))
        (_, _, _, g_acc, loss_acc), _ = jax.lax.scan(
            tick, (zero_h, zero_h, stash0, gz, zero_s), jnp.arange(ticks))

        gf, gb, gl = g_acc
        # first/last grads + loss live on one stage each: psum replicates
        loss = jax.lax.psum(loss_acc, axis)
        gf = jax.tree_util.tree_map(lambda a: jax.lax.psum(a, axis), gf)
        gl = jax.tree_util.tree_map(lambda a: jax.lax.psum(a, axis), gl)
        if ba is not None:
            loss = jax.lax.pmean(loss, ba)
            gf, gb, gl = jax.tree_util.tree_map(
                lambda a: jax.lax.pmean(a, ba), (gf, gb, gl))
        return loss, gf, gb, gl

    from .collectives import shard_map_fn

    sharded = shard_map_fn()(
        local, mesh=mesh,
        in_specs=(repl_spec, blocks_spec, repl_spec, data_spec, data_spec),
        out_specs=(repl_spec, repl_spec, blocks_spec, repl_spec))
    # always run compiled: the schedule only makes sense as one SPMD
    # program (jax's eager shard_map interpreter executes tick by tick);
    # inside an outer jit this inlines, and eager callers hit the
    # _1F1B_CACHE'd jit wrapper so repeat steps don't retrace
    return jax.jit(sharded)


def _sequential_value_and_grad(stage_fn, first_fn, last_fn, params, x, y,
                               mb):
    """Single-device reference semantics for the 1F1B step (also the
    degenerate no-pp-axis path): microbatched loss mean + plain AD."""
    def loss_fn(params):
        xm = x.reshape(mb, x.shape[0] // mb, *x.shape[1:])
        ym = y.reshape(mb, y.shape[0] // mb, *y.shape[1:])

        def one(acc, xy):
            x_m, y_m = xy
            h = first_fn(params["first"], x_m)

            def layer(h, p):
                return stage_fn(p, h), None
            h, _ = jax.lax.scan(layer, h, params["blocks"])
            return acc + last_fn(params["last"], h, y_m) / mb, None

        total, _ = jax.lax.scan(one, jnp.zeros(()), (xm, ym))
        return total

    return jax.value_and_grad(loss_fn)(params)
