"""Shape/layout manipulation ops.

Parity with the reference reshape/transpose/concat/split/slice family
(/root/reference/paddle/fluid/operators/{reshape_op,transpose_op,concat_op,
split_op,slice_op,stack_op,squeeze_op,unsqueeze_op,...}.cc). All static
shapes — dynamic-shape outputs (unique, nonzero, masked_select) return
host-side results in eager mode and are excluded from jit paths.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.op import primitive
from ..framework.tensor import Tensor, unwrap


def _norm_shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(v) for v in shape.numpy())
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(unwrap(s)) for s in shape)


def reshape(x, shape, name=None):
    return _reshape(x, shape=_norm_shape(shape))


@primitive("reshape")
def _reshape(x, shape):
    return jnp.reshape(x, shape)


def reshape_(x, shape, name=None):
    x._value = jnp.reshape(x._value, _norm_shape(shape))
    return x


@primitive("transpose")
def transpose(x, perm=None, name=None):
    return jnp.transpose(x, axes=tuple(perm) if perm is not None else None)


@primitive("flatten")
def flatten(x, start_axis=0, stop_axis=-1, name=None):
    nd = x.ndim
    if nd == 0:
        return x.reshape(1)
    start = start_axis % nd
    stop = stop_axis % nd
    shape = x.shape[:start] + (-1,) + x.shape[stop + 1:]
    return jnp.reshape(x, shape)


@primitive("squeeze")
def squeeze(x, axis=None, name=None):
    if axis is None:
        return jnp.squeeze(x)
    if isinstance(axis, int):
        axis = [axis]
    axis = tuple(a % x.ndim for a in axis if x.shape[a % x.ndim] == 1)
    return jnp.squeeze(x, axis=axis) if axis else x


@primitive("unsqueeze")
def unsqueeze(x, axis, name=None):
    if isinstance(axis, int):
        axis = [axis]
    out = x
    for a in sorted(int(v) for v in axis):
        out = jnp.expand_dims(out, a)
    return out


def concat(x, axis=0, name=None):
    return _concat(list(x), axis=int(unwrap(axis)))


@primitive("concat")
def _concat(tensors, axis=0):
    return jnp.concatenate(tensors, axis=axis)


def stack(x, axis=0, name=None):
    return _stack(list(x), axis=axis)


@primitive("stack")
def _stack(tensors, axis=0):
    return jnp.stack(tensors, axis=axis)


def unstack(x, axis=0, num=None, name=None):
    n = num if num is not None else unwrap(x).shape[axis]
    outs = _unstack(x, axis=axis, num=n)
    return list(outs)


@primitive("unstack")
def _unstack(x, axis, num):
    return tuple(jnp.squeeze(s, axis=axis)
                 for s in jnp.split(x, num, axis=axis))


def split(x, num_or_sections, axis=0, name=None):
    axis = int(unwrap(axis))
    if isinstance(num_or_sections, int):
        return list(_split_even(x, num=num_or_sections, axis=axis))
    sections = [int(unwrap(s)) for s in num_or_sections]
    total = unwrap(x).shape[axis]
    if any(s in (-1,) for s in sections):
        known = sum(s for s in sections if s != -1)
        sections = [total - known if s == -1 else s for s in sections]
    offsets = np.cumsum(sections)[:-1].tolist()
    return list(_split_sections(x, offsets=tuple(offsets), axis=axis))


@primitive("split")
def _split_even(x, num, axis):
    return tuple(jnp.split(x, num, axis=axis))


@primitive("split_sections")
def _split_sections(x, offsets, axis):
    return tuple(jnp.split(x, list(offsets), axis=axis))


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def unbind(x, axis=0, name=None):
    return unstack(x, axis=axis)


@primitive("tile")
def tile(x, repeat_times, name=None):
    return jnp.tile(x, tuple(int(r) for r in repeat_times))


expand = None  # defined below


@primitive("expand")
def _expand(x, shape):
    shape = tuple(
        x.shape[i - (len(shape) - x.ndim)] if s in (-1, None) and i >= len(shape) - x.ndim else s
        for i, s in enumerate(shape)
    )
    return jnp.broadcast_to(x, shape)


def expand(x, shape, name=None):  # noqa: F811
    return _expand(x, shape=_norm_shape(shape))


def expand_as(x, y, name=None):
    return _expand(x, shape=unwrap(y).shape)


def broadcast_to(x, shape, name=None):
    return _expand(x, shape=_norm_shape(shape))


def broadcast_tensors(inputs, name=None):
    arrays = [unwrap(t) for t in inputs]
    shape = jnp.broadcast_shapes(*[a.shape for a in arrays])
    return [_expand(t, shape=shape) for t in inputs]


@primitive("slice_op")
def slice(x, axes, starts, ends, name=None):
    out = x
    for ax, st, en in zip(axes, starts, ends):
        n = out.shape[ax]
        st = int(st)
        en = int(en)
        st = n + st if st < 0 else st
        en = n + en if en < 0 else builtins_min(en, n)
        out = jax.lax.slice_in_dim(out, st, en, axis=ax)
    return out


def builtins_min(a, b):
    return a if a < b else b


@primitive("strided_slice")
def strided_slice(x, axes, starts, ends, strides, name=None):
    idx = [jnp.s_[:]] * x.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        idx[ax] = jnp.s_[int(st):int(en):int(sd)]
    return x[tuple(idx)]


@primitive("getitem")
def getitem(x, idx):
    if isinstance(idx, tuple):
        idx = tuple(i._value if isinstance(i, Tensor) else i for i in idx)
    elif isinstance(idx, Tensor):
        idx = idx._value
    return x[idx]


@primitive("gather")
def gather(x, index, axis=0, name=None):
    index = index.reshape(-1) if index.ndim > 1 else index
    return jnp.take(x, index, axis=axis)


@primitive("gather_nd")
def gather_nd(x, index, name=None):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x[idx]


@primitive("take_along_axis")
def take_along_axis(arr, indices, axis, name=None):
    return jnp.take_along_axis(arr, indices, axis=axis)


@primitive("put_along_axis")
def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    values = jnp.broadcast_to(jnp.asarray(values, arr.dtype), indices.shape)
    dims = jnp.ogrid[tuple(jnp.s_[0:s] for s in indices.shape)]
    dims = [jnp.asarray(d) for d in dims]
    dims[axis] = indices
    at = arr.at[tuple(dims)]
    if reduce == "assign":
        return at.set(values)
    if reduce == "add":
        return at.add(values)
    if reduce == "multiply":
        return at.multiply(values)
    raise ValueError(f"Unknown reduce mode {reduce}")


@primitive("scatter")
def scatter(x, index, updates, overwrite=True, name=None):
    index = index.reshape(-1)
    if overwrite:
        return x.at[index].set(updates)
    zeroed = x.at[index].set(jnp.zeros_like(updates))
    return zeroed.at[index].add(updates)


@primitive("scatter_nd_add")
def scatter_nd_add(x, index, updates, name=None):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x.at[idx].add(updates)


def scatter_nd(index, updates, shape, name=None):
    from .creation import zeros

    base = zeros(shape, dtype=unwrap(updates).dtype)
    return scatter_nd_add(base, index, updates)


@primitive("index_select")
def index_select(x, index, axis=0, name=None):
    return jnp.take(x, index, axis=axis)


@primitive("index_sample")
def index_sample(x, index):
    return jnp.take_along_axis(x, index, axis=1)


@primitive("roll")
def roll(x, shifts, axis=None, name=None):
    return jnp.roll(x, shifts, axis=axis)


@primitive("flip")
def flip(x, axis, name=None):
    return jnp.flip(x, axis=axis)


def rot90(x, k=1, axes=(0, 1), name=None):
    return _rot90(x, k=k, axes=tuple(axes))


@primitive("rot90")
def _rot90(x, k, axes):
    return jnp.rot90(x, k=k, axes=axes)


@primitive("pad_nd")
def pad(x, pad, mode="constant", value=0.0, data_format=None, name=None):
    if len(pad) == 2 * x.ndim:
        cfg = [(int(pad[2 * i]), int(pad[2 * i + 1])) for i in range(x.ndim)]
    else:
        # paddle semantics: pad pairs apply last-spatial-dim-first
        # (pad_left, pad_right, pad_top, pad_bottom, ...) — reference
        # nn/functional/common.py pad; spatial dims depend on data_format.
        n_spatial = len(pad) // 2
        pairs = [(int(pad[2 * i]), int(pad[2 * i + 1]))
                 for i in range(n_spatial)]
        channel_last = data_format in ("NHWC", "NLC", "NDHWC")
        cfg = [(0, 0)] * x.ndim
        if channel_last:
            spatial_dims = list(range(1, 1 + n_spatial))
        else:
            spatial_dims = list(range(x.ndim - n_spatial, x.ndim))
        for i, dim in enumerate(reversed(spatial_dims)):
            cfg[dim] = pairs[i]
    jmode = {"constant": "constant", "reflect": "reflect",
             "replicate": "edge", "circular": "wrap"}[mode]
    if jmode == "constant":
        return jnp.pad(x, cfg, mode=jmode, constant_values=value)
    return jnp.pad(x, cfg, mode=jmode)


@primitive("shard_index")
def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    """operators/shard_index_op.cc parity."""
    shard_size = (index_num + nshards - 1) // nshards
    in_shard = (input // shard_size) == shard_id
    return jnp.where(in_shard, input % shard_size, ignore_value)


@primitive("repeat_interleave")
def repeat_interleave(x, repeats, axis=None, name=None):
    return jnp.repeat(x, repeats, axis=axis)


@primitive("moveaxis")
def moveaxis(x, source, destination, name=None):
    return jnp.moveaxis(x, source, destination)


@primitive("swapaxes")
def swapaxes(x, axis1, axis2, name=None):
    return jnp.swapaxes(x, axis1, axis2)


@primitive("as_real")
def as_real(x, name=None):
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


@primitive("as_complex")
def as_complex(x, name=None):
    return jax.lax.complex(x[..., 0], x[..., 1])


@primitive("real")
def real(x, name=None):
    return jnp.real(x)


@primitive("imag")
def imag(x, name=None):
    return jnp.imag(x)


# -- dynamic-shape ops: host-side eager only -------------------------------

def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype=np.int64, name=None):
    arr = np.asarray(unwrap(x))
    res = np.unique(arr, return_index=return_index,
                    return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(res)
    return tuple(Tensor(r) for r in res)


def nonzero(x, as_tuple=False, name=None):
    arr = np.asarray(unwrap(x))
    nz = np.nonzero(arr)
    if as_tuple:
        return tuple(Tensor(n.reshape(-1, 1)) for n in nz)
    return Tensor(np.stack(nz, axis=1).astype(np.int64))


def masked_select(x, mask, name=None):
    arr = np.asarray(unwrap(x))
    m = np.asarray(unwrap(mask))
    return Tensor(arr[m])


@primitive("masked_fill")
def masked_fill(x, mask, value, name=None):
    return jnp.where(mask, jnp.asarray(value, x.dtype), x)


@primitive("where")
def where(condition, x=None, y=None, name=None):
    return jnp.where(condition, x, y)


@primitive("select_scatter")
def select_scatter(x, values, axis, index, name=None):
    idx = [jnp.s_[:]] * x.ndim
    idx[axis] = index
    return x.at[tuple(idx)].set(values)


@primitive("diagonal")
def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return jnp.diagonal(x, offset=offset, axis1=axis1, axis2=axis2)


@primitive("unfold")
def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (reference operators/math/im2col.cc) as XLA patch extraction."""
    if isinstance(kernel_sizes, int):
        kernel_sizes = [kernel_sizes, kernel_sizes]
    if isinstance(strides, int):
        strides = [strides, strides]
    if isinstance(paddings, int):
        paddings = [paddings] * 4
    elif len(paddings) == 2:
        paddings = [paddings[0], paddings[1], paddings[0], paddings[1]]
    if isinstance(dilations, int):
        dilations = [dilations, dilations]
    n, c, h, w = x.shape
    kh, kw = kernel_sizes
    x = jnp.pad(x, [(0, 0), (0, 0), (paddings[0], paddings[2]),
                    (paddings[1], paddings[3])])
    patches = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), tuple(strides), "VALID",
        rhs_dilation=tuple(dilations),
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    # patches: (N, C*kh*kw, OH, OW) -> (N, C*kh*kw, OH*OW)
    return patches.reshape(n, c * kh * kw, -1)


def tensordot(x, y, axes=2, name=None):
    return _tensordot(x, y, axes=axes)


@primitive("tensordot")
def _tensordot(x, y, axes):
    if isinstance(axes, (list, tuple)):
        axes = tuple(tuple(a) if isinstance(a, (list, tuple)) else a for a in axes)
    return jnp.tensordot(x, y, axes=axes)


@primitive("crop")
def crop(x, shape=None, offsets=None, name=None):
    offsets = offsets or [0] * x.ndim
    slices = tuple(jnp.s_[int(o):int(o) + int(s)]
                   for o, s in zip(offsets, shape))
    return x[slices]


def unique_consecutive(x, return_inverse=False, return_counts=False,
                       axis=None, dtype=np.int64, name=None):
    """Deduplicate consecutive runs (reference unique_consecutive_op.cc).
    Host-side like unique(): the output shape is data-dependent."""
    arr = np.asarray(unwrap(x))
    if axis is None:
        flat = arr.reshape(-1)
        if flat.size == 0:
            keep = np.zeros(0, bool)
        else:
            keep = np.concatenate([[True], flat[1:] != flat[:-1]])
        out = flat[keep]
        outs = [Tensor(out)]
        if return_inverse:
            outs.append(Tensor((np.cumsum(keep) - 1).astype(dtype)))
        if return_counts:
            idx = np.nonzero(np.concatenate([keep, [True]]))[0] \
                if flat.size else np.zeros(1, np.int64)
            outs.append(Tensor((np.diff(idx) if flat.size
                                else np.zeros(0)).astype(dtype)))
        return outs[0] if len(outs) == 1 else tuple(outs)
    arr_m = np.moveaxis(arr, axis, 0)
    if arr_m.shape[0] == 0:
        keep = np.zeros(0, bool)
    else:
        flat2 = arr_m.reshape(arr_m.shape[0], -1)
        keep = np.concatenate(
            [[True], np.any(flat2[1:] != flat2[:-1], axis=1)])
    out = np.moveaxis(arr_m[keep], 0, axis)
    outs = [Tensor(out)]
    if return_inverse:
        outs.append(Tensor((np.cumsum(keep) - 1).astype(dtype)))
    if return_counts:
        idx = np.nonzero(np.concatenate([keep, [True]]))[0] \
            if keep.size else np.zeros(1, np.int64)
        outs.append(Tensor((np.diff(idx) if keep.size
                            else np.zeros(0)).astype(dtype)))
    return outs[0] if len(outs) == 1 else tuple(outs)


@primitive("as_strided", nondiff=("shape", "stride", "offset"))
def as_strided(x, shape, stride, offset=0, name=None):
    """Strided view (reference as_strided / torch parity). JAX arrays have
    no strides, so this materializes the gather: flat[offset + i·stride]."""
    x = jnp.asarray(x).reshape(-1)
    shape = tuple(int(s) for s in shape)
    stride = tuple(int(s) for s in stride)
    idx = jnp.asarray(offset)
    for s, st in zip(shape, stride):
        idx = idx[..., None] + jnp.arange(s) * st
    return jnp.take(x, idx.reshape(-1), axis=0).reshape(shape)


def view(x, shape_or_dtype, name=None):
    """Zero-copy reshape or bitcast (paddle.view): with a dtype the last
    dimension scales by the size ratio, e.g. float32 (2, 3) -> uint8
    (2, 12). Under XLA both are layout rewrites the compiler folds away."""
    from ..framework import dtype as dtype_mod

    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    dt = np.dtype(dtype_mod.dtype_name(
        dtype_mod.convert_dtype(shape_or_dtype)))
    arr = unwrap(x)
    src = np.dtype(str(arr.dtype))
    if dt.itemsize == src.itemsize:
        return Tensor(jax.lax.bitcast_convert_type(arr, dt))
    if dt.itemsize < src.itemsize:  # narrowing: (..., n) -> (..., n*r)
        out = jax.lax.bitcast_convert_type(arr, dt)  # (..., n, r)
        return Tensor(out.reshape(out.shape[:-2] + (-1,)))
    ratio = dt.itemsize // src.itemsize  # widening: (..., n) -> (..., n/r)
    if arr.shape[-1] % ratio:
        raise ValueError(
            f"view: last dim {arr.shape[-1]} not divisible by the "
            f"{src}->{dt} size ratio {ratio}")
    grouped = arr.reshape(arr.shape[:-1] + (arr.shape[-1] // ratio, ratio))
    return Tensor(jax.lax.bitcast_convert_type(grouped, dt))


def view_as(x, other, name=None):
    return reshape(x, tuple(unwrap(other).shape))


# -- fluid.layers long-tail parity ------------------------------------------
@primitive("reverse", nondiff=("axis",))
def reverse(x, axis, name=None):
    if isinstance(axis, int):
        axis = [axis]
    return jnp.flip(x, axis=tuple(axis))


def shape(x, name=None):
    """Shape as an int32 tensor (layers/nn.py shape)."""
    return Tensor(jnp.asarray(unwrap(x).shape, jnp.int32))


def size(x, name=None):
    return Tensor(jnp.asarray(unwrap(x).size, jnp.int64))


def rank(x, name=None):
    return Tensor(jnp.asarray(unwrap(x).ndim, jnp.int32))


@primitive("space_to_depth", nondiff=("blocksize",))
def space_to_depth(x, blocksize, name=None):
    """(N,C,H,W) -> (N,C*bs^2,H/bs,W/bs) (space_to_depth_op.cc)."""
    n, c, h, w = x.shape
    bs = int(blocksize)
    x = x.reshape(n, c, h // bs, bs, w // bs, bs)
    x = jnp.transpose(x, (0, 3, 5, 1, 2, 4))
    return x.reshape(n, c * bs * bs, h // bs, w // bs)


@primitive("shuffle_channel", nondiff=("group",))
def shuffle_channel(x, group, name=None):
    """ShuffleNet channel shuffle (shuffle_channel_op.cc)."""
    n, c, h, w = x.shape
    g = int(group)
    return jnp.transpose(x.reshape(n, g, c // g, h, w),
                         (0, 2, 1, 3, 4)).reshape(n, c, h, w)


@primitive("pad_constant_like")
def pad_constant_like(x, y, pad_value=0.0, name=None):
    """Pad y up to x's shape with pad_value (pad_constant_like_op.cc)."""
    pads = [(0, int(sx) - int(sy)) for sx, sy in zip(x.shape, y.shape)]
    return jnp.pad(y, pads, constant_values=pad_value)


def crop_tensor(x, shape=None, offsets=None, name=None):
    """Crop a window (crop_tensor_op.cc); same kernel as crop()."""
    return crop(x, shape if shape is not None else unwrap(x).shape,
                offsets)


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0,
                                  name=None):
    """shape[output_dim_idx] copies input's batch dim
    (fill_constant_batch_size_like_op.cc)."""
    from ..framework import dtype as dtype_mod

    shape = list(shape)
    shape[output_dim_idx] = unwrap(input).shape[input_dim_idx]
    return Tensor(jnp.full(tuple(int(s) for s in shape), value,
                           dtype_mod.convert_dtype(dtype)))


def unique_with_counts(x, dtype=np.int64, name=None):
    """(out, index, count) triple (unique_with_counts_op.cc)."""
    arr = np.asarray(unwrap(x)).ravel()
    out, inv, cnt = np.unique(arr, return_inverse=True, return_counts=True)
    return (Tensor(out), Tensor(inv.astype(dtype)),
            Tensor(cnt.astype(dtype)))
