"""Debug / host-callback ops.

Parity with the reference Print (layers/control_flow.py Print,
operators/print_op.cc), Assert (operators/assert_op.cc), and py_func
(layers/nn.py py_func, operators/py_func_op.cc).

TPU-native design: under jit these lower to XLA host callbacks
(jax.debug.print / jax.pure_callback), so they work inside compiled
training steps — the reference runs them as interpreter ops, which is
free for it but impossible inside a fused XLA program without callbacks.
"""
from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor, unwrap


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=False,
          print_phase="both", name=None):
    """Print a tensor's value when it is computed; returns the input
    unchanged so it can be chained into the graph."""
    arr = unwrap(input)
    if message:
        jax.debug.print("{m} {x}", m=message, x=arr)
    else:
        jax.debug.print("{x}", x=arr)
    return input


def Assert(cond, data: Optional[Sequence] = None, summarize=20, name=None):
    """Abort if cond is False (assert_op.cc). Eager: python raise.
    Traced: host callback that raises when the value arrives."""
    arr = unwrap(cond)

    def _check(c, *vals):
        if not bool(np.all(c)):
            parts = ", ".join(str(np.asarray(v)[:summarize]) for v in vals)
            raise AssertionError(
                f"paddle_tpu.Assert failed{(': ' + parts) if parts else ''}")

    vals = tuple(unwrap(d) for d in (data or ()))
    if isinstance(arr, jax.core.Tracer):
        jax.debug.callback(_check, arr, *vals)
    else:
        _check(arr, *vals)
    return cond


def py_func(func: Callable, x, out, backward_func: Optional[Callable] = None,
            skip_vars_in_backward_input=None, name=None):
    """Run a host python function as an op (py_func_op.cc).

    x: input Tensor or list of Tensors. out: template Tensor(s) (or
    jax.ShapeDtypeStruct) giving the output shape/dtype. backward_func,
    if given, computes input grads on host: backward_func(*inputs,
    *output_grads) -> input grad(s).
    """
    xs = x if isinstance(x, (list, tuple)) else [x]
    arrs = [unwrap(v) for v in xs]
    outs = out if isinstance(out, (list, tuple)) else [out]
    shapes = [jax.ShapeDtypeStruct(tuple(unwrap(o).shape),
                                   unwrap(o).dtype)
              if not isinstance(o, jax.ShapeDtypeStruct) else o
              for o in outs]
    single = not isinstance(out, (list, tuple))

    def host_fwd(*vals):
        res = func(*[np.asarray(v) for v in vals])
        res = res if isinstance(res, (list, tuple)) else [res]
        return tuple(np.asarray(r, s.dtype).reshape(s.shape)
                     for r, s in zip(res, shapes))

    if backward_func is None:
        res = jax.pure_callback(host_fwd, tuple(shapes), *arrs)
    else:
        @jax.custom_vjp
        def call(*vals):
            return jax.pure_callback(host_fwd, tuple(shapes), *vals)

        def fwd(*vals):
            return call(*vals), vals

        def bwd(vals, gs):
            in_shapes = tuple(jax.ShapeDtypeStruct(v.shape, v.dtype)
                              for v in vals)

            def host_bwd(*args):
                n = len(vals)
                res = backward_func(*[np.asarray(a) for a in args])
                res = res if isinstance(res, (list, tuple)) else [res]
                return tuple(np.asarray(r, s.dtype).reshape(s.shape)
                             for r, s in zip(res, in_shapes))

            return jax.pure_callback(host_bwd, in_shapes, *vals, *gs)

        call.defvjp(fwd, bwd)
        res = call(*arrs)
    res = tuple(Tensor(r) for r in res)
    return res[0] if single else list(res)
