"""Sequence ops: the LoD family on dense shapes.

TPU rewrite of the reference LoD sequence ops
(/root/reference/paddle/fluid/operators/sequence_ops/ — sequence_pool_op,
sequence_softmax_op, sequence_pad_op, sequence_unpad_op,
sequence_reverse_op, sequence_expand_op, sequence_conv_op, …) which
operate on ragged LoDTensors (lod_tensor.h offset vectors). XLA wants
static shapes, so the ragged representation becomes
**dense padded (batch, maxlen, ...) + lengths (batch,)**; each op masks by
position < length (SURVEY §5/§7: the segment-ids rewrite). Ops whose
output size is data-dependent (unpad/expand) return concrete arrays
eagerly and are documented as not jit-traceable.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.op import primitive
from ..framework.tensor import Tensor, unwrap

__all__ = [
    "sequence_pool", "sequence_softmax", "sequence_reverse",
    "sequence_pad", "sequence_unpad", "sequence_expand",
    "sequence_first_step", "sequence_last_step", "sequence_conv",
]


def _mask(lengths, maxlen):
    # (b, maxlen) bool: position < length
    return jnp.arange(maxlen)[None, :] < lengths[:, None]


@primitive("sequence_pool", nondiff=("lengths",))
def sequence_pool(x, lengths, pool_type="sum", pad_value=0.0, name=None):
    """x: (b, maxlen, ...) padded; lengths: (b,) valid counts.
    pool_type: sum/average/sqrt/max/last/first (sequence_pool_op.cc)."""
    pool_type = pool_type.lower()
    b, maxlen = x.shape[0], x.shape[1]
    m = _mask(lengths, maxlen)
    mx = m.reshape(m.shape + (1,) * (x.ndim - 2))
    lens = jnp.maximum(lengths, 1).astype(x.dtype)
    lens = lens.reshape((b,) + (1,) * (x.ndim - 2))
    if pool_type == "sum":
        out = jnp.sum(jnp.where(mx, x, 0), axis=1)
    elif pool_type in ("average", "mean"):
        out = jnp.sum(jnp.where(mx, x, 0), axis=1) / lens
    elif pool_type == "sqrt":
        out = jnp.sum(jnp.where(mx, x, 0), axis=1) / jnp.sqrt(lens)
    elif pool_type == "max":
        neg = jnp.asarray(jnp.finfo(x.dtype).min
                          if jnp.issubdtype(x.dtype, jnp.floating)
                          else jnp.iinfo(x.dtype).min, x.dtype)
        out = jnp.max(jnp.where(mx, x, neg), axis=1)
    elif pool_type == "last":
        idx = jnp.maximum(lengths - 1, 0)
        out = jnp.take_along_axis(
            x, idx.reshape((b, 1) + (1,) * (x.ndim - 2)), axis=1)[:, 0]
    elif pool_type == "first":
        out = x[:, 0]
    else:
        raise ValueError(f"unknown pool_type {pool_type}")
    # empty sequences produce pad_value (reference pad_value attr)
    empty = (lengths == 0).reshape((b,) + (1,) * (x.ndim - 2))
    return jnp.where(empty, jnp.asarray(pad_value, x.dtype), out)


def sequence_first_step(x, lengths=None, name=None):
    if lengths is None:
        lengths = jnp.full((x.shape[0],), x.shape[1], jnp.int32)
    return sequence_pool(x, lengths, "first")


def sequence_last_step(x, lengths=None, name=None):
    if lengths is None:
        lengths = jnp.full((x.shape[0],), x.shape[1], jnp.int32)
    return sequence_pool(x, lengths, "last")


@primitive("sequence_softmax", nondiff=("lengths",))
def sequence_softmax(x, lengths, name=None):
    """Softmax within each sequence, padding excluded
    (sequence_softmax_op.cc). x: (b, maxlen) or (b, maxlen, 1)."""
    squeeze = x.ndim == 3 and x.shape[-1] == 1
    v = x[..., 0] if squeeze else x
    m = _mask(lengths, v.shape[1])
    s = jnp.where(m, v, -1e30)
    out = jax.nn.softmax(s, axis=1)
    out = jnp.where(m, out, 0.0)
    return out[..., None] if squeeze else out


@primitive("sequence_reverse", nondiff=("lengths",))
def sequence_reverse(x, lengths, name=None):
    """Reverse each sequence's valid prefix in place
    (sequence_reverse_op.h). x: (b, maxlen, ...)."""
    maxlen = x.shape[1]
    pos = jnp.arange(maxlen)[None, :]                       # (1, maxlen)
    rev = lengths[:, None] - 1 - pos                         # reversed idx
    idx = jnp.where(pos < lengths[:, None], rev, pos)
    idx = jnp.clip(idx, 0, maxlen - 1)
    return jnp.take_along_axis(
        x, idx.reshape(idx.shape + (1,) * (x.ndim - 2)), axis=1)


def sequence_pad(x, pad_value=0.0, maxlen=None, lengths=None, name=None):
    """Flat (total, ...) + lengths -> (b, maxlen, ...) padded + lengths
    (sequence_pad_op.cc). Eager only: output batch comes from lengths."""
    lengths = np.asarray(lengths)
    ends = np.cumsum(lengths)
    starts = ends - lengths
    ml = int(maxlen) if maxlen else int(lengths.max() if len(lengths) else 0)
    xv = x.value if hasattr(x, "value") else jnp.asarray(x)
    rows = []
    for s, l in zip(starts, lengths):
        seg = xv[int(s):int(s + min(l, ml))]
        pad = [(0, ml - seg.shape[0])] + [(0, 0)] * (xv.ndim - 1)
        rows.append(jnp.pad(seg, pad, constant_values=pad_value))
    out = jnp.stack(rows) if rows else jnp.zeros((0, ml) + xv.shape[1:],
                                                 xv.dtype)
    from ..framework.tensor import Tensor

    return Tensor(out), Tensor(jnp.asarray(np.minimum(lengths, ml),
                                           jnp.int32))


def sequence_unpad(x, lengths, name=None):
    """(b, maxlen, ...) + lengths -> flat (total, ...)
    (sequence_unpad_op.cc). Eager only: output size is data-dependent."""
    xv = x.value if hasattr(x, "value") else jnp.asarray(x)
    lens = np.asarray(lengths.numpy() if hasattr(lengths, "numpy")
                      else lengths)
    parts = [xv[i, :int(l)] for i, l in enumerate(lens)]
    out = (jnp.concatenate(parts) if parts
           else jnp.zeros((0,) + xv.shape[2:], xv.dtype))
    from ..framework.tensor import Tensor

    return Tensor(out)


def sequence_expand(x, lengths, name=None):
    """Repeat row i of x lengths[i] times (sequence_expand_op.cc with the
    common ref_level=0 usage). Eager only."""
    xv = x.value if hasattr(x, "value") else jnp.asarray(x)
    lens = np.asarray(lengths.numpy() if hasattr(lengths, "numpy")
                      else lengths).astype(np.int64)
    idx = np.repeat(np.arange(len(lens)), lens)
    from ..framework.tensor import Tensor

    return Tensor(jnp.take(xv, jnp.asarray(idx), axis=0))


@primitive("sequence_conv", nondiff=("lengths",))
def sequence_conv(x, weight, lengths=None, context_length=3,
                  context_start=None, bias=None, name=None):
    """Context-window conv over the time axis (sequence_conv_op.cc):
    each step sees [t+context_start, t+context_start+context_length);
    positions outside the valid prefix contribute zeros.
    x: (b, maxlen, d); weight: (context_length*d, out_d)."""
    b, maxlen, d = x.shape
    if context_start is None:
        context_start = -((context_length - 1) // 2)
    m = _mask(lengths, maxlen)[..., None] if lengths is not None else None
    xm = jnp.where(m, x, 0.0) if m is not None else x
    cols = []
    for j in range(context_length):
        off = context_start + j
        shifted = jnp.roll(xm, -off, axis=1)
        pos = jnp.arange(maxlen) + off
        ok = (pos >= 0) & (pos < maxlen)
        cols.append(jnp.where(ok[None, :, None], shifted, 0.0))
    col = jnp.concatenate(cols, axis=-1)            # (b, maxlen, cl*d)
    out = col @ weight
    if bias is not None:
        out = out + bias
    if m is not None:
        out = jnp.where(m, out, 0.0)
    return out


def sequence_concat(xs, lengths_list, name=None):
    """Concatenate sequences row-wise (sequence_concat_op.cc):
    out row i = concat of each input's valid prefix. Returns
    (padded, lengths)."""
    mats = [np.asarray(unwrap(x)) for x in xs]
    lens = [np.asarray(unwrap(l)).astype(np.int64) for l in lengths_list]
    b = mats[0].shape[0]
    out_len = np.sum(np.stack(lens), axis=0)
    maxlen = int(out_len.max()) if b else 0
    tail = mats[0].shape[2:]
    out = np.zeros((b, maxlen) + tail, mats[0].dtype)
    for i in range(b):
        off = 0
        for m, l in zip(mats, lens):
            n = int(l[i])
            out[i, off:off + n] = m[i, :n]
            off += n
    return Tensor(out), Tensor(out_len)


def sequence_expand_as(x, lengths, name=None):
    """Expand each row of x to its target length
    (sequence_expand_as_op.cc): row i repeated lengths[i] times,
    concatenated flat like sequence_expand — shape (sum(lengths), ...)."""
    return sequence_expand(x, lengths, name=name)


def sequence_slice(x, lengths, offset, length, name=None):
    """Slice each sequence (sequence_slice_op.cc): take `length[i]`
    steps starting at offset[i]. Returns (padded, new_lengths)."""
    off = jnp.reshape(jnp.asarray(unwrap(offset)), (-1,))
    ln = jnp.reshape(jnp.asarray(unwrap(length)), (-1,))
    arr = unwrap(x)
    b, maxlen = arr.shape[0], arr.shape[1]
    pos = jnp.arange(maxlen)[None, :]
    src = pos + off[:, None]
    src = jnp.clip(src, 0, maxlen - 1)
    gathered = jnp.take_along_axis(
        arr, src.reshape(src.shape + (1,) * (arr.ndim - 2)).astype(jnp.int32),
        axis=1)
    mask = pos < ln[:, None]
    out = jnp.where(mask.reshape(mask.shape + (1,) * (arr.ndim - 2)),
                    gathered, 0)
    return Tensor(out), Tensor(ln)


def sequence_enumerate(x, lengths, win_size, pad_value=0, name=None):
    """Sliding-window id enumeration (sequence_enumerate_op.cc):
    (b, maxlen) int -> (b, maxlen, win_size)."""
    arr = unwrap(x)
    b, maxlen = arr.shape
    lens = jnp.reshape(jnp.asarray(unwrap(lengths)), (-1,))
    outs = []
    for w in range(win_size):
        shifted = jnp.concatenate(
            [arr[:, w:], jnp.full((b, w), pad_value, arr.dtype)], axis=1)
        # positions beyond len-w are pad
        valid = jnp.arange(maxlen)[None, :] + w < lens[:, None]
        outs.append(jnp.where(valid, shifted, pad_value))
    return Tensor(jnp.stack(outs, axis=-1))


def sequence_scatter(x, index, updates, lengths=None, name=None):
    """Scatter updates into each sequence at per-row indices
    (sequence_scatter_op.cc), dense form: x (b, n), index (b, k),
    updates (b, k)."""
    arr = unwrap(x)
    idx = jnp.asarray(unwrap(index))
    upd = jnp.asarray(unwrap(updates))
    rows = jnp.arange(arr.shape[0])[:, None]
    return Tensor(arr.at[rows, idx].add(upd.astype(arr.dtype)))
