"""Functional op library (TPU-native equivalent of the reference operator
library, /root/reference/paddle/fluid/operators/ — see SURVEY.md §2.4)."""
from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .beam_search import beam_search_decode, beam_search_step  # noqa: F401
from .sequence import *  # noqa: F401,F403
from .debug import Assert, Print, py_func  # noqa: F401

from . import (creation, math, manipulation, logic, linalg,  # noqa: F401
               search, sequence, beam_search, debug)
