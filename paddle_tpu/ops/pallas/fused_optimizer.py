"""Fused Pallas optimizer update kernels (sgd / momentum / adam / lamb).

The static optimizer ops (static/kernels.py) and the ZeRO per-bucket
chunk update (static/stepplan.py) lower each parameter update to 5-8
separate XLA elementwise ops; every one of them re-reads the param /
grad / moment buffers from HBM. Optimizer updates are pure bandwidth —
at ZeRO bucket sizes the update region is the post-backward hot loop
(ISSUE 19) — so the win is a single grid pass over (rows, 128) blocks
that reads grad + param + moments ONCE and writes param + moments ONCE,
with the step scalars (lr, beta-pows, the fp16 FoundInfinite skip flag)
prefetched into SMEM.

Established kernel pattern (fused_embedding / paged_attention):

- XLA fallback whose math is VERBATIM the static kernels' (bitwise: the
  ``PADDLE_FUSED_OPT=0`` escape and every ineligible shape produce
  exactly the pre-fusion update)
- ``fused_opt.pallas`` / ``fused_opt.xla`` dispatch counters with
  reasons (ops/pallas/counters.py)
- eligibility gate: f32, >= one (8, 128) tile, pallas importable and
  enabled for the backend (``PADDLE_FUSED_OPT_INTERPRET=1`` forces the
  kernel in interpret mode — CI / CPU-probe leg)
- autotune verdict per (op, n) persisted in the PR 10 disk cache
  (autotune.best_fused_opt_impl)

Three entry points:

- :func:`fused_op_update` — the static KERNELS delegate (plain step,
  the replicated ``_comm_step_fn`` optimizer region, and op_test)
- :func:`fused_chunk_update` — the ZeRO per-bucket (chunk,) update;
  for lamb it runs the TWO-PHASE trust-ratio plan: per-chunk partial
  per-param sq-norms -> tiny ``psum`` over the dp axis -> the fused
  elementwise update consumes the global norms. This is what makes
  lamb chunk-shardable and removes PR 18's counted ZeRO refusal.
- :func:`fused_try_rule` — the dygraph ``optimizer.step()`` hook;
  returns None unless the Pallas kernel actually engages, so the
  reference rule (and the CPU path) stays bitwise by construction.

The dygraph rules place epsilon differently from the static ops (eps
added to sqrt(vhat) of the NORMALIZED moment); the kernels carry a
``dygraph`` variant so each caller gets its own reference math.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

try:  # pallas imports kept lazy-tolerant (cpu wheels without pallas tpu)
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _PALLAS = True
except Exception:  # pragma: no cover
    _PALLAS = False

__all__ = ["FUSED_OPS", "fused_op_update", "fused_chunk_update",
           "fused_try_rule", "fused_opt_escaped"]

# rules with a fused kernel; lamb's trust ratio is two-phase (the
# elementwise m/v/r pass is the kernel, the norms stay XLA reductions)
FUSED_OPS = ("sgd", "momentum", "adam", "lamb")

_LANE = 128
_TILE = 8 * _LANE          # one f32 (8, 128) tile = 1024 elements


def fused_opt_escaped() -> bool:
    """True when ``PADDLE_FUSED_OPT=0`` pins the bitwise XLA escape."""
    return os.environ.get("PADDLE_FUSED_OPT", "").strip() in (
        "0", "off", "false")


def _interpret_forced() -> bool:
    return os.environ.get("PADDLE_FUSED_OPT_INTERPRET", "").strip() in (
        "1", "on", "true")


# ---------------------------------------------------------------------------
# XLA reference updates — VERBATIM static/kernels.py math (the escape
# leg must stay bitwise with the pre-fusion static ops) plus the
# dygraph-variant forms from optimizer/optimizer.py
# ---------------------------------------------------------------------------


def _gate_update(ins, outs):
    """FoundInfinite skip-step gate: on a non-finite step every output
    keeps its previous value (GradScaler semantics, compiled)."""
    found = ins.get("FoundInfinite")
    if not found:
        return outs
    skip = found[0].reshape(())
    olds = {"ParamOut": "Param", "VelocityOut": "Velocity",
            "Moment1Out": "Moment1", "Moment2Out": "Moment2",
            "Beta1PowOut": "Beta1Pow", "Beta2PowOut": "Beta2Pow"}
    return {slot: [jnp.where(skip, ins[olds[slot]][0], new)
                   for new in vals]
            for slot, vals in outs.items()}


def _xla_sgd(ins, attrs):
    p, g, lr = ins["Param"][0], ins["Grad"][0], ins["LearningRate"][0]
    return _gate_update(ins, {"ParamOut": [p - lr * g]})


def _xla_momentum(ins, attrs):
    p, g, v = ins["Param"][0], ins["Grad"][0], ins["Velocity"][0]
    lr = ins["LearningRate"][0]
    mu = attrs.get("mu", 0.9)
    use_nesterov = attrs.get("use_nesterov", False)
    v_new = mu * v + g
    if use_nesterov:
        p_new = p - (g + mu * v_new) * lr
    else:
        p_new = p - lr * v_new
    return _gate_update(ins, {"ParamOut": [p_new],
                              "VelocityOut": [v_new]})


def _xla_adam(ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    m, v = ins["Moment1"][0], ins["Moment2"][0]
    b1p, b2p = ins["Beta1Pow"][0], ins["Beta2Pow"][0]
    lr = ins["LearningRate"][0]
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    m_new = b1 * m + (1 - b1) * g
    v_new = b2 * v + (1 - b2) * g * g
    lr_t = lr * jnp.sqrt(1 - b2p * b2) / (1 - b1p * b1)
    p_new = p - lr_t * m_new / (jnp.sqrt(v_new) + eps)
    return _gate_update(ins, {
        "ParamOut": [p_new], "Moment1Out": [m_new],
        "Moment2Out": [v_new], "Beta1PowOut": [b1p * b1],
        "Beta2PowOut": [b2p * b2]})


def _xla_lamb(ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    m, v = ins["Moment1"][0], ins["Moment2"][0]
    b1p, b2p = ins["Beta1Pow"][0], ins["Beta2Pow"][0]
    lr = ins["LearningRate"][0]
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-6)
    wd = attrs.get("weight_decay", 0.01)
    m_new = b1 * m + (1 - b1) * g
    v_new = b2 * v + (1 - b2) * g * g
    m_hat = m_new / (1 - b1p * b1)
    v_hat = v_new / (1 - b2p * b2)
    r = m_hat / (jnp.sqrt(v_hat) + eps) + wd * p
    p_norm = jnp.linalg.norm(p)
    r_norm = jnp.linalg.norm(r)
    trust = jnp.where((p_norm > 0) & (r_norm > 0), p_norm / r_norm, 1.0)
    return _gate_update(ins, {
        "ParamOut": [p - lr * trust * r], "Moment1Out": [m_new],
        "Moment2Out": [v_new], "Beta1PowOut": [b1p * b1],
        "Beta2PowOut": [b2p * b2]})


_XLA = {"sgd": _xla_sgd, "momentum": _xla_momentum, "adam": _xla_adam,
        "lamb": _xla_lamb}


# ---------------------------------------------------------------------------
# Pallas kernel bodies: one grid pass over (block_rows, 128) VMEM
# blocks; scalars arrive as (1, 1) SMEM refs; the FoundInfinite gate
# folds into the SAME pass (no second read of the old state)
# ---------------------------------------------------------------------------


def _sgd_kernel(lr_ref, skip_ref, p_ref, g_ref, p_out):
    lr = lr_ref[0, 0]
    skip = skip_ref[0, 0] != 0
    p = p_ref[...]
    p_out[...] = jnp.where(skip, p, p - lr * g_ref[...])


def _momentum_kernel(lr_ref, skip_ref, p_ref, g_ref, v_ref, p_out,
                     v_out, *, mu, nesterov):
    lr = lr_ref[0, 0]
    skip = skip_ref[0, 0] != 0
    p, g, v = p_ref[...], g_ref[...], v_ref[...]
    v_new = mu * v + g
    if nesterov:
        p_new = p - (g + mu * v_new) * lr
    else:
        p_new = p - lr * v_new
    p_out[...] = jnp.where(skip, p, p_new)
    v_out[...] = jnp.where(skip, v, v_new)


def _adam_kernel(lr_ref, c1_ref, c2_ref, skip_ref, p_ref, g_ref, m_ref,
                 v_ref, p_out, m_out, v_out, *, b1, b2, eps, dygraph):
    """c1/c2: the ADVANCED beta-pows (static: b1p*b1, b2p*b2) or the
    dygraph bias-correction denominators (1 - b**t)."""
    lr = lr_ref[0, 0]
    c1 = c1_ref[0, 0]
    c2 = c2_ref[0, 0]
    skip = skip_ref[0, 0] != 0
    p, g, m, v = p_ref[...], g_ref[...], m_ref[...], v_ref[...]
    m_new = b1 * m + (1 - b1) * g
    v_new = b2 * v + (1 - b2) * g * g
    if dygraph:
        p_new = p - lr * (m_new / c1) / (jnp.sqrt(v_new / c2) + eps)
    else:
        lr_t = lr * jnp.sqrt(1 - c2) / (1 - c1)
        p_new = p - lr_t * m_new / (jnp.sqrt(v_new) + eps)
    p_out[...] = jnp.where(skip, p, p_new)
    m_out[...] = jnp.where(skip, m, m_new)
    v_out[...] = jnp.where(skip, v, v_new)


def _lamb_phase1_kernel(c1_ref, c2_ref, p_ref, g_ref, m_ref, v_ref,
                        m_out, v_out, r_out, *, b1, b2, eps, wd,
                        dygraph):
    """Lamb elementwise phase: m/v advance + the trust-ratio numerator
    ``r`` in one read of p/g/m/v. The norms (phase 2) are XLA
    reductions — per-param globally, per-segment + psum on a ZeRO
    chunk — and the final ``p - lr*trust*r`` is elementwise XLA."""
    c1 = c1_ref[0, 0]
    c2 = c2_ref[0, 0]
    p, g, m, v = p_ref[...], g_ref[...], m_ref[...], v_ref[...]
    m_new = b1 * m + (1 - b1) * g
    v_new = b2 * v + (1 - b2) * g * g
    if dygraph:
        m_hat = m_new / c1
        v_hat = v_new / c2
        r = m_hat / (jnp.sqrt(v_hat) + eps) + wd * p
    else:
        m_hat = m_new / (1 - c1)
        v_hat = v_new / (1 - c2)
        r = m_hat / (jnp.sqrt(v_hat) + eps) + wd * p
    m_out[...] = m_new
    v_out[...] = v_new
    r_out[...] = r


# ---------------------------------------------------------------------------
# dispatch plumbing
# ---------------------------------------------------------------------------


def _scal(x):
    """Any scalar-ish value -> (1, 1) f32 for the SMEM block."""
    return jnp.asarray(x, jnp.float32).reshape(-1)[:1].reshape(1, 1)


def _block_rows(rows: int) -> int:
    for br in (512, 256, 64, 8):
        if rows % br == 0:
            return br
    return 8


def _pad_flat(x, n_pad):
    flat = x.reshape(-1).astype(jnp.float32)
    if flat.shape[0] == n_pad:
        return flat
    return jnp.concatenate(
        [flat, jnp.zeros((n_pad - flat.shape[0],), jnp.float32)])


def _run_grid(kernel, scalars, tensors, n_outs, n, interpret):
    """Common pallas_call: scalars as SMEM (1,1) refs, tensors padded
    to whole (8, 128) tiles and blocked (block_rows, 128) over a 1-D
    grid. Returns the outputs sliced back to ``n`` flat elements."""
    n_pad = -(-n // _TILE) * _TILE
    rows = n_pad // _LANE
    br = _block_rows(rows)
    blk = pl.BlockSpec((br, _LANE), lambda i: (i, 0))
    outs = pl.pallas_call(
        kernel,
        grid=(rows // br,),
        in_specs=([pl.BlockSpec(memory_space=pltpu.SMEM)
                   for _ in scalars] + [blk for _ in tensors]),
        out_specs=[blk] * n_outs,
        out_shape=[jax.ShapeDtypeStruct((rows, _LANE), jnp.float32)
                   for _ in range(n_outs)],
        interpret=interpret,
    )(*scalars, *[_pad_flat(t, n_pad).reshape(rows, _LANE)
                  for t in tensors])
    return [o.reshape(-1)[:n] for o in outs]


def _dispatch(op_type: str, n: int, dtype) -> tuple:
    """('pallas'|'xla', reason, interpret) — the one gate every entry
    point funnels through. ``PADDLE_FUSED_OPT=0`` is the bitwise
    escape; the autotune verdict (TPU only) can demote to XLA."""
    if op_type not in FUSED_OPS:
        return "xla", f"no fused kernel for {op_type!r}", False
    if fused_opt_escaped():
        return "xla", "disabled (PADDLE_FUSED_OPT=0)", False
    if not _PALLAS:
        return "xla", "pallas unavailable in this jax build", False
    interpret = _interpret_forced()
    if not interpret:
        from ...framework.bringup import pallas_enabled

        if not pallas_enabled():
            return "xla", "pallas disabled for this backend", False
    if jnp.dtype(dtype) != jnp.float32:
        return "xla", f"dtype {jnp.dtype(dtype).name} is not f32", False
    if n < _TILE:
        return ("xla", f"n={n} below one (8, 128) tile "
                       f"({_TILE} elems)", False)
    from .autotune import fused_opt_choice

    if fused_opt_choice(op_type, n, str(jnp.dtype(dtype))) == "xla":
        return "xla", "autotune verdict: xla", False
    return "pallas", "", interpret


def _pick(ins, role):
    x = ins[role][0]
    return x


def _found_scal(ins):
    found = ins.get("FoundInfinite")
    if not found:
        return _scal(0.0)
    return _scal(found[0].reshape(()).astype(jnp.float32))


def _pallas_update(op_type, ins, attrs, interpret, dygraph=False,
                   c1=None, c2=None):
    """The fused kernel leg. c1/c2 override the beta-pow scalars for
    the dygraph variant (bias-correction by step count)."""
    p = _pick(ins, "Param")
    shape, dtype = p.shape, p.dtype
    n = p.size
    lr = _scal(ins["LearningRate"][0])
    skip = _found_scal(ins)
    if op_type == "sgd":
        (p_new,) = _run_grid(
            _sgd_kernel, [lr, skip], [p, _pick(ins, "Grad")], 1, n,
            interpret)
        return {"ParamOut": [p_new.reshape(shape).astype(dtype)]}
    if op_type == "momentum":
        kern = functools.partial(
            _momentum_kernel, mu=attrs.get("mu", 0.9),
            nesterov=bool(attrs.get("use_nesterov", False)))
        p_new, v_new = _run_grid(
            kern, [lr, skip],
            [p, _pick(ins, "Grad"), _pick(ins, "Velocity")], 2, n,
            interpret)
        return {"ParamOut": [p_new.reshape(shape).astype(dtype)],
                "VelocityOut": [v_new.reshape(shape).astype(dtype)]}
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    b1p = ins["Beta1Pow"][0].reshape(())
    b2p = ins["Beta2Pow"][0].reshape(())
    if c1 is None:
        c1, c2 = b1p * b1, b2p * b2
    if op_type == "adam":
        kern = functools.partial(
            _adam_kernel, b1=b1, b2=b2,
            eps=attrs.get("epsilon", 1e-8), dygraph=dygraph)
        p_new, m_new, v_new = _run_grid(
            kern, [lr, _scal(c1), _scal(c2), skip],
            [p, _pick(ins, "Grad"), _pick(ins, "Moment1"),
             _pick(ins, "Moment2")], 3, n, interpret)
        return _gate_scalars(ins, {
            "ParamOut": [p_new.reshape(shape).astype(dtype)],
            "Moment1Out": [m_new.reshape(shape).astype(dtype)],
            "Moment2Out": [v_new.reshape(shape).astype(dtype)],
            "Beta1PowOut": [b1p * b1], "Beta2PowOut": [b2p * b2]})
    # lamb: fused elementwise phase + XLA norms + elementwise finish
    kern = functools.partial(
        _lamb_phase1_kernel, b1=b1, b2=b2,
        eps=attrs.get("epsilon", 1e-6),
        wd=attrs.get("weight_decay", 0.01), dygraph=dygraph)
    m_new, v_new, r = _run_grid(
        kern, [_scal(c1), _scal(c2)],
        [p, _pick(ins, "Grad"), _pick(ins, "Moment1"),
         _pick(ins, "Moment2")], 3, n, interpret)
    pf = p.reshape(-1).astype(jnp.float32)
    p_norm = jnp.linalg.norm(pf)
    r_norm = jnp.linalg.norm(r)
    trust = jnp.where((p_norm > 0) & (r_norm > 0), p_norm / r_norm, 1.0)
    lr_s = ins["LearningRate"][0].reshape(())
    p_new = pf - lr_s * trust * r
    outs = _gate_update(
        {**ins, "Param": [pf],
         "Moment1": [ins["Moment1"][0].reshape(-1)],
         "Moment2": [ins["Moment2"][0].reshape(-1)]},
        {"ParamOut": [p_new], "Moment1Out": [m_new],
         "Moment2Out": [v_new], "Beta1PowOut": [b1p * b1],
         "Beta2PowOut": [b2p * b2]})
    return _shape_back(outs, shape, dtype)


def _shape_back(outs, shape, dtype):
    for slot in ("ParamOut", "Moment1Out", "Moment2Out"):
        if slot in outs:
            outs[slot] = [outs[slot][0].reshape(shape).astype(dtype)]
    return outs


def _gate_scalars(ins, outs):
    """The tensor slots were gated INSIDE the kernel; gate only the
    replicated scalar accumulators here."""
    found = ins.get("FoundInfinite")
    if not found:
        return outs
    skip = found[0].reshape(())
    for slot, old in (("Beta1PowOut", "Beta1Pow"),
                      ("Beta2PowOut", "Beta2Pow")):
        if slot in outs:
            outs[slot] = [jnp.where(skip, ins[old][0], outs[slot][0])]
    return outs


def fused_op_update(op_type, ins, attrs):
    """The static KERNELS delegate: same (ins, attrs) -> outs slot
    convention as static/kernels.py. Ineligible / escaped dispatches
    run the verbatim XLA reference (bitwise with the pre-fusion ops);
    an engaged kernel is counted ``fused_opt.pallas``."""
    from .counters import bump

    p = ins["Param"][0]
    path, reason, interpret = _dispatch(op_type, p.size, p.dtype)
    if path == "pallas":
        try:
            out = _pallas_update(op_type, ins, attrs, interpret)
            bump("fused_opt", "pallas")
            return out
        except Exception as e:
            bump("fused_opt", "xla",
                 f"kernel error {type(e).__name__}: {e}")
    else:
        bump("fused_opt", "xla", f"{op_type}: {reason}")
    return _XLA[op_type](ins, attrs)


# ---------------------------------------------------------------------------
# ZeRO chunk update (stepplan.apply_bucket): lamb's two-phase trust plan
# ---------------------------------------------------------------------------


def _chunk_segments(param_elems, position, c):
    """Per-element segment ids of a (c,) chunk inside the bucket's
    padded concat buffer: element j of param i maps to segment i, the
    padding tail to the sentinel segment len(param_elems)."""
    ends = np.cumsum(np.asarray(param_elems, np.int64))
    pos = position + jnp.arange(c, dtype=jnp.int32)
    return jnp.searchsorted(jnp.asarray(ends, jnp.int32), pos,
                            side="right")


def fused_chunk_update(op_type, ins, attrs, *, axis=None,
                       param_elems=None, position=None):
    """One ZeRO bucket's per-device (chunk,) update.

    sgd/momentum/adam are elementwise-closed on the chunk — they ARE
    :func:`fused_op_update`. lamb needs the per-param trust ratio, a
    GLOBAL norm over buffers this device only holds 1/g of — the
    two-phase plan:

    1. segment the chunk by ``param_elems`` (static per-param element
       counts; ``position`` is this device's traced flat offset) and
       reduce per-segment partial sq-norms of the param chunk and the
       lamb ``r`` numerator (whose m/v/r elementwise pass is the fused
       kernel when eligible)
    2. one tiny ``lax.psum`` of the two (n_params+1,) partials over
       ``axis`` -> global per-param norms -> per-element trust gathered
       back through the segment ids -> elementwise finish.

    Parity vs the unsharded lamb op is TOLERANCE, not bitwise: the
    sq-norm sum reassociates across devices (documented; the ZeRO
    parity gate is the same amp-style loss tolerance the int8 ring
    uses)."""
    if op_type != "lamb":
        return fused_op_update(op_type, ins, attrs)

    from .counters import bump

    p = ins["Param"][0].reshape(-1)
    g = ins["Grad"][0].reshape(-1)
    m = ins["Moment1"][0].reshape(-1)
    v = ins["Moment2"][0].reshape(-1)
    b1p = ins["Beta1Pow"][0].reshape(())
    b2p = ins["Beta2Pow"][0].reshape(())
    lr = ins["LearningRate"][0].reshape(())
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-6)
    wd = attrs.get("weight_decay", 0.01)
    c = p.shape[0]

    path, reason, interpret = _dispatch("lamb", c, p.dtype)
    if path == "pallas":
        try:
            kern = functools.partial(
                _lamb_phase1_kernel, b1=b1, b2=b2, eps=eps, wd=wd,
                dygraph=False)
            m_new, v_new, r = _run_grid(
                kern, [_scal(b1p * b1), _scal(b2p * b2)],
                [p, g, m, v], 3, c, interpret)
            bump("fused_opt", "pallas")
        except Exception as e:
            bump("fused_opt", "xla",
                 f"kernel error {type(e).__name__}: {e}")
            path = "xla"
    if path != "pallas":
        bump("fused_opt", "xla", f"lamb chunk: {reason}")
        m_new = b1 * m + (1 - b1) * g
        v_new = b2 * v + (1 - b2) * g * g
        m_hat = m_new / (1 - b1p * b1)
        v_hat = v_new / (1 - b2p * b2)
        r = m_hat / (jnp.sqrt(v_hat) + eps) + wd * p

    n_seg = len(param_elems) + 1
    seg = _chunk_segments(param_elems, position, c)
    sq_p = jax.ops.segment_sum(p * p, seg, num_segments=n_seg)
    sq_r = jax.ops.segment_sum(r * r, seg, num_segments=n_seg)
    if axis is not None:
        sq_p = jax.lax.psum(sq_p, axis)
        sq_r = jax.lax.psum(sq_r, axis)
    p_norm = jnp.sqrt(sq_p)
    r_norm = jnp.sqrt(sq_r)
    trust = jnp.where((p_norm > 0) & (r_norm > 0),
                      p_norm / jnp.where(r_norm > 0, r_norm, 1.0), 1.0)
    p_new = p - lr * trust[seg] * r
    return _gate_update(
        {**ins, "Param": [p], "Moment1": [m], "Moment2": [v]},
        {"ParamOut": [p_new], "Moment1Out": [m_new],
         "Moment2Out": [v_new], "Beta1PowOut": [b1p * b1],
         "Beta2PowOut": [b2p * b2]})


# ---------------------------------------------------------------------------
# dygraph hook (optimizer/optimizer.py): engage-or-None
# ---------------------------------------------------------------------------

# optimizer class name -> (rule kind, slot names in kernel order)
_DY_RULES = {
    "SGD": ("sgd", ()),
    "Momentum": ("momentum", ("velocity",)),
    "Adam": ("adam", ("moment1", "moment2")),
    "AdamW": ("adam", ("moment1", "moment2")),
    "Lamb": ("lamb", ("moment1", "moment2")),
}


def fused_try_rule(opt, g, p, slots, lr, step):
    """Fused replacement for ``opt.rule(g, p, slots, lr, step)``:
    returns ``(p2, new_slots)`` when the Pallas kernel engages, None
    otherwise — the caller then runs the reference rule, so every
    non-engaging path (CPU included) is bitwise the old behavior. The
    dygraph bias-correction variant (eps on the normalized moments) is
    what the kernels compute here."""
    ent = _DY_RULES.get(type(opt).__name__)
    if ent is None:
        return None
    kind, slot_names = ent
    path, _reason, interpret = _dispatch(kind, p.size, p.dtype)
    if path != "pallas":
        return None

    from .counters import bump

    shape, dtype = p.shape, p.dtype
    n = p.size
    try:
        if kind == "sgd":
            (p_new,) = _run_grid(_sgd_kernel, [_scal(lr), _scal(0.0)],
                                 [p, g], 1, n, interpret)
            bump("fused_opt", "pallas")
            return p_new.reshape(shape).astype(dtype), slots
        if kind == "momentum":
            kern = functools.partial(_momentum_kernel,
                                     mu=opt._momentum,
                                     nesterov=bool(opt._nesterov))
            p_new, v_new = _run_grid(
                kern, [_scal(lr), _scal(0.0)],
                [p, g, slots["velocity"]], 2, n, interpret)
            bump("fused_opt", "pallas")
            return (p_new.reshape(shape).astype(dtype),
                    {"velocity": v_new.reshape(shape).astype(dtype)})
        b1, b2 = opt._beta1, opt._beta2
        tf = step.astype(jnp.float32)
        c1 = (1 - b1 ** tf).astype(jnp.float32)
        c2 = (1 - b2 ** tf).astype(jnp.float32)
        if kind == "adam":
            kern = functools.partial(_adam_kernel, b1=b1, b2=b2,
                                     eps=opt._eps, dygraph=True)
            p_new, m_new, v_new = _run_grid(
                kern, [_scal(lr), _scal(c1), _scal(c2), _scal(0.0)],
                [p, g, slots["moment1"], slots["moment2"]], 3, n,
                interpret)
            bump("fused_opt", "pallas")
            return (p_new.reshape(shape).astype(dtype),
                    {"moment1": m_new.reshape(shape).astype(dtype),
                     "moment2": v_new.reshape(shape).astype(dtype)})
        # lamb
        kern = functools.partial(_lamb_phase1_kernel, b1=b1, b2=b2,
                                 eps=opt._eps, wd=opt._lamb_wd,
                                 dygraph=True)
        m_new, v_new, r = _run_grid(
            kern, [_scal(c1), _scal(c2)],
            [p, g, slots["moment1"], slots["moment2"]], 3, n,
            interpret)
        pf = p.reshape(-1).astype(jnp.float32)
        w_norm = jnp.sqrt(jnp.sum(jnp.square(pf)))
        r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
        trust = jnp.where((w_norm > 0) & (r_norm > 0),
                          w_norm / r_norm, 1.0)
        p_new = pf - jnp.asarray(lr, jnp.float32) * trust * r
        bump("fused_opt", "pallas")
        return (p_new.reshape(shape).astype(dtype),
                {"moment1": m_new.reshape(shape).astype(dtype),
                 "moment2": v_new.reshape(shape).astype(dtype)})
    except Exception as e:
        bump("fused_opt", "xla",
             f"dygraph kernel error {type(e).__name__}: {e}")
        return None
