"""Trace-time dispatch counters for the custom Pallas kernels.

VERDICT r3 weak #4/#8: the silent try/except fallback around the fused
embedding kernel hid a real lowering bug for a full round, and bench.py
had no way to report whether the flash kernel actually engaged. Every
kernel dispatch site now bumps a counter — ``<kernel>.pallas`` when the
custom kernel runs, ``<kernel>.xla`` (with a reason) when the XLA path
is taken — and ``FLAGS_log_pallas_fallback=True`` additionally writes
each fallback to stderr.

Counts are per DISPATCH DECISION (trace time under jit — once per
compilation, not per step; every call in eager mode). bench.py snapshots
before/after a config and reports the delta, so ``pallas_fallback`` in
its rows reflects reality rather than only compile exceptions.
"""
from __future__ import annotations

import collections
import sys
from typing import Dict

from ...framework.flags import define_flag, get_flag

define_flag("log_pallas_fallback", False,
            "Log every Pallas-kernel fallback to the XLA path with its "
            "reason (dispatch decisions are trace-time)")

_COUNTS: collections.Counter = collections.Counter()


def bump(kernel: str, path: str, reason: str = "") -> None:
    _COUNTS[f"{kernel}.{path}"] += 1
    if path != "pallas" and get_flag("log_pallas_fallback"):
        msg = f"pallas-fallback: {kernel} -> {path}"
        if reason:
            msg += f" ({reason})"
        sys.stderr.write(msg + "\n")


def snapshot() -> Dict[str, int]:
    return dict(_COUNTS)


def delta(before: Dict[str, int]) -> Dict[str, int]:
    return {k: v - before.get(k, 0) for k, v in _COUNTS.items()
            if v - before.get(k, 0)}


def reset() -> None:
    _COUNTS.clear()
