"""Pallas TPU kernels for ops XLA cannot fuse well (flash attention, ...).

TPU-native counterpart of the reference hand-written CUDA fused kernels
(/root/reference/paddle/fluid/operators/fused/)."""
