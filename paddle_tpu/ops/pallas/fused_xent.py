"""Fused linear + softmax cross-entropy for large vocabularies.

The BERT MLM head computes logits = h @ W^T + b with W the tied
(vocab, hidden) embedding table, then softmax-xent over vocab. At
bert512 bench shapes the logits tensor is (32*512, 30592) — ~1 GB in
bf16 — written to HBM by the matmul, read back by the softmax, and the
same again for dlogits in the backward. That HBM traffic is pure
overhead: these Pallas kernels stream W in vocab tiles over a 2D grid
(rows-block outer, vocab-block inner — the inner axis revisits the
same output block, the canonical Pallas reduction idiom), carrying an
online max/sumexp + label-logit forward and recomputing the logit
blocks in the backward for dh and dW/db (the flash trick: p =
exp(s - lse) needs only the saved lse). Logits never land in HBM in
either direction.

Reference analog: softmax_with_cross_entropy_op.cu fuses softmax+xent
(but not the matmul); the matmul fusion is the TPU-native extension
the MFU push needs (VERDICT r4 #2). XLA fallback covers ineligible
shapes/backends; dispatch truth via ops.pallas.counters("fused_xent").
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ...framework.flags import define_flag
from .flash_attention import _dot, _sds

define_flag("fused_vocab_xent", True,
            "Route large-vocab linear+cross-entropy heads (BERT MLM) "
            "through the streamed Pallas kernel; False materialises "
            "logits via XLA (the A/B arm for the live session)")

_F32 = jnp.float32
_NEG = -1e30



_BN_CANDIDATES = (1024, 512, 256)
_BV_CANDIDATES = (512, 384, 256, 128)
#: pad modulus = the smallest row block we can always fall back to
_BN_MIN = _BN_CANDIDATES[-1]
#: per-kernel VMEM budget (bytes) for the block-resident f32 tensors;
#: v5e has ~16 MB/core — leave headroom for Mosaic's own buffers
_VMEM_BUDGET = 10 * 1024 * 1024


def _fits(bn, bv, hd):
    """Both backward kernels' block-resident f32 footprints must fit:
    dh holds h + f32 dh accumulator + w tile + s/p pair; dW holds
    h + w + f32 dW accumulator + s/p pair. Overflow would fail Mosaic
    at COMPILE time — outside the dispatch try/except — so no
    over-budget pair may ever be picked."""
    dh_kernel = 4 * (2 * bn * hd + bv * hd + 2 * bn * bv)
    dw_kernel = 4 * (bn * hd + 2 * bv * hd + 2 * bn * bv)
    return max(dh_kernel, dw_kernel) <= _VMEM_BUDGET


def _pick_blocks(n, hd, v):
    """Joint (block_n, block_v) choice, LARGEST bn first: every grid
    row-block streams the ENTIRE weight table once (47 MB for BERT),
    so bn — not bv — sets the dominant HBM traffic; at bert512
    (n=16384, hd=768) 1024-row blocks read W 16x (~0.75 GB) vs 64x
    (~3 GB) at 256. A greedy-large bv that forced a smaller bn under
    the VMEM cap would double exactly that traffic, so bv concedes
    first. Returns None when nothing divides + fits (dispatch falls
    back to XLA via _eligible). Vocab lane modulus 128: BERT's 30592
    = 128 * 239 only admits 128-wide vocab blocks anyway."""
    for bn in _BN_CANDIDATES:
        if n % bn != 0:
            continue
        for bv in _BV_CANDIDATES:
            if v % bv == 0 and _fits(bn, bv, hd):
                return bn, bv
    return None


# ---------------------------------------------------------------------------
# forward: grid (rows/bn, vocab/bv); m/l/ll accumulators live in output
# refs indexed by the row block only (inner vocab steps revisit them)
# ---------------------------------------------------------------------------


def _fwd_kernel(h_ref, w_ref, b_ref, lab_ref, lse_ref, ll_ref, m_ref,
                l_ref, *, num_v, block_v):
    from jax.experimental import pallas as pl

    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG)
        l_ref[...] = jnp.zeros_like(l_ref)
        ll_ref[...] = jnp.zeros_like(ll_ref)

    h = h_ref[...].astype(_F32)                    # (bn, H)
    labels = lab_ref[0, :]                         # (bn,)
    bn = h.shape[0]
    s = _dot(h, w_ref[...].astype(_F32), trans_b=True)   # (bn, bv)
    s = s + b_ref[0, :][None, :]
    m = m_ref[0, :]
    l = l_ref[0, :]
    m_new = jnp.maximum(m, jnp.max(s, axis=1))
    l_new = l * jnp.exp(m - m_new) + jnp.sum(
        jnp.exp(s - m_new[:, None]), axis=1)
    col = j * block_v + jax.lax.broadcasted_iota(jnp.int32, (bn, block_v),
                                                 1)
    hit = col == labels[:, None]
    ll_ref[...] = ll_ref[...] + jnp.sum(
        jnp.where(hit, s, 0.0), axis=1)[None, :]
    m_ref[...] = m_new[None, :]
    l_ref[...] = l_new[None, :]

    @pl.when(j == num_v - 1)
    def _finalize():
        lse_ref[...] = m_ref[...] + jnp.log(jnp.maximum(l_ref[...], 1e-30))


# ---------------------------------------------------------------------------
# backward: dh over (rows, vocab) grid; dW/db over (vocab, rows) grid
# ---------------------------------------------------------------------------


def _bwd_dh_kernel(h_ref, w_ref, b_ref, lab_ref, lse_ref, g_ref, dh_ref, *,
                   block_v):
    from jax.experimental import pallas as pl

    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        dh_ref[...] = jnp.zeros_like(dh_ref)

    h = h_ref[...].astype(_F32)
    w = w_ref[...].astype(_F32)
    labels = lab_ref[0, :]
    lse = lse_ref[0, :]
    g = g_ref[0, :]
    bn = h.shape[0]
    s = _dot(h, w, trans_b=True) + b_ref[0, :][None, :]
    p = jnp.exp(s - lse[:, None])
    col = j * block_v + jax.lax.broadcasted_iota(jnp.int32, (bn, block_v),
                                                 1)
    p = p - (col == labels[:, None]).astype(_F32)
    # dh_ref is f32 regardless of input dtype: accumulating across the
    # vocab grid steps in bf16 would compound rounding per step
    dh_ref[...] = dh_ref[...] + _dot(p * g[:, None], w)


def _bwd_dw_kernel(h_ref, w_ref, b_ref, lab_ref, lse_ref, g_ref,
                   dw_ref, db_ref, *, block_n, block_v):
    from jax.experimental import pallas as pl

    vj = pl.program_id(0)
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        dw_ref[...] = jnp.zeros_like(dw_ref)
        db_ref[...] = jnp.zeros_like(db_ref)

    w = w_ref[...].astype(_F32)                     # (bv, H)
    bv = w.shape[0]
    h = h_ref[...].astype(_F32)                     # (bn, H)
    labels = lab_ref[0, :]
    lse = lse_ref[0, :]
    g = g_ref[0, :]
    s = _dot(h, w, trans_b=True) + b_ref[0, :][None, :]
    p = jnp.exp(s - lse[:, None])
    col = vj * block_v + jax.lax.broadcasted_iota(
        jnp.int32, (block_n, bv), 1)
    p = (p - (col == labels[:, None]).astype(_F32)) * g[:, None]
    # f32 accumulator refs (cast to the param dtype happens outside)
    dw_ref[...] = dw_ref[...] + _dot(p.T, h)
    db_ref[...] = db_ref[...] + jnp.sum(p, axis=0)[None, :]


# ---------------------------------------------------------------------------
# pallas_call plumbing + custom_vjp
# ---------------------------------------------------------------------------


def _fwd_call(h, w, bias, labels, block_n, block_v):
    from jax.experimental import pallas as pl

    n, hd = h.shape
    v = w.shape[0]
    num_v = v // block_v
    lse, ll, _m, _l = pl.pallas_call(
        functools.partial(_fwd_kernel, num_v=num_v, block_v=block_v),
        grid=(n // block_n, num_v),
        in_specs=[
            pl.BlockSpec((block_n, hd), lambda i, j: (i, 0)),
            pl.BlockSpec((block_v, hd), lambda i, j: (j, 0)),
            pl.BlockSpec((1, block_v), lambda i, j: (0, j)),
            pl.BlockSpec((1, block_n), lambda i, j: (0, i)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_n), lambda i, j: (0, i)),
            pl.BlockSpec((1, block_n), lambda i, j: (0, i)),
            pl.BlockSpec((1, block_n), lambda i, j: (0, i)),
            pl.BlockSpec((1, block_n), lambda i, j: (0, i)),
        ],
        out_shape=[
            _sds((1, n), _F32, h),     # lse
            _sds((1, n), _F32, h),     # label logit
            _sds((1, n), _F32, h),     # running max (scratch-as-output)
            _sds((1, n), _F32, h),     # running sumexp
        ],
    )(h, w, bias[None, :], labels[None, :])
    return lse[0], ll[0]


def _bwd_call(h, w, bias, labels, lse, g, block_n, block_v):
    from jax.experimental import pallas as pl

    n, hd = h.shape
    v = w.shape[0]
    dh = pl.pallas_call(
        functools.partial(_bwd_dh_kernel, block_v=block_v),
        grid=(n // block_n, v // block_v),
        in_specs=[
            pl.BlockSpec((block_n, hd), lambda i, j: (i, 0)),
            pl.BlockSpec((block_v, hd), lambda i, j: (j, 0)),
            pl.BlockSpec((1, block_v), lambda i, j: (0, j)),
            pl.BlockSpec((1, block_n), lambda i, j: (0, i)),
            pl.BlockSpec((1, block_n), lambda i, j: (0, i)),
            pl.BlockSpec((1, block_n), lambda i, j: (0, i)),
        ],
        out_specs=pl.BlockSpec((block_n, hd), lambda i, j: (i, 0)),
        out_shape=_sds((n, hd), _F32, h),
    )(h, w, bias[None, :], labels[None, :], lse[None, :], g[None, :])
    dw, db = pl.pallas_call(
        functools.partial(_bwd_dw_kernel, block_n=block_n,
                          block_v=block_v),
        grid=(v // block_v, n // block_n),
        in_specs=[
            pl.BlockSpec((block_n, hd), lambda vj, i: (i, 0)),
            pl.BlockSpec((block_v, hd), lambda vj, i: (vj, 0)),
            pl.BlockSpec((1, block_v), lambda vj, i: (0, vj)),
            pl.BlockSpec((1, block_n), lambda vj, i: (0, i)),
            pl.BlockSpec((1, block_n), lambda vj, i: (0, i)),
            pl.BlockSpec((1, block_n), lambda vj, i: (0, i)),
        ],
        out_specs=[
            pl.BlockSpec((block_v, hd), lambda vj, i: (vj, 0)),
            pl.BlockSpec((1, block_v), lambda vj, i: (0, vj)),
        ],
        out_shape=[
            _sds((v, hd), _F32, h),
            _sds((1, v), _F32, h),
        ],
    )(h, w, bias[None, :], labels[None, :], lse[None, :], g[None, :])
    return dh.astype(h.dtype), dw.astype(w.dtype), db[0]


def _fused_xent_core(h, w, bias, labels, ignore_index):
    """mean loss = sum / clamp(count): derived from the ONE sum-form
    custom_vjp below (autodiff of the division supplies the 1/count
    the hand-written mean backward used to hard-code — r5 review
    dedup)."""
    s, c = _fused_xent_sums(h, w, bias, labels, ignore_index)
    return s / jnp.maximum(c, 1.0)


# -- the single custom_vjp: per-shard (loss_sum, valid_count), so the
# shard_map'd multi-device path can psum BEFORE the mean --------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _fused_xent_sums(h, w, bias, labels, ignore_index):
    (s, c), _ = _fused_xent_sums_fwd(h, w, bias, labels, ignore_index)
    return s, c


def _fused_xent_sums_fwd(h, w, bias, labels, ignore_index):
    valid = labels != ignore_index
    # rows with ignored labels still flow through the kernel; clamp the
    # label so the in-kernel hit-test never matches, zero the loss after
    safe = jnp.where(valid, labels, -1).astype(jnp.int32)
    blocks = _pick_blocks(h.shape[0], h.shape[1], w.shape[0])
    if blocks is None:
        raise ValueError(
            f"fused_xent: no (block_n, block_v) divides+fits h "
            f"{h.shape} x w {w.shape} — dispatch should have taken the "
            "XLA path (_eligible)")
    bn, bv = blocks
    lse, ll = _fwd_call(h, w, bias, safe, bn, bv)
    s = jnp.sum(jnp.where(valid, lse - ll, 0.0))
    c = jnp.sum(valid.astype(_F32))
    return (s, c), (h, w, bias, safe, valid, lse)


def _fused_xent_sums_bwd(ignore_index, res, ct):
    ds, _dc = ct   # count is a step function of int labels: no grad path
    h, w, bias, safe, valid, lse = res
    g = jnp.where(valid, ds, 0.0).astype(_F32)
    bn, bv = _pick_blocks(h.shape[0], h.shape[1], w.shape[0])  # fwd validated
    dh, dw, db = _bwd_call(h, w, bias, safe, lse, g, bn, bv)
    return dh, dw, db.astype(bias.dtype), None


_fused_xent_sums.defvjp(_fused_xent_sums_fwd, _fused_xent_sums_bwd)


def _sharded_fused(h2, w, bias, lab, mesh, row_axes, ignore_index):
    """Row-parallel fused xent under a multi-device TrainStep trace:
    shard_map over the batch-row axes (each shard streams the full W —
    replicated spec; pjit inserts the gather if TP shards it), psum the
    per-shard sums, divide once. This is how the opaque pallas call
    becomes SPMD-partitionable — the manual axes make the partitioning
    explicit instead of asking XLA to infer it."""
    from jax.sharding import PartitionSpec as P

    from ...parallel.ring import _shard_map

    def local(hs, ws, bs, ls):
        s, c = _fused_xent_sums(hs, ws, bs, ls, ignore_index)
        s = jax.lax.psum(s, row_axes)
        c = jax.lax.psum(c, row_axes)
        return s / jnp.maximum(c, 1.0)

    return _shard_map(local, mesh,
                      (P(row_axes, None), P(None, None), P(None),
                       P(row_axes)), P())(h2, w, bias, lab)


def _trace_shard_plan(n, hd, v):
    """(mesh, row_axes) when the current TrainStep trace is multi-device
    AND the rows divide into kernel-eligible shards; 'gate' when it is
    multi-device but unshardable (XLA fallback keeps correctness);
    None for single-device/no-trace."""
    from ...parallel.mesh import active_trace_mesh, active_trace_row_axes

    mesh = active_trace_mesh()
    if mesh is None or mesh.size <= 1:
        return None
    row_axes = tuple(active_trace_row_axes())
    if row_axes:
        import math

        shards = math.prod(mesh.shape[a] for a in row_axes)
        if (shards > 0 and n % shards == 0
                and _eligible(n // shards, hd, v)):
            return mesh, row_axes
    return "gate"


def _eligible(n, hd, v):
    from ...framework.bringup import pallas_enabled

    if not pallas_enabled():
        return False
    return (_pick_blocks(n, hd, v) is not None and
            hd % 128 == 0 and hd <= 2048)


def fused_linear_cross_entropy(h, w, bias, labels, ignore_index=-100):
    """mean softmax-xent of (h @ w^T + bias) against labels, streaming
    the vocab axis so the logits never land in HBM. h: (..., H); w:
    (V, H); bias: (V,); labels: (...,) int. Falls back to the XLA
    logits path off-TPU / for ineligible shapes (counters record
    which)."""
    from .counters import bump

    hd = h.shape[-1]
    h2 = h.reshape(-1, hd)
    lab = labels.reshape(-1)
    n = h2.shape[0]
    pad = (-n) % _BN_MIN
    plan = _trace_shard_plan(n, hd, w.shape[0])
    if plan == "gate":
        bump("fused_xent", "xla",
             "multi-device trace without shard-divisible rows/row axes "
             "(opaque pallas call is unpartitionable; XLA path is "
             "value-identical and partitionable)")
    elif plan is not None:
        mesh, row_axes = plan
        try:
            out = _sharded_fused(h2, w, bias, lab, mesh, row_axes,
                                 int(ignore_index))
            bump("fused_xent", "pallas_sharded")
            return out
        except Exception as e:
            bump("fused_xent", "xla",
                 f"sharded kernel error {type(e).__name__}: {e}")
    elif _eligible(n + pad, hd, w.shape[0]):
        try:
            if pad:
                h2 = jnp.concatenate(
                    [h2, jnp.zeros((pad, hd), h2.dtype)], 0)
                lab = jnp.concatenate(
                    [lab, jnp.full((pad,), ignore_index, lab.dtype)], 0)
            out = _fused_xent_core(h2, w, bias, lab, int(ignore_index))
            bump("fused_xent", "pallas")
            return out
        except Exception as e:
            bump("fused_xent", "xla",
                 f"kernel error {type(e).__name__}: {e}")
    else:
        bump("fused_xent", "xla",
             f"dispatch ineligible (n={n}, w={tuple(w.shape)})")
    logits = (h2 @ w.T).astype(_F32) + bias.astype(_F32)
    valid = lab != ignore_index
    safe = jnp.where(valid, lab, 0)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    ll = jnp.take_along_axis(
        logits, safe[:, None].astype(jnp.int32), axis=1)[:, 0]
    count = jnp.maximum(jnp.sum(valid.astype(_F32)), 1.0)
    return jnp.sum(jnp.where(valid, lse - ll, 0.0)) / count
