"""On-device attention dispatch autotune.

Reference parity: FLAGS_cudnn_exhaustive_search (platform/flags.cc) —
the reference times every cuDNN conv algorithm on the real device and
caches the winner per shape. Here the uncertain window is short-seq
attention (128 <= seq <= 256), where the single-block short kernel, the
streaming flash kernel, and fused XLA attention trade places depending
on batch/heads/dropout: instead of a hard-coded dispatch floor, time
the eligible candidates once per (shape, dtype, causal, dropout) on
the REAL chip — forward + backward, since training is the headline —
and cache the winner for the process.

Runs only on a TPU backend. Dispatch decisions under jit happen at
Python trace time, so the tuner can execute the candidates on concrete
random inputs on the side; timing uses paddle_tpu.utils.timing (host
fetch sync + per-iteration varied inputs — the two axon-tunnel
lessons). Any failure falls back to the static dispatch.
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
from typing import Dict

from ...framework.flags import define_flag, get_flag

define_flag("flash_autotune", True,
            "Time short/streaming/XLA attention on-device once per "
            "shape in the 128-256 seq window and dispatch the winner "
            "(cudnn_exhaustive_search parity). TPU only; "
            "FLAGS_flash_short_seq=True overrides to always-short")

define_flag("sample_autotune", True,
            "Time the fused sampling Pallas kernel against the XLA "
            "path once per (batch, vocab, dtype, top_k) shape and "
            "dispatch the winner (persisted in the same disk cache as "
            "the flash/paged verdicts). TPU only")

define_flag("fused_opt_autotune", True,
            "Time the fused Pallas optimizer update kernel (sgd / "
            "momentum / adam / lamb) against the unfused XLA update "
            "once per (op, n, dtype) flat size and dispatch the winner "
            "(persisted in the same disk cache as the flash/paged "
            "verdicts). TPU only")

define_flag("paged_autotune", True,
            "Time the ragged paged-attention Pallas kernel against the "
            "XLA gather path once per (batch, pages, page_size, heads, "
            "head_dim, dtype) decode shape and dispatch the winner "
            "(persisted in the same disk cache as the flash verdicts). "
            "TPU only")

_cache: Dict[tuple, str] = {}
_ITERS = 8

# Verdicts persist across processes (the reference's cudnn algo cache is
# process-local, but here every re-probe burns scarce tunnel minutes —
# VERDICT r4 weak #5). One JSON file per device kind; dir resolution:
# PADDLE_TPU_AUTOTUNE_CACHE_DIR > PADDLE_COMPILE_CACHE_DIR/autotune
# (tuned configs relaunch alongside the persistent compiled steps;
# disk hits bump the autotune_disk_hits profiler counter) > the backend
# probe cache dir. Write-through on every new verdict.
_disk: Dict[str, str] | None = None
_stats = {"mem_hits": 0, "disk_hits": 0, "timed": 0}


def _cache_dir() -> str:
    p = os.environ.get("PADDLE_TPU_AUTOTUNE_CACHE_DIR")
    if p:
        return p
    # co-locate tuned configs with the persistent compile cache: a
    # relaunched trainer that skips its cold XLA compiles
    # (PADDLE_COMPILE_CACHE_DIR) skips its timing rounds too
    p = os.environ.get("PADDLE_COMPILE_CACHE_DIR")
    if p:
        return os.path.join(p, "autotune")
    from ...framework.bringup import cache_dir

    return cache_dir()


def _disk_path() -> str:
    import jax

    kind = jax.devices()[0].device_kind.replace(" ", "_").replace("/", "_")
    return os.path.join(_cache_dir(), f"autotune_{kind}.json")


def _disk_key(key: tuple) -> str:
    return "|".join(str(p) for p in key)


def _load_disk() -> Dict[str, str]:
    global _disk
    if _disk is None:
        try:
            with open(_disk_path()) as f:
                _disk = {str(k): str(v) for k, v in json.load(f).items()}
        except (OSError, ValueError):
            _disk = {}
    return _disk


def _save_disk() -> None:
    # merge-then-replace: re-read the file so a concurrent process's
    # fresh verdicts survive (lost-update), and os.replace keeps the
    # file itself atomic (torn-write)
    global _disk
    try:
        path = _disk_path()
        try:
            with open(path) as f:
                on_disk = {str(k): str(v) for k, v in json.load(f).items()}
        except (OSError, ValueError):
            on_disk = {}
        merged = {**on_disk, **(_disk or {})}
        _disk = merged
        os.makedirs(_cache_dir(), mode=0o700, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=_cache_dir(), suffix=".tmp")
        with os.fdopen(fd, "w") as f:
            json.dump(merged, f, indent=0, sort_keys=True)
        os.replace(tmp, path)
    except OSError as e:
        sys.stderr.write(f"flash autotune: cache persist failed ({e})\n")


def cached_choices() -> Dict[tuple, str]:
    return dict(_cache)


def stats() -> Dict[str, int]:
    """Hit/miss counters for bench rows: 'timed' is the number of
    on-chip timing rounds this process actually paid for."""
    return dict(_stats)


def reset(disk: bool = False) -> None:
    global _disk
    _cache.clear()
    _stats.update(mem_hits=0, disk_hits=0, timed=0)
    _disk = None
    if disk:
        try:
            os.remove(_disk_path())
        except OSError:
            pass


def best_short_window_impl(b, l, h, d, dtype, causal,
                           dropout_p) -> str | None:
    """'short' | 'stream' | 'xla' for this shape, timed fwd+bwd on the
    device (memoized), or None when no candidate could be timed. Must
    only be called with _short_ok shapes on a TPU backend."""
    key = (b, l, h, d, str(dtype), bool(causal), round(float(dropout_p), 4))
    if key in _cache:
        _stats["mem_hits"] += 1
        return _cache[key]

    import jax
    import jax.numpy as jnp

    disk = _load_disk()
    hit = disk.get(_disk_key(key))
    if hit in ("short", "stream", "xla"):
        _stats["disk_hits"] += 1
        try:
            from ... import profiler

            profiler.bump_counter("autotune_disk_hits")
        except Exception:
            pass  # counter is best-effort; the verdict still serves
        _cache[key] = hit
        return hit

    from ...utils.timing import timeit
    from . import flash_attention as fa

    kq = jax.random.key(0)
    q = jax.random.normal(kq, (b, l, h, d), jnp.float32).astype(dtype)
    seed = jnp.asarray([[17]], jnp.int32)

    def train_like(impl):
        # fwd+bwd through the impl's custom vjp: training is what the
        # headline measures, and fwd-only and train prefer different
        # kernels (the r3 block sweeps showed exactly that)
        def loss(a):
            return jnp.sum(impl(a))

        return jax.jit(jax.grad(loss))

    candidates = {}
    if dropout_p > 0.0:
        candidates["short"] = train_like(
            lambda a: fa._flash_attention_core_short(
                a, a, a, seed, causal, dropout_p))
        if fa._pallas_ok(q, q, causal):
            candidates["stream"] = train_like(
                lambda a: fa._flash_attention_core_dropout(
                    a, a, a, seed, causal, *fa._pick_blocks(
                        l, l, 512, 512), dropout_p))
        candidates["xla"] = train_like(
            lambda a: fa._xla_attention(a, a, a, None, dropout_p, causal,
                                        jax.random.key(3)))
    else:
        candidates["short"] = train_like(
            lambda a: fa._flash_attention_core_short(
                a, a, a, None, causal, 0.0))
        if fa._pallas_ok(q, q, causal):
            candidates["stream"] = train_like(
                lambda a: fa._flash_attention_core(
                    a, a, a, causal, *fa._pick_blocks(l, l, 512, 512)))
        candidates["xla"] = train_like(
            lambda a: fa._xla_attention(a, a, a, None, 0.0, causal, None))

    times = {}
    for name, fn in candidates.items():
        try:
            times[name] = timeit(fn, q, iters=_ITERS)
        except Exception as e:  # candidate fails to compile/run: skip it
            sys.stderr.write(f"flash autotune: {name} failed "
                             f"({type(e).__name__}: {e})\n")
    if not times:
        # a transient blip (the tunnel flaps) must not pin a verdict for
        # the whole process: leave uncached so static dispatch decides
        # now and tuning retries on the next fresh dispatch
        sys.stderr.write("flash autotune: all candidates failed; "
                         "keeping static dispatch\n")
        return None
    winner = min(times, key=times.get)
    sys.stderr.write(
        "flash autotune "
        f"(b={b} l={l} h={h} d={d} causal={causal} p={dropout_p}): "
        + " ".join(f"{n}={t:.3f}ms" for n, t in sorted(times.items()))
        + f" -> {winner}\n")
    _stats["timed"] += 1
    _cache[key] = winner
    disk[_disk_key(key)] = winner
    _save_disk()
    return winner


def paged_cache_key(b, pages, page_size, h, d, dtype) -> tuple:
    """The paged-attention verdict key: namespaced alongside the flash
    keys in the ONE memo/disk cache ('paged' leading component — a
    flash (b, l, ...) tuple can never collide with it)."""
    return ("paged", int(b), int(pages), int(page_size), int(h), int(d),
            str(dtype))


def best_paged_impl(b, pages, page_size, h, d, dtype,
                    pool_pages=None) -> str | None:
    """'pallas' | 'xla' for this decode shape, timed on the device over
    a representative random pool (memoized + disk-persisted like the
    flash verdicts), or None when no candidate could be timed. Must
    only be called with _paged_ok shapes on a TPU backend.

    ``pool_pages`` bounds the synthetic pool at the REAL pool's size:
    the tuner runs while the engine's donated pool and params are
    already resident, so allocating b*pages disjoint pages could
    transiently double HBM on a production config — table entries
    alias pages instead, exactly as live tables do. Not part of the
    verdict key (it only shapes the probe allocation)."""
    key = paged_cache_key(b, pages, page_size, h, d, dtype)
    if key in _cache:
        _stats["mem_hits"] += 1
        return _cache[key]

    import jax
    import jax.numpy as jnp

    disk = _load_disk()
    hit = disk.get(_disk_key(key))
    if hit in ("pallas", "xla"):
        _stats["disk_hits"] += 1
        try:
            from ... import profiler

            profiler.bump_counter("autotune_disk_hits")
        except Exception:
            pass  # counter is best-effort; the verdict still serves
        _cache[key] = hit
        return hit

    from ...utils.timing import timeit
    from . import paged_attention as pa

    rng = jax.random.key(1)
    pool = max(b * pages + 1, 2)
    if pool_pages:
        pool = max(2, min(pool, int(pool_pages)))
    k_pages = jax.random.normal(rng, (pool, page_size, h, d),
                                jnp.float32).astype(dtype)
    v_pages = k_pages + 1.0
    q = jax.random.normal(jax.random.key(2), (b, h, d),
                          jnp.float32).astype(dtype)
    # every sequence at the worst-case live length for the table width
    # (the shape being tuned, not a particular traffic mix); entries
    # alias the bounded pool like live page tables alias the real one
    table = (jnp.arange(b * pages, dtype=jnp.int32) % (pool - 1)
             + 1).reshape(b, pages)
    lens = jnp.full((b,), pages * page_size, jnp.int32)

    candidates = {
        "pallas": jax.jit(lambda qq: pa._paged_attention_pallas(
            qq, k_pages, v_pages, table, lens)),
        "xla": jax.jit(lambda qq: pa._xla_paged_attention(
            qq, k_pages, v_pages, table, lens)),
    }
    times = {}
    for name, fn in candidates.items():
        try:
            times[name] = timeit(fn, q, iters=_ITERS)
        except Exception as e:  # candidate fails to compile/run: skip it
            sys.stderr.write(f"paged autotune: {name} failed "
                             f"({type(e).__name__}: {e})\n")
    if not times:
        sys.stderr.write("paged autotune: all candidates failed; "
                         "keeping static dispatch\n")
        return None
    winner = min(times, key=times.get)
    sys.stderr.write(
        f"paged autotune (b={b} pages={pages} S={page_size} h={h} "
        f"d={d}): "
        + " ".join(f"{n}={t:.3f}ms" for n, t in sorted(times.items()))
        + f" -> {winner}\n")
    _stats["timed"] += 1
    _cache[key] = winner
    disk[_disk_key(key)] = winner
    _save_disk()
    return winner


def sample_cache_key(b, v, dtype, top_k) -> tuple:
    """The fused-sampling verdict key, namespaced like the paged keys
    in the ONE memo/disk cache."""
    return ("sample", int(b), int(v), str(dtype), int(top_k))


def best_sample_impl(b, v, dtype, top_k) -> str | None:
    """'pallas' | 'xla' for this sampling shape, timed on the device
    (memoized + disk-persisted like the flash/paged verdicts), or None
    when no candidate could be timed. Must only be called with
    _sample_ok shapes on a TPU backend."""
    key = sample_cache_key(b, v, dtype, top_k)
    if key in _cache:
        _stats["mem_hits"] += 1
        return _cache[key]

    import jax
    import jax.numpy as jnp

    disk = _load_disk()
    hit = disk.get(_disk_key(key))
    if hit in ("pallas", "xla"):
        _stats["disk_hits"] += 1
        try:
            from ... import profiler

            profiler.bump_counter("autotune_disk_hits")
        except Exception:
            pass  # counter is best-effort; the verdict still serves
        _cache[key] = hit
        return hit

    from ...utils.timing import timeit
    from . import sampling as sp

    logits = jax.random.normal(jax.random.key(5), (b, v),
                               jnp.float32).astype(dtype)
    noise = -jnp.log(-jnp.log(jax.random.uniform(
        jax.random.key(6), (b, v), jnp.float32, 1e-6, 1.0 - 1e-6)))
    candidates = {
        "pallas": jax.jit(lambda ll: sp._fused_sample_pallas(
            ll, noise, 1.0, top_k)),
        "xla": jax.jit(lambda ll: sp._xla_sample(
            ll, noise, 1.0, top_k, 1.0)),
    }
    times = {}
    for name, fn in candidates.items():
        try:
            times[name] = timeit(fn, logits, iters=_ITERS)
        except Exception as e:  # candidate fails to compile/run: skip it
            sys.stderr.write(f"sample autotune: {name} failed "
                             f"({type(e).__name__}: {e})\n")
    if not times:
        sys.stderr.write("sample autotune: all candidates failed; "
                         "keeping static dispatch\n")
        return None
    winner = min(times, key=times.get)
    sys.stderr.write(
        f"sample autotune (b={b} v={v} top_k={top_k}): "
        + " ".join(f"{n}={t:.3f}ms" for n, t in sorted(times.items()))
        + f" -> {winner}\n")
    _stats["timed"] += 1
    _cache[key] = winner
    disk[_disk_key(key)] = winner
    _save_disk()
    return winner


def fused_opt_cache_key(op_type, n, dtype) -> tuple:
    """The fused-optimizer verdict key, namespaced like the paged and
    sample keys in the ONE memo/disk cache."""
    return ("fused_opt", str(op_type), int(n), str(dtype))


def best_fused_opt_impl(op_type, n, dtype) -> str | None:
    """'pallas' | 'xla' for this (op, flat size), timed on the device
    (memoized + disk-persisted like the flash/paged verdicts), or None
    when no candidate could be timed. Must only be called with
    fused-eligible sizes on a TPU backend."""
    key = fused_opt_cache_key(op_type, n, dtype)
    if key in _cache:
        _stats["mem_hits"] += 1
        return _cache[key]

    import jax
    import jax.numpy as jnp

    disk = _load_disk()
    hit = disk.get(_disk_key(key))
    if hit in ("pallas", "xla"):
        _stats["disk_hits"] += 1
        try:
            from ... import profiler

            profiler.bump_counter("autotune_disk_hits")
        except Exception:
            pass  # counter is best-effort; the verdict still serves
        _cache[key] = hit
        return hit

    from ...utils.timing import timeit
    from . import fused_optimizer as fo

    g = jax.random.normal(jax.random.key(7), (n,), jnp.float32)

    def _ins(gg):
        ins = {"Param": [gg * 0.5], "Grad": [gg],
               "LearningRate": [jnp.asarray(1e-3, jnp.float32)]}
        if op_type == "momentum":
            ins["Velocity"] = [gg * 0.1]
        elif op_type in ("adam", "lamb"):
            ins["Moment1"] = [gg * 0.1]
            ins["Moment2"] = [gg * gg * 0.1]
            ins["Beta1Pow"] = [jnp.asarray([0.9], jnp.float32)]
            ins["Beta2Pow"] = [jnp.asarray([0.999], jnp.float32)]
        return ins

    candidates = {
        "pallas": jax.jit(lambda gg: fo._pallas_update(
            op_type, _ins(gg), {}, False)["ParamOut"][0]),
        "xla": jax.jit(lambda gg: fo._XLA[op_type](
            _ins(gg), {})["ParamOut"][0]),
    }
    times = {}
    for name, fn in candidates.items():
        try:
            times[name] = timeit(fn, g, iters=_ITERS)
        except Exception as e:  # candidate fails to compile/run: skip it
            sys.stderr.write(f"fused_opt autotune: {name} failed "
                             f"({type(e).__name__}: {e})\n")
    if not times:
        sys.stderr.write("fused_opt autotune: all candidates failed; "
                         "keeping static dispatch\n")
        return None
    winner = min(times, key=times.get)
    sys.stderr.write(
        f"fused_opt autotune (op={op_type} n={n}): "
        + " ".join(f"{nm}={t:.3f}ms" for nm, t in sorted(times.items()))
        + f" -> {winner}\n")
    _stats["timed"] += 1
    _cache[key] = winner
    disk[_disk_key(key)] = winner
    _save_disk()
    return winner


def fused_opt_choice(op_type, n, dtype) -> str | None:
    """The fused-optimizer dispatch entry: the tuned impl name, or None
    when autotuning does not apply (not TPU / flag off) — None keeps
    the static dispatch (kernel-first with XLA fallback)."""
    from ...framework.bringup import TPU_PLATFORMS

    if not get_flag("fused_opt_autotune"):
        return None
    import jax

    if jax.default_backend() not in TPU_PLATFORMS:
        return None
    try:
        return best_fused_opt_impl(op_type, n, dtype)
    except Exception as e:
        sys.stderr.write(f"fused_opt autotune failed, static dispatch "
                         f"keeps ({type(e).__name__}: {e})\n")
        return None


def fused_sample_choice(logits, top_k) -> str | None:
    """The sampling dispatch entry: the tuned impl name, or None when
    autotuning does not apply (not TPU / flag off) — None keeps the
    static dispatch (kernel-first with XLA fallback)."""
    from ...framework.bringup import TPU_PLATFORMS

    if not get_flag("sample_autotune"):
        return None
    import jax

    if jax.default_backend() not in TPU_PLATFORMS:
        return None
    b, v = logits.shape
    try:
        return best_sample_impl(b, v, logits.dtype, top_k)
    except Exception as e:
        sys.stderr.write(f"sample autotune failed, static dispatch "
                         f"keeps ({type(e).__name__}: {e})\n")
        return None


def paged_attention_choice(q, k_pages, page_table) -> str | None:
    """The paged dispatch entry: the tuned impl name, or None when
    autotuning does not apply (not TPU / flag off) — None keeps the
    static dispatch (kernel-first with XLA fallback)."""
    from ...framework.bringup import TPU_PLATFORMS

    if not get_flag("paged_autotune"):
        return None
    import jax

    if jax.default_backend() not in TPU_PLATFORMS:
        return None
    b, h, d = q.shape
    try:
        return best_paged_impl(b, page_table.shape[1], k_pages.shape[1],
                               h, d, q.dtype,
                               pool_pages=k_pages.shape[0])
    except Exception as e:
        sys.stderr.write(f"paged autotune failed, static dispatch keeps "
                         f"({type(e).__name__}: {e})\n")
        return None


def short_window_choice(q, k, causal, dropout_p) -> str | None:
    """The dispatch entry: returns the tuned impl name, or None when
    autotuning does not apply (not TPU / flag off / outside window)."""
    from ...framework.bringup import TPU_PLATFORMS
    from . import flash_attention as fa

    if not get_flag("flash_autotune"):
        return None
    if not fa._short_ok(q, k, causal):
        return None
    import jax

    if jax.default_backend() not in TPU_PLATFORMS:
        return None
    b, l, h, d = q.shape
    try:
        return best_short_window_impl(b, l, h, d, q.dtype, causal,
                                      dropout_p)
    except Exception as e:
        sys.stderr.write(f"flash autotune failed, static dispatch keeps "
                         f"({type(e).__name__}: {e})\n")
        return None
