"""Fused embedding lookup + sequence pool Pallas kernel.

Reference: /root/reference/paddle/fluid/operators/fused/
fused_embedding_seq_pool_op.cc (lookup_table + sequence_pool fused so the
(B, S, D) gathered tensor never exists). The XLA lowering of
gather-then-reduce materializes that intermediate in HBM; for CTR-style
models (tens of sparse fields, large D) the fused kernel keeps each
pooled row accumulating in VMEM and streams exactly one table row per
grid step via scalar-prefetched indices — HBM traffic drops from
O(B*S*D) write + read to O(B*S*D) read + O(B*D) write.

Forward runs the Pallas kernel on TPU (XLA fallback elsewhere); backward
is a plain XLA scatter-add (scatter is not an XLA weak spot, and the
(B, S, D) intermediate does not appear in the gradient either).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def _xla_bag(table, ids, combiner):
    """Reference path: masked gather + pooled reduce (what XLA fuses)."""
    valid = (ids >= 0)
    w = valid.astype(table.dtype)
    emb = table[jnp.maximum(ids, 0)] * w[..., None]     # (B, S, D)
    out = jnp.sum(emb, axis=1)
    if combiner == "sum":
        return out
    cnt = jnp.maximum(jnp.sum(w, axis=1, keepdims=True), 1.0)
    if combiner == "mean":
        return out / cnt
    if combiner == "sqrtn":
        return out / jnp.sqrt(cnt)
    raise ValueError(f"unknown combiner {combiner!r}")


def _bag_kernel(ids_ref, table_blk_ref, out_ref, cnt_ref, *, seq, combiner):
    """Blocks are 8 rows tall — the TPU sublane tile modulus; (1, d)
    row blocks do not lower on real hardware (Mosaic requires the
    second-to-last block dim % 8). The streamed table block is the
    8-row group containing the wanted row; the output block holds 8
    bags, revisited across the 8*seq grid steps that share it."""
    bi = pl.program_id(0)
    s = pl.program_id(1)
    off = bi % 8

    @pl.when(jnp.logical_and(s == 0, off == 0))
    def _init_out():
        out_ref[...] = jnp.zeros_like(out_ref)

    @pl.when(s == 0)
    def _init_cnt():
        cnt_ref[off] = 0.0

    idx = ids_ref[bi * seq + s]
    valid = (idx >= 0).astype(jnp.float32)
    # accumulate in f32 regardless of table dtype: bf16 += over long
    # bags loses low bits and diverges from the XLA fallback (ADVICE r2)
    row = table_blk_ref[pl.dslice(jnp.maximum(idx, 0) % 8, 1),
                        :].astype(jnp.float32)
    out_ref[pl.dslice(off, 1), :] += valid * row
    cnt_ref[off] += valid

    if combiner in ("mean", "sqrtn"):
        @pl.when(s == seq - 1)
        def _normalize():
            c = jnp.maximum(cnt_ref[off], 1.0)
            denom = c if combiner == "mean" else jnp.sqrt(c)
            out_ref[pl.dslice(off, 1), :] = \
                out_ref[pl.dslice(off, 1), :] / denom


try:  # pallas imports kept lazy-tolerant (cpu wheels without pallas tpu)
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _PALLAS = True
except Exception:  # pragma: no cover
    _PALLAS = False


def _bag_pallas(table, ids, combiner):
    b, s = ids.shape
    v, d = table.shape
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, s),
        in_specs=[
            # the 8-row table group containing the wanted row
            pl.BlockSpec(
                (8, d), lambda bi, si, idv: (jnp.maximum(
                    idv[bi * s + si], 0) // 8, 0)),
        ],
        # 8 bags per output block, shared by 8 consecutive bi
        out_specs=pl.BlockSpec((8, d), lambda bi, si, idv: (bi // 8, 0)),
        scratch_shapes=[pltpu.SMEM((8,), jnp.float32)],
    )
    kernel = functools.partial(_bag_kernel, seq=s, combiner=combiner)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        # f32 accumulator output; cast back to the table dtype at the end
        out_shape=jax.ShapeDtypeStruct((b, d), jnp.float32),
    )(ids.reshape(-1).astype(jnp.int32), table)
    return out.astype(table.dtype)


def _eligible(table, ids):
    from ...framework.bringup import pallas_enabled

    if not _PALLAS or not pallas_enabled():
        return False
    v, d = table.shape
    b = ids.shape[0]
    # lane-aligned embedding dim; tiny bags fuse fine in XLA; the 8-row
    # block layout needs vocab and batch on the sublane modulus
    return (d % 128 == 0 and ids.shape[1] >= 8
            and v % 8 == 0 and b % 8 == 0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _bag_core(table, ids, combiner):
    from .counters import bump

    if _eligible(table, ids):
        try:
            out = _bag_pallas(table, ids, combiner)
            bump("fused_embedding", "pallas")
            return out
        except Exception as e:
            # counted + optionally logged: this exact silent except hid
            # a Mosaic tile-rule bug for a full round
            bump("fused_embedding", "xla",
                 f"kernel error {type(e).__name__}: {e}")
    else:
        bump("fused_embedding", "xla",
             f"ineligible (table {tuple(table.shape)}, ids "
             f"{tuple(ids.shape)}: need d%128==0, seq>=8, vocab%8==0, "
             "batch%8==0, pallas enabled)")
    return _xla_bag(table, ids, combiner)


def _bag_fwd(table, ids, combiner):
    out = _bag_core(table, ids, combiner)
    valid = (ids >= 0)
    cnt = jnp.maximum(jnp.sum(valid.astype(table.dtype), axis=1), 1.0)
    # table rides along for its shape/dtype only (same buffer, no copy)
    return out, (ids, cnt, table)


def _bag_bwd(combiner, res, g):
    ids, cnt, table = res
    tshape, tdtype = table.shape, table.dtype
    if combiner == "mean":
        g = g / cnt[:, None]
    elif combiner == "sqrtn":
        g = g / jnp.sqrt(cnt)[:, None]
    valid = (ids >= 0)
    safe = jnp.where(valid, ids, 0)
    rows = jnp.broadcast_to(g[:, None, :], ids.shape + (g.shape[-1],))
    rows = rows * valid[..., None].astype(g.dtype)
    d_table = jnp.zeros(tshape, tdtype).at[safe.reshape(-1)].add(
        rows.reshape(-1, g.shape[-1]))
    return d_table, None


_bag_core.defvjp(_bag_fwd, _bag_bwd)


def fused_embedding_seq_pool(table, ids, combiner="sum", padding_idx=None,
                             name=None):
    """Pooled bag-of-ids embedding (fused_embedding_seq_pool_op.cc).

    table: (V, D) float; ids: (B, S) int — entries equal to
    ``padding_idx`` (or negative) contribute nothing. combiner:
    sum | mean | sqrtn (mean/sqrtn normalize by the VALID id count).
    Returns (B, D).
    """
    from ...framework.tensor import Tensor

    if combiner not in ("sum", "mean", "sqrtn"):
        # validate up front: the Pallas kernel would otherwise silently
        # sum-pool while the XLA fallback raises (platform-dependent bug)
        raise ValueError(f"unknown combiner {combiner!r}")
    t = table.value if isinstance(table, Tensor) else jnp.asarray(table)
    i = ids.value if isinstance(ids, Tensor) else jnp.asarray(ids)
    if padding_idx is not None and padding_idx >= 0:
        i = jnp.where(i == padding_idx, -1, i)
    out = _bag_core(t, i, combiner)
    return out
