"""Fused token sampling for the decode engine (Pallas kernel + XLA
fallback): temperature scale + top-k mask + Gumbel-max draw in one
VMEM pass over the logits row.

Determinism contract: the Gumbel noise is generated OUTSIDE (the
engine derives it from a seeded host RNG per tick) and passed in, so
the kernel and the XLA fallback are the SAME function of (logits,
noise) — interpret-mode parity is bitwise, and a seeded run replays
token for token. Sampling itself is the Gumbel-max trick:
``argmax(logits/T + g)`` draws from ``softmax(logits/T)``; masking
(top-k / top-p) before the argmax draws from the truncated,
renormalized distribution.

Dispatch follows the established kernel pattern (flash_attention.py /
paged_attention.py): an eligibility gate (``_sample_ok`` — top-p
routes to the XLA path, the sort has no good single-pass kernel
shape), per-decision counters (``fused_sample.pallas`` / ``.xla`` with
a reason), an autotuned choice persisted in the PR 10 disk cache
(autotune.py), and ``PADDLE_FUSED_SAMPLING=0`` as the escape leg that
pins the XLA path.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

_NEG_INF = -1e30
_F32 = jnp.float32

__all__ = ["fused_sample"]

#: static top-k ceiling for the kernel: the threshold is found by
#: top_k unrolled max+mask rounds, so large k would bloat the kernel
_KERNEL_TOPK_MAX = 8


# ---------------------------------------------------------------------------
# XLA fallback — the reference path (and the only one for top-p)
# ---------------------------------------------------------------------------
def _xla_sample(logits, noise, temperature, top_k, top_p):
    x = logits.astype(_F32) / temperature
    V = x.shape[-1]
    if top_k and top_k < V:
        kth = jax.lax.top_k(x, int(top_k))[0][..., -1]
        x = jnp.where(x < kth[..., None], _NEG_INF, x)
    if top_p < 1.0:
        srt = jnp.sort(x, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(srt, axis=-1)
        csum = jnp.cumsum(probs, axis=-1)
        # smallest set whose mass reaches top_p: keep a token while the
        # mass BEFORE it is still short (the head token always stays)
        keep = (csum - probs) < top_p
        thresh = jnp.min(jnp.where(keep, srt, jnp.inf), axis=-1)
        x = jnp.where(x < thresh[..., None], _NEG_INF, x)
    return jnp.argmax(x + noise.astype(_F32), axis=-1).astype(jnp.int32)


# ---------------------------------------------------------------------------
# Pallas kernel: grid (B,), one logits row per step, fused
# scale + top-k threshold + Gumbel add + argmax
# ---------------------------------------------------------------------------
def _sample_kernel(l_ref, n_ref, o_ref, *, temperature, top_k):
    x = l_ref[...].astype(_F32) / temperature          # (1, V)
    if top_k:
        # k-th max by top_k unrolled max+mask rounds (k is static and
        # small — the _sample_ok ceiling)
        work = x
        thr = jnp.max(work, axis=1, keepdims=True)
        for _ in range(int(top_k) - 1):
            work = jnp.where(work >= thr, _NEG_INF, work)
            thr = jnp.max(work, axis=1, keepdims=True)
        x = jnp.where(x < thr, _NEG_INF, x)
    y = x + n_ref[...].astype(_F32)
    m = jnp.max(y, axis=1, keepdims=True)
    # first-max index (argmax tie rule) via 2D iota — 1D iota fails on
    # TPU (pallas guide)
    idx = jax.lax.broadcasted_iota(jnp.int32, y.shape, 1)
    cand = jnp.where(y >= m, idx, jnp.int32(2147483647))
    o_ref[0, 0] = jnp.min(cand)


def _fused_sample_pallas(logits, noise, temperature, top_k):
    from jax.experimental import pallas as pl

    B, V = logits.shape
    out = pl.pallas_call(
        functools.partial(_sample_kernel,
                          temperature=float(temperature),
                          top_k=int(top_k)),
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, V), lambda b: (b, 0)),
            pl.BlockSpec((1, V), lambda b: (b, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1), lambda b: (b, 0)),
        out_shape=jax.ShapeDtypeStruct((B, 1), jnp.int32),
    )(logits, noise)
    return out[:, 0]


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------
def _sample_ok(logits, top_k, top_p) -> bool:
    from ...framework.bringup import pallas_enabled

    if not pallas_enabled():
        return False
    V = logits.shape[-1]
    # top-p needs the sorted-cumsum pass — XLA's sort is the right tool;
    # the lane dim must tile (V % 128) and fit VMEM comfortably
    return (float(top_p) >= 1.0 and 0 <= int(top_k) <= _KERNEL_TOPK_MAX
            and V % 128 == 0 and V <= 16384)


def _escape_pinned() -> bool:
    """PADDLE_FUSED_SAMPLING=0 pins the XLA path — the bitwise escape
    leg (same shape as PADDLE_PAGED_ATTENTION=0)."""
    return os.environ.get("PADDLE_FUSED_SAMPLING", "").strip() == "0"


def fused_sample(logits, noise, temperature, top_k: int = 0,
                 top_p: float = 1.0):
    """Draw one token per row from ``softmax(logits/temperature)``
    truncated by top-k/top-p, using caller-supplied Gumbel ``noise``
    (same shape as ``logits``). ``temperature <= 0`` short-circuits to
    greedy argmax (noise ignored) — the spec-decode-compatible leg.
    Returns int32 token ids (B,)."""
    from .counters import bump

    if float(temperature) <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if _escape_pinned():
        bump("fused_sample", "xla", "PADDLE_FUSED_SAMPLING=0 pin")
        return _xla_sample(logits, noise, temperature, top_k, top_p)
    if _sample_ok(logits, top_k, top_p):
        from .autotune import fused_sample_choice

        choice = fused_sample_choice(logits, top_k)
        if choice == "xla":
            bump("fused_sample", "xla", "autotuned: xla wins this shape")
            return _xla_sample(logits, noise, temperature, top_k, top_p)
        try:
            out = _fused_sample_pallas(logits, noise, temperature, top_k)
            bump("fused_sample", "pallas")
            return out
        except Exception as e:
            bump("fused_sample", "xla",
                 f"kernel error {type(e).__name__}: {e}")
    else:
        bump("fused_sample", "xla",
             f"dispatch ineligible (logits {tuple(logits.shape)}, "
             f"top_k={top_k}, top_p={top_p}; gate in _sample_ok)")
    return _xla_sample(logits, noise, temperature, top_k, top_p)
