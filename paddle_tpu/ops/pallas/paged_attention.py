"""Ragged paged attention for TPU decode steps (Pallas kernel + XLA
gather fallback).

The LLM decode data path (PAPERS.md "Ragged Paged Attention: A
High-Performance and Flexible LLM Inference Kernel for TPU"): each
sequence's KV history lives in fixed-size PAGES of a device-resident
pool, and a decode step attends one query token per sequence against
only that sequence's LIVE pages, addressed through a per-sequence page
table — no length padding, so a batch mixing a 40-token and a
4000-token context does 40+4000 tokens of work, not 2×4000.

Layout:

- ``q``          (B, H, D)        one query token per sequence
- ``k_pages``    (P, S, H, D)     the pool: P pages of S tokens each
- ``v_pages``    (P, S, H, D)
- ``page_table`` (B, T) int32     page ids per sequence, -1 = unused
- ``seq_lens``   (B,) int32       live tokens per sequence (ragged)

Kernel shape: grid (B, T) with the page table SCALAR-PREFETCHED
(``PrefetchScalarGridSpec``) so each grid step's KV block is DMA'd
straight from the page the table names — the gather never materializes
a contiguous copy of the context. Online-softmax carries (m, l, acc)
persist in VMEM scratch across a sequence's page steps; pages past
``ceil(seq_len/S)`` are skipped (``pl.when``), which is where the
ragged win comes from.

Dispatch follows the established kernel pattern (flash_attention.py):
an eligibility gate (``_paged_ok``), per-decision counters
(``paged_attention.pallas`` / ``.xla`` with a reason), an autotuned
choice persisted in the PR 10 disk cache (autotune.py), and
``PADDLE_PAGED_ATTENTION=0`` as the bitwise escape leg that pins the
XLA gather path.
"""
from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp

_NEG_INF = -1e30
_F32 = jnp.float32

__all__ = ["paged_attention", "paged_write", "paged_prefill_write",
           "paged_write_quant", "paged_prefill_write_quant"]


# ---------------------------------------------------------------------------
# XLA gather fallback — the reference data path the kernel is parity-
# gated against (and the only path off-TPU / for ineligible shapes)
# ---------------------------------------------------------------------------
def _xla_paged_attention(q, k_pages, v_pages, page_table, seq_lens):
    """Gather each sequence's pages, mask the ragged tail, attend."""
    B, H, D = q.shape
    S = k_pages.shape[1]
    T = page_table.shape[1]
    safe = jnp.maximum(page_table, 0)                      # (B, T)
    k = k_pages[safe].reshape(B, T * S, H, D)
    v = v_pages[safe].reshape(B, T * S, H, D)
    s = jnp.einsum("bhd,bkhd->bhk", q.astype(_F32), k.astype(_F32),
                   preferred_element_type=_F32) / math.sqrt(D)
    pos = jnp.arange(T * S, dtype=jnp.int32)
    s = jnp.where(pos[None, None, :] < seq_lens[:, None, None],
                  s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhk,bkhd->bhd", p, v.astype(_F32))
    return out.astype(q.dtype)


def _xla_paged_attention_quant(q, k_pages, v_pages, k_scales, v_scales,
                               page_table, seq_lens):
    """Quantized-pool twin of :func:`_xla_paged_attention`: the pool
    holds int8 rows with one f32 scale per token row (codec.py's
    ``jnp_encode_kv_rows`` layout, block = H*D); dequant happens inside
    the gather, so nothing f32-sized ever persists in HBM."""
    B, H, D = q.shape
    S = k_pages.shape[1]
    T = page_table.shape[1]
    safe = jnp.maximum(page_table, 0)                      # (B, T)
    ks = k_scales[safe].reshape(B, T * S)                  # (B, K)
    vs = v_scales[safe].reshape(B, T * S)
    k = k_pages[safe].reshape(B, T * S, H, D).astype(_F32)
    v = v_pages[safe].reshape(B, T * S, H, D).astype(_F32)
    k = k * ks[..., None, None]
    v = v * vs[..., None, None]
    s = jnp.einsum("bhd,bkhd->bhk", q.astype(_F32), k,
                   preferred_element_type=_F32) / math.sqrt(D)
    pos = jnp.arange(T * S, dtype=jnp.int32)
    s = jnp.where(pos[None, None, :] < seq_lens[:, None, None],
                  s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhk,bkhd->bhd", p, v)
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas kernel: grid (B, T), page table scalar-prefetched, online
# softmax carried in VMEM scratch across a sequence's page steps
# ---------------------------------------------------------------------------
def _paged_attn_kernel(pt_ref, lens_ref, q_ref, k_ref, v_ref, o_ref,
                       m_sc, l_sc, acc_sc, *, page_size, sm_scale):
    from jax.experimental import pallas as pl

    b = pl.program_id(0)
    j = pl.program_id(1)
    num_pages = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        m_sc[...] = jnp.full_like(m_sc[...], _NEG_INF)
        l_sc[...] = jnp.zeros_like(l_sc[...])
        acc_sc[...] = jnp.zeros_like(acc_sc[...])

    length = lens_ref[b]

    @pl.when(j * page_size < length)
    def _page():
        q = q_ref[...].astype(_F32) * sm_scale          # (H, D)
        k = jnp.swapaxes(k_ref[...].astype(_F32), 0, 1)  # (H, S, D)
        v = jnp.swapaxes(v_ref[...].astype(_F32), 0, 1)  # (H, S, D)
        H, S = q.shape[0], k.shape[1]
        # per-head batched q·K^T: (H, D) x (H, S, D) -> (H, S)
        s = jax.lax.dot_general(q, k, (((1,), (2,)), ((0,), (0,))),
                                preferred_element_type=_F32)
        pos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (H, S), 1)
        s = jnp.where(pos < length, s, _NEG_INF)
        m_prev = m_sc[:, 0]
        l_prev = l_sc[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_sc[:, 0] = alpha * l_prev + jnp.sum(p, axis=1)
        m_sc[:, 0] = m_new
        # (H, S) x (H, S, D) -> (H, D)
        pv = jax.lax.dot_general(p, v, (((1,), (1,)), ((0,), (0,))),
                                 preferred_element_type=_F32)
        acc_sc[...] = acc_sc[...] * alpha[:, None] + pv

    @pl.when(j == num_pages - 1)
    def _flush():
        norm = jnp.maximum(l_sc[:, 0], 1e-30)[:, None]
        o_ref[...] = (acc_sc[...] / norm).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=())
def _paged_attention_pallas(q, k_pages, v_pages, page_table, seq_lens):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, D = q.shape
    S = k_pages.shape[1]
    T = page_table.shape[1]
    sm_scale = 1.0 / math.sqrt(D)
    # dead/unused table entries route the DMA at a real page (0); the
    # pl.when page gate skips their compute and the ragged mask keeps
    # their positions out of the softmax either way
    safe_table = jnp.maximum(page_table, 0).astype(jnp.int32)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,       # page_table, seq_lens
        grid=(B, T),
        in_specs=[
            pl.BlockSpec((None, H, D), lambda b, j, pt, lens: (b, 0, 0)),
            pl.BlockSpec((None, S, H, D),
                         lambda b, j, pt, lens: (pt[b, j], 0, 0, 0)),
            pl.BlockSpec((None, S, H, D),
                         lambda b, j, pt, lens: (pt[b, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, H, D),
                               lambda b, j, pt, lens: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, 1), _F32),       # running max m
            pltpu.VMEM((H, 1), _F32),       # running normalizer l
            pltpu.VMEM((H, D), _F32),       # value accumulator
        ],
    )
    return pl.pallas_call(
        functools.partial(_paged_attn_kernel, page_size=S,
                          sm_scale=sm_scale),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, D), q.dtype),
    )(safe_table, seq_lens.astype(jnp.int32), q, k_pages, v_pages)


def _paged_attn_kernel_quant(pt_ref, lens_ref, q_ref, k_ref, v_ref,
                             ks_ref, vs_ref, o_ref, m_sc, l_sc, acc_sc,
                             *, page_size, sm_scale):
    """Quantized twin of :func:`_paged_attn_kernel`: the page DMA
    brings int8 rows + their per-row f32 scales into VMEM and the
    dequant (one multiply per row) happens right there — the f32 view
    of a page exists only transiently in registers/VMEM, which is the
    whole ~4x pool-headroom win."""
    from jax.experimental import pallas as pl

    b = pl.program_id(0)
    j = pl.program_id(1)
    num_pages = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        m_sc[...] = jnp.full_like(m_sc[...], _NEG_INF)
        l_sc[...] = jnp.zeros_like(l_sc[...])
        acc_sc[...] = jnp.zeros_like(acc_sc[...])

    length = lens_ref[b]

    @pl.when(j * page_size < length)
    def _page():
        q = q_ref[...].astype(_F32) * sm_scale          # (H, D)
        kq = k_ref[...].astype(_F32) * ks_ref[...][:, None, None]
        vq = v_ref[...].astype(_F32) * vs_ref[...][:, None, None]
        k = jnp.swapaxes(kq, 0, 1)                      # (H, S, D)
        v = jnp.swapaxes(vq, 0, 1)                      # (H, S, D)
        H, S = q.shape[0], k.shape[1]
        s = jax.lax.dot_general(q, k, (((1,), (2,)), ((0,), (0,))),
                                preferred_element_type=_F32)
        pos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (H, S), 1)
        s = jnp.where(pos < length, s, _NEG_INF)
        m_prev = m_sc[:, 0]
        l_prev = l_sc[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_sc[:, 0] = alpha * l_prev + jnp.sum(p, axis=1)
        m_sc[:, 0] = m_new
        pv = jax.lax.dot_general(p, v, (((1,), (1,)), ((0,), (0,))),
                                 preferred_element_type=_F32)
        acc_sc[...] = acc_sc[...] * alpha[:, None] + pv

    @pl.when(j == num_pages - 1)
    def _flush():
        norm = jnp.maximum(l_sc[:, 0], 1e-30)[:, None]
        o_ref[...] = (acc_sc[...] / norm).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=())
def _paged_attention_pallas_quant(q, k_pages, v_pages, k_scales,
                                  v_scales, page_table, seq_lens):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, D = q.shape
    S = k_pages.shape[1]
    T = page_table.shape[1]
    sm_scale = 1.0 / math.sqrt(D)
    safe_table = jnp.maximum(page_table, 0).astype(jnp.int32)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,       # page_table, seq_lens
        grid=(B, T),
        in_specs=[
            pl.BlockSpec((None, H, D), lambda b, j, pt, lens: (b, 0, 0)),
            pl.BlockSpec((None, S, H, D),
                         lambda b, j, pt, lens: (pt[b, j], 0, 0, 0)),
            pl.BlockSpec((None, S, H, D),
                         lambda b, j, pt, lens: (pt[b, j], 0, 0, 0)),
            pl.BlockSpec((None, S), lambda b, j, pt, lens: (pt[b, j], 0)),
            pl.BlockSpec((None, S), lambda b, j, pt, lens: (pt[b, j], 0)),
        ],
        out_specs=pl.BlockSpec((None, H, D),
                               lambda b, j, pt, lens: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, 1), _F32),
            pltpu.VMEM((H, 1), _F32),
            pltpu.VMEM((H, D), _F32),
        ],
    )
    return pl.pallas_call(
        functools.partial(_paged_attn_kernel_quant, page_size=S,
                          sm_scale=sm_scale),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, D), q.dtype),
    )(safe_table, seq_lens.astype(jnp.int32), q, k_pages, v_pages,
      k_scales, v_scales)


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------
def _paged_ok(q, k_pages) -> bool:
    from ...framework.bringup import pallas_enabled

    if not pallas_enabled():
        return False
    B, H, D = q.shape
    S = k_pages.shape[1]
    # S % 128: the score tile's lane dim is the page; D % 64 / <= 256
    # mirrors the flash kernel's head-dim contract; the H*S + H*D
    # scratch stays far inside VMEM at these ceilings
    return (S % 128 == 0 and D % 64 == 0 and D <= 256 and
            H <= 128 and S <= 1024)


def _escape_pinned() -> bool:
    """PADDLE_PAGED_ATTENTION=0 pins the XLA gather path — the bitwise
    escape leg (same shape as PADDLE_IR_PASSES=0 for the pass
    pipeline)."""
    return os.environ.get("PADDLE_PAGED_ATTENTION", "").strip() == "0"


def paged_attention(q, k_pages, v_pages, page_table, seq_lens,
                    k_scales=None, v_scales=None):
    """Decode-step attention over the paged KV pool: best path for the
    backend (Pallas when eligible — autotune-arbitrated in the window
    where it competes with XLA — else the XLA gather fallback). One
    counter bump per dispatch decision (trace time under jit).

    When ``k_scales``/``v_scales`` (P, S) are given the pool is int8
    (``kv_codec="int8"``): both paths dequant per token row inside the
    gather/page-DMA; the quant leg keeps the same escape env and
    counters but skips the f32 autotune verdict (different memory
    traffic, not comparable)."""
    from .counters import bump

    quant = k_scales is not None
    if _escape_pinned():
        bump("paged_attention", "xla", "PADDLE_PAGED_ATTENTION=0 pin")
        if quant:
            return _xla_paged_attention_quant(q, k_pages, v_pages,
                                              k_scales, v_scales,
                                              page_table, seq_lens)
        return _xla_paged_attention(q, k_pages, v_pages, page_table,
                                    seq_lens)
    if quant:
        if _paged_ok(q, k_pages):
            try:
                out = _paged_attention_pallas_quant(
                    q, k_pages, v_pages, k_scales, v_scales,
                    page_table, seq_lens)
                bump("paged_attention", "pallas")
                return out
            except Exception as e:
                bump("paged_attention", "xla",
                     f"kernel error {type(e).__name__}: {e}")
        else:
            bump("paged_attention", "xla",
                 f"dispatch ineligible (q {tuple(q.shape)}, page "
                 f"{k_pages.shape[1]}; gate in _paged_ok)")
        return _xla_paged_attention_quant(q, k_pages, v_pages, k_scales,
                                          v_scales, page_table, seq_lens)
    if _paged_ok(q, k_pages):
        from .autotune import paged_attention_choice

        choice = paged_attention_choice(q, k_pages, page_table)
        if choice == "xla":
            bump("paged_attention", "xla", "autotuned: xla wins this shape")
            return _xla_paged_attention(q, k_pages, v_pages, page_table,
                                        seq_lens)
        try:
            out = _paged_attention_pallas(q, k_pages, v_pages,
                                          page_table, seq_lens)
            bump("paged_attention", "pallas")
            return out
        except Exception as e:
            bump("paged_attention", "xla",
                 f"kernel error {type(e).__name__}: {e}")
    else:
        bump("paged_attention", "xla",
             f"dispatch ineligible (q {tuple(q.shape)}, page "
             f"{k_pages.shape[1]}; gate in _paged_ok)")
    return _xla_paged_attention(q, k_pages, v_pages, page_table, seq_lens)


# ---------------------------------------------------------------------------
# page writes: decode-step single-token scatter + prefill bulk scatter
# ---------------------------------------------------------------------------
def paged_write(k_pages, v_pages, page_table, positions, new_k, new_v,
                active=None):
    """Scatter ONE new token's K/V per sequence into its page slot.

    ``positions`` (B,) is the absolute write position; the owning page
    is ``page_table[b, positions[b] // S]``. Inactive batch slots (and
    unused -1 table entries) are routed at the reserved trash page 0,
    which the pool manager never allocates — their writes land
    harmlessly where no live page table points."""
    S = k_pages.shape[1]
    pidx = jnp.take_along_axis(page_table,
                               (positions // S)[:, None], axis=1)[:, 0]
    pidx = jnp.maximum(pidx, 0)
    if active is not None:
        pidx = jnp.where(active, pidx, 0)
    off = positions % S
    k_pages = k_pages.at[pidx, off].set(new_k.astype(k_pages.dtype))
    v_pages = v_pages.at[pidx, off].set(new_v.astype(v_pages.dtype))
    return k_pages, v_pages


def paged_prefill_write(k_pages, v_pages, page_ids, new_k, new_v):
    """Scatter one prefilled prompt's K/V into its allocated pages.

    ``page_ids`` (n,) names the pages; ``new_k``/``new_v`` are
    (n * S, H, D) — the prompt padded up to a whole number of pages
    (pad positions are dead: seq_lens masks them at attention time)."""
    S = k_pages.shape[1]
    n = page_ids.shape[0]
    H, D = new_k.shape[-2], new_k.shape[-1]
    k_pages = k_pages.at[page_ids].set(
        new_k.reshape(n, S, H, D).astype(k_pages.dtype))
    v_pages = v_pages.at[page_ids].set(
        new_v.reshape(n, S, H, D).astype(v_pages.dtype))
    return k_pages, v_pages


def paged_write_quant(k_pages, v_pages, k_scales, v_scales, page_table,
                      positions, new_k, new_v, active=None):
    """int8-pool twin of :func:`paged_write`: each token row is
    encoded (codec.py ``jnp_encode_kv_rows``, one scale per row) and
    both the int8 payload and the f32 scale land in the slot the page
    table names. Trash-page-0 routing for inactive lanes is identical
    — their scales land there too, harmlessly."""
    from ...ps.codec import jnp_encode_kv_rows

    S = k_pages.shape[1]
    pidx = jnp.take_along_axis(page_table,
                               (positions // S)[:, None], axis=1)[:, 0]
    pidx = jnp.maximum(pidx, 0)
    if active is not None:
        pidx = jnp.where(active, pidx, 0)
    off = positions % S
    qk, sk = jnp_encode_kv_rows(new_k)                  # (B,H,D) / (B,)
    qv, sv = jnp_encode_kv_rows(new_v)
    k_pages = k_pages.at[pidx, off].set(qk)
    v_pages = v_pages.at[pidx, off].set(qv)
    k_scales = k_scales.at[pidx, off].set(sk)
    v_scales = v_scales.at[pidx, off].set(sv)
    return k_pages, v_pages, k_scales, v_scales


def paged_prefill_write_quant(k_pages, v_pages, k_scales, v_scales,
                              page_ids, new_k, new_v):
    """int8-pool twin of :func:`paged_prefill_write`: the (n * S, H, D)
    prompt K/V is row-encoded and scattered as whole pages, scales
    reshaped alongside as (n, S)."""
    from ...ps.codec import jnp_encode_kv_rows

    S = k_pages.shape[1]
    n = page_ids.shape[0]
    H, D = new_k.shape[-2], new_k.shape[-1]
    qk, sk = jnp_encode_kv_rows(new_k)              # (n*S,H,D) / (n*S,)
    qv, sv = jnp_encode_kv_rows(new_v)
    k_pages = k_pages.at[page_ids].set(qk.reshape(n, S, H, D))
    v_pages = v_pages.at[page_ids].set(qv.reshape(n, S, H, D))
    k_scales = k_scales.at[page_ids].set(sk.reshape(n, S))
    v_scales = v_scales.at[page_ids].set(sv.reshape(n, S))
    return k_pages, v_pages, k_scales, v_scales
